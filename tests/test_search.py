"""Closed-loop auto-strategy search tests (autodist_tpu/search/).

Pins the subsystem's contracts: seeded determinism (identical plan AND
identical dumped trace), budget-bounded termination for both drivers,
mutation validity (every materialized mutation passes ``analysis.verify``
or is counted as pruned), searched-beats-zoo under the shared cost model
on >= 2 bench-family models, the AutoStrategy wiring (search entry in the
ranking, skipped-candidate metadata, all-OOM fallback), trace
reproducibility, and the CLI.
"""
import json
import random

import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.analysis import verify
from autodist_tpu.analysis.diagnostics import Severity
from autodist_tpu.model_item import ModelItem
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.search.drivers import SearchConfig, run_search
from autodist_tpu.search.space import PlanSpace, VarChoice
from autodist_tpu.search.trace import SearchTrace
from autodist_tpu.simulator.simulator import Simulator, _risk_premium
from autodist_tpu.strategy.auto_strategy import (AutoStrategy, Ranking,
                                                 SEARCH_LABEL)


def _emb_item(dense_dim=512, vocab=4096):
    """Embedding + MLP — the sparse/dense mix where per-variable choice
    matters (same fixture family as test_simulator)."""
    params = {"emb": jnp.zeros((vocab, 64)),
              "w1": jnp.zeros((64, dense_dim)),
              "w2": jnp.zeros((dense_dim, 1))}

    def loss_fn(p, batch):
        e = jnp.take(p["emb"], batch["ids"], axis=0)
        h = jnp.tanh(e @ p["w1"])
        return jnp.mean((h @ p["w2"] - batch["y"]) ** 2)

    batch = {"ids": np.zeros((32,), np.int32),
             "y": np.zeros((32, 1), np.float32)}
    return ModelItem(loss_fn=loss_fn, optimizer=optax.adam(1e-3),
                     params=params, example_batch=batch).prepare()


def _mlp_item(width=256, depth=4, batch=64):
    params = {"w%d" % i: jnp.zeros((width, width)) for i in range(depth)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(depth):
            h = jnp.tanh(h @ p["w%d" % i])
        return jnp.mean(h ** 2)

    batch_np = {"x": np.zeros((batch, width), np.float32)}
    return ModelItem(loss_fn=loss_fn, optimizer=optax.sgd(0.1),
                     params=params, example_batch=batch_np).prepare()


def _spec_2x2():
    """Single-node 4-device spec — the 2x2 CPU mesh of the CI runs."""
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True, "tpus": 4}]})


def _spec_cluster(n_nodes=4, tpus=4):
    nodes = [{"address": "10.0.0.%d" % (i + 1), "tpus": tpus,
              "chief": i == 0, "network_bandwidth": 25}
             for i in range(n_nodes)]
    return ResourceSpec.from_dict(
        {"nodes": nodes, "slice": {"type": "v5e", "ici_bandwidth": 400}})


def _zoo_best_score(item, spec, sim):
    from autodist_tpu.search.scoring import zoo_best
    label, score, _best = zoo_best(item, spec, sim)
    return label, score


# ------------------------------------------------------- determinism


def test_fixed_seed_identical_plan_and_trace():
    """Acceptance: fixed seed => identical chosen plan and identical
    search trace on the 2x2 CPU mesh, for both drivers."""
    item, spec = _emb_item(), _spec_2x2()
    for algo in ("beam", "anneal"):
        cfg = SearchConfig(algo=algo, budget=48, seed=7)
        r1 = run_search(item, spec, config=cfg)
        r2 = run_search(item, spec, config=cfg)
        assert r1.ok and r2.ok
        d1, d2 = r1.strategy.to_dict(), r2.strategy.to_dict()
        d1.pop("id"), d2.pop("id")
        assert d1 == d2, algo
        assert r1.trace.to_dict() == r2.trace.to_dict(), algo


def test_different_seeds_may_walk_differently():
    """Not an equality guarantee — but the rng must actually steer the
    walk: the visit traces of two seeds differ (same model, same
    budget)."""
    item, spec = _emb_item(), _spec_2x2()
    r1 = run_search(item, spec, config=SearchConfig(budget=48, seed=0))
    r2 = run_search(item, spec, config=SearchConfig(budget=48, seed=1))
    ops1 = [e.get("op") for e in r1.trace.entries]
    ops2 = [e.get("op") for e in r2.trace.entries]
    assert ops1 != ops2


# ---------------------------------------------- termination / budget


def test_config_rejects_degenerate_knobs():
    """A beam_width/branch/patience/budget of 0 would silently turn the
    search into a false 'all pruned' run — reject at construction like
    a bad algo name."""
    with pytest.raises(ValueError, match="algo"):
        SearchConfig(algo="bogus")
    for knob in ("budget", "beam_width", "branch", "patience"):
        with pytest.raises(ValueError, match=knob):
            SearchConfig(**{knob: 0})


@pytest.mark.parametrize("algo", ["beam", "anneal", "both"])
def test_terminates_within_candidate_budget(algo):
    item, spec = _emb_item(), _spec_2x2()
    budget = 32
    r = run_search(item, spec, config=SearchConfig(algo=algo,
                                                   budget=budget))
    assert r.ok
    assert r.candidates <= budget
    assert len(r.trace.scored()) == r.candidates
    # the chosen plan is at least as good as every seed the run scored
    seed_scores = [e["score_ms"] for e in r.trace.scored()
                   if e["algo"] == "seed" and "score_ms" in e]
    assert seed_scores
    # trace scores are ms rounded to 6 places; compare on that grid
    assert round(r.record.score_s * 1e3, 6) <= min(seed_scores) + 1e-9


# ------------------------------------------------- mutation validity


def test_mutations_always_verify_or_are_pruned():
    """Acceptance: mutation operators always produce plans that pass
    ``analysis.verify()`` (the space is constrained by construction) —
    and the scorer accounts every candidate as scored-or-pruned."""
    item, spec = _emb_item(), _spec_cluster()
    space = PlanSpace(item, spec)
    rng = random.Random(0)
    frontier = [plan for _, plan in space.seeds()]
    checked = 0
    for _ in range(120):
        plan = frontier[rng.randrange(len(frontier))]
        mut = space.mutate(plan, rng)
        if mut is None:
            continue
        child, op = mut
        strategy = space.build(child)
        errs = [d for d in verify(strategy, item, spec)
                if d.severity >= Severity.ERROR]
        assert not errs, (op, [d.format() for d in errs])
        frontier.append(child)
        checked += 1
    assert checked >= 60  # the walk genuinely explored


def test_scorer_accounts_scored_plus_pruned():
    item, spec = _emb_item(), _spec_2x2()
    # absurd capacity: every candidate projects OOM -> all pruned
    r = run_search(item, spec, config=SearchConfig(budget=16),
                   hbm_capacity_bytes=1.0)
    assert not r.ok
    assert r.pruned == r.candidates > 0
    assert r.trace.prune_reasons() == {"oom:ADT501": r.candidates}
    assert r.trace.result["chosen"] is None


def test_sparse_vars_never_partition_onto_dense_allreduce():
    """The ADT309 hazard (reduce-scatter densifying a row-sparse
    gradient) is excluded from the space by construction."""
    item, spec = _emb_item(), _spec_cluster()
    space = PlanSpace(item, spec)
    c = space.canon(VarChoice(sync="AllReduce", shards=4, axis=0), "emb")
    assert c.shards == 1
    rng = random.Random(3)
    plan = space.seeds()[0][1]
    for _ in range(200):
        mut = space.mutate(plan, rng)
        if mut is None:
            continue
        plan = mut[0]
        for name, choice in plan.choices:
            if space.infos[name].sparse and choice.sync == "AllReduce":
                assert choice.shards == 1, (name, choice)


# ------------------------------------- searched vs zoo (acceptance)


@pytest.mark.parametrize("make_item,spec_fn", [
    (_emb_item, _spec_cluster),   # bert/dlrm-family: sparse + dense mix
    (_mlp_item, _spec_cluster),   # resnet-family: dense stacks
])
def test_searched_plan_beats_or_matches_zoo(make_item, spec_fn):
    """Acceptance: on >= 2 bench-family models the searched per-variable
    strategy scores <= the best hand-picked zoo strategy under the SAME
    calibrated cost model, is chosen without compiling anything, and the
    chosen plan passes verify() and the ADT501 gate."""
    item, spec = make_item(), spec_fn()
    sim = Simulator(item, spec)
    r = run_search(item, spec, config=SearchConfig(budget=64),
                   simulator=sim)
    assert r.ok
    zoo_label, zoo_score = _zoo_best_score(item, spec, sim)
    assert r.record.score_s <= zoo_score + 1e-12, (
        r.record.score_s, zoo_label, zoo_score)
    errs = [d for d in verify(r.strategy, item, spec)
            if d.severity >= Severity.ERROR]
    assert not errs
    from autodist_tpu.analysis.memory import budget_diagnostics
    assert not [d for d in budget_diagnostics(
        r.record.breakdown.hbm_bytes, r.record.breakdown.hbm_capacity,
        source="plan-level") if d.code == "ADT501"]


def test_search_smoke_small_budget_lints_clean():
    """CI tier-1-fast smoke: a tight-budget search on one small model
    still produces a plan with zero ADT errors."""
    item, spec = _mlp_item(width=64, depth=2, batch=16), _spec_2x2()
    r = run_search(item, spec, config=SearchConfig(budget=20))
    assert r.ok and r.candidates <= 20
    assert not [d for d in verify(r.strategy, item, spec)
                if d.severity >= Severity.ERROR]


# ------------------------------------------------ trace reproducibility


def test_trace_dump_reproduces_run(tmp_path):
    """Acceptance: search runs are reproducible from the dumped trace —
    its header carries the full SearchConfig; re-running yields the same
    chosen plan and score."""
    item, spec = _emb_item(), _spec_2x2()
    path = str(tmp_path / "trace.json")
    cfg = SearchConfig(algo="both", budget=40, seed=11)
    r1 = run_search(item, spec, config=cfg, trace_path=path)
    loaded = SearchTrace.load(path)
    assert loaded.to_dict() == r1.trace.to_dict()
    cfg2 = SearchConfig.from_dict(loaded.header["config"])
    assert cfg2 == cfg
    r2 = run_search(item, spec, config=cfg2)
    assert r2.trace.result == loaded.result
    d1, d2 = r1.strategy.to_dict(), r2.strategy.to_dict()
    d1.pop("id"), d2.pop("id")
    assert d1 == d2


# ------------------------------------------------- AutoStrategy wiring


def test_autostrategy_ranks_search_entry_and_picks_at_least_zoo():
    item, spec = _emb_item(), _spec_cluster()
    auto = AutoStrategy()
    chosen = auto.build(item, spec)
    assert isinstance(auto.last_ranking, Ranking)
    labels = [r.label for r in auto.last_ranking]
    assert SEARCH_LABEL in labels
    best = auto.last_ranking[0]
    zoo_scores = [r.step_time_s * _risk_premium(r.strategy)
                  for r in auto.last_ranking if r.label != SEARCH_LABEL]
    assert (best.step_time_s * _risk_premium(best.strategy)
            <= min(zoo_scores) + 1e-12)
    assert auto.last_ranking.search_trace is not None
    assert auto.last_ranking.search_trace.result["candidates"] > 0
    # the chosen plan still verifies clean against the real inputs
    assert not [d for d in verify(chosen, item, spec)
                if d.severity >= Severity.ERROR]


def test_autostrategy_search_off_keeps_zoo_only():
    item, spec = _emb_item(), _spec_cluster()
    auto = AutoStrategy(search=False)
    auto.build(item, spec)
    assert SEARCH_LABEL not in [r.label for r in auto.last_ranking]
    assert auto.last_ranking.search_trace is None


def test_autostrategy_records_skipped_candidates(caplog):
    """Satellite: builder failures log at WARNING (with the ADT
    diagnostic when present) and land on last_ranking.skipped."""
    import logging as pylogging

    from autodist_tpu.analysis.diagnostics import DiagnosticError, error
    from autodist_tpu.strategy.base import StrategyBuilder
    from autodist_tpu.utils.logging import get_logger

    class _Boom(StrategyBuilder):
        def build(self, model_item, resource_spec):
            raise DiagnosticError(error(
                "ADT301", "synthetic builder failure", var="w1"))

    item, spec = _emb_item(), _spec_cluster()
    auto = AutoStrategy(search=False,
                        extra_candidates=[("boom", _Boom())])
    logger = get_logger()
    logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(pylogging.WARNING, logger="autodist_tpu"):
            auto.build(item, spec)
    finally:
        logger.removeHandler(caplog.handler)
    assert auto.last_ranking.skipped == [
        {"label": "boom",
         "reason": auto.last_ranking.skipped[0]["reason"]}]
    assert "ADT301" in auto.last_ranking.skipped[0]["reason"]
    warnings = [r.getMessage() for r in caplog.records
                if r.levelno >= pylogging.WARNING]
    assert any("candidate boom failed" in m and "ADT301" in m
               for m in warnings)


def test_autostrategy_all_oom_fallback(caplog):
    """Satellite: when EVERY candidate (zoo and searched) projects OOM,
    the skip path falls back to the unskipped ranking and AutoStrategy
    still returns a plan instead of raising."""
    import logging as pylogging

    from autodist_tpu.utils.logging import get_logger
    item, spec = _emb_item(), _spec_cluster()
    auto = AutoStrategy(hbm_capacity_bytes=1.0)
    logger = get_logger()
    logger.addHandler(caplog.handler)
    try:
        with caplog.at_level(pylogging.INFO, logger="autodist_tpu"):
            chosen = auto.build(item, spec)
    finally:
        logger.removeHandler(caplog.handler)
    assert chosen is not None
    assert len(auto.last_ranking) > 0
    assert not auto.last_ranking[0].breakdown.feasible
    msgs = [r.getMessage() for r in caplog.records]
    assert any("every candidate is projected to OOM" in m for m in msgs)


def test_autostrategy_still_trains_end_to_end():
    """The searched plan must lower and train through the full stack."""
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16, 4).astype(np.float32))}
    loss = lambda p, b: jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)  # noqa: E731
    batch = {"x": rng.randn(16, 16).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}
    builder = AutoStrategy(search=SearchConfig(budget=32))
    ad = autodist_tpu.AutoDist(strategy_builder=builder)
    step = ad.function(loss, optimizer=optax.sgd(0.1), params=params)
    losses = [step(batch)["loss"] for _ in range(5)]
    assert losses[-1] < losses[0]
    assert SEARCH_LABEL in [r.label for r in builder.last_ranking]
    autodist_tpu.reset()


# -------------------------------------------------------- telemetry


def test_search_telemetry_counters():
    from autodist_tpu.telemetry import spans as tel
    rec = tel.get_recorder()
    before = rec.counters().get("search.candidates", 0.0)
    item, spec = _mlp_item(width=64, depth=2, batch=16), _spec_2x2()
    r = run_search(item, spec, config=SearchConfig(budget=16))
    after = rec.counters().get("search.candidates", 0.0)
    assert after - before == r.candidates
    assert rec.gauges().get("search.candidates_per_s", 0.0) > 0


# --------------------------------------------------------------- CLI


def test_cli_json_trace_and_plan(tmp_path, capsys):
    from autodist_tpu.search import cli
    trace = tmp_path / "trace.json"
    plan = tmp_path / "plan.json"
    rc = cli.main(["linear_regression", "--budget", "16", "--seed", "1",
                   "--format", "json", "--trace-out", str(trace),
                   "--dump-plan", str(plan)])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["chosen"]
    assert doc["verify_errors"] == 0
    assert doc["candidates"] <= 16
    assert doc["beats_zoo"] is True
    assert SearchTrace.load(str(trace)).result["chosen"]
    from autodist_tpu.strategy.base import Strategy
    loaded = Strategy.deserialize(path=str(plan))
    assert loaded.node_config


def test_cli_unknown_example_exit_2(capsys):
    from autodist_tpu.search import cli
    assert cli.main(["nope"]) == 2
