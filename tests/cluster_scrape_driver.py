"""CI driver: 2-process scrape -> ONE step-aligned merged cluster trace.

Usage (the per-PR CI leg)::

    python tests/cluster_scrape_driver.py /tmp/cluster-trace.json [port]

The chief starts the real coordination service + a ClockSyncResponder,
then spawns two WORKER processes (this same file with ``--worker``).
Each worker simulates a skewed host clock (worker w1 runs 2s ahead:
its recorder's wall-clock anchor AND its handshake clock both carry the
skew), estimates its offset over the real wire, records barrier-aligned
``runner.dispatch`` spans with global ``step`` args, and publishes its
telemetry blob. The chief scrapes, merges, validates, and ASSERTS:

- no worker missing, per-worker scrape ages present;
- the merged trace is schema-valid;
- ``step_alignment``: every step's cross-worker start spread is within
  tolerance — i.e. the 2s injected skew was corrected by the handshake
  (uncorrected, the spread would BE the 2s skew);
- per-process goodput reports decompose (buckets sum to wall).

Exit 0 = all assertions hold; the merged trace lands at argv[1] for the
artifact upload.
"""
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SKEWS_NS = {"w0": 0, "w1": 2_000_000_000}
STEPS = 4
ALIGN_TOLERANCE_US = 300_000  # 300ms >> rpc latency, << the 2s skew


def worker_main(name: str, port: int, skew_ns: int) -> int:
    from autodist_tpu.runtime.coordination import CoordinationClient
    from autodist_tpu.telemetry import cluster, export
    from autodist_tpu.telemetry import spans as tel

    tel.configure("1")
    rec = tel.get_recorder()
    # simulate a host whose wall clock runs `skew_ns` ahead: the
    # recorder's wall anchor and the handshake's clock source must agree
    rec.epoch_offset_ns += skew_ns
    client = CoordinationClient("127.0.0.1", port)
    est = cluster.sync_recorder_clock(
        client, name, clock=lambda: time.time_ns() + skew_ns)
    # the estimator must have seen (and cancelled) the skew
    assert abs(est.offset_ns + skew_ns) <= max(est.error_ns, 100_000_000), \
        "worker %s: offset %d did not cancel skew %d (err %d)" \
        % (name, est.offset_ns, skew_ns, est.error_ns)
    for step in range(STEPS):
        # the barrier aligns both workers in TRUE time, so the merged
        # trace's per-step spread measures clock correction, not drift
        client.barrier("clockstep-%d" % step, 2)
        with tel.span("runner.dispatch", "runner", step=step,
                      microsteps=1):
            time.sleep(0.02)
    export.publish_telemetry(client, name)
    client.close()
    return 0


def chief_main(out_path: str, port: int) -> int:
    from autodist_tpu.runtime.coordination import (CoordinationClient,
                                                   CoordinationServer)
    from autodist_tpu.telemetry import cluster, export, goodput

    srv = CoordinationServer(port=port)
    srv.start()
    responder_client = CoordinationClient("127.0.0.1", port)
    responder = cluster.ClockSyncResponder(responder_client).start()
    procs = []
    try:
        for name, skew in SKEWS_NS.items():
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 name, str(port), str(skew)],
                env=dict(os.environ, JAX_PLATFORMS="cpu")))
        for p in procs:
            assert p.wait(timeout=120) == 0, "worker exited %d" % p.returncode
        client = CoordinationClient("127.0.0.1", port)
        scraped = export.scrape_cluster(client, list(SKEWS_NS))
        client.close()
        assert scraped["missing"] == [], scraped["missing"]
        assert scraped["workers"] == sorted(SKEWS_NS)
        for w in SKEWS_NS:
            assert scraped["scrape_age_s"][w] is not None
        # w1's published clock metadata must carry its estimated offset
        assert abs(scraped["clocks"]["w1"]["offset_ns"]
                   + SKEWS_NS["w1"]) <= 100_000_000
        trace = scraped["trace"]
        errors = export.validate_chrome_trace(trace)
        assert not errors, errors
        align = cluster.step_alignment(trace)
        assert align["aligned_steps"] == STEPS, align
        assert align["max_spread_us"] < ALIGN_TOLERANCE_US, (
            "steps NOT aligned: max spread %.1fms (injected skew was "
            "%.1fms — the offset correction failed)"
            % (align["max_spread_us"] / 1e3, SKEWS_NS["w1"] / 1e6))
        # per-process goodput decomposes on the merged trace
        for pid, report in goodput.report_from_trace(trace).items():
            assert report.num_dispatches == STEPS, (pid, report.to_dict())
            assert abs(report.coverage - 1.0) < 0.02, report.to_dict()
        os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                    exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(trace, f)
        print("cluster scrape OK: %d workers, %d aligned steps, max "
              "spread %.2fms (injected skew %.0fms), trace -> %s"
              % (len(SKEWS_NS), align["aligned_steps"],
                 align["max_spread_us"] / 1e3, SKEWS_NS["w1"] / 1e6,
                 out_path))
        print("metrics exposition tail:\n"
              + "\n".join(scraped["metrics_text"].splitlines()[-6:]))
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        responder.stop()
        try:
            responder_client.close()
        except OSError:
            pass
        srv.stop()


def main(argv) -> int:
    if argv and argv[0] == "--worker":
        return worker_main(argv[1], int(argv[2]), int(argv[3]))
    out = argv[0] if argv else "/tmp/adt-cluster-trace.json"
    port = int(argv[1]) if len(argv) > 1 else 15909
    return chief_main(out, port)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
