"""End-to-end numeric correctness on an 8-device mesh.

The analog of reference ``tests/integration/cases/c0.py:92-121``: after one
distributed step, the variable values must equal the hand-computed
single-device update on the full global batch (mean of per-replica
gradients == full-batch gradient), for EVERY builder — the strategy ×
model coverage matrix of reference ``tests/integration/test_all.py:20-46``.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S

BUILDERS = [
    ("PS", lambda: S.PS()),
    ("PS_proxy", lambda: S.PS(local_proxy_variable=True)),
    ("PSLoadBalancing", lambda: S.PSLoadBalancing()),
    ("PartitionedPS", lambda: S.PartitionedPS()),
    ("UnevenPartitionedPS", lambda: S.UnevenPartitionedPS()),
    ("AllReduce", lambda: S.AllReduce(chunk_size=2)),
    ("AllReduce_bf16", lambda: S.AllReduce(compressor="HorovodCompressor")),
    ("PartitionedAR", lambda: S.PartitionedAR()),
    ("RandomAxisPartitionAR", lambda: S.RandomAxisPartitionAR(seed=3)),
    ("Parallax", lambda: S.Parallax()),
]


def _make_problem(seed=0):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32)),
              "b": jnp.zeros((2,), jnp.float32),
              "emb": jnp.asarray(rng.randn(16, 4).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)  # [B, 4]
        pred = feat @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"ids": rng.randint(0, 16, size=(16,)).astype(np.int32),
             "y": rng.randn(16, 2).astype(np.float32)}
    return params, loss_fn, batch


def _single_device_reference(params, loss_fn, batch, opt):
    grads = jax.grad(loss_fn)(params, batch)
    updates, _ = opt.update(grads, opt.init(params), params)
    return optax.apply_updates(params, updates)


@pytest.mark.parametrize("name,make_builder", BUILDERS, ids=[b[0] for b in BUILDERS])
def test_one_step_matches_single_device(name, make_builder):
    params, loss_fn, batch = _make_problem()
    opt = optax.sgd(0.1)
    expected = _single_device_reference(params, loss_fn, batch, opt)

    ad = autodist_tpu.AutoDist(strategy_builder=make_builder())
    runner = ad.build(loss_fn, opt, params, batch)
    runner.init(params)
    metrics = runner.run(batch)
    assert np.isfinite(metrics["loss"])

    got = runner.gather_params()
    tol = 2e-2 if "bf16" in name else 1e-5
    for key in expected:
        np.testing.assert_allclose(np.asarray(got[key]), np.asarray(expected[key]),
                                   rtol=tol, atol=tol, err_msg="var %s" % key)
    autodist_tpu.reset()


def test_multiple_steps_decrease_loss():
    params, loss_fn, batch = _make_problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
    step = ad.function(loss_fn, optimizer=optax.adam(0.05), params=params)
    losses = [step(batch)["loss"] for _ in range(20)]
    assert losses[-1] < losses[0] * 0.5


def test_partitioned_state_is_actually_sharded():
    """Partitioned vars must be stored sharded (padded) on the mesh."""
    params, loss_fn, batch = _make_problem()
    ad = autodist_tpu.AutoDist(strategy_builder=S.PartitionedAR())
    runner = ad.build(loss_fn, optax.adam(0.1), params, batch)
    runner.init(params)
    layouts = runner.distributed_step.layouts
    assert layouts["emb"].partitioned  # 16 rows over 8 devices
    st_emb = runner.state.params["emb"]
    assert st_emb.shape[0] == layouts["emb"].padded_dim
    # each device holds 1/8 of the rows
    shard_shape = st_emb.sharding.shard_shape(st_emb.shape)
    assert shard_shape[0] == layouts["emb"].padded_dim // 8
