"""Communication–computation overlap: the bucketed gradient-sync schedule.

The perf tentpole's correctness contract: ``overlap=True`` lowers the
gradient sync as a :class:`~autodist_tpu.parallel.collectives.
GradSyncSchedule` — the exact same sync units (concat buckets, per-var
syncs, ZeRO reduce-scatters) in reverse layer order, chained through
``optimization_barrier`` so XLA can launch each unit's collective while
the remaining backward still runs — and must match the epilogue lowering
exactly (params, optimizer state, metrics, sentinel verdicts): the
schedule reorders WHEN collectives launch, never what they compute. The
cost model prices the schedule by its exposed wire tail, and the
searcher's overlap knob must rank it above the epilogue exactly when the
wire dominates.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.model_item import ModelItem
from autodist_tpu.parallel import collectives
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.search.space import PlanSpace, VarChoice
from autodist_tpu.simulator.simulator import Simulator
from autodist_tpu.strategy.base import GraphConfig
from autodist_tpu.telemetry import spans as tel


def _problem(seed=0, n_batches=8):
    rng = np.random.RandomState(seed)
    params = {"w1": jnp.asarray(rng.randn(8, 16).astype(np.float32) * 0.1),
              "b1": jnp.zeros((16,), jnp.float32),
              "w2": jnp.asarray(rng.randn(16, 4).astype(np.float32) * 0.1),
              "b2": jnp.zeros((4,), jnp.float32)}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] + p["b2"] - b["y"]) ** 2)

    batches = [{"x": rng.randn(16, 8).astype(np.float32),
                "y": rng.randn(16, 4).astype(np.float32)}
               for _ in range(n_batches)]
    return params, loss_fn, batches


def _build(make_builder, params, loss_fn, batch, opt=None, sentinel=None):
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=make_builder())
    runner = ad.build(loss_fn, opt or optax.adam(0.1), params, batch,
                      sentinel=sentinel)
    runner.init(params)
    return runner


def _train_pair(base_builder, overlap_builder, steps=6, fuse=0,
                sentinel=None, seed=0):
    """Train the SAME problem under both lowerings; each leg returns
    (losses, gathered params, gathered opt state, runner) so a parity
    assertion has everything it needs."""
    params, loss_fn, batches = _problem(seed=seed, n_batches=steps)

    def leg(make_builder):
        runner = _build(make_builder, params, loss_fn, batches[0],
                        sentinel=sentinel)
        if fuse:
            hist = runner.fit(iter(batches), fuse_steps=fuse,
                              metrics_every=2)
        else:
            hist = runner.fit(iter(batches))
        losses = [float(m["loss"]) for m in hist]
        gp = runner.gather_params()
        go = runner.distributed_step.gather_opt_state(runner.state)
        return losses, gp, go, runner

    base = leg(base_builder)
    over = leg(overlap_builder)
    return base, over


def _assert_parity(base, over, rtol=1e-6, atol=1e-7):
    b_losses, b_params, b_opt, _ = base
    o_losses, o_params, o_opt, _ = over
    np.testing.assert_allclose(o_losses, b_losses, rtol=rtol, atol=atol)
    for key in b_params:
        np.testing.assert_allclose(
            np.asarray(o_params[key]), np.asarray(b_params[key]),
            rtol=rtol, atol=atol, err_msg="var %s" % key)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=rtol, atol=atol),
        o_opt, b_opt)


# ------------------------------------------------------- schedule IR


def test_schedule_build_reverse_layer_order():
    """Units launch in descending max-var-position (the backward sweep
    produces the LAST layer's gradients first), each stage depending on
    the previous one."""
    units = [("var:a", "reduce", ("a",), 10, "fp32", ("data",)),
             ("var:c", "reduce", ("c",), 30, "fp32", ("data",)),
             ("bucket:g0", "reduce", ("b", "d"), 20, "fp32", ("data",))]
    pos = {"a": 0, "b": 1, "c": 2, "d": 3}
    sched = collectives.build_grad_sync_schedule(units, pos)
    sched.validate()
    assert sched.num_stages == 3 and sched.num_collectives == 3
    # bucket g0 holds d (pos 3) -> first; then c (2); then a (0)
    assert [st.ops[0].unit for st in sched.stages] == [
        "bucket:g0", "var:c", "var:a"]
    assert [st.deps for st in sched.stages] == [(), (0,), (1,)]
    assert [st.ready_rank for st in sched.stages] == [3, 2, 0]
    text = sched.describe()
    assert "stage 0 [ready@3]" in text and "bucket:g0" in text


def test_schedule_build_rejects_unknown_kind():
    with pytest.raises(ValueError, match="kind"):
        collectives.build_grad_sync_schedule(
            [("var:a", "gossip", ("a",), 1, "fp32", ("data",))], {"a": 0})


def test_schedule_validate_rejects_malformed():
    def stage(idx, kind="reduce", unit="var:a", deps=(), axes=("data",),
              rank=0):
        op = collectives.CollectiveOp(kind=kind, unit=unit, axes=axes,
                                      var_names=("a",), payload_elems=1)
        return collectives.ScheduleStage(index=idx, ops=(op,), deps=deps,
                                         ready_rank=rank)

    collectives.GradSyncSchedule(stages=(stage(0),)).validate()
    with pytest.raises(ValueError, match="dense"):
        collectives.GradSyncSchedule(stages=(stage(1),)).validate()
    with pytest.raises(ValueError, match="kind"):
        collectives.GradSyncSchedule(
            stages=(stage(0, kind="gossip"),)).validate()
    with pytest.raises(ValueError, match="axes"):
        collectives.GradSyncSchedule(stages=(stage(0, axes=()),)).validate()
    with pytest.raises(ValueError, match="precede"):
        collectives.GradSyncSchedule(stages=(stage(0, deps=(0,)),)).validate()
    with pytest.raises(ValueError, match="twice"):
        collectives.GradSyncSchedule(
            stages=(stage(0), stage(1, deps=(0,)))).validate()
    with pytest.raises(ValueError, match="no ops"):
        collectives.GradSyncSchedule(stages=(
            collectives.ScheduleStage(index=0, ops=(), deps=()),)).validate()
    with pytest.raises(ValueError, match="reverse"):
        collectives.GradSyncSchedule(stages=(
            stage(0, rank=1),
            stage(1, unit="var:b", deps=(0,), rank=2))).validate()


def test_barrier_chain_is_identity():
    """barrier_chain must never change values — only add ordering."""
    tree = {"a": jnp.arange(4.0), "b": jnp.ones((2, 2))}
    out, token = collectives.barrier_chain(tree, None)
    assert out is tree and token is None  # no token: nothing to chain
    token0 = collectives.overlap_token(tree)
    assert token0 is not None and token0.shape == (1,)
    out, token1 = collectives.barrier_chain(tree, token0)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.arange(4.0))
    np.testing.assert_array_equal(np.asarray(out["b"]), np.ones((2, 2)))
    np.testing.assert_array_equal(np.asarray(token1), np.asarray(token0))
    assert collectives.overlap_token({}) is None


# ----------------------------------------------------- lowering parity


OVERLAP_BUILDERS = [
    # one sync unit per var: the deepest schedule
    ("AllReduce/chunk1", lambda ov: S.AllReduce(chunk_size=1, overlap=ov)),
    # two vars per concat bucket (compressed wire, bucket state rides)
    ("AllReduce/compressed", lambda ov: S.AllReduce(
        compressor="HorovodCompressor", chunk_size=2, overlap=ov)),
    # ZeRO rs+ag: reduce_scatter stages + sharded applies
    ("ZeroSharded", lambda ov: S.ZeroSharded(overlap=ov)),
]


@pytest.mark.parametrize("name,mk", OVERLAP_BUILDERS,
                         ids=[b[0] for b in OVERLAP_BUILDERS])
def test_overlap_parity(name, mk):
    """The schedule lowering must match the epilogue exactly: losses,
    params, and optimizer state, with the schedule really armed and the
    barrier chain really in the program."""
    base, over = _train_pair(lambda: mk(False), lambda: mk(True))
    _assert_parity(base, over)
    meta = over[3].distributed_step.metadata
    assert meta["overlap"] and meta["overlap_stages"] >= 2, meta
    assert meta["overlap_schedule"]
    assert not base[3].distributed_step.metadata["overlap"]
    _, _, batches = _problem()
    text = over[3].lowered_text(batches[0])
    assert (text.count("optimization_barrier")
            + text.count("opt-barrier")) >= meta["overlap_stages"] - 1
    autodist_tpu.reset()


def test_overlap_parity_with_host_ps_mix():
    """A mixed plan (host-PS store + AllReduce vars) keeps the PS wire
    outside the schedule; parity must hold and the schedule covers only
    the device-resident sync units."""
    from autodist_tpu.strategy.base import (AllReduceSynchronizer,
                                            PSSynchronizer, Strategy,
                                            VarConfig)
    from autodist_tpu.strategy.ps_strategy import (reduction_devices,
                                                   replica_devices)

    class Mixed:
        def __init__(self, overlap):
            self.overlap = overlap

        def build(self, item, spec):
            dest = reduction_devices(spec)[0]
            nodes = []
            for i, n in enumerate(item.trainable_var_names):
                if i % 2 == 0:
                    sync = AllReduceSynchronizer(group=i)
                else:
                    sync = PSSynchronizer(reduction_destination=dest,
                                          sync=True)
                nodes.append(VarConfig(var_name=n, synchronizer=sync))
            return Strategy(node_config=nodes, graph_config=GraphConfig(
                replicas=list(replica_devices(spec)),
                overlap=self.overlap))

    base, over = _train_pair(lambda: Mixed(False), lambda: Mixed(True))
    _assert_parity(base, over)
    meta = over[3].distributed_step.metadata
    # two AllReduce vars in distinct groups -> a 2-stage schedule; the
    # two host-PS vars sync through the store, outside the schedule
    assert meta["overlap"] and meta["overlap_stages"] == 2, meta
    autodist_tpu.reset()


def test_overlap_parity_fused_k4():
    """The schedule must ride the fused lax.scan engine unchanged:
    fit(fuse_steps=4) under overlap == fit(fuse_steps=4) under the
    epilogue, with the k-fold dispatch saving intact."""
    base, over = _train_pair(lambda: S.AllReduce(chunk_size=1),
                             lambda: S.AllReduce(chunk_size=1,
                                                 overlap=True),
                             steps=8, fuse=4)
    _assert_parity(base, over)
    assert over[3].distributed_step.metadata["overlap"]
    assert (over[3].distributed_step.dispatches
            == base[3].distributed_step.dispatches == 8 // 4)
    autodist_tpu.reset()


def test_overlap_int8_wire_bf16_compute_composition():
    """int8 quantized wire + managed bf16 compute tier + overlap must
    compose: the schedule is the only difference between the legs, so
    even the lossy paths line up."""
    def mk(ov):
        return S.AllReduce(wire_dtype="int8", chunk_size=1,
                           compute_dtype="bf16", overlap=ov)

    base, over = _train_pair(lambda: mk(False), lambda: mk(True))
    _assert_parity(base, over, rtol=1e-5, atol=1e-6)
    meta = over[3].distributed_step.metadata
    assert meta["overlap"] and meta["compute_dtype"] == "bf16"
    autodist_tpu.reset()


def test_overlap_sentinel_verdict_identity(monkeypatch):
    """The sentinel judges the COMPLETE synced gradient: an injected NaN
    step must produce the identical skip verdict (and final state) under
    the schedule as under the epilogue."""
    monkeypatch.setenv("ADT_GRAD_FAULT_PLAN", json.dumps(
        {"faults": [{"var": "w1", "mode": "nan", "step": 3}]}))
    base, over = _train_pair(
        lambda: S.AllReduce(chunk_size=1),
        lambda: S.AllReduce(chunk_size=1, overlap=True),
        steps=8, sentinel=True)
    _assert_parity(base, over)
    assert all(np.isfinite(over[0]))
    b_stats = base[3].step_stats()["sentinel"]
    o_stats = over[3].step_stats()["sentinel"]
    assert b_stats["skips"] == o_stats["skips"] == 1
    autodist_tpu.reset()


def test_overlap_disarms_for_stale_host_ps():
    """A stale host-PS plan cannot overlap (the schedule sequences SYNC
    collectives): the lowering disarms with a warning instead of lowering
    a wrong schedule, and the metadata records request vs reality."""
    class StalePSOverlap(S.PS):
        def build(self, item, spec):
            strat = super().build(item, spec)
            strat.graph_config.overlap = True
            return strat

    params, loss_fn, batches = _problem()
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=StalePSOverlap(staleness=2))
    runner = ad.build(loss_fn, optax.adam(0.1), params, batches[0])
    runner.init(params)
    meta = runner.distributed_step.metadata
    assert meta["overlap_requested"] and not meta["overlap"]
    assert meta["overlap_stages"] == 0
    autodist_tpu.reset()


# -------------------------------------------------- telemetry counters


def test_overlap_counters_preregistered_and_credited():
    params, loss_fn, batches = _problem()
    runner = _build(lambda: S.AllReduce(chunk_size=1, overlap=True),
                    params, loss_fn, batches[0])
    counters = tel.counters()
    assert "overlap.exposed_wait_ms" in counters  # pre-registered at 0
    stages = runner.distributed_step.metadata["overlap_stages"]
    assert counters["overlap.buckets"] == stages > 0
    autodist_tpu.reset()
    # epilogue build: keys still present (scrapers see a stable schema)
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batches[0])
    counters = tel.counters()
    assert counters["overlap.buckets"] == 0.0
    assert "overlap.exposed_wait_ms" in counters
    autodist_tpu.reset()


# ------------------------------------------------------ ADT409 lint


def test_adt409_fires_on_barrier_free_armed_program():
    from autodist_tpu.analysis.lowered import lint_lowered_text
    serialized = """
    %0 = "stablehlo.all_reduce"(%g0) : tensor<64xf32>
    %1 = "stablehlo.all_reduce"(%g1) : tensor<64xf32>
    """
    codes = {d.code for d in lint_lowered_text(serialized,
                                               overlap_armed=True)}
    assert "ADT409" in codes
    # same text, overlap not armed: silent
    codes = {d.code for d in lint_lowered_text(serialized)}
    assert "ADT409" not in codes
    # armed AND chained: the schedule reached the program — silent
    chained = serialized + '\n%2 = stablehlo.optimization_barrier %t\n'
    codes = {d.code for d in lint_lowered_text(chained, overlap_armed=True)}
    assert "ADT409" not in codes


def test_adt409_through_lint_runner():
    """End to end: a multi-stage overlap program lints clean; a one-var
    model (degenerate 1-stage schedule — nothing to overlap, nothing to
    chain) fires ADT409 through Runner.lint_lowered."""
    params, loss_fn, batches = _problem()
    runner = _build(lambda: S.AllReduce(chunk_size=1, overlap=True),
                    params, loss_fn, batches[0])
    codes = [d.code for d in runner.lint_lowered(batches[0])]
    assert "ADT409" not in codes
    autodist_tpu.reset()

    rng = np.random.RandomState(0)
    one_params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32))}

    def one_loss(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    batch = {"x": rng.randn(8, 4).astype(np.float32),
             "y": rng.randn(8, 2).astype(np.float32)}
    runner = _build(lambda: S.AllReduce(chunk_size=1, overlap=True),
                    one_params, one_loss, batch)
    meta = runner.distributed_step.metadata
    assert meta["overlap"] and meta["overlap_stages"] == 1
    codes = [d.code for d in runner.lint_lowered(batch)]
    assert "ADT409" in codes
    autodist_tpu.reset()


# ------------------------------------------------------- cost model


def _cm_item(dense, layers, batch):
    params = {"w%d" % i: jnp.zeros((dense, dense)) for i in range(layers)}

    def loss_fn(p, b):
        h = b["x"]
        for i in range(layers):
            h = jnp.tanh(h @ p["w%d" % i])
        return jnp.mean(h ** 2)

    return ModelItem(
        loss_fn=loss_fn, optimizer=optax.sgd(0.1), params=params,
        example_batch={"x": np.zeros((batch, dense), np.float32)}).prepare()


def _cm_spec(ici):
    nodes = [{"address": "10.0.0.%d" % (i + 1), "tpus": 4, "chief": i == 0,
              "network_bandwidth": 25} for i in range(2)]
    return ResourceSpec.from_dict(
        {"nodes": nodes, "slice": {"type": "v5e", "ici_bandwidth": ici}})


def test_cost_model_ranks_overlap_by_boundedness():
    """The overlap estimate must rank ABOVE the epilogue when the wire
    dominates (backward compute hides most of it) and BELOW when the
    collectives are already cheap (per-stage launch latency outweighs
    the hiding) — the two directions the searcher's knob turns on."""
    # wire-dominated: 4 x 2048^2 fp32 grads over a 10 GB/s interconnect
    it, sp = _cm_item(2048, 4, 2048), _cm_spec(10)
    sim = Simulator(it, sp)
    ep = sim.simulate(S.AllReduce(chunk_size=1).build(it, sp),
                      "ep").breakdown
    ov = sim.simulate(S.AllReduce(chunk_size=1, overlap=True).build(it, sp),
                      "ov").breakdown
    assert ov.overlap and ov.overlap_stages == 4
    assert not ep.overlap
    assert 0.0 < ov.overlap_exposed_s < ov.allreduce_s
    assert ov.step_time_s < ep.step_time_s
    # compute-dominated, fast wire: tiny collectives, the k extra
    # launches cost more than the hiding saves
    it, sp = _cm_item(256, 4, 65536), _cm_spec(800)
    sim = Simulator(it, sp)
    ep = sim.simulate(S.AllReduce(chunk_size=1).build(it, sp),
                      "ep").breakdown
    ov = sim.simulate(S.AllReduce(chunk_size=1, overlap=True).build(it, sp),
                      "ov").breakdown
    assert ep.compute_s > ep.allreduce_s
    assert ov.step_time_s >= ep.step_time_s


def test_cost_model_overlap_disarms_for_stale_ps():
    """estimate() must mirror the lowering: a stale host-PS plan never
    prices as overlapped (the lowering would disarm it)."""
    class StalePSOverlap(S.PS):
        def build(self, item, spec):
            strat = super().build(item, spec)
            strat.graph_config.overlap = True
            return strat

    it, sp = _cm_item(256, 4, 32), _cm_spec(400)
    bd = Simulator(it, sp).simulate(
        StalePSOverlap(staleness=2).build(it, sp), "stale").breakdown
    assert not bd.overlap and bd.overlap_exposed_s == 0.0


def test_calibration_scales_overlap_tail():
    """The exposed overlap tail is wire time: a measured set whose only
    error is the collective bandwidth must land on ar_scale and correct
    the overlapped prediction too."""
    import dataclasses
    from autodist_tpu.simulator import calibration as cal_lib
    from autodist_tpu.simulator.cost_model import CostBreakdown
    compute_only = CostBreakdown(compute_s=1e-3, allreduce_s=0.0,
                                 ps_s=0.0, latency_s=1e-5)
    overlapped = CostBreakdown(compute_s=1e-3, allreduce_s=4e-3,
                               ps_s=0.0, latency_s=1e-5, overlap=True,
                               overlap_stages=4, overlap_exposed_s=2e-3)
    # the "hardware" runs the wire 2x slower than modeled; compute and
    # latency are measured dead-on (pinning their scales near 1)
    truth = dataclasses.replace(overlapped, allreduce_s=8e-3,
                                overlap_exposed_s=4e-3)
    cal = cal_lib.fit([compute_only, overlapped],
                      [compute_only.step_time_s, truth.step_time_s])
    assert cal.ar_scale > 1.5
    pred = cal_lib._predict(overlapped,
                            (cal.compute_scale, cal.ar_scale,
                             cal.ps_scale, cal.latency_scale))
    assert abs(pred - truth.step_time_s) / truth.step_time_s < 0.05


# -------------------------------------------------------- search space


def _space():
    it = _cm_item(64, 4, 32)
    sp = _cm_spec(400)
    return PlanSpace(it, sp), it, sp


def test_planspec_overlap_axis_canon():
    space, _, _ = _space()
    plan = space.make_plan({n: VarChoice() for n in space.var_names},
                           chunk_size=8, overlap=True)
    assert plan.overlap and "overlap" in plan.describe()
    # staleness window: the bit is dropped in the SPEC
    host = {n: VarChoice(sync="PS") for n in space.var_names}
    plan = space.make_plan(host, staleness=2, overlap=True)
    assert not plan.overlap
    # < 2 AllReduce-family sync units: nothing to overlap
    one_ar = dict(host)
    one_ar[space.var_names[0]] = VarChoice()
    plan = space.make_plan(one_ar, overlap=True)
    assert not plan.overlap


def test_planspec_overlap_round_trips_and_builds():
    space, it, sp = _space()
    plan = space.make_plan({n: VarChoice() for n in space.var_names},
                           chunk_size=8, overlap=True)
    strat = space.build(plan)
    assert strat.graph_config.overlap
    back = space.from_strategy(strat)
    assert back is not None and back.overlap
    # GraphConfig dict round-trip carries the bit
    d = strat.graph_config.to_dict()
    assert d["overlap"] is True
    assert GraphConfig.from_dict(d).overlap
    assert not GraphConfig.from_dict({"replicas": []}).overlap
    # zoo builder round-trip: AllReduce(overlap=True) -> spec -> build
    back2 = space.from_strategy(
        S.AllReduce(chunk_size=8, overlap=True).build(it, sp))
    assert back2 is not None and back2.overlap
    assert space.build(back2).graph_config.overlap


def test_planspec_toggle_overlap_mutation():
    import random
    space, _, _ = _space()
    plan = space.make_plan({n: VarChoice() for n in space.var_names})
    assert not plan.overlap
    rng = random.Random(7)
    toggled = False
    for _ in range(300):
        out = space.mutate(plan, rng)
        if out is None:
            continue
        new_plan, desc = out
        if desc.startswith("overlap="):
            toggled = True
            assert new_plan.overlap != plan.overlap
    assert toggled, "toggle_overlap never offered on an all-AR plan"
    # a host-PS-mixed overlapped plan that mutates a staleness window on
    # must drop the overlap bit in the same move
    host = {n: VarChoice(sync="PS") for n in space.var_names}
    host[space.var_names[0]] = VarChoice()
    host[space.var_names[1]] = VarChoice()
    plan = space.make_plan(host, overlap=True)
    assert plan.overlap
    hit = False
    for _ in range(300):
        out = space.mutate(plan, rng)
        if out is None:
            continue
        new_plan, desc = out
        if desc.startswith("stale=") and new_plan.staleness:
            assert not new_plan.overlap
            hit = True
    assert hit, "staleness mutation never offered on the host-PS plan"


def test_planspec_overlap_seeds_present():
    space, _, _ = _space()
    by_name = dict(space.seeds())
    assert by_name["seed:ar-overlap"].overlap
    assert by_name["seed:ar-overlap"].chunk_size == 8
    # the zero seed keeps its zero vars AND the overlap bit
    zp = by_name["seed:zero-overlap"]
    assert zp.overlap and any(c.zero for _, c in zp.choices)
