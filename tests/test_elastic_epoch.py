"""Epoch-fenced elastic membership (runtime/elastic.py).

The in-run shrink/grow plane's testable core, against a REAL coordination
service: the epoch/roster protocol, the zombie-writer fence on every
mutating wire path (KV marks, barrier arrival, PS push/publish, checkpoint
commit), watchdog mark hygiene across epochs, loud knob validation, the
partition (zombie-revival) fault op, and a real single-process
reconfiguration — epoch bump → readback-boundary pickup → backend
teardown/rebuild → in-memory re-shard — driven end to end in a subprocess.
The multi-process SIGKILL shrink/grow chaos legs live in
``tests/test_elastic.py`` (slow, nightly).
"""
import json
import os
import socket
import subprocess
import sys
import time

import pytest

from autodist_tpu.runtime import elastic
from autodist_tpu.runtime.coordination import (CoordinationClient,
                                               CoordinationServer)
from autodist_tpu.telemetry import spans as tel

HERE = os.path.dirname(os.path.abspath(__file__))
PORT = 15911


@pytest.fixture(scope="module")
def server():
    srv = CoordinationServer(port=PORT)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture(autouse=True)
def _clean_membership():
    yield
    elastic.clear()


def _client(**kw):
    return CoordinationClient("127.0.0.1", PORT, **kw)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ------------------------------------------------------------ knob validation


def test_elastic_knobs_validated_loudly(monkeypatch):
    """Garbage/negative elastic knobs raise a typed error NAMING the knob
    instead of silently disabling elasticity."""
    monkeypatch.setenv("ADT_ELASTIC", "-1")
    with pytest.raises(elastic.ElasticConfigError) as e:
        elastic.validate_elastic_knobs()
    assert "ADT_ELASTIC" in str(e.value) and e.value.knob == "ADT_ELASTIC"

    monkeypatch.setenv("ADT_ELASTIC", "two")
    with pytest.raises(elastic.ElasticConfigError, match="ADT_ELASTIC"):
        elastic.validate_elastic_knobs()

    monkeypatch.setenv("ADT_ELASTIC", "1")
    monkeypatch.setenv("ADT_ELASTIC_SYNC", "yes")  # permissive bool trap
    with pytest.raises(elastic.ElasticConfigError,
                       match="ADT_ELASTIC_SYNC"):
        elastic.validate_elastic_knobs()

    # inrun needs the sync-elastic bring-up AND a positive budget
    monkeypatch.setenv("ADT_ELASTIC_SYNC", "0")
    monkeypatch.setenv("ADT_ELASTIC_INRUN", "1")
    with pytest.raises(elastic.ElasticConfigError,
                       match="ADT_ELASTIC_INRUN"):
        elastic.validate_elastic_knobs()
    monkeypatch.setenv("ADT_ELASTIC_SYNC", "1")
    monkeypatch.setenv("ADT_ELASTIC", "0")
    with pytest.raises(elastic.ElasticConfigError,
                       match="ADT_ELASTIC_INRUN"):
        elastic.validate_elastic_knobs()

    monkeypatch.setenv("ADT_ELASTIC", "2")
    assert elastic.validate_elastic_knobs() == (2, True, True)


def test_coordinator_validates_knobs_at_construction(tmp_path, monkeypatch):
    """The Coordinator (chief supervision) refuses to come up over a
    garbage budget — the error must fire at bring-up, not at first death."""
    monkeypatch.setenv("ADT_ELASTIC", "nope")
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.runtime.cluster import Cluster
    from autodist_tpu.runtime.coordinator import Coordinator
    spec = tmp_path / "spec.yml"
    spec.write_text("nodes:\n  - address: 127.0.0.1\n    chief: true\n"
                    "    cpus: [0]\n")
    with pytest.raises(elastic.ElasticConfigError, match="ADT_ELASTIC"):
        Coordinator("sid", Cluster(ResourceSpec(str(spec))))


# ------------------------------------------------------------- epoch protocol


def test_epoch_publish_read_monotonic(server):
    c = _client()
    assert elastic.read_epoch(c) is None or True  # service may be shared
    elastic.publish_epoch(c, 10, ["a", "b"])
    assert elastic.read_epoch(c) == (10, ["a", "b"])
    with pytest.raises(ValueError, match="monotonically"):
        elastic.publish_epoch(c, 10, ["a"])
    elastic.publish_epoch(c, 11, ["a"])
    assert elastic.read_epoch(c) == (11, ["a"])
    c.close()


def test_roster_layout_and_epoch_address(monkeypatch):
    assert elastic.roster_layout(["w2", "chiefy", "w1"], "chiefy") == \
        ["chiefy", "w1", "w2"]
    with pytest.raises(ValueError, match="chief"):
        elastic.roster_layout(["w1"], "chiefy")
    monkeypatch.setenv("ADT_COORDINATOR_ADDR", "10.0.0.1:16000")
    # epoch 1 (launch) IS the configured address; later epochs offset
    assert elastic.epoch_coordinator_address(1) == "10.0.0.1:16000"
    assert elastic.epoch_coordinator_address(2) == "10.0.0.1:15999"
    assert elastic.epoch_coordinator_address(3) != \
        elastic.epoch_coordinator_address(2)


# ------------------------------------------------------------ the write fence


def _counter(name):
    return tel.counters().get(name, 0.0)


def test_fence_rejects_zombie_but_not_lagging_survivor(server):
    """A zombie (stale epoch, evicted from the roster) gets FencedOut on
    every mutating path; a lagging survivor (stale epoch, still rostered)
    keeps writing until its own reconfigure boundary."""
    c = _client()
    base = 100
    elastic.publish_epoch(c, base, ["chief", "w2"])

    zombie = elastic.Membership("w2", base, ["chief", "w2"],
                                client_factory=_client)
    survivor = elastic.Membership("chief", base, ["chief", "w2"],
                                  client_factory=_client)
    # membership change: w2 is declared dead, the job shrinks
    elastic.publish_epoch(c, base + 1, ["chief"])

    survivor.fence("anything")  # lagging but rostered: allowed
    before = _counter("elastic.fenced_writes")
    with pytest.raises(elastic.FencedOut) as e:
        zombie.fence("ps.push")
    assert e.value.op == "ps.push"
    assert e.value.my_epoch == base and e.value.current_epoch == base + 1
    assert _counter("elastic.fenced_writes") == before + 1

    # the fence hooks in the resilient client: every mutating RPC of an
    # installed zombie raises FencedOut; reads still pass
    elastic.install(zombie)
    from autodist_tpu.runtime.resilience import ResilientCoordinationClient
    rc = ResilientCoordinationClient("127.0.0.1", PORT)
    rejected = 0
    for call in (lambda: rc.put("straggler/w2", "123.0"),
                 lambda: rc.heartbeat("w2"),
                 lambda: rc.barrier("late-barrier", 1),
                 lambda: rc.report_step("w2", 9),
                 lambda: rc.bput("ps/vals", 7, b"zzz"),
                 lambda: rc.qpush("ps/grads", b"zzz")):
        with pytest.raises(elastic.FencedOut):
            call()
        rejected += 1
    assert rc.get("straggler/w2") is None  # the marks never landed
    assert _counter("elastic.fenced_writes") >= before + 1 + rejected

    # PS wire facade over a raw client: fenced at the service boundary too
    from autodist_tpu.runtime import ps_service
    svc = ps_service.CoordPSService(_client, prefix="fencetest")
    with pytest.raises(elastic.FencedOut):
        svc.push_grads(b"blob")
    with pytest.raises(elastic.FencedOut):
        svc.publish(1, b"blob")
    assert svc.pending_grads() == 0  # read path open; nothing enqueued
    svc.close()
    rc.close()
    zombie.close()
    survivor.close()
    c.close()


def test_fence_open_when_service_unreachable():
    """The fence guards against zombies, not against control-plane blips:
    with the service down, writes proceed (the resilience plane owns that
    failure class)."""
    def refuse():
        raise OSError("nobody home")
    m = elastic.Membership("w", 1, ["w"], client_factory=refuse)
    m.fence("ps.push")  # no raise
    m.close()


def test_fenced_checkpoint_save_leaves_directory_untouched(server, tmp_path,
                                                           monkeypatch):
    """A zombie's late checkpoint save is rejected BEFORE any file is
    written: the checkpoint directory stays byte-identical to a run where
    the zombie never woke."""
    jax = pytest.importorskip("jax")
    import numpy as np
    import optax
    import autodist_tpu as adt
    from autodist_tpu import strategy as S
    from autodist_tpu.checkpoint.saver import Saver
    adt.reset()
    rng = np.random.RandomState(0)
    params = {"w": jax.numpy.asarray(rng.randn(4, 2), jax.numpy.float32)}

    def loss_fn(p, batch):
        return jax.numpy.mean((batch["x"] @ p["w"]) ** 2)

    batch = {"x": rng.randn(8, 4).astype(np.float32)}
    ad = adt.AutoDist(strategy_builder=S.AllReduce())
    runner = ad.build(loss_fn, optax.sgd(0.1), params, batch)
    runner.init(params)
    runner.run(batch)

    ckpt_dir = tmp_path / "ckpt"
    saver = Saver(directory=str(ckpt_dir))
    c = _client()
    base = 200
    elastic.publish_epoch(c, base, ["chief", "w9"])
    elastic.install(elastic.Membership("w9", base, ["chief", "w9"],
                                       client_factory=_client))
    elastic.publish_epoch(c, base + 1, ["chief"])  # w9 is now a zombie
    before = _counter("elastic.fenced_writes")
    with pytest.raises(elastic.FencedOut, match="ckpt.save"):
        saver.save(runner)
    assert sorted(os.listdir(ckpt_dir)) == []  # byte-identical: nothing
    assert _counter("elastic.fenced_writes") == before + 1

    # the successor (current epoch) saves fine into the same directory
    elastic.clear()
    elastic.install(elastic.Membership("chief", base + 1, ["chief"],
                                       client_factory=_client))
    assert saver.save(runner) is not None
    assert any(f.endswith(".meta.json") for f in os.listdir(ckpt_dir))
    c.close()
    adt.reset()


# ------------------------------------------- watchdog mark hygiene × epochs


def _mini_coordinator(tmp_path, monkeypatch):
    monkeypatch.setenv("ADT_COORDSVC_PORT", str(PORT))
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.runtime.cluster import Cluster
    from autodist_tpu.runtime.coordinator import Coordinator
    spec = tmp_path / "spec.yml"
    spec.write_text(
        "nodes:\n  - address: 127.0.0.1\n    chief: true\n    cpus: [0]\n"
        "  - address: localhost\n    cpus: [0]\n")
    return Coordinator("sid-hygiene", Cluster(ResourceSpec(str(spec))),
                       heartbeat_timeout=5.0, max_restarts=0)


def test_mark_gc_scrubs_dead_incarnation(server, tmp_path, monkeypatch):
    """gc_worker_marks clears heartbeat + compiling + straggler records of
    a worker leaving the roster, so a dead incarnation can neither satisfy
    nor poison freshness checks across epochs."""
    coord = _mini_coordinator(tmp_path, monkeypatch)
    c = _client()
    c.heartbeat("wgc")
    c.put("compiling/wgc", repr(time.time()))
    c.put("straggler/wgc", repr(time.time()))
    assert coord._in_compile_grace(c, "wgc") is True
    assert coord._is_straggling(c, "wgc") is True
    assert "wgc" not in c.dead_workers(5.0)  # fresh beat

    elastic.gc_worker_marks(c, "wgc")
    assert coord._in_compile_grace(c, "wgc") is False
    assert coord._is_straggling(c, "wgc") is False
    # deregistered: the stale beat cannot age into a false death either
    assert "wgc" not in c.dead_workers(0.0)
    c.close()


def test_straggler_flag_does_not_carry_across_epochs(server, tmp_path,
                                                     monkeypatch):
    """Satellite: a worker flagged straggling in epoch N must not carry
    the flag into its epoch N+1 incarnation — the admission path GCs the
    marks, and the new incarnation starts clean while a compile-grace
    mark it writes itself still works."""
    coord = _mini_coordinator(tmp_path, monkeypatch)
    c = _client()
    # epoch N: the incarnation is flagged slow-but-alive mid-compile
    c.put("straggler/wsx", repr(time.time()))
    c.put("compiling/wsx", repr(time.time()))
    assert coord._is_straggling(c, "wsx") is True
    # epoch N+1: wsx died, was shrunk away, relaunched, admitted — the
    # admission path (coordinator._maybe_admit_joiners) GCs its marks
    elastic.gc_worker_marks(c, "wsx")
    assert coord._is_straggling(c, "wsx") is False
    assert coord._in_compile_grace(c, "wsx") is False
    # the NEW incarnation's own compile grace works from a clean slate
    c.put("compiling/wsx", repr(time.time()))
    assert coord._in_compile_grace(c, "wsx") is True
    assert coord._is_straggling(c, "wsx") is False
    c.close()


# ----------------------------------------------- partition (zombie) fault op


@pytest.mark.chaos
def test_partition_fault_holds_and_then_delivers(server):
    """The ``partition`` op blackholes ALL proxied traffic for its window,
    then delivers LATE — the zombie-revival timing the epoch fence must
    beat (writes arrive after the worker was declared dead)."""
    from autodist_tpu.runtime.faultinject import FaultPlan, FaultyProxy
    plan = FaultPlan({"faults": [
        {"op": "partition", "match": "INC", "nth": 1, "duration_s": 0.8}]})
    with FaultyProxy("127.0.0.1", PORT, plan=plan) as proxy:
        c = CoordinationClient("127.0.0.1", proxy.port)
        t0 = time.monotonic()
        assert c.incr("part-n") >= 1      # fires AND is held itself
        held = time.monotonic() - t0
        assert held >= 0.7, held           # delivered late, not dropped
        t0 = time.monotonic()
        c.put("part-k", "v")               # window over: fast again
        assert time.monotonic() - t0 < 0.5
        assert c.get("part-k") == "v"
        assert "partition:INC" in plan.injected
        c.close()


# ------------------------------------- real single-process reconfigure (e2e)


INRUN_DRIVER = """
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import optax
import autodist_tpu as adt
from autodist_tpu import strategy
from autodist_tpu.runtime import elastic
from autodist_tpu.runtime.coordination import (CoordinationClient,
                                               CoordinationServer)
from autodist_tpu.telemetry import spans as tel

outdir = sys.argv[1]
builder_name = sys.argv[2] if len(sys.argv) > 2 else "AllReduce"
def make_builder():
    # PS() exercises the host-PS-resident half of the snapshot: the
    # rebuilt store must be re-seeded from the filled snapshot trees
    return getattr(strategy, builder_name)(sync=True) \
        if builder_name == "PS" else getattr(strategy, builder_name)()
port = int(os.environ["ADT_COORDSVC_PORT"])
srv = CoordinationServer(port)
srv.start()

rng = np.random.RandomState(0)
params = {"w": jax.numpy.asarray(rng.randn(8, 4) * 0.3, jax.numpy.float32)}

def loss_fn(p, batch):
    return jax.numpy.mean((batch["x"] @ p["w"] - batch["y"]) ** 2)

batch = {"x": rng.randn(8, 8).astype(np.float32),
         "y": rng.randn(8, 4).astype(np.float32)}

# uninterrupted reference first (no elastic knobs read at build)
ad = adt.AutoDist(strategy_builder=make_builder())
step = ad.function(loss_fn, optimizer=optax.sgd(0.05), params=params)
ref = [float(step(batch)["loss"]) for _ in range(10)]
adt.reset()

os.environ["ADT_ELASTIC"] = "1"
os.environ["ADT_ELASTIC_SYNC"] = "1"
os.environ["ADT_ELASTIC_INRUN"] = "1"
os.environ["ADT_ELASTIC_POLL_S"] = "0.01"
ad = adt.AutoDist(strategy_builder=make_builder())
runner = ad.build(loss_fn, optax.sgd(0.05), params, batch)
runner.init(params)
m = elastic.current()
assert m is not None, "in-run membership was not armed"
assert m.epoch == 1, m.epoch

client = CoordinationClient("127.0.0.1", port)
losses = []
for i in range(10):
    losses.append(float(runner.run(batch)["loss"]))
    if i == 4:
        # membership change with the same roster: the runner must pick it
        # up at a readback boundary, tear down + rebuild mesh/programs,
        # and re-shard its state in memory — losses continue exactly
        elastic.publish_epoch(client, 2, m.roster)
        time.sleep(0.05)  # let the poll window lapse

stats = runner.step_stats()
spans = tel.get_recorder().durations_s("elastic.reconfigure")
out = {"ref": ref, "losses": losses, "reconfigs": runner._reconfigs,
       "epoch": elastic.current().epoch, "elastic": stats["elastic"],
       "reconfigure_spans": spans}
with open(os.path.join(outdir, "out.json"), "w") as f:
    json.dump(out, f)
print("DRIVER_DONE", flush=True)
srv.stop()
"""


@pytest.mark.parametrize("builder", ["AllReduce", "PS"])
def test_inrun_reconfigure_single_process_e2e(tmp_path, builder):
    """A REAL in-run reconfiguration driven end to end (subprocess, so
    the backend teardown cannot disturb other tests): publish epoch 2 →
    the runner reconfigures at its next boundary (backend cleared, mesh +
    programs rebuilt, state re-sharded from the in-memory snapshot — for
    PS, the rebuilt host store re-seeded from the filled snapshot) → the
    loss trajectory is exactly the uninterrupted run's, the reconfigure
    span carries the downtime, and the epoch gauge/counters advance."""
    script = tmp_path / "driver.py"
    script.write_text(INRUN_DRIVER)
    env = dict(os.environ)
    for k in ("ADT_WORKER", "ADT_ELASTIC", "ADT_ELASTIC_SYNC",
              "ADT_ELASTIC_INRUN"):
        env.pop(k, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "ADT_COORDSVC_PORT": str(_free_port()),
        "ADT_TRACE": "1",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(HERE)] +
            ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH")
             else [])),
    })
    proc = subprocess.run([sys.executable, str(script), str(tmp_path),
                           builder],
                          env=env, capture_output=True, text=True,
                          timeout=240)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-4000:]
    out = json.loads((tmp_path / "out.json").read_text())
    assert out["reconfigs"] == 1, out
    assert out["epoch"] == 2, out
    assert out["elastic"]["last_reconfigure_s"] > 0
    assert len(out["reconfigure_spans"]) == 1  # downtime is span-derived
    assert out["reconfigure_spans"][0] > 0
    # state survived the reconfiguration bit-exactly: the interrupted
    # run's losses match the uninterrupted reference at every step
    import numpy as np
    np.testing.assert_allclose(out["losses"], out["ref"],
                               rtol=1e-6, atol=1e-7)
