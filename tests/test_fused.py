"""Fused multi-step engine + async metrics pipeline.

The perf tentpole's correctness contract: ``fit(fuse_steps=k)`` runs k
microsteps per jitted dispatch under ``lax.scan`` and must be allclose —
params, optimizer state AND per-step metrics — to the per-step loop, for
both the AllReduce and the host-PS families (whose pull/push hooks are
device-emulated inside the scan). The dispatch counter proves the k×
reduction in host round-trips, and the ``sync=False`` handle path proves
the steady-state loop issues zero device→host copies between
``metrics_every`` boundaries.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import autodist_tpu
from autodist_tpu import strategy as S
from autodist_tpu.data import DevicePrefetcher
from autodist_tpu.remapper import Remapper
from autodist_tpu.runtime.runner import MetricsHandle


def _make_problem(seed=0, n_batches=8):
    rng = np.random.RandomState(seed)
    params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32)),
              "b": jnp.zeros((2,), jnp.float32),
              "emb": jnp.asarray(rng.randn(16, 4).astype(np.float32))}

    def loss_fn(p, batch):
        feat = jnp.take(p["emb"], batch["ids"], axis=0)
        pred = feat @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batches = [{"ids": rng.randint(0, 16, size=(16,)).astype(np.int32),
                "y": rng.randn(16, 2).astype(np.float32)}
               for _ in range(n_batches)]
    return params, loss_fn, batches


def _build(make_builder, params, loss_fn, batch, opt=None):
    autodist_tpu.reset()
    ad = autodist_tpu.AutoDist(strategy_builder=make_builder())
    runner = ad.build(loss_fn, opt or optax.adam(0.1), params, batch)
    runner.init(params)
    return runner


# the acceptance matrix: a PS strategy (host-resident store, pull/push
# emulated inside the scan), an AllReduce strategy (pure device
# collectives), and a partitioned host store (uneven shard writeback)
PARITY_BUILDERS = [
    ("PS", lambda: S.PS()),
    ("AllReduce", lambda: S.AllReduce()),
    ("UnevenPartitionedPS", lambda: S.UnevenPartitionedPS()),
]


@pytest.mark.parametrize("name,make_builder", PARITY_BUILDERS,
                         ids=[b[0] for b in PARITY_BUILDERS])
def test_fused_parity_and_dispatch_count(name, make_builder):
    """fit(fuse_steps=4) over 8 batches == 8 per-step runs (params, opt
    state, metrics), with 4x fewer jitted dispatches."""
    params, loss_fn, batches = _make_problem()

    runner_a = _build(make_builder, params, loss_fn, batches[0])
    hist_a = runner_a.fit(iter(batches))
    params_a = runner_a.gather_params()
    opt_a = runner_a.distributed_step.gather_opt_state(runner_a.state)
    dispatches_a = runner_a.distributed_step.dispatches

    runner_b = _build(make_builder, params, loss_fn, batches[0])
    hist_b = runner_b.fit(iter(batches), fuse_steps=4, metrics_every=2)
    params_b = runner_b.gather_params()
    opt_b = runner_b.distributed_step.gather_opt_state(runner_b.state)
    dispatches_b = runner_b.distributed_step.dispatches

    # k x fewer host dispatches is the whole point
    assert dispatches_a == len(batches)
    assert dispatches_b == len(batches) // 4

    assert len(hist_a) == len(hist_b) == len(batches)
    np.testing.assert_allclose([m["loss"] for m in hist_a],
                               [m["loss"] for m in hist_b],
                               rtol=1e-5, atol=1e-6)
    for key in params_a:
        np.testing.assert_allclose(
            np.asarray(params_a[key]), np.asarray(params_b[key]),
            rtol=1e-5, atol=1e-6, err_msg="var %s" % key)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        opt_a, opt_b)
    autodist_tpu.reset()


def test_async_handles_zero_readbacks_between_boundaries(monkeypatch):
    """sync=False stepping issues NO device→host metric copies until the
    handle is materialized; fit(metrics_every=n) therefore reads back only
    at boundaries. Counted at the single funnel every readback goes
    through (Remapper.remap_fetch)."""
    params, loss_fn, batches = _make_problem()
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batches[0])

    fetches = []
    real_fetch = Remapper.remap_fetch
    monkeypatch.setattr(Remapper, "remap_fetch",
                        lambda self, fetched: fetches.append(1)
                        or real_fetch(self, fetched))

    # direct handle path: two supersteps, zero fetches until result()
    stack = jax.tree_util.tree_map(lambda *ls: np.stack(ls), *batches[:4])
    h1 = runner.run_superstep(stack, sync=False)
    h2 = runner.run_superstep(stack, sync=False)
    assert isinstance(h1, MetricsHandle) and not h1.materialized
    assert fetches == []
    host = h1.result()
    assert len(fetches) == 1 and np.shape(host["loss"]) == (4,)
    assert h1.result() is host  # second access is free
    h2.result()
    assert len(fetches) == 2

    # fit boundary accounting: 4 supersteps of k=2, readback every 2 —
    # the per-superstep fetch count stays 0 between boundaries
    del fetches[:]
    boundary_counts = []
    orig_superstep = type(runner).run_superstep

    def spying_superstep(self, *a, **kw):
        out = orig_superstep(self, *a, **kw)
        boundary_counts.append(len(fetches))
        return out
    monkeypatch.setattr(type(runner), "run_superstep", spying_superstep)
    hist = runner.fit(iter(batches), fuse_steps=2, metrics_every=2)
    assert len(hist) == 8
    # after supersteps 1 and 3: no readback yet; materialization happens
    # AFTER supersteps 2 and 4, so the counts recorded at dispatch time
    # are [0, 0, 2, 2] — never a fetch between boundaries
    assert boundary_counts == [0, 0, 2, 2]
    assert len(fetches) == 4  # one per superstep handle, paid in bursts
    autodist_tpu.reset()


def test_step_stats_superstep_microstep_accounting():
    """step_stats must report BOTH counters: supersteps (dispatches — the
    unit of the wall-time samples and goodput) and microsteps (optimizer
    applies — the unit examples/s math multiplies by batch size)."""
    params, loss_fn, batches = _make_problem(n_batches=10)
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batches[0])
    stats0 = runner.step_stats()
    assert (stats0["steps"], stats0["supersteps"], stats0["microsteps"],
            stats0["total_s"], stats0["first_step_s"]) == (0, 0, 0, 0.0, None)
    # stable JSON shape: every key exists from step zero (None pre-sample)
    assert stats0["steady_median_s"] is None and stats0["goodput"] is None
    assert set(stats0["telemetry"]) >= {"dispatches", "d2h_bytes",
                                        "coord_retries"}
    assert set(stats0["sentinel"]) == {"skips", "rollbacks",
                                       "last_grad_norm", "quarantined"}
    # 10 batches at k=4: two fused supersteps + a trailing per-step pair
    hist = runner.fit(iter(batches), fuse_steps=4)
    assert len(hist) == 10
    stats = runner.step_stats()
    assert stats["microsteps"] == 10
    assert stats["steps"] == 10  # back-compat alias of microsteps
    assert stats["supersteps"] == 4  # 2 fused dispatches + 2 per-step
    # goodput is defined over dispatches: ideal time uses the superstep
    # median x superstep count, so it can never exceed 1 even though each
    # dispatch covers k microsteps
    assert 0.0 < stats["goodput"] <= 1.0
    # plain run() advances both counters by one
    runner.run(batches[0])
    stats = runner.step_stats()
    assert (stats["supersteps"], stats["microsteps"]) == (5, 11)
    autodist_tpu.reset()


def test_fused_refuses_async_and_stale_host_ps():
    """A scan compiled around a superstep-start PS snapshot cannot observe
    peers' applies between microsteps — staleness/async host-PS must be
    refused loudly, not silently mis-trained."""
    params, loss_fn, batches = _make_problem()
    runner = _build(lambda: S.PS(staleness=2), params, loss_fn, batches[0])
    stack = jax.tree_util.tree_map(lambda *ls: np.stack(ls), *batches[:2])
    with pytest.raises(ValueError, match="fused multi-step"):
        runner.run_superstep(stack)
    with pytest.raises(ValueError, match="fused multi-step"):
        runner.distributed_step.multi_step(2)
    autodist_tpu.reset()


def test_fused_lowering_and_adt408_lint():
    """The fused program lowers to ONE scan (while) with no host traffic
    in its body — Runner.lowered_text(fuse_steps=k) + the ADT408 rule."""
    params, loss_fn, batches = _make_problem()
    runner = _build(lambda: S.PS(), params, loss_fn, batches[0])
    text = runner.lowered_text(batches[0], fuse_steps=4)
    assert "stablehlo.while" in text  # the k-microstep scan
    codes = [d.code for d in runner.lint_lowered(batches[0], fuse_steps=4)]
    assert "ADT408" not in codes and "ADT406" not in codes
    autodist_tpu.reset()


def test_adt408_fires_on_host_transfer_inside_scan_body():
    """Synthetic text: the same host token is ADT406 at top level but
    ADT408 inside a while/scan body (per-microstep cost)."""
    from autodist_tpu.analysis.lowered import lint_lowered_text

    def codes(text):
        return {d.code for d in lint_lowered_text(text)}

    inside = """
    %0 = stablehlo.while(%arg = %init) : tensor<4xf32>
     cond {
      stablehlo.compare ...
     } do {
      %1 = "stablehlo.custom_call"(%x) {call_target_name = "SendToHost"}
     }
    """
    assert "ADT408" in codes(inside)
    assert "ADT406" not in codes(inside)

    outside = '%1 = "stablehlo.custom_call"(%x) {call_target_name = "SendToHost"}'
    assert codes(outside) == {"ADT406"}

    jaxpr_style = """
    c:f32[8] = scan[
      jaxpr={ lambda ; a:f32[] b:f32[].
        d:f32[] = outfeed a b
      }
    ] x y
    """
    assert "ADT408" in codes(jaxpr_style)


def test_prefetcher_stack_mode_shapes_and_tail_drop():
    """DevicePrefetcher(stack=k) yields [k, ...] stacked feeds and drops a
    trailing short group (a short stack would recompile the fused
    program)."""
    batches = [{"x": np.full((4, 2), i, np.float32)} for i in range(10)]
    pf = DevicePrefetcher(iter(batches), lambda b: b, depth=2, stack=4)
    assert pf.stack_k == 4
    items = list(pf)
    assert len(items) == 2  # 10 batches -> 2 full stacks, tail of 2 dropped
    assert items[0]["x"].shape == (4, 4, 2)
    np.testing.assert_array_equal(items[1]["x"][0], batches[4]["x"])


def test_close_flushes_fused_ps_carry():
    """Runner.close() right after fused supersteps must land the carry in
    the host store — a close must never silently discard PS updates."""
    params, loss_fn, batches = _make_problem()
    runner = _build(lambda: S.PS(), params, loss_fn, batches[0])
    store = runner.distributed_step.ps_store
    before = {k: v.copy() for k, v in store.pull().items()}
    stack = jax.tree_util.tree_map(lambda *ls: np.stack(ls), *batches[:4])
    runner.run_superstep(stack, sync=False)
    runner.close()
    after = store.pull()
    changed = any(not np.allclose(before[k], after[k]) for k in before)
    assert changed, "close() dropped the fused PS carry"
    autodist_tpu.reset()


def test_fit_rejects_mismatched_prestacked_source():
    """A pre-stacked source whose stack doesn't match fuse_steps would
    silently train on mis-shaped data — must be refused loudly."""
    params, loss_fn, batches = _make_problem()
    runner = _build(lambda: S.AllReduce(), params, loss_fn, batches[0])
    pf = DevicePrefetcher(iter(batches), runner, stack=4)
    with pytest.raises(ValueError, match="pre-stacked"):
        runner.fit(pf)  # default fuse_steps=1
    with pytest.raises(ValueError, match="pre-stacked"):
        runner.fit(pf, fuse_steps=2)
    autodist_tpu.reset()


def test_fused_step_fn_mode_parity():
    """The opaque step_fn capture mode gets the same scan fusion."""
    rng = np.random.RandomState(3)
    params = {"w": jnp.asarray(rng.randn(4, 2).astype(np.float32))}
    opt = optax.sgd(0.1)

    def step_fn(p, batch):
        def loss(q):
            return jnp.mean((batch["x"] @ q["w"] - batch["y"]) ** 2)
        l, g = jax.value_and_grad(loss)(p)
        updates, _ = opt.update(g, opt.init(p), p)
        return optax.apply_updates(p, updates), {"loss": l}

    batches = [{"x": rng.randn(8, 4).astype(np.float32),
                "y": rng.randn(8, 2).astype(np.float32)} for _ in range(8)]

    def train(fuse):
        autodist_tpu.reset()
        ad = autodist_tpu.AutoDist(strategy_builder=S.AllReduce())
        runner = ad.build_step(step_fn, params, batches[0])
        runner.init(params)
        hist = runner.fit(iter(batches), fuse_steps=fuse)
        return hist, runner.gather_params(), runner.distributed_step.dispatches

    hist_a, params_a, d_a = train(1)
    hist_b, params_b, d_b = train(4)
    assert (d_a, d_b) == (8, 2)
    np.testing.assert_allclose([m["loss"] for m in hist_a],
                               [m["loss"] for m in hist_b],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(params_a["w"]),
                               np.asarray(params_b["w"]),
                               rtol=1e-5, atol=1e-6)
    autodist_tpu.reset()
