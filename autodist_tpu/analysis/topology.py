"""Topology-aware communication analyzer (ADT520-ADT525).

The lowering emits collectives over *logical* mesh axes; this module maps
each collective's replica groups onto the *physical* multi-level topology
(``ResourceSpec.topology()``: hosts x chips with per-level link
bandwidth) and attributes every wire byte to the link level it actually
crosses. That turns "hierarchical is cheaper here" into a lint-checkable
fact on a dryrun pod — no hardware touched:

- :func:`schedule_level_bytes` — per-link-level byte profile of a lowered
  :class:`~autodist_tpu.analysis.hlo.CollectiveSchedule` (what extends
  ``StaticCollectiveProfile`` from one "wire bytes" number to per-level
  rows);
- :func:`lint_schedule` — the lowered-program lints: ADT520 (a flat
  collective spans the slow inter-host level when a synthesized
  hierarchical schedule provably crosses fewer inter-host bytes), ADT521
  (replica groups straddle hosts non-contiguously), ADT523 (a level's
  byte estimate exceeds its bandwidth-delay budget), ADT525 (groups the
  topology cannot price);
- :func:`verify_topology` — the plan-level pass: the same ADT520/523/525
  findings derived from the strategy's synchronizers (before any
  lowering exists), plus ADT522 for a schedule whose synthesized stage
  composition is not reduction-equivalent to the reduce it replaces;
- :func:`diagnostic_for_config_error` — ADT524: a malformed topology
  spec, reported as a diagnostic instead of a traceback.

The byte algebra (all "bytes" are totals crossing one level's links per
step): a flat ring over a group of ``n`` members carries
``2(n-1)/n * P`` per link; with ``B`` of the ring's ``n`` edges crossing
the inter-host level, inter bytes are ``B * 2(n-1)/n * P`` (``B = H``
for a contiguous group spanning ``H`` hosts). The hierarchical schedule
(intra reduce-scatter, leader all-reduce, intra all-gather; arXiv
2110.10548's two-level reduction) crosses ``2(H-1) * P/c`` inter-host
bytes for ``c`` members per host — strictly fewer than the flat ring's
whenever ``c > 1``, which is exactly the ADT520 premise (and why
leader-subgroup collectives, ``c == 1``, never fire it).
"""
import math
from typing import Dict, Iterable, List, Optional, Tuple

from autodist_tpu.analysis.diagnostics import (Diagnostic, error,
                                               sort_diagnostics, warning)
from autodist_tpu.resource_spec import (Topology, TopologyConfigError,
                                        TopologyLevel)

__all__ = [
    "Topology", "TopologyLevel", "TopologyConfigError",
    "resolve_schedule", "group_geometry", "op_level_bytes",
    "schedule_level_bytes", "hier_inter_bytes", "flat_inter_bytes",
    "lint_schedule", "lint_stage_composition", "verify_topology",
    "plan_level_bytes", "diagnostic_for_config_error",
]


def resolve_schedule(choice: Optional[str], topology: Optional[Topology],
                     n: int) -> str:
    """Resolve a synchronizer's ``schedule`` knob to the algorithm the
    lowering/pricing actually uses. ``auto`` picks hierarchical exactly
    when the topology has a priceable inter-host level the sync spans;
    an explicit ``hier`` on a flat (single-level / single-host) mesh is
    REFUSED back to ring — there is nothing to hierarchize, and the
    acceptance contract is that the flat mesh keeps the ring silently."""
    c = (choice or "auto").lower()
    multi_host = (topology is not None and topology.hosts > 1
                  and topology.inter_level is not None
                  and n > topology.chips_per_host)
    if c == "auto":
        return "hier" if multi_host else "ring"
    if c == "hier" and not multi_host:
        return "ring"
    return c


def group_geometry(group: Tuple[int, ...], topology: Topology
                   ) -> Optional[Tuple[int, int, Dict[int, int]]]:
    """Map one replica group onto the topology: ``(hosts_spanned,
    boundary_edges, members_per_host)``. ``boundary_edges`` counts the
    ring edges (consecutive members in group order, wraparound included)
    whose endpoints sit on different hosts — for a contiguous group this
    equals ``hosts_spanned`` (or 0 when single-host); more means the
    device order straddles hosts avoidably (ADT521). ``None`` when a
    member is outside the topology (the ADT525 condition)."""
    n = len(group)
    if n == 0:
        return None
    per_host: Dict[int, int] = {}
    hosts = []
    for dev in group:
        if not 0 <= dev < topology.num_devices:
            return None
        h = dev // topology.chips_per_host
        per_host[h] = per_host.get(h, 0) + 1
        hosts.append(h)
    if n == 1:
        return (1, 0, per_host)
    boundary = sum(1 for i in range(n) if hosts[i] != hosts[(i + 1) % n])
    return (len(per_host), boundary, per_host)


def flat_inter_bytes(payload_bytes: float, n: int, boundary_edges: int
                     ) -> float:
    """Inter-host bytes of the flat ring: each of the group's ``n`` ring
    edges carries ``2(n-1)/n * P``; ``boundary_edges`` of them cross the
    inter-host level."""
    if n <= 1:
        return 0.0
    return boundary_edges * 2.0 * (n - 1) / n * payload_bytes


def hier_inter_bytes(payload_bytes: float, hosts: int, per_host: int
                     ) -> float:
    """Inter-host bytes of the hierarchical two-level schedule: the
    leader all-reduce moves ``P/c`` over a ring of ``H`` hosts — ``H``
    inter-host links at ``2(H-1)/H * P/c`` each."""
    if hosts <= 1:
        return 0.0
    return 2.0 * (hosts - 1) * payload_bytes / max(per_host, 1)


def op_level_bytes(kind: str, payload_bytes: float,
                   groups: Iterable[Tuple[int, ...]],
                   topology: Topology) -> Optional[Dict[str, float]]:
    """Per-level wire bytes of one lowered collective: ring-priced per
    group at its own size, each ring edge attributed to the level it
    crosses. ``None`` when any group member falls outside the topology
    (unpriceable — the caller's ADT525)."""
    from autodist_tpu.simulator.cost_model import collective_wire_bytes
    intra = topology.intra_level.name
    inter = (topology.inter_level.name if topology.inter_level is not None
             else intra)
    out = {intra: 0.0}
    if inter != intra:
        out[inter] = 0.0
    for group in groups:
        geo = group_geometry(tuple(group), topology)
        if geo is None:
            return None
        _, boundary, _ = geo
        k = len(group)
        if k <= 1:
            continue
        per_link = collective_wire_bytes(kind, payload_bytes, k)
        out[intra] += (k - boundary) * per_link
        if boundary:
            out[inter] = out.get(inter, 0.0) + boundary * per_link
    return out


def schedule_level_bytes(schedule, topology: Topology,
                         default_group_size: int = 1) -> Dict[str, float]:
    """Per-link-level wire bytes of a lowered collective schedule —
    the per-level rows ``StaticCollectiveProfile.from_schedule`` attaches
    when built with a topology. Ops with no replica-group annotation are
    priced as one contiguous group of ``default_group_size`` devices;
    unpriceable groups are skipped here (``lint_schedule`` reports them
    as ADT525 — a profile must never raise mid-build)."""
    per_step = (schedule.per_step() if hasattr(schedule, "per_step")
                else schedule)
    total: Dict[str, float] = {lv.name: 0.0 for lv in topology.levels}
    for c in per_step:
        groups = c.replica_groups
        if not groups:
            k = max(int(default_group_size), 1)
            if k <= 1:
                continue
            groups = (tuple(range(min(k, topology.num_devices))),)
        rows = op_level_bytes(c.kind, c.payload_bytes, groups, topology)
        if rows is None:
            continue
        for name, b in rows.items():
            total[name] = total.get(name, 0.0) + b
    return total


# ------------------------------------------------------------------- lints


def _budget_diags(level_bytes: Dict[str, float], topology: Topology,
                  label: str = "") -> List[Diagnostic]:
    """ADT523: a level's per-step byte estimate exceeds its
    bandwidth-delay budget (``budget_ms`` on the level, when declared)."""
    out: List[Diagnostic] = []
    where = " in %s" % label if label else ""
    for lv in topology.levels:
        if lv.budget_ms is None:
            continue
        b = level_bytes.get(lv.name, 0.0)
        est_ms = b / lv.bandwidth_bytes_s * 1e3
        if est_ms > lv.budget_ms:
            out.append(warning(
                "ADT523",
                "level %r%s carries %.0f bytes/step ~ %.2f ms at %.3g "
                "Gbps, over its %.2f ms budget" % (
                    lv.name, where, b, est_ms, lv.bandwidth_gbps,
                    lv.budget_ms),
                fixit="shrink the payload crossing this level "
                      "(hierarchical schedule, int8 wire, ZeRO) or raise "
                      "topology.levels[].budget_ms"))
    return out


def lint_schedule(schedule, topology: Topology,
                  label: str = "") -> List[Diagnostic]:
    """The ADT52x pass over one LOWERED program's collective schedule:
    every replica group is mapped onto the topology, and

    - ADT520 (error): a flat reduce spans >= 2 hosts with >= 2 members
      per host — the synthesized hierarchical schedule provably crosses
      strictly fewer inter-host bytes (the proof is in the message);
      leader-subgroup reduces (one member per host) are exactly the
      hierarchical lowering's inter stage and stay silent;
    - ADT521 (warning): a group straddles hosts non-contiguously — the
      ring takes more inter-host hops than the span requires;
    - ADT523 (warning): a level's byte total exceeds its declared budget;
    - ADT525 (error): a group names a device the topology does not have.
    """
    per_step = (schedule.per_step() if hasattr(schedule, "per_step")
                else schedule)
    out: List[Diagnostic] = []
    where = " in %s" % label if label else ""
    for c in per_step:
        if not c.replica_groups:
            continue
        for group in c.replica_groups:
            geo = group_geometry(tuple(group), topology)
            if geo is None:
                out.append(error(
                    "ADT525",
                    "%s collective%s (line %d) names device(s) outside "
                    "the %d-host x %d-chip topology: groups=%s — the "
                    "per-level profile cannot price it" % (
                        c.kind, where, c.lineno, topology.hosts,
                        topology.chips_per_host,
                        [list(g) for g in c.replica_groups]),
                    fixit="lint with the topology the program was "
                          "lowered for (matching host x chip counts)"))
                break
            hosts_spanned, boundary, per_host = geo
            n = len(group)
            if hosts_spanned > 1 and boundary > hosts_spanned:
                out.append(warning(
                    "ADT521",
                    "%s collective%s (line %d) replica group straddles "
                    "%d hosts non-contiguously: %d of %d ring edges "
                    "cross the inter-host level (a contiguous layout "
                    "needs %d)" % (
                        c.kind, where, c.lineno, hosts_spanned, boundary,
                        n, hosts_spanned),
                    fixit="order replica groups host-major so "
                          "consecutive members share a host"))
            if (c.kind == "reduce" and hosts_spanned > 1
                    and min(per_host.values()) >= 2
                    and len(set(per_host.values())) == 1):
                cc = n // hosts_spanned
                flat = flat_inter_bytes(c.payload_bytes, n, boundary)
                hier = hier_inter_bytes(c.payload_bytes, hosts_spanned, cc)
                if hier < flat:
                    out.append(error(
                        "ADT520",
                        "flat %s%s (line %d, %dB) spans the inter-host "
                        "level over %d hosts x %d chips: it crosses "
                        "%.0f inter-host bytes where the hierarchical "
                        "two-level schedule crosses %.0f (%.1fx fewer)"
                        % (c.op or c.kind, where, c.lineno,
                           c.payload_bytes, hosts_spanned, cc, flat,
                           hier, flat / max(hier, 1.0)),
                        fixit="lower with schedule=hier (or auto) so the "
                              "inter-host links carry only the 1/%d "
                              "leader shard" % cc))
    out += _budget_diags(schedule_level_bytes(per_step, topology),
                         topology, label)
    return sort_diagnostics(out)


def lint_stage_composition(stages, target, var: str = "") -> List[Diagnostic]:
    """ADT522: a synthesized schedule whose stage composition is not
    reduction-equivalent to the reduce it replaces. ``stages`` is an
    iterable of :class:`~autodist_tpu.parallel.collectives.CollectiveOp`;
    ``target`` the flat reduce being replaced."""
    from autodist_tpu.parallel.collectives import reduction_equivalent
    if reduction_equivalent(stages, target):
        return []
    return [error(
        "ADT522",
        "synthesized schedule [%s] is not reduction-equivalent to "
        "reduce over %s — lowering it would change the reduced value, "
        "not just its route" % (
            ", ".join("%s(%s)" % (op.kind, ",".join(op.axes))
                      for op in stages),
            ",".join(target.axes)),
        var=var,
        fixit="every reduce_scatter must pair with an all_gather over "
              "the same axes and each target axis must be reduced "
              "exactly once")]


def diagnostic_for_config_error(e: TopologyConfigError) -> Diagnostic:
    """ADT524: a malformed/unpriceable topology spec, surfaced as a
    diagnostic (the CLI's ``--topology`` error path) instead of a
    traceback."""
    return error("ADT524", "malformed topology spec: %s" % e,
                 fixit="fix the named knob in the topology yaml")


# ----------------------------------------------------------- plan-level pass


def _ar_payload_by_schedule(strategy, model_item, topology: Topology
                            ) -> Tuple[Dict[str, float], List[Diagnostic]]:
    """Per-resolved-algorithm gradient-sync payload bytes of a plan, plus
    the ADT520/522/525 findings the resolution surfaces. Mirrors the cost
    model's classification: plain AllReduce syncs carry the schedule
    knob; ZeRO's rs+ag and partitioned paths price as rhd (they already
    are a scatter+gather composition)."""
    from autodist_tpu.parallel.collectives import (
        SCHEDULE_ALGORITHMS, synthesize_collective_candidates)
    from autodist_tpu.strategy.base import (AllReduceSynchronizer,
                                            ZeroShardedSynchronizer)
    infos = (getattr(model_item, "var_infos", None)
             or (model_item if isinstance(model_item, dict) else {}))
    n = max(len(strategy.graph_config.replicas), 1)
    by_sched: Dict[str, float] = {}
    diags: List[Diagnostic] = []
    cph = topology.chips_per_host
    hosts_spanned = min(max(1, -(-n // cph)), topology.hosts)
    per_host = min(n, cph)
    checked_axes = set()
    for node in strategy.node_config:
        info = infos.get(node.var_name)
        if info is None:
            continue
        syncs = ([node.synchronizer] if node.synchronizer else
                 [p.synchronizer for p in node.part_configs])
        for sync in syncs:
            if isinstance(sync, ZeroShardedSynchronizer):
                by_sched["rhd"] = (by_sched.get("rhd", 0.0)
                                   + info.byte_size / max(len(syncs), 1))
                continue
            if not isinstance(sync, AllReduceSynchronizer):
                continue
            choice = getattr(sync, "schedule", "auto") or "auto"
            if choice not in ("auto",) + tuple(SCHEDULE_ALGORITHMS):
                diags.append(error(
                    "ADT525",
                    "unknown collective schedule %r — the topology "
                    "pricer cannot cost it and the lowering would fall "
                    "back to the flat psum" % choice,
                    var=node.var_name,
                    fixit="use one of auto, %s"
                          % ", ".join(SCHEDULE_ALGORITHMS)))
                continue
            resolved = resolve_schedule(choice, topology, n)
            by_sched[resolved] = (by_sched.get(resolved, 0.0)
                                  + info.byte_size / max(len(syncs), 1))
            if (resolved == "ring" and choice == "ring"
                    and hosts_spanned > 1 and per_host > 1):
                flat = flat_inter_bytes(info.byte_size, n, hosts_spanned)
                hier = hier_inter_bytes(info.byte_size, hosts_spanned,
                                        per_host)
                if hier < flat:
                    diags.append(error(
                        "ADT520",
                        "schedule pinned to the flat ring while the "
                        "replicas span %d hosts x %d chips: %.0f "
                        "inter-host bytes vs the hierarchical "
                        "schedule's %.0f (%.1fx fewer)" % (
                            hosts_spanned, per_host, flat, hier,
                            flat / max(hier, 1.0)),
                        var=node.var_name,
                        fixit="set schedule=hier (or auto) on this "
                              "synchronizer"))
            if resolved == "hier":
                # ADT522 self-check: the composition the lowering would
                # execute must be reduction-equivalent to the flat
                # reduce it replaces (checked once per axis layout)
                key = ("data",)
                if key not in checked_axes:
                    checked_axes.add(key)
                    cands = synthesize_collective_candidates(
                        "var:%s" % node.var_name, ("ici", "dcn"),
                        intra_axes=("ici",), inter_axes=("dcn",))
                    target = cands["ring"][0]
                    for name, stages in cands.items():
                        diags += lint_stage_composition(
                            stages, target, var=node.var_name)
    return by_sched, diags


def plan_level_bytes(strategy, model_item, topology: Topology
                     ) -> Dict[str, float]:
    """Predicted per-level wire bytes of a plan's gradient sync on this
    topology (contiguous replica layout) — the prediction the drift
    report's ``levels`` section joins against the lowered profile's
    measured per-level rows."""
    by_sched, _ = _ar_payload_by_schedule(strategy, model_item, topology)
    n = max(len(strategy.graph_config.replicas), 1)
    cph = topology.chips_per_host
    hosts = min(max(1, -(-n // cph)), topology.hosts)
    per_host = min(n, cph)
    intra = topology.intra_level.name
    inter = (topology.inter_level.name if topology.inter_level is not None
             else intra)
    out = {lv.name: 0.0 for lv in topology.levels}
    for sched, payload in by_sched.items():
        if n <= 1 or payload <= 0:
            continue
        if sched == "hier" and hosts > 1 and per_host > 1:
            out[intra] += 2.0 * (per_host - 1) * payload * hosts
            out[inter] += hier_inter_bytes(payload, hosts, per_host)
        else:
            per_link = 2.0 * (n - 1) / n * payload
            boundary = hosts if hosts > 1 else 0
            out[intra] += (n - boundary) * per_link
            out[inter] = out.get(inter, 0.0) + boundary * per_link
    return out


def verify_topology(strategy, model_item, resource_spec) -> List[Diagnostic]:
    """Plan-level ADT52x pass (rules.py style): silently empty when the
    spec declares no multi-level topology, so flat specs lint exactly as
    before. On a hierarchy: ADT520 for flat-pinned schedules that span
    the slow level, ADT522 for non-equivalent synthesized compositions,
    ADT523 for per-level budget overruns, ADT525 for unpriceable
    configurations (more replicas than the topology has devices, unknown
    schedule names)."""
    topology = None
    if resource_spec is not None and hasattr(resource_spec, "topology"):
        topology = resource_spec.topology()
    if topology is None:
        return []
    out: List[Diagnostic] = []
    n = max(len(strategy.graph_config.replicas), 1)
    if n > topology.num_devices:
        out.append(error(
            "ADT525",
            "plan has %d replicas but the topology only describes %d "
            "devices (%d hosts x %d chips) — per-level attribution is "
            "impossible" % (n, topology.num_devices, topology.hosts,
                            topology.chips_per_host),
            fixit="grow topology.hosts/chips_per_host or shrink the "
                  "replica set"))
        return sort_diagnostics(out)
    by_sched, diags = _ar_payload_by_schedule(strategy, model_item,
                                              topology)
    out += diags
    out += _budget_diags(plan_level_bytes(strategy, model_item, topology),
                         topology)
    return sort_diagnostics(out)


def describe_levels(level_bytes: Dict[str, float], topology: Topology
                    ) -> str:
    """One-line per-level profile for CLI output: bytes and estimated
    link-seconds per level."""
    bits = []
    for lv in topology.levels:
        b = level_bytes.get(lv.name, 0.0)
        bits.append("%s=%.0fB (%.3g ms @ %.3g Gbps)"
                    % (lv.name, b, b / lv.bandwidth_bytes_s * 1e3,
                       lv.bandwidth_gbps))
    return ", ".join(bits)


# ``math`` is used by callers pricing log2 hop counts; keep the import
# explicit for them rather than re-deriving it per call site.
_ = math
