"""Entry point for ``python -m autodist_tpu.analysis``."""
import sys

from autodist_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
