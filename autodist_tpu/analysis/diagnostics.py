"""Typed diagnostics for the pre-compile strategy verifier.

Every check in ``autodist_tpu.analysis`` reports through one shape:
:class:`Diagnostic` — a stable code, a severity, the variable (or graph
node) it anchors to, a human message, and a one-line suggested fix. Codes
are stable across releases so CI greps, issue reports, and suppressions
can key on them:

- ``ADT1xx`` — plan-shape errors (missing/duplicate/unknown nodes,
  replica and mesh geometry);
- ``ADT2xx`` — partitioning/divisibility (partitioner strings, shard
  sizes, model-parallel ``mp_axes``);
- ``ADT3xx`` — synchronizer/compressor configuration;
- ``ADT4xx`` — runtime hazards (warnings by default: pipeline bubbles,
  PS hot spots, lowered-program smells);
- ``ADT5xx`` — memory footprint and collective schedule (projected OOM,
  budget pressure, cross-program schedule deadlocks).

The compile path raises :class:`DiagnosticError` — a ``ValueError``
carrying the same :class:`Diagnostic` the linter would report — so lint
time and compile time can never disagree about what is wrong.
"""
import dataclasses
import enum
from typing import Iterable, List, Sequence


class Severity(enum.IntEnum):
    """Ordered so ``severity >= Severity.ERROR`` reads naturally."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding of the static analyzer.

    ``var`` is the strategy node (variable name) the finding anchors to;
    empty for graph-level findings. ``fixit`` is a one-line suggested fix,
    empty when there is no mechanical suggestion.
    """

    code: str
    severity: Severity
    message: str
    var: str = ""
    fixit: str = ""

    def format(self) -> str:
        where = " [%s]" % self.var if self.var else ""
        fix = " (fix: %s)" % self.fixit if self.fixit else ""
        return "%s %s%s: %s%s" % (self.code, self.severity, where,
                                  self.message, fix)

    def to_dict(self) -> dict:
        return {"code": self.code, "severity": str(self.severity),
                "var": self.var, "message": self.message, "fixit": self.fixit}


def error(code: str, message: str, var: str = "", fixit: str = "") -> Diagnostic:
    return Diagnostic(code, Severity.ERROR, message, var, fixit)


def warning(code: str, message: str, var: str = "", fixit: str = "") -> Diagnostic:
    return Diagnostic(code, Severity.WARNING, message, var, fixit)


def info(code: str, message: str, var: str = "", fixit: str = "") -> Diagnostic:
    return Diagnostic(code, Severity.INFO, message, var, fixit)


class DiagnosticError(ValueError):
    """A rule violation raised on the compile path.

    Subclasses ``ValueError`` so every pre-existing ``except ValueError``
    (and test asserting one) keeps working; carries the structured
    :class:`Diagnostic` so callers — and the linter, which runs the same
    rule functions — see identical content.
    """

    def __init__(self, diagnostic: Diagnostic):
        super().__init__(diagnostic.format())
        self.diagnostic = diagnostic

    @property
    def code(self) -> str:
        return self.diagnostic.code


class StrategyVerificationError(ValueError):
    """Raised by ``AutoDist(validate="error")`` when the verifier finds
    error-severity diagnostics before kernel transformation."""

    def __init__(self, diagnostics: Sequence[Diagnostic]):
        self.diagnostics = list(diagnostics)
        lines = [d.format() for d in self.diagnostics]
        super().__init__(
            "strategy failed verification with %d error(s):\n  %s"
            % (len(lines), "\n  ".join(lines)))


def max_severity(diags: Iterable[Diagnostic]) -> Severity:
    out = Severity.INFO
    for d in diags:
        if d.severity > out:
            out = d.severity
    return out


def has_errors(diags: Iterable[Diagnostic]) -> bool:
    return any(d.severity >= Severity.ERROR for d in diags)


def sort_diagnostics(diags: Iterable[Diagnostic]) -> List[Diagnostic]:
    """Most severe first, then by code, then by anchoring var."""
    return sorted(diags, key=lambda d: (-int(d.severity), d.code, d.var))


def format_table(diags: Sequence[Diagnostic]) -> str:
    """Render diagnostics as an aligned text table (the CLI's output)."""
    if not diags:
        return "no diagnostics: plan is clean"
    rows = [("CODE", "SEVERITY", "VAR", "MESSAGE")]
    for d in sort_diagnostics(diags):
        msg = d.message + (" | fix: %s" % d.fixit if d.fixit else "")
        rows.append((d.code, str(d.severity), d.var or "-", msg))
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    lines = []
    for r in rows:
        lines.append("  ".join([r[0].ljust(widths[0]), r[1].ljust(widths[1]),
                                r[2].ljust(widths[2]), r[3]]).rstrip())
    n_err = sum(1 for d in diags if d.severity == Severity.ERROR)
    n_warn = sum(1 for d in diags if d.severity == Severity.WARNING)
    n_info = len(diags) - n_err - n_warn
    lines.append("%d error(s), %d warning(s), %d info" % (n_err, n_warn, n_info))
    return "\n".join(lines)


# ---------------------------------------------------------------- catalog

# Stable code -> short title. The single registry docs/linting.md and the
# tests enumerate; adding a rule means adding its code here.
CODES = {
    # ADT1xx — plan shape
    "ADT101": "trainable variable has no strategy node",
    "ADT102": "strategy node references unknown variable",
    "ADT103": "duplicate strategy node for one variable",
    "ADT104": "strategy has no replica devices",
    "ADT105": "replica device not in the resource spec",
    "ADT106": "mesh shape does not multiply out to the replica count",
    "ADT107": "mesh axis name unknown to the framework",
    "ADT108": "trainable node carries no synchronizer",
    "ADT109": "part_configs count disagrees with the partitioner",
    "ADT110": "batch/sequence axis missing from the mesh",
    # ADT2xx — partitioning / divisibility
    "ADT201": "malformed partitioner string",
    "ADT202": "partitioner rank disagrees with the variable rank",
    "ADT203": "split dimension smaller than the device count",
    "ADT204": "multi-axis partitioner unsupported",
    "ADT205": "mp_axes names a mesh axis absent from the mesh",
    "ADT206": "mp_axes dimension not exactly divisible by its mesh axis",
    "ADT207": "duplicate-axis sharding conflict",
    "ADT208": "shard_sizes inconsistent with the split dimension",
    "ADT209": "split dimension pads to a multiple of the mesh axis",
    # ADT3xx — synchronizer / compressor
    "ADT301": "unknown synchronizer kind",
    "ADT302": "PS reduction_destination is empty",
    "ADT303": "PS reduction_destination not in the resource spec",
    "ADT304": "invalid staleness configuration",
    "ADT305": "unknown or malformed compressor",
    "ADT306": "compressor is ignored on this synchronization path",
    "ADT307": "async PS plan is not all-or-nothing",
    "ADT308": "PowerSGD on a sub-matrix tensor passes through",
    "ADT309": "sparse variable on a dense-only synchronization path",
    "ADT310": "wire_dtype quantization on an incompatible variable or path",
    "ADT311": "quantized variable smaller than one scale block",
    # ADT4xx — runtime hazards
    "ADT401": "pipeline bubble dominates the schedule",
    "ADT402": "invalid pipeline schedule configuration",
    "ADT403": "parameter-server load imbalance",
    "ADT404": "staleness window is a no-op in this topology",
    "ADT405": "lowered program all-gathers a model-parallel parameter",
    "ADT406": "lowered program transfers to host on the hot path",
    "ADT407": "collective under divergent control flow",
    "ADT408": "host transfer inside a while/scan body (per-iteration cost)",
    "ADT420": "sentinel requested but the program lowered without health "
              "guards",
    "ADT421": "PS apply window larger than the sentinel skip window",
    "ADT430": "in-run elastic shrink requested on a topology that cannot "
              "shrink",
    "ADT431": "in-run elastic shrink loses a PS owner (checkpoint "
              "fallback required)",
    "ADT432": "preemption handoff armed on a fail-fast (model-parallel) "
              "topology",
    "ADT440": "autoscale bounds unsound for this strategy (shrink below "
              "the safe replica floor)",
    "ADT441": "autoscale thresholds cannot work as configured",
    # ADT5xx — memory footprint & collective schedule (analysis/hlo.py,
    # analysis/memory.py)
    "ADT501": "projected per-device OOM: peak HBM exceeds the budget",
    "ADT502": "peak HBM within 10% of the budget",
    "ADT503": "un-donated superstep carry doubles state residency",
    "ADT510": "same-mesh programs issue incompatible collective orders",
    "ADT511": "cross-program replica-group mismatch on a collective",
    "ADT520": "flat collective spans the inter-host level where the "
              "hierarchical schedule crosses provably fewer bytes",
    "ADT521": "replica group straddles hosts non-contiguously",
    "ADT522": "synthesized schedule is not reduction-equivalent to the "
              "op it replaces",
    "ADT523": "per-level byte estimate exceeds the level's "
              "bandwidth-delay budget",
    "ADT524": "malformed topology spec",
    "ADT525": "topology cannot price this collective/plan",
    # ADT6xx — numerics safety (analysis/numerics.py, rules.verify_numerics):
    # the static gate that makes the bf16 compute tier shippable — low-
    # precision compute is allowed, low-precision ACCUMULATION and low-
    # precision MASTER STATE are not
    "ADT601": "half-precision accumulation in a reduction/psum",
    "ADT602": "optimizer state or master params stored in half precision",
    "ADT603": "loss/verdict computed in half precision",
    "ADT604": "bf16 compute armed without a sentinel policy",
    "ADT605": "cross-program dtype mismatch on order-compatible collectives",
}
