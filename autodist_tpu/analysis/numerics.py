"""ADT6xx numerics-safety analysis: a dtype-flow pass over the lowering.

The bf16 compute tier (``GraphConfig.compute_dtype = "bf16"``) is only
shippable if something *static* proves a plan is numerically sound before
a single compile — the same contract ADT501 provides for memory and
ADT310 for the quantized wire. The discipline being certified is the
f32-master rule of mixed-precision training (arXiv 2004.13336): **low-
precision compute is allowed, low-precision ACCUMULATION and low-
precision MASTER STATE are not.** Concretely:

- gradients may be *computed* in bf16, but the cross-replica sum
  (psum / reduce-scatter) must run on f32 values — summing P bf16
  gradients loses low-order bits at every hop (ADT601);
- the authoritative parameter copy and the optimizer state must live in
  f32; a parameter that round-trips ``f32 -> bf16 -> f32`` has silently
  absorbed bf16 rounding into its master (ADT602);
- the loss / sentinel verdict must be f32 — the divergence sentinel's
  EWMA judges these values, and judging rounded values moves the
  skip/rollback thresholds (ADT603);
- two programs on one mesh must agree on collective *dtypes*, not just
  kinds and groups — an f32 sender rendezvousing with a bf16 receiver is
  the ADT511 deadlock with a sharper diagnosis (ADT605).

Two layers, matching the memory analyzer's split:

- :func:`lint_text` — the dtype-flow pass over one lowered program's
  text (ADT601/602/603). Works on any ``as_text()`` dump; no re-lowering.
- :func:`compare_schedule_dtypes` — the cross-program check (ADT605),
  the dtype analog of ``hlo.compare_schedules``.

Plan-level rules (ADT601/602/604 before any trace) live in
``analysis/rules.py`` (``verify_numerics``); both layers report through
the same :class:`Diagnostic` shape and stable codes.
"""
from typing import Dict, List, Mapping, Optional

from autodist_tpu.analysis.diagnostics import (Diagnostic, error,
                                               sort_diagnostics, warning)
from autodist_tpu.analysis.hlo import (HALF_DTYPES, CollectiveSchedule,
                                       HloProgram, _as_schedule,
                                       parse_hlo_text)

# Ops that carry a value through unchanged (element-for-element) — the
# only edges the f32-master taint may propagate across. Anything doing
# arithmetic (dot, add, reduce) legitimately *derives* a new value, so a
# later cast back to f32 is not a master round-trip.
VALUE_PRESERVING_OPS = frozenset({
    "convert", "reshape", "transpose", "copy", "optimization_barrier",
})

# Collective classes that ACCUMULATE (sum across replicas) — the ones
# whose element dtype is an accumulator dtype. Gathers/permutes only move
# bits, so a half-precision payload there is lossless.
_ACCUMULATING_KINDS = frozenset({"reduce", "scatter"})


def _half_width(dtype: str) -> int:
    return 2 if dtype in HALF_DTYPES else 4


def lint_text(text_or_program, label: str = "") -> List[Diagnostic]:
    """Dtype-flow lint of one lowered program (ADT601/602/603).

    Accepts program text or a pre-parsed :class:`HloProgram`. Forgiving
    like the parser: a dump without dtype annotations produces no
    findings rather than an exception.
    """
    program = (text_or_program if isinstance(text_or_program, HloProgram)
               else parse_hlo_text(text_or_program))
    where = " in %s" % label if label else ""
    out: List[Diagnostic] = []

    # ---- ADT601 / ADT603: accumulating collectives in half precision
    for coll in program.collectives():
        if coll.kind not in _ACCUMULATING_KINDS:
            continue
        if coll.elem_dtype not in HALF_DTYPES:
            continue
        elems = coll.payload_elems
        if elems == 0 and coll.payload_bytes:
            elems = coll.payload_bytes // _half_width(coll.elem_dtype)
        if elems > 1:
            out.append(error(
                "ADT601",
                "%s accumulation in %s%s: %s at line %d sums %d %s "
                "elements across replicas — every hop of the reduction "
                "rounds, so the gradient sum loses low-order bits that "
                "f32 accumulation would keep" % (
                    coll.elem_dtype, coll.op, where, coll.describe(),
                    coll.lineno, elems, coll.elem_dtype),
                fixit="cast the operand to f32 before the collective "
                      "(bf16 compute, f32 accumulation) — the built-in "
                      "bf16 lowering does this"))
        else:
            # a SCALAR half-precision cross-replica sum is almost
            # certainly the loss / grad-norm mean — rounded before the
            # sentinel ever sees it
            out.append(warning(
                "ADT603",
                "scalar %s %s%s at line %d: a cross-replica scalar sum "
                "in half precision is a loss/verdict computed on rounded "
                "values — the sentinel's EWMA judges what it is given" % (
                    coll.elem_dtype, coll.op, where, coll.lineno),
                fixit="cast the loss to f32 before the pmean"))

    # ---- ADT602: f32 master destroyed by a value-preserving round-trip
    out.extend(_master_roundtrips(program, where))

    # ---- ADT603: entry returns a half-precision scalar (rounded loss)
    entry = program.entry
    if entry is not None:
        for res in entry.results:
            if res.dtype in HALF_DTYPES and res.type_bytes <= 2:
                out.append(warning(
                    "ADT603",
                    "entry result #%d%s is a %s scalar — a loss/metric "
                    "returned in half precision feeds rounded values to "
                    "everything that judges it (sentinel EWMA, early "
                    "stopping, logging)" % (res.index, where, res.dtype),
                    fixit="compute and return the loss in f32"))
    return sort_diagnostics(out)


def _master_roundtrips(program: HloProgram, where: str) -> List[Diagnostic]:
    """Find f32 entry values that flow ``f32 -> half -> f32`` through
    value-preserving ops only: the produced f32 value *is* the rounded
    half value, so any consumer (a returned "updated" param above all)
    has lost the master copy."""
    entry = program.entry
    if entry is None:
        return []
    # taint: value id -> ("master", origin) | ("half", origin)
    taint: Dict[str, tuple] = {}
    for a in entry.args:
        if a.dtype == "f32":
            taint["arg%d" % a.index] = ("master", a.index)
    if not taint:
        return []
    used: set = set()
    for st in entry.statements:
        used.update(st.operand_ids)
    out: List[Diagnostic] = []
    flagged: set = set()
    for st in entry.statements:
        if st.op not in VALUE_PRESERVING_OPS or not st.result_id:
            continue
        src = next((taint[o] for o in st.operand_ids if o in taint), None)
        if src is None:
            continue
        state, origin = src
        dt = st.out_dtype
        if st.op == "convert":
            if state == "master" and dt in HALF_DTYPES:
                taint[st.result_id] = ("half", origin)
            elif state == "half" and dt == "f32":
                if (st.result_id in used
                        or st.result_id in entry.returned_ids):
                    if origin not in flagged:
                        flagged.add(origin)
                        out.append(error(
                            "ADT602",
                            "f32 master destroyed%s: %%arg%d round-trips "
                            "f32 -> half -> f32 through value-preserving "
                            "ops (cast back at line %d) — the 'f32' "
                            "result carries bf16 rounding, so no "
                            "authoritative copy survives the step" % (
                                where, origin, st.lineno),
                            fixit="keep the f32 master out of the half "
                                  "cast chain: update params from f32 "
                                  "grads and only cast a COPY down for "
                                  "compute"))
            elif dt == "f32" or dt in HALF_DTYPES:
                # convert within the same precision class keeps the state
                taint[st.result_id] = (state, origin)
        else:
            taint[st.result_id] = (state, origin)
    return out


def compare_schedule_dtypes(ref, other, ref_label: str = "train",
                            other_label: str = "eval") -> List[Diagnostic]:
    """Cross-program collective DTYPE consistency (ADT605).

    The dtype analog of ``hlo.compare_schedules``: two programs whose
    collectives are order-compatible (same kind, groups and element
    count at matching positions) but disagree on the element dtype will
    pass the shape-level checks right up until one side feeds bf16 words
    into an f32 rendezvous. Accepts schedules, programs, or raw text.
    """
    ref_sched: CollectiveSchedule = _as_schedule(ref).per_step()
    other_sched: CollectiveSchedule = _as_schedule(other).per_step()
    out: List[Diagnostic] = []
    it = iter(ref_sched)
    for oc in other_sched:
        if not (oc.elem_dtype and oc.payload_elems):
            continue
        for rc in it:
            if (rc.kind == oc.kind
                    and rc.replica_groups == oc.replica_groups
                    and rc.payload_elems == oc.payload_elems
                    and rc.elem_dtype and rc.payload_elems):
                if rc.elem_dtype != oc.elem_dtype:
                    out.append(error(
                        "ADT605",
                        "%s and %s programs disagree on the element dtype "
                        "of an order-compatible %s collective: %s sends "
                        "%s, %s sends %s (%d elements, lines %d/%d) — the "
                        "rendezvous exchanges mistyped words" % (
                            ref_label, other_label, oc.kind, ref_label,
                            rc.elem_dtype, other_label, oc.elem_dtype,
                            oc.payload_elems, rc.lineno, oc.lineno),
                        fixit="build both programs from one compiled "
                              "strategy with one compute_dtype"))
                break
    return sort_diagnostics(out)


def lint_programs(programs: Mapping[str, str],
                  parsed: Optional[Dict[str, HloProgram]] = None
                  ) -> List[Diagnostic]:
    """Numerics lint over a set of same-mesh programs: the per-program
    dtype-flow pass on each, plus pairwise dtype alignment (ADT605)
    against the first program (the reference, mirroring the CLI's
    cross-program schedule mode)."""
    out: List[Diagnostic] = []
    names = list(programs)
    progs = {}
    for name in names:
        prog = (parsed or {}).get(name)
        if prog is None:
            prog = parse_hlo_text(programs[name])
        progs[name] = prog
        out.extend(lint_text(prog, label=name))
    for name in names[1:]:
        out.extend(compare_schedule_dtypes(
            progs[names[0]], progs[name],
            ref_label=names[0], other_label=name))
    return sort_diagnostics(out)
