"""Structured parser over lowered StableHLO/MHLO program text.

``lowered.py`` answers yes/no questions with token scans; this module
builds an actual model of the program — functions, SSA statements with
result sizes, brace-tracked regions, the call graph — so two deeper
analyses become possible:

- the **collective schedule**: the ordered cross-replica collectives a
  program issues (kind, replica groups, payload bytes, loop depth), the
  artifact TACCL (arXiv:2111.04867) treats as first-class. Two programs
  that run on the same mesh (train on some processes, eval on others; a
  fused superstep vs the per-step loop it replaces) must issue
  *compatible* schedules or they deadlock at the first mismatched
  collective — :func:`compare_schedules` turns that runtime hang into
  ``ADT510``/``ADT511`` lint findings.
- the **memory analysis** (``analysis/memory.py``): entry buffer sizes,
  donation aliases, and a statement-level liveness sweep need def/use
  chains and per-value byte sizes, which the parse provides.

Text-based on purpose, like ``lowered.py``: it works on any ``as_text()``
dump (including ones saved from a real TPU run and shipped to a dev box)
without re-lowering, and has no opinion about which JAX version produced
the text. The parser is deliberately forgiving — unknown constructs parse
as opaque statements rather than failing the analysis.
"""
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

from autodist_tpu.analysis.diagnostics import (Diagnostic, error,
                                               sort_diagnostics, warning)

# ------------------------------------------------------------------ types

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i4": 1, "ui4": 1, "i1": 1,
    "c64": 8, "c128": 16,
}

_TENSOR_TYPE_RE = re.compile(r"tensor<([^<>]*)>")


def tensor_type_bytes(spec: str) -> int:
    """Bytes of one ``tensor<...>`` type spec, e.g. ``8x4xf32`` -> 128;
    a bare dtype (``i32``) is a scalar. Unknown dtypes count 4 bytes."""
    parts = spec.split("x")
    dtype = parts[-1]
    n = 1
    for p in parts[:-1]:
        try:
            n *= int(p)
        except ValueError:
            pass  # dynamic dim "?" — count it as 1 rather than failing
    return n * _DTYPE_BYTES.get(dtype, 4)


def tensor_type_dtype(spec: str) -> str:
    """Element dtype of one ``tensor<...>`` type spec, e.g. ``8x4xbf16``
    -> ``bf16``; a bare dtype (``i32``) is its own element type."""
    return spec.split("x")[-1].strip()


def tensor_type_elems(spec: str) -> int:
    """Element count of one ``tensor<...>`` type spec (1 for scalars)."""
    n = 1
    for p in spec.split("x")[:-1]:
        try:
            n *= int(p)
        except ValueError:
            pass  # dynamic dim "?"
    return n


HALF_DTYPES = frozenset({"bf16", "f16"})


def _types_bytes(segment: str) -> List[int]:
    return [tensor_type_bytes(m.group(1))
            for m in _TENSOR_TYPE_RE.finditer(segment)]


def _types_dtypes(segment: str) -> List[str]:
    return [tensor_type_dtype(m.group(1))
            for m in _TENSOR_TYPE_RE.finditer(segment)]


def _types_elems(segment: str) -> List[int]:
    return [tensor_type_elems(m.group(1))
            for m in _TENSOR_TYPE_RE.finditer(segment)]


_SHARDING_DEVICES_RE = re.compile(r"devices=\[([0-9,]+)\]")


def sharding_divisor(sharding: str) -> int:
    """How many distinct shards an ``mhlo.sharding`` attribute splits a
    value into — the global-to-per-device byte divisor. ``{replicated}``
    and ``{manual}`` divide by 1."""
    m = _SHARDING_DEVICES_RE.search(sharding or "")
    if not m:
        return 1
    tiles = [int(x) for x in m.group(1).split(",") if x]
    div = 1
    for t in tiles:
        div *= max(t, 1)
    if "last_tile_dim_replicate" in sharding and tiles:
        div //= max(tiles[-1], 1)
    return max(div, 1)


# ------------------------------------------------------------- dataclasses


@dataclasses.dataclass(frozen=True)
class HloArg:
    """One entry-function argument."""

    index: int
    type_bytes: int
    sharding: str = ""
    dtype: str = ""  # element dtype, e.g. "f32"/"bf16" ("" when unparsed)
    # index of the output this arg's buffer is donated to (tf.aliasing_output),
    # or None when the caller keeps ownership
    aliased_output: Optional[int] = None
    # jax >= 0.4.x sharded lowerings mark donation with
    # ``jax.buffer_donor = true`` instead and resolve the alias at compile
    buffer_donor: bool = False

    @property
    def donated(self) -> bool:
        return self.aliased_output is not None or self.buffer_donor

    @property
    def per_device_bytes(self) -> float:
        return self.type_bytes / sharding_divisor(self.sharding)


@dataclasses.dataclass(frozen=True)
class HloResult:
    index: int
    type_bytes: int
    sharding: str = ""
    result_info: str = ""  # jax.result_info label, e.g. "[0].params['w']"
    dtype: str = ""        # element dtype ("" when unparsed)

    @property
    def per_device_bytes(self) -> float:
        return self.type_bytes / sharding_divisor(self.sharding)


@dataclasses.dataclass
class HloStatement:
    """One SSA statement of a function body."""

    result_id: str                 # "" for return/terminators
    op: str                        # mnemonic, e.g. "dot_general", "call"
    operand_ids: List[str]
    out_bytes: List[int]
    lineno: int
    loop_depth: int                # while/scan regions enclosing it
    call_target: str = ""          # @target of call/func.call/custom_call
    # element dtype of each result (parallel to out_bytes) — the SSA
    # seed values the numerics dtype-flow pass propagates
    out_dtypes: List[str] = dataclasses.field(default_factory=list)

    @property
    def total_out_bytes(self) -> int:
        return sum(self.out_bytes)

    @property
    def out_dtype(self) -> str:
        """First result's element dtype ("" for terminators)."""
        return self.out_dtypes[0] if self.out_dtypes else ""


# StableHLO / MHLO / jaxpr spellings -> the cost model's collective classes
# (the same classes _COLLECTIVE_KINDS in kernel/common/utils.py prices)
COLLECTIVE_CLASS = {
    "all_reduce": "reduce", "all-reduce": "reduce", "psum": "reduce",
    "reduce_scatter": "scatter", "reduce-scatter": "scatter",
    "psum_scatter": "scatter",
    "all_gather": "gather", "all-gather": "gather", "pgather": "gather",
    "collective_permute": "permute", "collective-permute": "permute",
    "ppermute": "permute",
    "all_to_all": "alltoall", "all-to-all": "alltoall",
}


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One cross-replica collective in program order."""

    kind: str                                    # cost class, e.g. "reduce"
    op: str                                      # spelled op, "all_reduce"
    payload_bytes: int                           # operand bytes (per device)
    result_bytes: int
    replica_groups: Tuple[Tuple[int, ...], ...]  # () when unannotated
    channel: int
    lineno: int
    loop_depth: int                              # >0: inside a while/scan
    elem_dtype: str = ""                         # payload element dtype
    payload_elems: int = 0                       # payload element count

    @property
    def group_size(self) -> int:
        return len(self.replica_groups[0]) if self.replica_groups else 1

    def signature(self) -> tuple:
        """Identity for cross-program matching: what must agree for two
        programs to rendezvous on this collective."""
        return (self.kind, self.replica_groups, self.payload_bytes)

    def describe(self) -> str:
        return "%s(%dB, groups=%s)" % (
            self.op, self.payload_bytes,
            [list(g) for g in self.replica_groups] or "?")


@dataclasses.dataclass
class HloFunction:
    name: str
    args: List[HloArg]
    results: List[HloResult]
    statements: List[HloStatement]
    lineno: int = 0

    @property
    def returned_ids(self) -> set:
        out = set()
        for st in self.statements:
            if st.op in ("return", "func.return"):
                out.update(st.operand_ids)
        return out


@dataclasses.dataclass
class HloProgram:
    funcs: Dict[str, HloFunction]
    entry: Optional[HloFunction]
    num_partitions: int = 1
    num_replicas: int = 1
    module_name: str = ""

    def collectives(self) -> List["CollectiveOp"]:
        return collective_schedule(self)


# ------------------------------------------------------------------ parser

_FUNC_NAME_RE = re.compile(r"func\.func\s+(?:public\s+|private\s+)?@([\w$.-]+)")
# attr dicts can nest one brace level: {mhlo.sharding = "{replicated}"}
_ATTRS = r"(?:\s*\{((?:[^{}]|\{[^{}]*\})*)\})?"
_ARG_RE = re.compile(r"%arg(\d+):\s*tensor<([^<>]*)>" + _ATTRS)
_RESULT_RE = re.compile(r"tensor<([^<>]*)>" + _ATTRS)
_STMT_RE = re.compile(r'^\s*%([\w.$-]+)(?::(\d+))?\s*=\s*"?([\w.$-]+)"?')
_OPERAND_RE = re.compile(r"%([\w.$-]+)(?:#\d+)?")
_CALL_TARGET_RE = re.compile(r"@([\w$.-]+)")
_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")
_DONOR_RE = re.compile(r"jax\.buffer_donor\s*=\s*true")
_SHARDING_ATTR_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_RESULT_INFO_RE = re.compile(r'jax\.result_info\s*=\s*"([^"]*)"')
_REPLICA_GROUPS_RE = re.compile(r"replica_groups\s*=\s*dense<(.*?)>")
_CHANNEL_RE = re.compile(r"handle\s*=\s*(\d+)")
_NUM_PARTITIONS_RE = re.compile(r"mhlo\.num_partitions\s*=\s*(\d+)")
_NUM_REPLICAS_RE = re.compile(r"mhlo\.num_replicas\s*=\s*(\d+)")
_MODULE_RE = re.compile(r"module\s+@([\w$.-]+)")

# lines that OPEN a while/scan-style loop region; ``stablehlo.while``'s
# two regions print as `` cond {`` / ``} do {`` on later lines
_LOOP_OPENERS = ("stablehlo.while", "mhlo.while")
_LOOP_REGION_RE = re.compile(r"(?:^\s*|\}\s*)(?:cond|do)\s*\{")

def _parse_replica_groups(line: str) -> Tuple[Tuple[int, ...], ...]:
    m = _REPLICA_GROUPS_RE.search(line)
    if not m:
        return ()
    body = m.group(1)
    groups = []
    for grp in re.findall(r"\[([0-9,\s]*)\]", body):
        ids = tuple(int(x) for x in grp.replace(" ", "").split(",") if x)
        if ids:
            groups.append(ids)
    if not groups:
        # dense<0> style scalar init (single group of everything)
        flat = tuple(int(x) for x in re.findall(r"-?\d+", body))
        if flat:
            groups.append(flat)
    return tuple(groups)


def _split_signature(sig_line: str) -> Tuple[str, str]:
    """Split a ``func.func`` line into the args segment and the results
    segment (after ``->``)."""
    if ") -> " in sig_line:
        args_part, results_part = sig_line.split(") -> ", 1)
        return args_part, results_part
    return sig_line, ""


def _statement_out_bytes(line: str) -> List[int]:
    """Result byte sizes of one single-line statement: the types after the
    last ``->`` when present, else the trailing ``: T1, T2`` annotation."""
    if "->" in line:
        return _types_bytes(line.rsplit("->", 1)[1])
    if " : " in line:
        return _types_bytes(line.rsplit(" : ", 1)[1])
    return []


def _statement_out_dtypes(line: str) -> List[str]:
    """Result element dtypes, parallel to :func:`_statement_out_bytes`."""
    if "->" in line:
        return _types_dtypes(line.rsplit("->", 1)[1])
    if " : " in line:
        return _types_dtypes(line.rsplit(" : ", 1)[1])
    return []


def parse_hlo_text(text: str) -> HloProgram:
    """Parse a lowered-program dump into functions, statements and
    regions. Forgiving by design: lines that match no construct are
    skipped, so partial dumps and future dialect changes degrade to a
    smaller model rather than an exception."""
    funcs: Dict[str, HloFunction] = {}
    entry_name: Optional[str] = None
    entry_public = False
    num_partitions = num_replicas = 1
    module_name = ""

    cur: Optional[HloFunction] = None
    cur_depth = 0            # brace depth inside the current function
    loop_starts: List[int] = []
    pending_loops = 0        # openers whose '{' lands on a later line
    # a multi-line statement being stitched (collective with a region
    # whose `(A) -> R` type signature arrives on the closing line)
    pending_stmt: Optional[dict] = None
    pending_region_depth = 0

    for lineno, line in enumerate(text.splitlines(), 1):
        if cur is None:
            m = _MODULE_RE.search(line)
            if m and not module_name:
                module_name = m.group(1)
            m = _NUM_PARTITIONS_RE.search(line)
            if m:
                num_partitions = int(m.group(1))
            m = _NUM_REPLICAS_RE.search(line)
            if m:
                num_replicas = int(m.group(1))
        fm = _FUNC_NAME_RE.search(line) if "func.func" in line else None
        if fm:
            name = fm.group(1)
            args_seg, results_seg = _split_signature(line)
            args = []
            for am in _ARG_RE.finditer(args_seg):
                attrs = am.group(3) or ""
                alias = _ALIAS_RE.search(attrs)
                shard = _SHARDING_ATTR_RE.search(attrs)
                args.append(HloArg(
                    index=int(am.group(1)),
                    type_bytes=tensor_type_bytes(am.group(2)),
                    sharding=shard.group(1) if shard else "",
                    dtype=tensor_type_dtype(am.group(2)),
                    aliased_output=(int(alias.group(1)) if alias
                                    else None),
                    buffer_donor=bool(_DONOR_RE.search(attrs))))
            results = []
            for i, rm in enumerate(_RESULT_RE.finditer(results_seg)):
                attrs = rm.group(2) or ""
                shard = _SHARDING_ATTR_RE.search(attrs)
                info_m = _RESULT_INFO_RE.search(attrs)
                results.append(HloResult(
                    index=i,
                    type_bytes=tensor_type_bytes(rm.group(1)),
                    sharding=shard.group(1) if shard else "",
                    result_info=info_m.group(1) if info_m else "",
                    dtype=tensor_type_dtype(rm.group(1))))
            cur = HloFunction(name=name, args=args, results=results,
                              statements=[], lineno=lineno)
            funcs[name] = cur
            is_public = "public" in line.split("@")[0]
            if entry_name is None or (is_public and not entry_public):
                entry_name, entry_public = name, is_public
            cur_depth = 1  # the signature line's body-opening brace
            loop_starts = []
            pending_loops = 0
            pending_stmt = None
            continue
        if cur is None:
            continue

        opens, closes = line.count("{"), line.count("}")
        first_open = line.find("{")
        closes_before = line.count(
            "}", 0, first_open if first_open >= 0 else len(line))
        closes_after = closes - closes_before

        # -------- multi-line statement stitching (collective regions)
        if pending_stmt is not None:
            cur_depth += opens - closes
            if cur_depth <= pending_region_depth:
                # region closed: the `}) : (A) -> R` line carries the types
                pending_stmt["out_bytes"] = _statement_out_bytes(line)
                pending_stmt["out_dtypes"] = _statement_out_dtypes(line)
                operand_seg = (line.rsplit(":", 1)[1].split("->")[0]
                               if ":" in line else "")
                pending_stmt["payload_bytes"] = _types_bytes(operand_seg)
                pending_stmt["payload_dtypes"] = _types_dtypes(operand_seg)
                pending_stmt["payload_elems"] = _types_elems(operand_seg)
                _finish_statement(cur, pending_stmt)
                pending_stmt = None
            continue

        is_loop_open = any(tok in line for tok in _LOOP_OPENERS)
        loop_region = bool(_LOOP_REGION_RE.search(line))

        # closes textually BEFORE the first open (`} do {`, a bare `}`)
        cur_depth -= closes_before
        while loop_starts and cur_depth <= loop_starts[-1]:
            loop_starts.pop()
        if cur_depth <= 0:
            cur = None
            continue

        sm = _STMT_RE.match(line)
        terminator = re.match(r"^\s*(?:stablehlo\.|func\.)?return\b",
                              line.lstrip("} "))
        if sm or terminator:
            if sm:
                result_id, op = sm.group(1), sm.group(3)
                op = op.split(".")[-1]  # stablehlo.add -> add
                rhs = line[sm.end():]
            else:
                result_id, op = "", "return"
                rhs = line
            operands = [m.group(1) for m in _OPERAND_RE.finditer(rhs)]
            target_m = _CALL_TARGET_RE.search(rhs)
            stmt = HloStatement(
                result_id=result_id, op=op,
                operand_ids=operands,
                out_bytes=_statement_out_bytes(line),
                lineno=lineno,
                loop_depth=len(loop_starts) + pending_loops,
                call_target=target_m.group(1) if target_m else "",
                out_dtypes=_statement_out_dtypes(line))
            cls = COLLECTIVE_CLASS.get(op)
            if cls is not None and opens > closes:
                # region-carrying collective: its `(A) -> R` signature is
                # on the region-closing line — stitch it there
                pending_stmt = {
                    "stmt": stmt, "class": cls,
                    "groups": _parse_replica_groups(line),
                    "channel": _channel_of(line)}
                pending_region_depth = cur_depth
                cur_depth += opens - closes_after
                continue
            if cls is not None:
                # region-free collective (collective_permute, all_to_all)
                operand_seg = (line.split("->")[0].rsplit(":", 1)[-1]
                               if ":" in line else "")
                _attach_collective(stmt, cls, _parse_replica_groups(line),
                                   _channel_of(line),
                                   _types_bytes(operand_seg),
                                   _types_dtypes(operand_seg),
                                   _types_elems(operand_seg))
            cur.statements.append(stmt)

        # -------- region bookkeeping (lowered.py's brace machinery,
        # extended: counted pending openers + `cond {`/`} do {` regions)
        remaining = opens
        if remaining > 0:
            while pending_loops > 0 and remaining > 0:
                loop_starts.append(cur_depth)
                pending_loops -= 1
                remaining -= 1
                cur_depth += 1
            if (is_loop_open or loop_region) and remaining > 0:
                loop_starts.append(cur_depth)
                remaining -= 1
                cur_depth += 1
            cur_depth += remaining
        elif is_loop_open:
            pending_loops += 1
        cur_depth -= closes_after
        while loop_starts and cur_depth <= loop_starts[-1]:
            loop_starts.pop()
        if cur_depth <= 0:
            cur = None

    entry = funcs.get(entry_name) if entry_name else None
    return HloProgram(funcs=funcs, entry=entry,
                      num_partitions=num_partitions,
                      num_replicas=num_replicas, module_name=module_name)


def _channel_of(line: str) -> int:
    m = _CHANNEL_RE.search(line)
    return int(m.group(1)) if m else 0


def _attach_collective(stmt: HloStatement, cls: str, groups, channel,
                       payload: List[int],
                       payload_dtypes: Optional[List[str]] = None,
                       payload_elems: Optional[List[int]] = None):
    dtypes = payload_dtypes or stmt.out_dtypes
    stmt.collective = CollectiveOp(  # type: ignore[attr-defined]
        kind=cls, op=stmt.op,
        payload_bytes=sum(payload) or stmt.total_out_bytes,
        result_bytes=stmt.total_out_bytes,
        replica_groups=groups, channel=channel,
        lineno=stmt.lineno, loop_depth=stmt.loop_depth,
        elem_dtype=dtypes[0] if dtypes else "",
        payload_elems=sum(payload_elems or []))


def _finish_statement(func: HloFunction, pending: dict):
    stmt: HloStatement = pending["stmt"]
    stmt.out_bytes = pending["out_bytes"]
    stmt.out_dtypes = pending.get("out_dtypes", [])
    _attach_collective(stmt, pending["class"], pending["groups"],
                       pending["channel"], pending["payload_bytes"],
                       pending.get("payload_dtypes"),
                       pending.get("payload_elems"))
    func.statements.append(stmt)


# ------------------------------------------------------------- schedules


class CollectiveSchedule(list):
    """Ordered :class:`CollectiveOp`\\ s of one program (a ``list`` with
    schedule-level helpers)."""

    @property
    def total_payload_bytes(self) -> int:
        return sum(c.payload_bytes for c in self)

    def class_payload_bytes(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for c in self:
            out[c.kind] = out.get(c.kind, 0) + c.payload_bytes
        return out

    def per_step(self) -> "CollectiveSchedule":
        """The per-iteration schedule: a fused ``multi_step(k)`` program
        runs its microstep inside a while/scan body, so EVERY collective
        sits at loop depth >= 1 and the loop body IS the per-step
        schedule. A program with any top-level collective is already
        per-step — a model-internal while/scan (ring attention, a scanned
        layer stack) must NOT strip the gradient collectives around it —
        so the unwrap applies only when all collectives are in-loop."""
        if not self or any(c.loop_depth == 0 for c in self):
            return self
        return CollectiveSchedule(
            dataclasses.replace(c, loop_depth=c.loop_depth - 1)
            for c in self)

    def signature(self) -> tuple:
        return tuple(c.signature() for c in self)


def collective_schedule(text_or_program) -> CollectiveSchedule:
    """Extract the ordered collective schedule of a lowered program,
    walking the call graph from the entry function (call-site loop depth
    propagates into callees — a collective in a function called from a
    scan body is an in-loop collective)."""
    program = (text_or_program if isinstance(text_or_program, HloProgram)
               else parse_hlo_text(text_or_program))
    out = CollectiveSchedule()
    if program.entry is None:
        return out
    seen: List[str] = []

    def walk(func: HloFunction, depth_offset: int):
        if func.name in seen:
            return  # defensive: no recursion in HLO, but never loop
        seen.append(func.name)
        for st in func.statements:
            coll = getattr(st, "collective", None)
            if coll is not None:
                out.append(dataclasses.replace(
                    coll, loop_depth=coll.loop_depth + depth_offset))
            elif st.call_target and st.call_target in program.funcs:
                walk(program.funcs[st.call_target],
                     depth_offset + st.loop_depth)
        seen.pop()

    walk(program.entry, 0)
    return out


def _embeds(needle: Sequence[tuple], haystack: Sequence[tuple]) -> bool:
    """True when ``needle`` is an ordered subsequence of ``haystack``."""
    it = iter(haystack)
    return all(any(h == n for h in it) for n in needle)


def compare_schedules(ref, other, ref_label: str = "train",
                      other_label: str = "eval") -> List[Diagnostic]:
    """Cross-program collective-schedule consistency (ADT510/ADT511).

    Two programs that can run concurrently on the same mesh must agree on
    the order and grouping of the collectives they share: a replica
    executing program A blocks in its i-th collective while a replica
    executing program B blocks in a *different* one — the classic
    mismatched-schedule deadlock. ``other``'s per-step schedule must embed
    (as an ordered subsequence, matching kind + replica groups + payload)
    into ``ref``'s; a kind-sequence that embeds but with different replica
    groups is the softer ``ADT511``.

    Accepts schedules, programs, or raw text for both sides.
    """
    ref_sched = _as_schedule(ref).per_step()
    other_sched = _as_schedule(other).per_step()
    out: List[Diagnostic] = []
    if not other_sched or not ref_sched:
        return out

    full_ref = [c.signature() for c in ref_sched]
    full_other = [c.signature() for c in other_sched]
    if _embeds(full_other, full_ref):
        return out

    order_ref = [(c.kind, c.payload_bytes) for c in ref_sched]
    order_other = [(c.kind, c.payload_bytes) for c in other_sched]
    if _embeds(order_other, order_ref):
        # the ORDER of collectives is compatible; the matched ops must
        # disagree on replica groups. Greedy-align to name the first.
        it = iter(ref_sched)
        for oc in other_sched:
            for rc in it:
                if (rc.kind, rc.payload_bytes) == (oc.kind,
                                                   oc.payload_bytes):
                    if (rc.replica_groups != oc.replica_groups
                            and rc.replica_groups and oc.replica_groups):
                        out.append(warning(
                            "ADT511",
                            "%s and %s programs disagree on replica groups "
                            "for a %s collective: %s vs %s (lines %d/%d) — "
                            "on a shared mesh the rendezvous never "
                            "completes" % (
                                ref_label, other_label, oc.kind,
                                rc.describe(), oc.describe(),
                                rc.lineno, oc.lineno),
                            fixit="rebuild both programs from the same "
                                  "compiled strategy so device meshes and "
                                  "axis groupings agree"))
                    break
        if not out:
            out.append(warning(
                "ADT511",
                "%s program's collectives embed into %s's by kind and "
                "payload but differ in grouping/channel annotations"
                % (other_label, ref_label),
                fixit="rebuild both programs from the same compiled "
                      "strategy"))
        return sort_diagnostics(out)

    out.append(error(
        "ADT510",
        "%s and %s programs issue incompatible collective orders on the "
        "same mesh: %s's sequence [%s] does not embed into %s's [%s] — "
        "replicas running different programs will block in mismatched "
        "collectives and deadlock" % (
            ref_label, other_label, other_label,
            ", ".join("%s:%dB" % (c.kind, c.payload_bytes)
                      for c in other_sched), ref_label,
            ", ".join("%s:%dB" % (c.kind, c.payload_bytes)
                      for c in ref_sched)),
        fixit="derive every same-mesh program (train/eval/fused) from one "
              "compiled strategy and do not reorder collectives by hand"))
    return sort_diagnostics(out)


def _as_schedule(x) -> CollectiveSchedule:
    if isinstance(x, CollectiveSchedule):
        return x
    if isinstance(x, (HloProgram, str)):
        return collective_schedule(x)
    return CollectiveSchedule(x)
