"""Partitioner-string parsing — the single implementation.

A ``VarConfig.partitioner`` is a comma-joined per-axis shard-count string
like ``"4,1"`` (reference ``kernel/partitioner.py:38-150``
PartitionerConfig). Both the compile path (``strategy/base.py``
``VarConfig.partition_axis``/``num_shards``) and the linter
(``analysis/rules.py`` ADT2xx) parse through here, so a malformed string
produces the same ``ADT201`` diagnostic everywhere instead of a raw
``int()`` traceback.

This module is a dependency-free leaf (it imports only the diagnostics
types) so ``strategy/base.py`` can import it without cycles.
"""
from typing import List, Optional

from autodist_tpu.analysis.diagnostics import DiagnosticError, error


def parse_partitioner(partitioner: str, var_name: str = "") -> List[int]:
    """Parse ``"4,1"`` into ``[4, 1]``.

    Raises :class:`DiagnosticError` (code ``ADT201``, a ``ValueError``)
    on malformed input: empty/dangling segments (``"4,"``), non-integer
    counts (``"a,1"``), or counts < 1 (``"0,1"``).
    """
    fixit = ('use a comma-joined list of per-axis shard counts >= 1, '
             'e.g. "4,1" for 4 shards along axis 0')
    tokens = str(partitioner).split(",")
    counts = []
    for tok in tokens:
        tok = tok.strip()
        if not tok:
            raise DiagnosticError(error(
                "ADT201",
                "malformed partitioner %r: empty shard count segment"
                % (partitioner,), var=var_name, fixit=fixit))
        try:
            c = int(tok)
        except ValueError:
            raise DiagnosticError(error(
                "ADT201",
                "malformed partitioner %r: %r is not an integer"
                % (partitioner, tok), var=var_name, fixit=fixit))
        if c < 1:
            raise DiagnosticError(error(
                "ADT201",
                "malformed partitioner %r: shard count %d < 1"
                % (partitioner, c), var=var_name, fixit=fixit))
        counts.append(c)
    return counts


def partition_axis_of(counts: List[int]) -> Optional[int]:
    """First axis with more than one shard (None when unpartitioned)."""
    for ax, c in enumerate(counts):
        if c > 1:
            return ax
    return None


def num_shards_of(counts: List[int]) -> int:
    n = 1
    for c in counts:
        n *= c
    return n


def split_axes_of(counts: List[int]) -> List[int]:
    """Every axis with more than one shard (the lowering supports one)."""
    return [ax for ax, c in enumerate(counts) if c > 1]
