"""Pre-compile strategy verifier — static analysis with typed diagnostics.

Public surface:

- :func:`verify` — ``(Strategy, ModelItem, ResourceSpec) ->
  list[Diagnostic]``: the pure plan-level pass (``rules.py``);
- :func:`lint_lowered_text` / :func:`lint_runner` — the second pass over
  the lowered jaxpr/StableHLO program (``lowered.py``);
- :func:`parse_hlo_text` / :func:`collective_schedule` /
  :func:`compare_schedules` — the structured lowered-program parser and
  the cross-program collective-schedule checks, ADT510/511 (``hlo.py``);
- :func:`estimate_from_text` / :func:`plan_memory_report` /
  :func:`budget_diagnostics` — the static peak-HBM analyzers, ADT501-503
  (``memory.py``);
- :func:`verify_topology` / :func:`lint_schedule` /
  :func:`schedule_level_bytes` — the topology-aware communication
  analyzer, ADT520-525 (``topology.py``);
- :class:`Diagnostic` / :class:`Severity` / :class:`DiagnosticError` /
  :class:`StrategyVerificationError` — the typed diagnostics framework
  (``diagnostics.py``);
- ``python -m autodist_tpu.analysis`` — the plan linter CLI (``cli.py``).

Exports resolve lazily (PEP 562): ``strategy/base.py`` imports the leaf
``analysis.partition`` module for partitioner parsing, and an eager
``from .rules import verify`` here would close an import cycle back
through ``strategy.base``.
"""

__all__ = ["verify", "lint_lowered_text", "lint_runner", "Diagnostic",
           "Severity", "DiagnosticError", "StrategyVerificationError",
           "format_table", "sort_diagnostics", "has_errors", "CODES",
           "parse_hlo_text", "collective_schedule", "compare_schedules",
           "CollectiveSchedule", "estimate_from_text", "MemoryEstimate",
           "plan_memory_report", "budget_diagnostics",
           "donation_diagnostics", "verify_topology", "lint_schedule",
           "schedule_level_bytes", "plan_level_bytes", "resolve_schedule",
           "Topology", "TopologyConfigError"]

_DIAG_NAMES = {"Diagnostic", "Severity", "DiagnosticError",
               "StrategyVerificationError", "format_table",
               "sort_diagnostics", "has_errors", "CODES"}
_HLO_NAMES = {"parse_hlo_text", "collective_schedule", "compare_schedules",
              "CollectiveSchedule"}
_MEMORY_NAMES = {"estimate_from_text", "MemoryEstimate",
                 "plan_memory_report", "budget_diagnostics",
                 "donation_diagnostics"}
_TOPOLOGY_NAMES = {"verify_topology", "lint_schedule",
                   "schedule_level_bytes", "plan_level_bytes",
                   "resolve_schedule", "Topology", "TopologyConfigError"}


def __getattr__(name):
    if name == "verify":
        from autodist_tpu.analysis.rules import verify
        return verify
    if name in ("lint_lowered_text", "lint_runner"):
        from autodist_tpu.analysis import lowered
        return getattr(lowered, name)
    if name in _HLO_NAMES:
        from autodist_tpu.analysis import hlo
        return getattr(hlo, name)
    if name in _MEMORY_NAMES:
        from autodist_tpu.analysis import memory
        return getattr(memory, name)
    if name in _TOPOLOGY_NAMES:
        from autodist_tpu.analysis import topology
        return getattr(topology, name)
    if name in _DIAG_NAMES:
        from autodist_tpu.analysis import diagnostics
        return getattr(diagnostics, name)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
