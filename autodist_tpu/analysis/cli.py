"""Plan linter CLI: ``python -m autodist_tpu.analysis <example> --strategy <name>``.

Builds (or loads) a Strategy for one of the bundled examples, runs the
static verifier against the example's ModelItem and a resource spec, and
prints the diagnostic table. Exit codes: 0 = no errors (warnings/info
allowed), 1 = at least one ``ADT`` error, 2 = usage/build failure.

Used by CI to gate every example x strategy combination, and by hand to
answer "will this plan compile?" — and now "will it FIT?" — without
compiling:

    python -m autodist_tpu.analysis linear_regression --strategy PS
    python -m autodist_tpu.analysis lm1b --strategy Parallax --format json
    python -m autodist_tpu.analysis tp_lm --strategy TensorParallel
    python -m autodist_tpu.analysis lm1b --strategy-json plan.json

``--hbm-budget <GiB>`` adds the plan-level memory gate (ADT501 projected
OOM / ADT502 budget pressure) with NO trace of the lowered program —
``--fuse-steps k`` prices the fused engine's device-resident PS carry on
top:

    python -m autodist_tpu.analysis lm1b --strategy PS --hbm-budget 16
    python -m autodist_tpu.analysis lm1b --strategy PS --hbm-budget 16 --fuse-steps 8

``--numerics`` adds the plan-level numerics-safety gate (ADT601/602
errors plus the sentinel-aware ADT603/604 warnings) — and
``--compute-dtype bf16`` overrides the built plan's compute tier so the
bf16 shape of ANY builder can be linted without editing code:

    python -m autodist_tpu.analysis lm1b --strategy AllReduce --numerics --compute-dtype bf16

``--programs`` lints saved lowered-program dumps instead (per-program
memory/donation/communication findings and the ADT60x dtype-flow pass,
plus the cross-program collective-schedule checks ADT510/511 — and the
ADT605 collective-dtype check — against the FIRST file):

    python -m autodist_tpu.analysis --programs train.hlo eval.hlo fused.hlo --hbm-budget 16
"""
import argparse
import json
import sys
from typing import Callable, Dict, Optional, Tuple

# (loss_fn, params, example_batch, mp_rules-or-None) factories. Tiny
# configurations of the same models the example scripts train — the lint
# needs shapes and sparsity, not realistic capacity.
ExampleSetup = Tuple[Callable, object, object, Optional[list]]


def _ex_linear_regression() -> ExampleSetup:
    import jax.numpy as jnp

    params = {"W": jnp.zeros(()), "b": jnp.zeros(())}

    def loss_fn(p, batch):
        pred = batch["x"] * p["W"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": jnp.zeros((64,), jnp.float32),
             "y": jnp.zeros((64,), jnp.float32)}
    return loss_fn, params, batch, None


def _ex_sentiment_classifier() -> ExampleSetup:
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    params = {
        "embedding": jax.random.normal(key, (512, 16)) * 0.05,
        "dense": {"kernel": jax.random.normal(key, (16, 1)) * 0.1,
                  "bias": jnp.zeros((1,))},
    }

    def loss_fn(p, batch):
        emb = jnp.take(p["embedding"], batch["tokens"], axis=0)  # gather
        pooled = jnp.mean(emb, axis=1)
        logits = (pooled @ p["dense"]["kernel"] + p["dense"]["bias"])[..., 0]
        labels = batch["label"].astype(jnp.float32)
        return jnp.mean(jnp.maximum(logits, 0) - logits * labels
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    batch = {"tokens": jnp.zeros((8, 16), jnp.int32),
             "label": jnp.zeros((8,), jnp.int32)}
    return loss_fn, params, batch, None


def _ex_image_classifier() -> ExampleSetup:
    from autodist_tpu.models import resnet
    loss_fn, params, batch, _ = resnet.make_train_setup(
        resnet.ResNetTiny, num_classes=10, image_size=32, batch_size=8)
    return loss_fn, params, batch, None


def _ex_lm1b() -> ExampleSetup:
    from autodist_tpu.models import lm
    cfg = lm.LMConfig(vocab_size=256, d_model=32, num_layers=2,
                      num_heads=4, mlp_dim=64, max_seq_len=32)
    loss_fn, params, batch, _ = lm.make_train_setup(cfg, seq_len=16,
                                                    batch_size=4)
    return loss_fn, params, batch, None


def _ex_tp_lm() -> ExampleSetup:
    from autodist_tpu.models import tp_lm
    cfg = tp_lm.TPLMConfig(vocab_size=256, d_model=32, num_layers=2,
                           num_heads=4, mlp_dim=64, max_seq_len=32)
    loss_fn, params, batch, _ = tp_lm.make_train_setup(cfg, seq_len=16,
                                                       batch_size=4)
    return loss_fn, params, batch, tp_lm.tp_rules()


def _ex_pipe_lm() -> ExampleSetup:
    from autodist_tpu.models import pipe_lm
    cfg = pipe_lm.TPLMConfig(vocab_size=256, d_model=32, num_layers=2,
                             num_heads=4, mlp_dim=64, max_seq_len=32)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=4, n_microbatches=2)
    return loss_fn, params, batch, pipe_lm.pp_rules()


def _ex_moe_lm() -> ExampleSetup:
    from autodist_tpu.models import moe_lm
    cfg = moe_lm.MoEConfig(vocab_size=256, d_model=32, num_layers=2,
                           num_heads=4, expert_dim=64, max_seq_len=32,
                           num_experts=2)
    loss_fn, params, batch, _ = moe_lm.make_train_setup(cfg, seq_len=16,
                                                        batch_size=4)
    return loss_fn, params, batch, moe_lm.ep_rules()


EXAMPLES: Dict[str, Callable[[], ExampleSetup]] = {
    "linear_regression": _ex_linear_regression,
    "sentiment_classifier": _ex_sentiment_classifier,
    "image_classifier": _ex_image_classifier,
    "lm1b": _ex_lm1b,
    "tp_lm": _ex_tp_lm,
    "pipe_lm": _ex_pipe_lm,
    "moe_lm": _ex_moe_lm,
}


def _builders(mp_rules):
    """Strategy-name -> builder factory. Model-parallel builders need the
    example's mp_rules and are only offered when the example has them."""
    from autodist_tpu import strategy as S
    out = {
        "PS": lambda: S.PS(),
        "PSLoadBalancing": lambda: S.PSLoadBalancing(),
        "PartitionedPS": lambda: S.PartitionedPS(),
        "UnevenPartitionedPS": lambda: S.UnevenPartitionedPS(),
        "AllReduce": lambda: S.AllReduce(),
        "AllReduceInt8Wire": lambda: S.AllReduce(wire_dtype="int8"),
        "PSInt8Wire": lambda: S.PS(wire_dtype="int8"),
        "PartitionedAR": lambda: S.PartitionedAR(),
        "ZeroSharded": lambda: S.ZeroSharded(),
        "ZeroShardedInt8Wire": lambda: S.ZeroSharded(wire_dtype="int8"),
        "RandomAxisPartitionAR": lambda: S.RandomAxisPartitionAR(),
        "Parallax": lambda: S.Parallax(),
        "SequenceParallelAR": lambda: S.SequenceParallelAR(seq_shards=2),
        "WithRemat": lambda: S.WithRemat(S.AllReduce(), policy="dots"),
        "AutoStrategy": lambda: S.AutoStrategy(),
    }
    if mp_rules:
        out["TensorParallel"] = lambda: S.TensorParallel(
            tp_shards=2, mp_rules=mp_rules)
        out["PipelineParallel"] = lambda: S.PipelineParallel(
            pp_shards=2, mp_rules=mp_rules, n_microbatches=2)
        out["ExpertParallel"] = lambda: S.ExpertParallel(
            ep_shards=2, mp_rules=mp_rules)
    return out


def default_spec(num_devices: int = 4):
    """Synthetic single-node 2x2 slice — the lint-time stand-in topology
    (verification is static; no accelerator is touched)."""
    from autodist_tpu.resource_spec import ResourceSpec
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True,
                    "tpus": num_devices}]})


def topology_spec(topology):
    """Synthesize the multi-node dryrun spec a topology describes — one
    node per host, ``chips_per_host`` chips each — with the topology
    attached, so a 64-chip pod plan lints (ADT52x, per-level pricing)
    with zero hardware."""
    from autodist_tpu.resource_spec import ResourceSpec
    nodes = [{"address": "10.0.0.%d" % (h + 1), "chief": h == 0,
              "tpus": topology.chips_per_host}
             for h in range(topology.hosts)]
    return ResourceSpec.from_dict({"nodes": nodes}).set_topology(topology)


def _load_topology(args):
    """Resolve ``--topology``: ``(topology, error_diagnostic)`` — a
    malformed file becomes an ADT524 finding, never a traceback."""
    if not args.topology:
        return None, None
    from autodist_tpu.analysis.topology import (TopologyConfigError,
                                                diagnostic_for_config_error)
    from autodist_tpu.resource_spec import Topology
    try:
        return Topology.from_yaml(args.topology), None
    except TopologyConfigError as e:
        return None, diagnostic_for_config_error(e)


def _report(args, label, diags, spec, memory: Optional[dict] = None) -> int:
    """Print the diagnostics (table or JSON); returns the error count."""
    from autodist_tpu.analysis.diagnostics import (Severity, format_table,
                                                   sort_diagnostics)
    n_errors = sum(1 for d in diags if d.severity >= Severity.ERROR)
    if args.format == "json":
        doc = {
            "example": args.example, "strategy": label,
            "errors": n_errors,
            "diagnostics": [d.to_dict() for d in sort_diagnostics(diags)],
        }
        if memory is not None:
            doc["memory"] = {k: v for k, v in memory.items()
                             if k != "diagnostics"}
        print(json.dumps(doc, indent=1, sort_keys=True))
    elif diags or not args.quiet:
        print("%s x %s on %d devices:"
              % (args.example, label, len(spec.devices)))
        if memory is not None:
            print("memory: peak %.3f GiB of %.3f GiB budget (%.0f%%%s)"
                  % (memory["peak_hbm_gib"], memory["budget_gib"],
                     100.0 * (memory["utilization"] or 0.0),
                     ", fuse_steps=%d" % memory["fuse_steps"]
                     if memory.get("fuse_steps", 1) > 1 else ""))
        print(format_table(diags))
    return n_errors


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m autodist_tpu.analysis",
        description="Static pre-compile strategy verifier (plan linter). "
                    "Exit 0 = clean, 1 = ADT errors, 2 = usage/build "
                    "failure.")
    p.add_argument("example", nargs="?",
                   help="bundled example: %s" % ", ".join(sorted(EXAMPLES)))
    p.add_argument("--strategy", default="AllReduce",
                   help="strategy builder name (see --list)")
    p.add_argument("--strategy-json", default=None, metavar="FILE",
                   help="lint a serialized Strategy JSON file instead of "
                        "building one")
    p.add_argument("--spec", default=None, metavar="YAML",
                   help="resource spec yaml (default: synthetic 4-chip "
                        "single node)")
    p.add_argument("--topology", default=None, metavar="YAML",
                   help="multi-level topology yaml (hosts x chips with "
                        "per-level link bandwidth): arms the ADT52x "
                        "topology-aware communication lints and, without "
                        "--spec, synthesizes a matching hosts x "
                        "chips_per_host dryrun spec — how CI lints "
                        "pod-scale plans with zero hardware. A malformed "
                        "file is reported as ADT524 (exit 1)")
    p.add_argument("--devices", type=int, default=4,
                   help="device count of the synthetic spec (default 4)")
    p.add_argument("--format", choices=("table", "json"), default="table",
                   help="output format; json emits one machine-readable "
                        "document (code/severity/var/message/fixit per "
                        "finding) for CI and external tooling")
    p.add_argument("--json", action="store_true",
                   help="alias for --format json")
    p.add_argument("--hbm-budget", type=float, default=None, metavar="GIB",
                   help="per-device HBM budget in GiB: run the plan-level "
                        "memory gate (ADT501 projected OOM, ADT502 budget "
                        "pressure) with no compile attempt")
    p.add_argument("--fuse-steps", type=int, default=1, metavar="K",
                   help="price the fused multi-step engine's device-"
                        "resident PS carry into the memory gate (and the "
                        "donation check in --programs mode)")
    p.add_argument("--programs", nargs="+", metavar="FILE", default=None,
                   help="lint saved lowered-program dumps (StableHLO "
                        "as_text) instead of building a plan: per-program "
                        "memory + communication findings, plus cross-"
                        "program collective-schedule checks (ADT510/511) "
                        "against the FIRST file")
    p.add_argument("--numerics", action="store_true",
                   help="add the plan-level numerics-safety gate "
                        "(rules.verify_numerics): ADT601/602 errors plus "
                        "the ADT603 loss-tier and ADT604 sentinel-less "
                        "half-precision warnings")
    p.add_argument("--compute-dtype", choices=("f32", "bf16"), default=None,
                   help="override the built strategy's compute tier "
                        "before linting (lint the bf16 shape of any "
                        "builder without a dedicated builder flag)")
    p.add_argument("--quiet", action="store_true",
                   help="print nothing on a clean plan")
    p.add_argument("--list", action="store_true",
                   help="list examples and strategies, then exit")
    return p


def _programs_mode(args) -> int:
    """Lint lowered-program text dumps: memory/donation/communication per
    program, cross-program schedule consistency vs the first (reference)
    program, and — with ``--topology`` — the per-link-level ADT52x pass
    over every program's collective schedule. Exit 1 on any ADT error."""
    import dataclasses as _dc

    from autodist_tpu.analysis import hlo as hlo_lib
    from autodist_tpu.analysis import memory as memory_lib
    from autodist_tpu.analysis import numerics as numerics_lib
    from autodist_tpu.analysis.diagnostics import (Severity, format_table,
                                                   sort_diagnostics)
    from autodist_tpu.analysis.lowered import lint_lowered_text
    budget = (args.hbm_budget * memory_lib.GIB
              if args.hbm_budget is not None else None)
    topology, topo_diag = _load_topology(args)
    if topo_diag is not None:
        print(format_table([topo_diag]))
        return 1

    def _attribute(diags, path):
        # every finding names its file: CI output over N programs is
        # unactionable when all findings read as the reference's
        return [d if d.var else _dc.replace(d, var=path) for d in diags]

    per_program = []
    for path in args.programs:
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            print("error: cannot read %s: %s" % (path, e), file=sys.stderr)
            return 2
        # the full invocation path, not the basename: two dumps named
        # train.hlo in different directories must stay distinguishable
        label = path
        prog = hlo_lib.parse_hlo_text(text)
        est = memory_lib.estimate_from_text(prog)
        sched = hlo_lib.collective_schedule(prog)
        diags = list(lint_lowered_text(text))
        diags += numerics_lib.lint_text(prog, label=label)
        diags += memory_lib.donation_diagnostics(
            prog, fuse_steps=args.fuse_steps)
        if budget is not None:
            diags += memory_lib.budget_diagnostics(
                est.peak_hbm_bytes, budget, source="lowered-program")
        if topology is not None:
            from autodist_tpu.analysis.topology import lint_schedule
            diags += lint_schedule(sched, topology, label=label)
        per_program.append((label, est, sched, _attribute(diags, path)))
    ref_label, _, ref_sched, _ = per_program[0]
    cross = []
    for label, _, sched, _ in per_program[1:]:
        # cross-program findings anchor to the OFFENDING (non-reference)
        # file's path via ``var`` so multi-file CI output is actionable
        batch = hlo_lib.compare_schedules(ref_sched, sched,
                                          ref_label, label)
        batch += numerics_lib.compare_schedule_dtypes(ref_sched, sched,
                                                      ref_label, label)
        cross += _attribute(batch, label)
    all_diags = [d for (_, _, _, ds) in per_program for d in ds] + cross
    n_errors = sum(1 for d in all_diags if d.severity >= Severity.ERROR)
    if args.format == "json":
        print(json.dumps({
            "programs": [{
                "program": label,
                "memory": est.to_dict(),
                "collectives": len(sched),
                "diagnostics": [d.to_dict()
                                for d in sort_diagnostics(diags)],
            } for label, est, sched, diags in per_program],
            "schedule_check": {
                "reference": ref_label,
                "diagnostics": [d.to_dict()
                                for d in sort_diagnostics(cross)],
            },
            "errors": n_errors,
        }, indent=1, sort_keys=True))
    elif all_diags or not args.quiet:
        for label, est, sched, diags in per_program:
            print("%s: peak %.4f GiB, %d collective(s)"
                  % (label, est.peak_hbm_bytes / memory_lib.GIB,
                     len(sched)))
        print(format_table(all_diags))
    return 1 if n_errors else 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.json:
        args.format = "json"
    if args.list:
        print("examples:   " + " ".join(sorted(EXAMPLES)))
        print("strategies: " + " ".join(sorted(_builders([""]))))
        return 0
    if args.programs:
        return _programs_mode(args)
    if not args.example:
        print("error: an example name is required (see --list)",
              file=sys.stderr)
        return 2
    if args.example not in EXAMPLES:
        print("error: unknown example %r (have %s)"
              % (args.example, ", ".join(sorted(EXAMPLES))), file=sys.stderr)
        return 2

    from autodist_tpu.analysis.rules import verify
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy.base import Strategy

    try:
        loss_fn, params, batch, mp_rules = EXAMPLES[args.example]()
        item = ModelItem(loss_fn=loss_fn, params=params,
                         example_batch=batch).prepare()
    except Exception as e:  # noqa: BLE001 — build failures are exit 2
        print("error: example %r failed to build: %s: %s"
              % (args.example, type(e).__name__, e), file=sys.stderr)
        return 2

    topology, topo_diag = _load_topology(args)
    if topo_diag is not None:
        _report(args, args.strategy, [topo_diag], default_spec(1))
        return 1
    if args.spec:
        spec = ResourceSpec(args.spec)
        if topology is not None:
            spec.set_topology(topology)
    elif topology is not None:
        spec = topology_spec(topology)
    else:
        spec = default_spec(args.devices)

    if args.strategy_json:
        from autodist_tpu.analysis.diagnostics import DiagnosticError
        try:
            strategy = Strategy.deserialize(path=args.strategy_json)
        except DiagnosticError as e:
            # a defect the DESERIALIZER itself detects (e.g. ADT301
            # unknown synchronizer kind) is still an ADT finding, not a
            # tooling failure — report it through the normal output path
            _report(args, args.strategy_json, [e.diagnostic], spec)
            return 1
        except Exception as e:  # noqa: BLE001
            print("error: cannot load strategy from %s: %s"
                  % (args.strategy_json, e), file=sys.stderr)
            return 2
        label = args.strategy_json
    else:
        builders = _builders(mp_rules)
        if args.strategy not in builders:
            print("error: unknown strategy %r for example %r (have %s)"
                  % (args.strategy, args.example,
                     ", ".join(sorted(builders))), file=sys.stderr)
            return 2
        try:
            strategy = builders[args.strategy]().build(item, spec)
        except Exception as e:  # noqa: BLE001
            print("error: builder %s failed: %s: %s"
                  % (args.strategy, type(e).__name__, e), file=sys.stderr)
            return 2
        label = args.strategy

    if args.compute_dtype is not None:
        # GraphConfig is a mutable plan object; overriding the tier here
        # lints exactly the strategy the builder would emit with
        # compute_dtype=..., no per-builder CLI flag needed
        strategy.graph_config.compute_dtype = args.compute_dtype
        label += "[%s]" % args.compute_dtype

    diags = list(verify(strategy, item, spec))
    if args.numerics:
        from autodist_tpu.analysis.rules import verify_numerics
        seen = {(d.code, d.message) for d in diags}
        diags += [d for d in verify_numerics(strategy, item, spec)
                  if (d.code, d.message) not in seen]
    memory = None
    if args.hbm_budget is not None:
        from autodist_tpu.analysis import memory as memory_lib
        memory = memory_lib.plan_memory_report(
            strategy, item, spec,
            budget_bytes=args.hbm_budget * memory_lib.GIB,
            fuse_steps=args.fuse_steps)
        diags += memory["diagnostics"]
    return 1 if _report(args, label, diags, spec, memory) else 0
