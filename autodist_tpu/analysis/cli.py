"""Plan linter CLI: ``python -m autodist_tpu.analysis <example> --strategy <name>``.

Builds (or loads) a Strategy for one of the bundled examples, runs the
static verifier against the example's ModelItem and a resource spec, and
prints the diagnostic table. Exit codes: 0 = no errors (warnings/info
allowed), 1 = at least one ``ADT`` error, 2 = usage/build failure.

Used by CI to gate every example x strategy combination, and by hand to
answer "will this plan compile?" without compiling:

    python -m autodist_tpu.analysis linear_regression --strategy PS
    python -m autodist_tpu.analysis lm1b --strategy Parallax --json
    python -m autodist_tpu.analysis tp_lm --strategy TensorParallel
    python -m autodist_tpu.analysis lm1b --strategy-json plan.json
"""
import argparse
import json
import sys
from typing import Callable, Dict, Optional, Tuple

# (loss_fn, params, example_batch, mp_rules-or-None) factories. Tiny
# configurations of the same models the example scripts train — the lint
# needs shapes and sparsity, not realistic capacity.
ExampleSetup = Tuple[Callable, object, object, Optional[list]]


def _ex_linear_regression() -> ExampleSetup:
    import jax.numpy as jnp

    params = {"W": jnp.zeros(()), "b": jnp.zeros(())}

    def loss_fn(p, batch):
        pred = batch["x"] * p["W"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    batch = {"x": jnp.zeros((64,), jnp.float32),
             "y": jnp.zeros((64,), jnp.float32)}
    return loss_fn, params, batch, None


def _ex_sentiment_classifier() -> ExampleSetup:
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    params = {
        "embedding": jax.random.normal(key, (512, 16)) * 0.05,
        "dense": {"kernel": jax.random.normal(key, (16, 1)) * 0.1,
                  "bias": jnp.zeros((1,))},
    }

    def loss_fn(p, batch):
        emb = jnp.take(p["embedding"], batch["tokens"], axis=0)  # gather
        pooled = jnp.mean(emb, axis=1)
        logits = (pooled @ p["dense"]["kernel"] + p["dense"]["bias"])[..., 0]
        labels = batch["label"].astype(jnp.float32)
        return jnp.mean(jnp.maximum(logits, 0) - logits * labels
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    batch = {"tokens": jnp.zeros((8, 16), jnp.int32),
             "label": jnp.zeros((8,), jnp.int32)}
    return loss_fn, params, batch, None


def _ex_image_classifier() -> ExampleSetup:
    from autodist_tpu.models import resnet
    loss_fn, params, batch, _ = resnet.make_train_setup(
        resnet.ResNetTiny, num_classes=10, image_size=32, batch_size=8)
    return loss_fn, params, batch, None


def _ex_lm1b() -> ExampleSetup:
    from autodist_tpu.models import lm
    cfg = lm.LMConfig(vocab_size=256, d_model=32, num_layers=2,
                      num_heads=4, mlp_dim=64, max_seq_len=32)
    loss_fn, params, batch, _ = lm.make_train_setup(cfg, seq_len=16,
                                                    batch_size=4)
    return loss_fn, params, batch, None


def _ex_tp_lm() -> ExampleSetup:
    from autodist_tpu.models import tp_lm
    cfg = tp_lm.TPLMConfig(vocab_size=256, d_model=32, num_layers=2,
                           num_heads=4, mlp_dim=64, max_seq_len=32)
    loss_fn, params, batch, _ = tp_lm.make_train_setup(cfg, seq_len=16,
                                                       batch_size=4)
    return loss_fn, params, batch, tp_lm.tp_rules()


def _ex_pipe_lm() -> ExampleSetup:
    from autodist_tpu.models import pipe_lm
    cfg = pipe_lm.TPLMConfig(vocab_size=256, d_model=32, num_layers=2,
                             num_heads=4, mlp_dim=64, max_seq_len=32)
    loss_fn, params, batch, _ = pipe_lm.make_train_setup(
        cfg, seq_len=16, batch_size=4, n_microbatches=2)
    return loss_fn, params, batch, pipe_lm.pp_rules()


def _ex_moe_lm() -> ExampleSetup:
    from autodist_tpu.models import moe_lm
    cfg = moe_lm.MoEConfig(vocab_size=256, d_model=32, num_layers=2,
                           num_heads=4, expert_dim=64, max_seq_len=32,
                           num_experts=2)
    loss_fn, params, batch, _ = moe_lm.make_train_setup(cfg, seq_len=16,
                                                        batch_size=4)
    return loss_fn, params, batch, moe_lm.ep_rules()


EXAMPLES: Dict[str, Callable[[], ExampleSetup]] = {
    "linear_regression": _ex_linear_regression,
    "sentiment_classifier": _ex_sentiment_classifier,
    "image_classifier": _ex_image_classifier,
    "lm1b": _ex_lm1b,
    "tp_lm": _ex_tp_lm,
    "pipe_lm": _ex_pipe_lm,
    "moe_lm": _ex_moe_lm,
}


def _builders(mp_rules):
    """Strategy-name -> builder factory. Model-parallel builders need the
    example's mp_rules and are only offered when the example has them."""
    from autodist_tpu import strategy as S
    out = {
        "PS": lambda: S.PS(),
        "PSLoadBalancing": lambda: S.PSLoadBalancing(),
        "PartitionedPS": lambda: S.PartitionedPS(),
        "UnevenPartitionedPS": lambda: S.UnevenPartitionedPS(),
        "AllReduce": lambda: S.AllReduce(),
        "PartitionedAR": lambda: S.PartitionedAR(),
        "RandomAxisPartitionAR": lambda: S.RandomAxisPartitionAR(),
        "Parallax": lambda: S.Parallax(),
        "SequenceParallelAR": lambda: S.SequenceParallelAR(seq_shards=2),
        "WithRemat": lambda: S.WithRemat(S.AllReduce(), policy="dots"),
        "AutoStrategy": lambda: S.AutoStrategy(),
    }
    if mp_rules:
        out["TensorParallel"] = lambda: S.TensorParallel(
            tp_shards=2, mp_rules=mp_rules)
        out["PipelineParallel"] = lambda: S.PipelineParallel(
            pp_shards=2, mp_rules=mp_rules, n_microbatches=2)
        out["ExpertParallel"] = lambda: S.ExpertParallel(
            ep_shards=2, mp_rules=mp_rules)
    return out


def default_spec(num_devices: int = 4):
    """Synthetic single-node 2x2 slice — the lint-time stand-in topology
    (verification is static; no accelerator is touched)."""
    from autodist_tpu.resource_spec import ResourceSpec
    return ResourceSpec.from_dict(
        {"nodes": [{"address": "127.0.0.1", "chief": True,
                    "tpus": num_devices}]})


def _report(args, label, diags, spec) -> int:
    """Print the diagnostics (table or JSON); returns the error count."""
    from autodist_tpu.analysis.diagnostics import (Severity, format_table,
                                                   sort_diagnostics)
    n_errors = sum(1 for d in diags if d.severity >= Severity.ERROR)
    if args.json:
        print(json.dumps({
            "example": args.example, "strategy": label,
            "errors": n_errors,
            "diagnostics": [d.to_dict() for d in sort_diagnostics(diags)],
        }, indent=1, sort_keys=True))
    elif diags or not args.quiet:
        print("%s x %s on %d devices:"
              % (args.example, label, len(spec.devices)))
        print(format_table(diags))
    return n_errors


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m autodist_tpu.analysis",
        description="Static pre-compile strategy verifier (plan linter). "
                    "Exit 0 = clean, 1 = ADT errors, 2 = usage/build "
                    "failure.")
    p.add_argument("example", nargs="?",
                   help="bundled example: %s" % ", ".join(sorted(EXAMPLES)))
    p.add_argument("--strategy", default="AllReduce",
                   help="strategy builder name (see --list)")
    p.add_argument("--strategy-json", default=None, metavar="FILE",
                   help="lint a serialized Strategy JSON file instead of "
                        "building one")
    p.add_argument("--spec", default=None, metavar="YAML",
                   help="resource spec yaml (default: synthetic 4-chip "
                        "single node)")
    p.add_argument("--devices", type=int, default=4,
                   help="device count of the synthetic spec (default 4)")
    p.add_argument("--json", action="store_true",
                   help="emit diagnostics as JSON instead of a table")
    p.add_argument("--quiet", action="store_true",
                   help="print nothing on a clean plan")
    p.add_argument("--list", action="store_true",
                   help="list examples and strategies, then exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list:
        print("examples:   " + " ".join(sorted(EXAMPLES)))
        print("strategies: " + " ".join(sorted(_builders([""]))))
        return 0
    if not args.example:
        print("error: an example name is required (see --list)",
              file=sys.stderr)
        return 2
    if args.example not in EXAMPLES:
        print("error: unknown example %r (have %s)"
              % (args.example, ", ".join(sorted(EXAMPLES))), file=sys.stderr)
        return 2

    from autodist_tpu.analysis.rules import verify
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy.base import Strategy

    try:
        loss_fn, params, batch, mp_rules = EXAMPLES[args.example]()
        item = ModelItem(loss_fn=loss_fn, params=params,
                         example_batch=batch).prepare()
    except Exception as e:  # noqa: BLE001 — build failures are exit 2
        print("error: example %r failed to build: %s: %s"
              % (args.example, type(e).__name__, e), file=sys.stderr)
        return 2

    spec = (ResourceSpec(args.spec) if args.spec
            else default_spec(args.devices))

    if args.strategy_json:
        from autodist_tpu.analysis.diagnostics import DiagnosticError
        try:
            strategy = Strategy.deserialize(path=args.strategy_json)
        except DiagnosticError as e:
            # a defect the DESERIALIZER itself detects (e.g. ADT301
            # unknown synchronizer kind) is still an ADT finding, not a
            # tooling failure — report it through the normal output path
            _report(args, args.strategy_json, [e.diagnostic], spec)
            return 1
        except Exception as e:  # noqa: BLE001
            print("error: cannot load strategy from %s: %s"
                  % (args.strategy_json, e), file=sys.stderr)
            return 2
        label = args.strategy_json
    else:
        builders = _builders(mp_rules)
        if args.strategy not in builders:
            print("error: unknown strategy %r for example %r (have %s)"
                  % (args.strategy, args.example,
                     ", ".join(sorted(builders))), file=sys.stderr)
            return 2
        try:
            strategy = builders[args.strategy]().build(item, spec)
        except Exception as e:  # noqa: BLE001
            print("error: builder %s failed: %s: %s"
                  % (args.strategy, type(e).__name__, e), file=sys.stderr)
            return 2
        label = args.strategy

    diags = verify(strategy, item, spec)
    return 1 if _report(args, label, diags, spec) else 0
