"""Static per-device peak-HBM analysis (the ADT5xx memory pass).

Two estimators share one reporting shape, so OOM surfaces at lint time
instead of as a runtime allocation failure:

- :func:`estimate_from_text` — the **lowered-program** estimator: entry
  buffer sizes (sharding-aware, donation-aware) plus a statement-level
  liveness sweep over the parsed program (``analysis/hlo.py``) for the
  temporaries XLA actually materializes. This is the number
  ``Runner.memory_report()`` reports, and the one checked against
  ``compiled.memory_analysis()`` in tests.
- :func:`plan_memory_report` — the **plan-level** estimator: the cost
  model's strategy-aware heuristic (params + optimizer state + gradient
  buffer + activations under partitioning/host-PS/remat), available
  BEFORE any tracing or lowering — the CLI's ``--hbm-budget`` gate runs
  here, so a projected OOM fails the lint with no compile attempt.

Both check against a budget derived from ``ResourceSpec.chip_hbm_bytes()``
(per-chip capacity by generation, overridable per cluster) and report:

- ``ADT501`` (error): projected per-device peak exceeds the budget;
- ``ADT502`` (warning): peak within 10% of the budget — one allocator
  fragmentation event from an OOM;
- ``ADT503`` (warning): a fused superstep program whose carry is not
  donated — state lives twice for the whole superstep.

The liveness sweep is a conservative model of XLA's buffer assignment:
only "anchor" ops that survive fusion (contractions, reductions,
collectives, data movement, loops) are charged a buffer from definition
to last use; elementwise chains fuse into their consumers and charge
nothing. No attempt is made to model rematerialization or buffer
reuse beyond liveness — the estimate is meant to be within tens of
percent, biased high.
"""
import dataclasses
from typing import Dict, List, Optional

from autodist_tpu.analysis.diagnostics import (Diagnostic, error, warning)
from autodist_tpu.analysis.hlo import (COLLECTIVE_CLASS, HloFunction,
                                       HloProgram, parse_hlo_text)

GIB = float(1 << 30)

# op mnemonics whose outputs XLA materializes as real buffers (fusion
# boundaries); everything else is assumed to fuse into its consumer
_ANCHOR_OPS = frozenset({
    "dot_general", "dot", "convolution", "conv_general_dilated",
    "reduce", "reduce_window", "sort", "while", "gather", "scatter",
    "concatenate", "pad", "dynamic_slice", "rng_bit_generator", "fft",
    "cholesky", "triangular_solve", "custom_call",
}) | frozenset(COLLECTIVE_CLASS)

# custom_call targets that are sharding annotations, not real computations
_PASS_THROUGH_TARGETS = ("Sharding", "SPMDFullToShardShape",
                         "SPMDShardToFullShape")


@dataclasses.dataclass
class MemoryEstimate:
    """Per-device peak-HBM estimate of one lowered program."""

    num_partitions: int = 1
    args_bytes: float = 0.0           # entry arguments (per-device)
    output_bytes: float = 0.0         # entry results (per-device)
    aliased_bytes: float = 0.0        # donated args (buffer shared w/ output)
    peak_temp_bytes: float = 0.0      # liveness-sweep peak of anchors
    # largest single in-flight collective payload — informational: the
    # liveness sweep already holds both the operand and the result of a
    # collective live across it, so adding this again would double-count
    collective_scratch_bytes: float = 0.0
    outputs_by_label: Dict[str, float] = dataclasses.field(
        default_factory=dict)

    @property
    def peak_hbm_bytes(self) -> float:
        return (self.args_bytes + self.output_bytes - self.aliased_bytes
                + self.peak_temp_bytes)

    def to_dict(self) -> dict:
        return {
            "num_partitions": self.num_partitions,
            "args_bytes": round(self.args_bytes),
            "output_bytes": round(self.output_bytes),
            "aliased_bytes": round(self.aliased_bytes),
            "peak_temp_bytes": round(self.peak_temp_bytes),
            "collective_scratch_bytes": round(self.collective_scratch_bytes),
            "peak_hbm_bytes": round(self.peak_hbm_bytes),
            "peak_hbm_gib": round(self.peak_hbm_bytes / GIB, 4),
            "outputs_by_label": {k: round(v) for k, v in
                                 sorted(self.outputs_by_label.items())},
        }


def _classify_result(info: str) -> str:
    if ".params" in info or "param" in info:
        return "params"
    if "opt_state" in info or "opt" in info:
        return "opt_state"
    if info in ("[]", "") or ".step" in info:
        return "counters"
    return "metrics"


def _function_temp_peak(func: HloFunction) -> float:
    """Liveness-sweep peak over this function's anchor-op values: each
    anchor output is live from its defining statement to its last use;
    values the function returns are charged to the caller's output
    accounting instead."""
    last_use: Dict[str, int] = {}
    for idx, st in enumerate(func.statements):
        for op_id in st.operand_ids:
            last_use[op_id] = idx
    returned = func.returned_ids
    live_until: List[tuple] = []  # (def_idx, last_use_idx, bytes)
    for idx, st in enumerate(func.statements):
        if not st.result_id or st.result_id in returned:
            continue
        if st.op == "custom_call" and st.call_target in _PASS_THROUGH_TARGETS:
            continue
        if st.op not in _ANCHOR_OPS:
            continue
        end = last_use.get(st.result_id, idx)
        live_until.append((idx, end, st.total_out_bytes))
    # event sweep: +bytes at def, -bytes after last use, prefix-sum for
    # the peak — O(S + A), not O(S x A) (a fused dump of a large model
    # has 1e4+ statements)
    delta = [0.0] * (len(func.statements) + 1)
    for d, e, b in live_until:
        delta[d] += b
        delta[e + 1] -= b
    peak = live = 0.0
    for change in delta:
        live += change
        peak = max(peak, live)
    return peak


def estimate_from_text(text_or_program) -> MemoryEstimate:
    """Per-device peak-HBM estimate of a lowered program dump.

    ``peak = args + outputs - donated_aliases + temp_peak + collective
    scratch``, with entry buffers divided by their sharding (a
    ``{devices=[4,1]}`` batch arg costs a quarter per device) and the
    temp peak taken from the per-function liveness sweep (function frames
    are not concurrent in XLA, so the max over functions, not the sum).
    """
    program = (text_or_program if isinstance(text_or_program, HloProgram)
               else parse_hlo_text(text_or_program))
    est = MemoryEstimate(num_partitions=program.num_partitions)
    if program.entry is None:
        return est
    entry = program.entry
    est.args_bytes = float(sum(a.per_device_bytes for a in entry.args))
    est.output_bytes = float(sum(r.per_device_bytes for r in entry.results))
    # donated args share buffers with outputs — explicitly
    # (tf.aliasing_output = N) or lazily (jax.buffer_donor, resolved by
    # XLA at compile time); either way at most output_bytes can alias
    est.aliased_bytes = min(
        float(sum(a.per_device_bytes for a in entry.args if a.donated)),
        est.output_bytes)
    for r in entry.results:
        label = _classify_result(r.result_info)
        est.outputs_by_label[label] = (est.outputs_by_label.get(label, 0.0)
                                       + r.per_device_bytes)
    est.peak_temp_bytes = max(
        (_function_temp_peak(f) for f in program.funcs.values()),
        default=0.0)
    est.collective_scratch_bytes = float(max(
        (c.payload_bytes for c in program.collectives()), default=0))
    return est


# ---------------------------------------------------------------- budgets


def budget_diagnostics(peak_bytes: float, budget_bytes: float,
                       source: str = "lowered program",
                       headroom_warn: float = 0.9) -> List[Diagnostic]:
    """ADT501/ADT502 against a per-device HBM budget."""
    out: List[Diagnostic] = []
    if budget_bytes <= 0:
        return out
    if peak_bytes > budget_bytes:
        out.append(error(
            "ADT501",
            "projected OOM: per-device peak HBM %.3f GiB exceeds the "
            "%.3f GiB budget (%s estimate) — this plan crashes at the "
            "first step's allocation, not at lint time" % (
                peak_bytes / GIB, budget_bytes / GIB, source),
            fixit="partition storage (ZeRO/PartitionedPS), offload to "
                  "host-PS, enable remat, or shrink the per-device "
                  "batch"))
    elif peak_bytes > headroom_warn * budget_bytes:
        out.append(warning(
            "ADT502",
            "per-device peak HBM %.2f GiB is within %d%% of the %.2f GiB "
            "budget (%s estimate) — allocator fragmentation or a larger "
            "batch tips this into OOM" % (
                peak_bytes / GIB, round((1 - headroom_warn) * 100),
                budget_bytes / GIB, source),
            fixit="leave >=10% headroom: partition storage, remat, or "
                  "shrink the batch"))
    return out


def donation_diagnostics(text_or_program,
                         fuse_steps: int = 1) -> List[Diagnostic]:
    """ADT503: a fused superstep program (its microstep loop is the
    program body) whose entry carry is not donated keeps TWO copies of
    params + optimizer state resident for the whole superstep.

    Fires only when the caller declares the program fused
    (``fuse_steps > 1`` — Runner.memory_report and the CLI's
    ``--fuse-steps`` both know): a while op alone is no evidence, since
    per-step programs legitimately contain model-internal loops (scanned
    layer stacks, ring attention) and eval programs are never donated."""
    if fuse_steps <= 1:
        return []
    program = (text_or_program if isinstance(text_or_program, HloProgram)
               else parse_hlo_text(text_or_program))
    if program.entry is None:
        return []
    if any(a.donated for a in program.entry.args):
        return []
    carry = sum(a.per_device_bytes for a in program.entry.args)
    return [warning(
        "ADT503",
        "fused superstep carry is not donated: none of the %d entry "
        "arguments alias an output, so ~%.2f GiB of state is resident "
        "twice for the whole superstep" % (
            len(program.entry.args), carry / GIB),
        fixit="dispatch through run_superstep/multi_step (donate=True) "
              "so the carry buffers are reused in place")]


# ------------------------------------------------------------- plan level


def plan_peak_hbm(strategy, model_item, resource_spec,
                  fuse_steps: int = 1, cost_model=None) -> float:
    """Strategy-aware per-device peak estimate with NO tracing of the
    lowered program — the cost model's heuristic (params + opt state +
    gradient buffer + activations under partitioning/host-PS/remat),
    plus the fused engine's device-resident PS carry (values stay
    counted as the pulled copy; the carry additionally pins each host-PS
    var's optimizer state on device for the superstep)."""
    from autodist_tpu.simulator.cost_model import CostModel
    cm = cost_model or CostModel(model_item, resource_spec)
    peak = cm.hbm_bytes(strategy)
    if fuse_steps > 1:
        peak += _fused_carry_opt_bytes(strategy, model_item, cm)
    return peak


def _fused_carry_opt_bytes(strategy, model_item, cost_model) -> float:
    """Optimizer-state bytes the fused carry keeps device-resident for
    host-PS vars (per-step execution leaves them in host RAM)."""
    from autodist_tpu.strategy.base import PSSynchronizer
    infos = model_item.var_infos
    params_total = float(model_item.total_bytes()) or 1.0
    opt_total = cost_model.opt_state_bytes()
    carry = 0.0
    for node in strategy.node_config:
        info = infos.get(node.var_name)
        if info is None:
            continue
        syncs = ([node.synchronizer] if node.synchronizer else
                 [p.synchronizer for p in node.part_configs])
        if any(isinstance(s, PSSynchronizer) and not s.local_replication
               for s in syncs):
            carry += opt_total * info.byte_size / params_total
    return carry


def plan_memory_report(strategy, model_item, resource_spec,
                       budget_bytes: Optional[float] = None,
                       fuse_steps: int = 1) -> dict:
    """The CLI/AutoDist-facing plan-level memory gate: heuristic peak,
    budget (explicit GiB or the spec's chip capacity), utilization and
    the ADT501/502 diagnostics."""
    peak = plan_peak_hbm(strategy, model_item, resource_spec,
                         fuse_steps=fuse_steps)
    budget = (budget_bytes if budget_bytes is not None
              else resource_spec.chip_hbm_bytes())
    diags = budget_diagnostics(peak, budget, source="plan-level")
    return {
        "peak_hbm_bytes": round(peak),
        "peak_hbm_gib": round(peak / GIB, 4),
        "budget_bytes": round(budget),
        "budget_gib": round(budget / GIB, 4),
        "utilization": round(peak / budget, 4) if budget else None,
        "fuse_steps": fuse_steps,
        "diagnostics": diags,
    }
