"""Rule-based static analysis over ``(Strategy, ModelItem, ResourceSpec)``.

The verifier proves a Strategy well-formed *before any tracing* — in the
spirit of P^2's constraint checking over parallelism placements
(arXiv:2110.10548) and TACCL's sketch validation (arXiv:2111.04867) — so
bad plans surface as lint-time :class:`Diagnostic` lists instead of
``ValueError`` tracebacks deep in ``kernel/partitioner.py`` or runtime
collective deadlocks.

Layout:

- each ``@rule`` function inspects one aspect and yields Diagnostics;
- :func:`verify` runs them all and returns the sorted findings;
- the *shared* check functions (``check_partitioner_node``,
  ``check_mp_axes_node``, ``missing_trainable_configs``) are imported by
  the compile path (``strategy/base.py``, ``kernel/partitioner.py``) so
  lint time and compile time execute the same code — no rule is
  implemented twice.

``model_item`` may be a full ``ModelItem`` or anything exposing
``var_infos`` (name -> ``VarInfo``); rules must stay pure and cheap — the
auto-strategy search calls :func:`verify` once per candidate to prune
un-compilable plans without compiling them.
"""
from typing import Dict, Iterable, List, Optional, Tuple

from autodist_tpu import const
from autodist_tpu.analysis import partition as partition_lib
from autodist_tpu.analysis.diagnostics import (Diagnostic, DiagnosticError,
                                               error, info, sort_diagnostics,
                                               warning)

# Axis names the framework's meshes understand (parallel/mesh.py builds
# meshes from these; an unknown name silently materializes nothing).
KNOWN_MESH_AXES = (const.DATA_AXIS, const.MODEL_AXIS, const.PIPELINE_AXIS,
                   const.SEQUENCE_AXIS, const.EXPERT_AXIS)

_PIPELINE_SCHEDULES = ("gpipe", "1f1b", "interleaved")


# ------------------------------------------------------------------ context


class Context:
    """Everything the rules need, computed once per :func:`verify` call."""

    def __init__(self, strategy, model_item, resource_spec):
        self.strategy = strategy
        self.spec = resource_spec
        self.var_infos = dict(getattr(model_item, "var_infos", None)
                              or (model_item if isinstance(model_item, dict)
                                  else {}))
        self.trainable = {n for n, v in self.var_infos.items()
                          if getattr(v, "trainable", True)}
        gc = strategy.graph_config
        self.replicas = list(gc.replicas)
        self.mesh_shape = dict(gc.mesh_shape or {})
        # device universe, canonicalized like kernel/device/resolver.py
        from autodist_tpu.resource_spec import DeviceSpec
        self.device_names = set()
        self.cpu_names = set()
        if resource_spec is not None:
            self.device_names = {d.name_string()
                                 for d in resource_spec.devices}
            self.cpu_names = {d.name_string()
                              for d in resource_spec.cpu_devices}
        self._canon = DeviceSpec.from_string

    def canonical(self, name: str) -> Optional[str]:
        """Canonical ``host:TYPE:index`` or None when unparseable."""
        try:
            return self._canon(name).name_string()
        except (ValueError, KeyError, IndexError):
            return None

    def synchronizers(self, node) -> List[Tuple[str, object]]:
        """(owning var name, synchronizer) pairs for one strategy node —
        the node's own synchronizer, or its shards'."""
        out = []
        if node.synchronizer is not None:
            out.append((node.var_name, node.synchronizer))
        for part in node.part_configs:
            if part.synchronizer is not None:
                out.append((part.var_name or node.var_name,
                            part.synchronizer))
        return out

    def mesh_axis_sizes(self) -> Dict[str, int]:
        """mesh_shape, or the implicit data-only mesh of a DP strategy."""
        if self.mesh_shape:
            return dict(self.mesh_shape)
        return {const.DATA_AXIS: max(len(self.replicas), 1)}


# ----------------------------------------------------------- rule registry

_RULES = []


def rule(fn):
    _RULES.append(fn)
    return fn


def verify(strategy, model_item, resource_spec) -> List[Diagnostic]:
    """Run every rule; returns diagnostics sorted most-severe-first.

    Pure and trace-free: safe to call per candidate in the auto-strategy
    search, from the CLI, or from ``AutoDist(validate=...)`` before the
    kernels ever see the plan.
    """
    ctx = Context(strategy, model_item, resource_spec)
    out: List[Diagnostic] = []
    for r in _RULES:
        out.extend(r(ctx))
    return sort_diagnostics(out)


# ------------------------------------------------- shared check functions
# (imported by strategy/base.py and kernel/partitioner.py — the compile
# path raises DiagnosticError from the FIRST error these return)


def missing_trainable_configs(strategy, trainable_names) -> List[str]:
    """Trainable variables the strategy has no node for (ADT101).

    The single implementation behind both the linter rule and
    ``StrategyCompiler.compile``'s hard failure."""
    have = {n.var_name for n in strategy.node_config}
    return sorted(set(trainable_names) - have)


def check_partitioner_node(node, shape) -> List[Diagnostic]:
    """ADT2xx checks for one node's ``partitioner`` string against the
    variable's shape (``shape`` may be None when unknown)."""
    out: List[Diagnostic] = []
    if not node.partitioner:
        return out
    try:
        counts = partition_lib.parse_partitioner(node.partitioner,
                                                 node.var_name)
    except DiagnosticError as e:
        return [e.diagnostic]
    split_axes = partition_lib.split_axes_of(counts)
    if len(split_axes) > 1:
        out.append(error(
            "ADT204",
            "partitioner %r splits %d axes; the lowering supports exactly "
            "one split axis" % (node.partitioner, len(split_axes)),
            var=node.var_name,
            fixit="keep one count > 1, e.g. %r"
                  % ",".join(str(c) if i == split_axes[0] else "1"
                             for i, c in enumerate(counts))))
    if shape is not None and len(counts) != max(len(shape), 1):
        out.append(error(
            "ADT202",
            "partitioner %r has %d axis counts but the variable has rank %d"
            % (node.partitioner, len(counts), len(shape)),
            var=node.var_name,
            fixit="emit one count per tensor axis (scalars use a single "
                  "count)"))
    num_shards = partition_lib.num_shards_of(counts)
    if node.part_configs and len(node.part_configs) != num_shards:
        out.append(error(
            "ADT109",
            "partitioner %r implies %d shards but the node carries %d "
            "part_configs" % (node.partitioner, num_shards,
                              len(node.part_configs)),
            var=node.var_name,
            fixit="emit exactly one part config per shard"))
    if (shape is not None and split_axes
            and not any(d.code == "ADT202" for d in out)):
        axis = split_axes[0]
        if axis < len(shape):
            dim = shape[axis]
            if dim < num_shards:
                out.append(warning(
                    "ADT203",
                    "split dim %d (size %d) has fewer rows than %d shards; "
                    "the partitioner will keep the variable replicated"
                    % (axis, dim, num_shards), var=node.var_name,
                    fixit="drop the partitioner or split a larger axis"))
            elif dim % num_shards != 0 and not node.shard_sizes:
                out.append(info(
                    "ADT209",
                    "split dim %d (size %d) is not divisible by %d shards; "
                    "device storage pads to the next multiple"
                    % (axis, dim, num_shards), var=node.var_name))
    if node.shard_sizes is not None and shape is not None and split_axes:
        axis = split_axes[0]
        dim = shape[axis] if axis < len(shape) else None
        if len(node.shard_sizes) != num_shards:
            out.append(error(
                "ADT208",
                "shard_sizes has %d entries for %d shards"
                % (len(node.shard_sizes), num_shards), var=node.var_name,
                fixit="emit one size per shard"))
        elif dim is not None and sum(node.shard_sizes) != dim:
            out.append(error(
                "ADT208",
                "shard_sizes %s sums to %d but split dim %d has size %d"
                % (list(node.shard_sizes), sum(node.shard_sizes), axis, dim),
                var=node.var_name,
                fixit="make the sizes sum to the split dimension"))
    return out


def check_mp_axes_node(var_name: str, mp_axes: Dict[int, str], shape,
                       mesh_axis_sizes: Dict[str, int]) -> List[Diagnostic]:
    """ADT205/206/207 for one node's model-parallel ``mp_axes`` spec.

    The same function ``kernel/partitioner.VariablePartitioner._mp_layout``
    raises from, so the lint table and the compile error always agree."""
    out: List[Diagnostic] = []
    seen_axes: Dict[str, int] = {}
    for dim, ax_name in sorted(mp_axes.items()):
        size = mesh_axis_sizes.get(ax_name)
        if size is None:
            out.append(error(
                "ADT205",
                "mp axis %r not in mesh %s" % (ax_name, mesh_axis_sizes),
                var=var_name,
                fixit="add the axis to graph_config.mesh_shape or shard "
                      "over an existing axis"))
            continue
        if ax_name in seen_axes:
            out.append(error(
                "ADT207",
                "mesh axis %r shards both dim %d and dim %d of the same "
                "variable" % (ax_name, seen_axes[ax_name], dim),
                var=var_name,
                fixit="shard each mesh axis over at most one tensor dim"))
        seen_axes[ax_name] = dim
        if shape is not None and (dim >= len(shape)
                                  or shape[dim] % size != 0):
            out.append(error(
                "ADT206",
                "dim %d (shape %s) not divisible by mesh axis %r size %d"
                % (dim, tuple(shape), ax_name, size), var=var_name,
                fixit="model-parallel storage needs exact divisibility "
                      "(no padding): adjust the mesh axis size or the "
                      "model dimension"))
    return out


def check_compressor_name(name: str, var_name: str = "") -> List[Diagnostic]:
    """ADT305 for one compressor name (shared with the factory path)."""
    if not name:
        return []
    from autodist_tpu.kernel.synchronization import compressor as comp_lib
    try:
        comp_lib.validate_name(name)
    except ValueError as e:
        return [error("ADT305", str(e), var=var_name,
                      fixit="pick one of %s (PowerSGD takes a rank "
                            "suffix, e.g. 'PowerSGDCompressor:2')"
                            % sorted(comp_lib.known_names()))]
    return []


# ------------------------------------------------------------- ADT1xx rules


@rule
def _r_missing_configs(ctx: Context) -> Iterable[Diagnostic]:
    for name in missing_trainable_configs(ctx.strategy, ctx.trainable):
        yield error(
            "ADT101", "trainable variable has no strategy node", var=name,
            fixit="emit a VarConfig for every trainable variable (or mark "
                  "it frozen via trainable_filter)")


@rule
def _r_unknown_and_duplicate(ctx: Context) -> Iterable[Diagnostic]:
    seen = set()
    for node in ctx.strategy.node_config:
        if node.var_name in seen:
            yield error("ADT103",
                        "duplicate strategy node for one variable",
                        var=node.var_name,
                        fixit="emit exactly one VarConfig per variable")
        seen.add(node.var_name)
        if ctx.var_infos and node.var_name not in ctx.var_infos:
            yield warning(
                "ADT102",
                "strategy node references a variable the model does not "
                "have (the compiler will prune it)", var=node.var_name)


@rule
def _r_replicas(ctx: Context) -> Iterable[Diagnostic]:
    if not ctx.replicas:
        yield error("ADT104", "strategy has no replica devices",
                    fixit="set graph_config.replicas to the compute "
                          "devices of the resource spec")
        return
    if ctx.spec is None:
        return
    for name in ctx.replicas:
        canon = ctx.canonical(name)
        if canon is None or (canon not in ctx.device_names
                             and canon not in ctx.cpu_names):
            yield error(
                "ADT105",
                "replica device %r is not in the resource spec (has %d "
                "devices)" % (name, len(ctx.device_names)), var="",
                fixit="build replicas from resource_spec.devices")


@rule
def _r_mesh_shape(ctx: Context) -> Iterable[Diagnostic]:
    if not ctx.mesh_shape:
        return
    product = 1
    for ax, size in ctx.mesh_shape.items():
        if ax not in KNOWN_MESH_AXES:
            yield warning(
                "ADT107",
                "mesh axis %r is not one the framework materializes %s"
                % (ax, list(KNOWN_MESH_AXES)))
        if int(size) < 1:
            yield error("ADT106", "mesh axis %r has size %d < 1" % (ax, size))
            return
        product *= int(size)
    n = len(ctx.replicas)
    if n and product != n:
        yield error(
            "ADT106",
            "mesh shape %s multiplies out to %d devices but the strategy "
            "has %d replicas" % (ctx.mesh_shape, product, n),
            fixit="make the mesh axis sizes factor the replica count")
    gc = ctx.strategy.graph_config
    if gc.seq_axis and gc.seq_axis not in ctx.mesh_axis_sizes():
        yield error(
            "ADT110",
            "seq_axis %r is not in the mesh %s"
            % (gc.seq_axis, ctx.mesh_axis_sizes()),
            fixit="add the sequence axis to mesh_shape")
    for ax in (gc.batch_axes or []):
        if ax not in ctx.mesh_axis_sizes():
            yield error(
                "ADT110",
                "batch axis %r is not in the mesh %s"
                % (ax, ctx.mesh_axis_sizes()),
                fixit="batch_axes may only name mesh axes")


@rule
def _r_node_shape(ctx: Context) -> Iterable[Diagnostic]:
    for node in ctx.strategy.node_config:
        info_ = ctx.var_infos.get(node.var_name)
        trainable = (node.var_name in ctx.trainable) if ctx.var_infos else True
        if (trainable and node.synchronizer is None and not node.part_configs
                and not node.mp_axes):
            yield error(
                "ADT108",
                "trainable node carries no synchronizer, shards, or "
                "mp_axes — the lowering cannot synchronize its gradient",
                var=node.var_name,
                fixit="attach an AllReduceSynchronizer or PSSynchronizer")
        shape = tuple(info_.shape) if info_ is not None else None
        for d in check_partitioner_node(node, shape):
            yield d
        if node.mp_axes:
            for d in check_mp_axes_node(node.var_name, node.mp_axes, shape,
                                        ctx.mesh_axis_sizes()):
                yield d
            if node.partitioner:
                yield warning(
                    "ADT207",
                    "mp_axes and partitioner both set; mp_axes wins "
                    "(ZeRO+MP on one variable is unsupported)",
                    var=node.var_name,
                    fixit="drop the partitioner on model-parallel "
                          "variables")


# ------------------------------------------------------------- ADT3xx rules


def _is_ps(sync) -> bool:
    return getattr(sync, "kind", "") == "PS"


def _is_ar(sync) -> bool:
    return getattr(sync, "kind", "") == "AllReduce"


def _is_zero(sync) -> bool:
    return getattr(sync, "kind", "") == "ZeroSharded"


@rule
def _r_synchronizers(ctx: Context) -> Iterable[Diagnostic]:
    for node in ctx.strategy.node_config:
        info_ = ctx.var_infos.get(node.var_name)
        trainable = (node.var_name in ctx.trainable) if ctx.var_infos else True
        for owner, sync in ctx.synchronizers(node):
            if _is_ps(sync):
                if not sync.reduction_destination:
                    sev = error if trainable else warning
                    yield sev(
                        "ADT302",
                        "PS reduction_destination is empty — no device "
                        "owns this variable's update", var=owner,
                        fixit="set it to a host device, e.g. "
                              "'%s:CPU:0'" % (ctx.spec.chief if ctx.spec
                                              else "<chief>"))
                elif ctx.spec is not None:
                    canon = ctx.canonical(sync.reduction_destination)
                    if canon is None or (canon not in ctx.device_names
                                         and canon not in ctx.cpu_names):
                        yield error(
                            "ADT303",
                            "PS reduction_destination %r is not a device "
                            "of the resource spec"
                            % sync.reduction_destination, var=owner,
                            fixit="use a node address from the spec "
                                  "(host CPUs are valid PS destinations)")
                if sync.staleness < 0:
                    yield error("ADT304",
                                "staleness %d < 0" % sync.staleness,
                                var=owner)
                if sync.staleness > 0 and not sync.sync:
                    yield error(
                        "ADT304",
                        "staleness is a SYNC-training window; async PS "
                        "always reads the latest published version",
                        var=owner, fixit="drop staleness or set sync=True")
            comp = getattr(sync, "compressor", "") or ""
            comp_diags = check_compressor_name(comp, owner)
            for d in comp_diags:
                yield d
            if comp and comp != "NoneCompressor" and not comp_diags:
                if node.partitioner:
                    yield warning(
                        "ADT306",
                        "compressor %s is ignored — partitioned variables "
                        "sync via reduce-scatter" % comp, var=owner,
                        fixit="drop the compressor or the partitioner")
                elif node.mp_axes:
                    yield warning(
                        "ADT306",
                        "compressor %s is ignored — model-parallel "
                        "gradients reduce uncompressed over the "
                        "complement axes" % comp, var=owner)
                elif info_ is not None and getattr(info_, "sparse", False):
                    yield warning(
                        "ADT306",
                        "compressor %s is ignored — sparse-wire gradients "
                        "ship as (ids, values) pairs, already batch-sized"
                        % comp, var=owner)
                elif comp.split(":")[0] == "PowerSGDCompressor" and (
                        info_ is not None and len(info_.shape) < 2):
                    yield warning(
                        "ADT308",
                        "PowerSGD on a rank-%d tensor passes through "
                        "uncompressed" % len(info_.shape), var=owner)
                elif info_ is not None and not str(
                        getattr(info_, "dtype", "float32")).startswith(
                            ("float", "bfloat")):
                    yield warning(
                        "ADT306",
                        "compressor %s has no effect on dtype %s — the "
                        "reduced-precision cast only applies to float "
                        "gradients" % (comp, info_.dtype), var=owner)


@rule
def _r_async_all_or_nothing(ctx: Context) -> Iterable[Diagnostic]:
    """Mirror of ``AutoDist._validate_async``: async PS must be PURE
    host-PS — every trainable variable on the no-proxy PS path, no
    model-parallel mesh (collectives are lockstep)."""
    all_syncs = []
    for node in ctx.strategy.node_config:
        trainable = (node.var_name in ctx.trainable) if ctx.var_infos else True
        if not trainable:
            continue
        for owner, sync in ctx.synchronizers(node):
            all_syncs.append((node, owner, sync))
    is_async = any(_is_ps(s) and not s.sync for _, _, s in all_syncs)
    if not is_async:
        return
    for node, owner, sync in all_syncs:
        if _is_ar(sync) or _is_zero(sync):
            yield error(
                "ADT307",
                "async PS is all-or-nothing: this variable rides %s "
                "while others are async"
                % ("ZeroSharded" if _is_zero(sync) else "AllReduce"),
                var=owner,
                fixit="route every trainable variable through "
                      "PS(sync=False)")
        elif _is_ps(sync) and sync.sync:
            yield error(
                "ADT307",
                "async PS is all-or-nothing: this variable requests "
                "sync=True", var=owner,
                fixit="set sync=False on every variable or none")
        elif _is_ps(sync) and sync.local_replication:
            yield error(
                "ADT307",
                "async PS cannot use proxy (local_replication) variables "
                "— they are not host-resident", var=owner,
                fixit="set local_replication=False for async training")
    if ctx.mesh_shape:
        yield error(
            "ADT307",
            "async PS cannot combine with model-parallel mesh axes "
            "(collectives are lockstep); mesh %s" % ctx.mesh_shape,
            fixit="drop mesh_shape or train synchronously")


@rule
def _r_sparse_dense_path(ctx: Context) -> Iterable[Diagnostic]:
    """Sparse (embedding) variables on dense-only sync paths: their
    gradient is batch-row-sized, and a partitioned reduce-scatter (ZeRO)
    densifies it to the full table every step."""
    require = bool(ctx.strategy.graph_config.require_sparse)
    for node in ctx.strategy.node_config:
        info_ = ctx.var_infos.get(node.var_name)
        if info_ is None or not getattr(info_, "sparse", False):
            continue
        if not getattr(info_, "trainable", True):
            continue
        syncs = [s for _, s in ctx.synchronizers(node)]
        dense_partitioned = node.partitioner and any(_is_ar(s) for s in syncs)
        if dense_partitioned:
            sev = error if require else warning
            yield sev(
                "ADT309",
                "sparse (gather-indexed) variable is partitioned with "
                "AllReduce sync — the reduce-scatter densifies its "
                "row-sparse gradient to the full table every step",
                var=node.var_name,
                fixit="route embeddings to PS (Parallax) or keep them "
                      "unpartitioned so the (ids, values) sparse wire "
                      "engages")


@rule
def _r_wire_dtype(ctx: Context) -> Iterable[Diagnostic]:
    """Quantized-wire (``wire_dtype="int8"``) validity: the blockwise
    int8 codec only exists for dense float payloads on wires the lowering
    actually quantizes — sparse (ids, values) pairs have no absmax
    blocks, integer values no scale, and the partitioned / proxied /
    model-parallel paths never cross the quantized wire. A variable
    smaller than one scale block pays more sidecar than it saves
    (ADT311)."""
    from autodist_tpu.parallel.collectives import wire_block_size
    block = wire_block_size()
    for node in ctx.strategy.node_config:
        info_ = ctx.var_infos.get(node.var_name)
        for owner, sync in ctx.synchronizers(node):
            wd = getattr(sync, "wire_dtype", "fp32") or "fp32"
            if wd == "fp32":
                continue
            if wd != "int8":
                yield error(
                    "ADT310",
                    "unknown wire_dtype %r (allowed: fp32, int8)" % wd,
                    var=owner, fixit="use wire_dtype='int8' or drop it")
                continue
            if info_ is not None and getattr(info_, "sparse", False):
                yield error(
                    "ADT310",
                    "wire_dtype=int8 on a sparse variable — its gradient "
                    "ships as (ids, values) pairs, which the blockwise "
                    "codec cannot quantize", var=owner,
                    fixit="drop wire_dtype; the sparse wire is already "
                          "batch-sized")
                continue
            if info_ is not None and not str(
                    getattr(info_, "dtype", "float32")).startswith(
                        ("float", "bfloat")):
                yield error(
                    "ADT310",
                    "wire_dtype=int8 on dtype %s — absmax scaling only "
                    "exists for float payloads" % info_.dtype, var=owner,
                    fixit="drop wire_dtype on integer variables")
                continue
            comp = getattr(sync, "compressor", "") or ""
            if _is_ar(sync) and comp and comp != "NoneCompressor":
                yield error(
                    "ADT310",
                    "wire_dtype=int8 conflicts with compressor %s — the "
                    "wire codec and the gradient compressor both own the "
                    "payload transform" % comp, var=owner,
                    fixit="keep one: wire_dtype='int8' (blockwise wire "
                          "codec) or the compressor")
                continue
            if _is_ar(sync) and node.partitioner:
                yield warning(
                    "ADT310",
                    "wire_dtype=int8 is ignored — partitioned variables "
                    "sync via reduce-scatter, which the wire codec does "
                    "not cover", var=owner,
                    fixit="drop the partitioner or the wire_dtype")
                continue
            if node.mp_axes:
                yield warning(
                    "ADT310",
                    "wire_dtype=int8 is ignored — model-parallel "
                    "gradients reduce uncompressed over the complement "
                    "axes", var=owner)
                continue
            if _is_ps(sync) and sync.local_replication:
                yield warning(
                    "ADT310",
                    "wire_dtype=int8 is ignored — a proxied PS variable "
                    "is device-resident, no host wire exists", var=owner,
                    fixit="set local_replication=False for the host wire")
                continue
            if info_ is not None and info_.num_elements < block:
                yield warning(
                    "ADT311",
                    "quantizing a %d-element variable with %d-element "
                    "scale blocks: the padded block + f32 sidecar "
                    "outweighs the int8 saving"
                    % (info_.num_elements, block), var=owner,
                    fixit="keep variables smaller than one block "
                          "(ADT_WIRE_BLOCK=%d) on the fp32 wire" % block)


@rule
def _r_zero_sharded(ctx: Context) -> Iterable[Diagnostic]:
    """ZeRO-sharded update (``ZeroShardedSynchronizer``) validity.

    - ``ADT312`` (error): combinations the sharded update cannot lower —
      sparse variables (the reduce-scatter densifies the batch-row-sized
      gradient to the full table), ``mp_axes``/``partitioner`` storage on
      the same variable (the flat shard math owns the whole value), and
      mixing ZeroSharded with stale/async PS variables (the rs+ag pair
      is a lockstep collective every step; decoupled peers deadlock or
      apply against drifted params).
    - ``ADT313`` (warning): a variable smaller than one per-replica
      shard — the padding and two collective launches exceed the
      opt-state saving; keep it on plain AllReduce."""
    from autodist_tpu.strategy.zero_sharded_strategy import zero_shardable
    n_data = int(ctx.mesh_axis_sizes().get(const.DATA_AXIS,
                                           max(len(ctx.replicas), 1)))
    zero_owners = []
    decoupled_ps = []
    for node in ctx.strategy.node_config:
        info_ = ctx.var_infos.get(node.var_name)
        for owner, sync in ctx.synchronizers(node):
            if _is_ps(sync) and (not sync.sync or sync.staleness > 0):
                decoupled_ps.append(owner)
            if not _is_zero(sync):
                continue
            zero_owners.append(owner)
            if info_ is not None and getattr(info_, "sparse", False):
                yield error(
                    "ADT312",
                    "ZeroSharded on a sparse (gather-indexed) variable — "
                    "the reduce-scatter densifies its batch-row-sized "
                    "gradient to the full table every step", var=owner,
                    fixit="route embeddings to PS (Parallax) or plain "
                          "AllReduce so the (ids, values) sparse wire "
                          "engages")
            if node.mp_axes:
                yield error(
                    "ADT312",
                    "ZeroSharded cannot combine with mp_axes storage — "
                    "the sharded update owns the whole flat variable",
                    var=owner,
                    fixit="drop one: model-parallel storage or the "
                          "sharded update")
            if node.partitioner:
                yield error(
                    "ADT312",
                    "ZeroSharded cannot combine with a partitioner — "
                    "partitioned storage already shards the update "
                    "(reduce-scatter path)", var=owner,
                    fixit="drop the partitioner (ZeroSharded shards the "
                          "flat variable itself)")
            if (info_ is not None and not node.mp_axes
                    and not node.partitioner
                    and not getattr(info_, "sparse", False)
                    and not zero_shardable(info_, n_data)):
                yield warning(
                    "ADT313",
                    "ZeroSharded on a %d-element variable with %d "
                    "replicas: each shard is smaller than one element — "
                    "the padding + rs/ag launches outweigh the opt-state "
                    "saving" % (getattr(info_, "num_elements", 0), n_data),
                    var=owner,
                    fixit="keep variables smaller than one per-replica "
                          "shard on plain AllReduce")
    if zero_owners and decoupled_ps:
        yield error(
            "ADT312",
            "ZeroSharded vars %s mix with stale/async PS vars %s: the "
            "sharded update's rs+ag pair is a lockstep collective every "
            "step, but a stale/async PS window lets peers run decoupled "
            "steps" % (sorted(set(zero_owners))[:3],
                       sorted(set(decoupled_ps))[:3]),
            var=zero_owners[0],
            fixit="use sync staleness=0 PS beside ZeroSharded, or keep "
                  "the whole plan on one discipline")


# ----------------------------------------------------- ADT6xx numerics rules

_COMPUTE_DTYPES = ("f32", "bf16")


def _stored_half(info_) -> bool:
    """Is this variable's RESIDENT storage half precision? (``VarInfo``
    dtypes stringify as numpy names: ``bfloat16`` / ``float16``.)"""
    dt = str(getattr(info_, "dtype", "float32"))
    return "bfloat16" in dt or "float16" in dt


@rule
def _r_numerics(ctx: Context) -> Iterable[Diagnostic]:
    """ADT601/ADT602 at plan level — the f32-master discipline, provable
    before any trace (docs/performance.md):

    - a trainable variable STORED in bf16/f16 accumulates its gradient in
      that dtype (psum / PS-sum of half words — ADT601) *and* has no
      authoritative f32 copy to update (ADT602). ``ZeroSharded`` is
      exempt from both: its flat-shard math runs in f32 (``_pad_flat``
      casts up before the reduce-scatter) and the sharded optimizer step
      owns an f32 view — the arXiv 2004.13336 contract.
    - the managed bf16 tier (``compute_dtype="bf16"``) keeps params f32
      and casts a COPY down inside the loss, so it trips neither; an
      unknown tier is an ADT602 error because the lowering can guarantee
      nothing about it.
    """
    gc = ctx.strategy.graph_config
    cd = getattr(gc, "compute_dtype", "f32") or "f32"
    if cd not in _COMPUTE_DTYPES:
        yield error(
            "ADT602",
            "unknown compute_dtype %r (allowed: %s) — the lowering "
            "cannot guarantee an f32 master for an unknown compute tier"
            % (cd, "/".join(_COMPUTE_DTYPES)),
            fixit="use compute_dtype='bf16' (f32 master, bf16 compute) "
                  "or leave it 'f32'")
    for node in ctx.strategy.node_config:
        info_ = ctx.var_infos.get(node.var_name)
        if info_ is None or node.var_name not in ctx.trainable:
            continue
        if not _stored_half(info_):
            continue
        syncs = [s for _, s in ctx.synchronizers(node)]
        if syncs and all(_is_zero(s) for s in syncs):
            continue  # f32 shard math + f32 opt state: master survives
        dt = str(getattr(info_, "dtype", ""))
        yield error(
            "ADT601",
            "trainable %r is stored in %s: its gradient accumulates in "
            "half precision (every psum/PS-sum hop rounds the running "
            "sum)" % (node.var_name, dt), var=node.var_name,
            fixit="store params in f32 and set compute_dtype='bf16' "
                  "(the lowering casts a copy down for compute), or "
                  "sync via ZeroSharded (f32 shard accumulation)")
        yield error(
            "ADT602",
            "trainable %r is stored in %s with no f32 master copy — "
            "every optimizer apply rounds into the only copy of the "
            "weights" % (node.var_name, dt), var=node.var_name,
            fixit="keep the resident params f32 (compute_dtype='bf16' "
                  "gives the speed without losing the master), or use "
                  "ZeroSharded for an f32-sharded update")


def verify_numerics(strategy, model_item=None, resource_spec=None,
                    sentinel_policy=None, metadata=None) -> List[Diagnostic]:
    """ADT6xx — full plan-level numerics verdict for one strategy, no
    trace/lower/compile (the ADT501 pattern). Runs the registered
    ADT601/602 rule plus the two checks that need context :func:`verify`
    does not carry:

    - ``ADT603`` (warning): half-stored params WITHOUT the managed bf16
      tier — the loss inherits the compute dtype, so the value the
      divergence sentinel's EWMA judges is rounded before it is seen.
      (The managed tier casts the loss to f32 by construction, so
      ``compute_dtype="bf16"`` alone never trips this.)
    - ``ADT604`` (warning): half-precision compute armed with no enabled
      sentinel policy — aggressive precision with no skip/rollback net.

    ``metadata`` (a lowered ``DistributedStep.metadata``) is optional; it
    only sharpens messages, never gates a finding.
    """
    ctx = Context(strategy, model_item, resource_spec)
    out = list(_r_numerics(ctx))
    gc = strategy.graph_config
    cd = getattr(gc, "compute_dtype", "f32") or "f32"
    half_vars = sorted(
        n.var_name for n in strategy.node_config
        if n.var_name in ctx.trainable
        and _stored_half(ctx.var_infos.get(n.var_name)))
    half_armed = cd == "bf16" or bool(half_vars)
    if half_vars and cd != "bf16":
        out.append(warning(
            "ADT603",
            "loss/verdict will be computed in half precision: trainable "
            "%s stored in bf16/f16 without the managed compute tier — "
            "the sentinel's EWMA judges rounded loss values"
            % (half_vars[:3],), var=half_vars[0],
            fixit="store params f32 with compute_dtype='bf16' (the "
                  "lowering keeps the loss f32)"))
    if half_armed and not getattr(sentinel_policy, "enabled", False):
        out.append(warning(
            "ADT604",
            "half-precision compute (%s) is armed without an enabled "
            "sentinel policy — a loss spike from precision loss has no "
            "skip/rollback net"
            % ("compute_dtype=bf16" if cd == "bf16"
               else "bf16/f16 params"),
            fixit="arm SentinelPolicy(enabled=True) (docs/sentinel.md) "
                  "when training in half precision"))
    return sort_diagnostics(out)


# ------------------------------------------------------------- ADT4xx rules


@rule
def _r_pipeline(ctx: Context) -> Iterable[Diagnostic]:
    gc = ctx.strategy.graph_config
    stages = int(ctx.mesh_shape.get(const.PIPELINE_AXIS, 1))
    sched = gc.pp_schedule
    m = int(gc.pp_microbatches or 0)
    if sched is not None and sched not in _PIPELINE_SCHEDULES:
        yield error(
            "ADT402",
            "unknown pipeline schedule %r (have %s)"
            % (sched, list(_PIPELINE_SCHEDULES)))
        return
    if sched and stages <= 1:
        yield warning(
            "ADT402",
            "pp_schedule=%r set but the mesh has no %r axis — the "
            "schedule never engages" % (sched, const.PIPELINE_AXIS),
            fixit="add the pipeline axis to mesh_shape or drop the "
                  "schedule")
    if stages > 1:
        if m < 1:
            yield warning(
                "ADT401",
                "%d pipeline stages with no pp_microbatches recorded — "
                "the cost model prices a full bubble" % stages,
                fixit="set graph_config.pp_microbatches")
        elif m < stages:
            bubble = (stages - 1) / (stages - 1 + m)
            yield warning(
                "ADT401",
                "%d microbatches over %d stages leaves a %.0f%% fill/"
                "drain bubble" % (m, stages, 100 * bubble),
                var="", fixit="use at least as many microbatches as "
                              "stages (ideally 4x)")
        if sched == "interleaved":
            if int(gc.pp_virtual or 0) < 2:
                yield error(
                    "ADT402",
                    "interleaved schedule needs pp_virtual >= 2 (got %r)"
                    % gc.pp_virtual)
            if m and m % stages != 0:
                yield error(
                    "ADT402",
                    "interleaved schedule needs pp_microbatches (%d) "
                    "divisible by the stage count (%d)" % (m, stages))


@rule
def _r_ps_load_balance(ctx: Context) -> Iterable[Diagnostic]:
    load: Dict[str, float] = {}
    for node in ctx.strategy.node_config:
        info_ = ctx.var_infos.get(node.var_name)
        if info_ is None:
            continue
        syncs = [s for _, s in ctx.synchronizers(node)]
        ps = [s for s in syncs if _is_ps(s) and s.reduction_destination]
        for s in ps:
            host = str(s.reduction_destination).split(":")[0]
            load[host] = load.get(host, 0.0) + (
                float(getattr(info_, "byte_size", 0)) / max(len(ps), 1))
    if len(load) >= 2:
        total = sum(load.values())
        worst_host, worst = max(load.items(), key=lambda kv: kv[1])
        # with k hosts a balanced plan puts 1/k of the bytes on each; one
        # host carrying >75% of the total will bottleneck the push/pull
        # phase no matter how many peers idle beside it
        if total > 0 and worst / total > 0.75:
            yield warning(
                "ADT403",
                "PS host %s carries %.0f%% of the parameter bytes across "
                "%d PS hosts — it will bottleneck the push/pull phase"
                % (worst_host, 100.0 * worst / total, len(load)),
                fixit="use PSLoadBalancing or partition the heavy "
                      "variables")


def verify_sentinel(policy, metadata: dict) -> List[Diagnostic]:
    """ADT42x — health-sentinel configuration hazards, checked against a
    LOWERED program's metadata (``DistributedStep.metadata``); the Runner
    runs this whenever a policy is armed (docs/sentinel.md).

    - ``ADT420``: the policy is active but the program carries no
      in-graph guards (step_fn capture mode) — NaN/Inf detection and the
      in-graph skip are unavailable; the sentinel degrades to host-side
      loss monitoring, which can only roll back, never skip.
    - ``ADT421``: a stale/async PS apply window larger than the
      sentinel's skip window — a peer's delayed push can land a poisoned
      gradient AFTER the window that judged those steps closed, so a bad
      update can slip past the skip budget's accounting.
    """
    out: List[Diagnostic] = []
    if policy is None or not getattr(policy, "enabled", False):
        return out
    metadata = metadata or {}
    if not metadata.get("sentinel_guards", False):
        out.append(warning(
            "ADT420",
            "sentinel policy is active but the lowered program has no "
            "in-graph health guards — gradient/param NaN detection and "
            "the in-graph skip are unavailable (loss-only monitoring)",
            fixit="build with loss_fn mode (AutoDist.build) so the "
                  "guards compile into the step"))
    window = int(metadata.get("staleness", 0) or 0)
    if metadata.get("async"):
        window = max(window, int(const.ENV.ADT_PS_MAX_LAG.val))
    if window > int(policy.window_steps):
        out.append(warning(
            "ADT421",
            "PS apply window (%d steps stale/async lag) exceeds the "
            "sentinel skip window (%d steps) — a delayed poisoned push "
            "can apply after its window's verdict accounting closed"
            % (window, policy.window_steps),
            fixit="raise SentinelPolicy.window_steps above the "
                  "staleness/lag bound, or tighten the PS window"))
    return out


def fail_fast_model_axes(strategy) -> dict:
    """The model-parallel mesh axes that make a topology fail-fast for
    BOTH in-run shrink (ADT430) and planned preemption handoff (ADT432)
    — one predicate, so the two lints and the coordinator's runtime
    shrink decision can never disagree about what "fail-fast" means."""
    mesh_shape = strategy.graph_config.mesh_shape or {}
    return {ax: n for ax, n in mesh_shape.items()
            if ax != const.DATA_AXIS and int(n) > 1}


def verify_elastic(strategy, dead_worker: str = "") -> List[Diagnostic]:
    """ADT43x — can this job's topology survive an IN-RUN elastic shrink
    (``runtime/elastic.py``)? Shared by the pre-compile lint and the
    coordinator's runtime shrink decision (``_shrink_unsound_reason``), so
    the two can never disagree.

    - ``ADT430`` (error-strength for the shrink path): the strategy pins
      model-parallel mesh axes — a tensor/pipeline/expert-partitioned
      program spans the full mesh, and removing a process removes shards
      no survivor replicates. Recovery must go through the cross-topology
      checkpoint re-shard (whole-job restart) instead.
    - ``ADT431``: a PS group's ``reduction_destination`` lives on the dead
      worker — its authoritative host-resident state died with it, so the
      in-memory re-shard cannot cover it; the shrink is sound only with a
      committed checkpoint to fall back to for that state.
    """
    out: List[Diagnostic] = []
    model_axes = fail_fast_model_axes(strategy)
    if model_axes:
        out.append(warning(
            "ADT430",
            "strategy partitions state over model-parallel mesh axes %s — "
            "removing a process removes shards no survivor replicates, so "
            "the job cannot shrink in-run" % (model_axes,),
            fixit="rely on the whole-job checkpoint restart "
                  "(ADT_ELASTIC_SYNC without ADT_ELASTIC_INRUN), or use a "
                  "data-parallel strategy for in-run elasticity"))
    dead_host = (dead_worker or "").split(":")[0]
    for node in strategy.node_config:
        for leaf in (node.part_configs or [node]):
            sync = leaf.synchronizer or node.synchronizer
            dest = getattr(sync, "reduction_destination", "") or ""
            if dead_host and dest.split(":")[0] == dead_host:
                out.append(warning(
                    "ADT431",
                    "PS group of %r is owned by dying worker %s — its "
                    "host-resident state has no live replica; the shrink "
                    "must re-shard that state from the last-good "
                    "checkpoint" % (node.var_name, dead_worker),
                    var=node.var_name,
                    fixit="keep PS destinations on the chief, or "
                          "checkpoint at least once per restart window"))
                break
    return out


def verify_preemption(strategy) -> List[Diagnostic]:
    """ADT432 — preemption handoff armed on a topology the elasticity
    matrix marks fail-fast. The planned-handoff path
    (``runtime/preemption.py``) rides the in-run elastic shrink, and a
    model-parallel strategy cannot shrink (ADT430): every announced
    departure then degrades to rescue-checkpoint + whole-job restart —
    legal, but the operator armed a graceful-handoff feature that can
    never actually hand off. Warned at BUILD time, not at the first
    eviction (docs/failure_model.md has the per-family matrix)."""
    out: List[Diagnostic] = []
    model_axes = fail_fast_model_axes(strategy)
    if model_axes:
        out.append(warning(
            "ADT432",
            "preemption handoff is armed but the strategy partitions "
            "state over model-parallel mesh axes %s — the elasticity "
            "matrix marks this family fail-fast, so every planned "
            "departure degrades to rescue-checkpoint + whole-job "
            "restart instead of a live handoff" % (model_axes,),
            fixit="use a data-parallel strategy for live handoffs, or "
                  "accept the checkpoint-restart path and size "
                  "ADT_PREEMPT_DEADLINE_S to cover a full save"))
    return out


def verify_autoscale(policy, strategy=None,
                     max_queue: Optional[int] = None) -> List[Diagnostic]:
    """ADT44x — are a serving autoscaler's bounds sound for the strategy
    it will scale (``serving/autoscale.py``)? Run at controller
    construction, so an unsound clamp fails loudly at deploy time, not
    at the 3 a.m. shrink that would have fallen back to a checkpoint.

    - ``ADT440`` (error): the bounds arm a move the elasticity matrix
      forbids. A fail-fast model-parallel family (ADT430) cannot change
      replica count in-run at all, so any ``min_replicas <
      max_replicas`` would eventually command an impossible resize; a
      PS-backed family's floor is its distinct reduction-destination
      host count — shrinking below it retires a PS owner, and ADT431
      prices that as a checkpoint fallback, the exact thing the
      planned-departure contract promises to avoid.
    - ``ADT441`` (warning): thresholds that cannot fire or cannot
      settle — a grow trigger at/above ``max_queue`` (the tier sheds
      before the controller ever arms), or a zero sustain window with
      zero cooldowns (every sample may scale; the hysteresis band is
      the only flap guard left).
    """
    out: List[Diagnostic] = []
    if strategy is not None:
        model_axes = fail_fast_model_axes(strategy)
        if model_axes and policy.min_replicas < policy.max_replicas:
            out.append(error(
                "ADT440",
                "autoscale bounds [%d, %d] arm replica-count changes on "
                "a strategy that partitions state over model-parallel "
                "mesh axes %s — this family is fail-fast (ADT430): it "
                "can neither shrink nor grow in-run, so the first scale "
                "decision commands an impossible resize"
                % (policy.min_replicas, policy.max_replicas,
                   model_axes),
                fixit="pin min_replicas == max_replicas for this "
                      "family, or serve it from a data-parallel "
                      "strategy"))
        ps_hosts = set()
        for node in strategy.node_config:
            for leaf in (node.part_configs or [node]):
                sync = leaf.synchronizer or node.synchronizer
                dest = getattr(sync, "reduction_destination", "") or ""
                if dest:
                    ps_hosts.add(dest.split(":")[0])
        if ps_hosts and policy.min_replicas < len(ps_hosts):
            out.append(error(
                "ADT440",
                "min_replicas %d is below the PS-owner floor %d (distinct "
                "reduction-destination hosts %s) — an idle shrink would "
                "retire an owner and its authoritative host-resident "
                "state with it, forcing the checkpoint fallback (ADT431) "
                "the planned-departure path exists to avoid"
                % (policy.min_replicas, len(ps_hosts),
                   sorted(ps_hosts)),
                fixit="raise min_replicas to the PS-owner host count, "
                      "or concentrate reduction_destination on fewer "
                      "hosts"))
    if max_queue is not None and policy.queue_high >= max_queue:
        out.append(warning(
            "ADT441",
            "queue_high %.0f >= max_queue %d — submits shed at the "
            "queue bound before the grow trigger can ever arm, so the "
            "controller only ever observes a post-shed queue"
            % (policy.queue_high, max_queue),
            fixit="set queue_high well below max_queue (e.g. half) so "
                  "overload grows the fleet before it sheds clients"))
    if (policy.sustain_s == 0 and policy.grow_cooldown_s == 0
            and policy.shrink_cooldown_s == 0):
        out.append(warning(
            "ADT441",
            "sustain_s and both cooldowns are 0 — every poll may scale, "
            "leaving the hysteresis band as the only flap guard",
            fixit="give the policy a sustain window (seconds) or "
                  "non-zero per-direction cooldowns"))
    return out


def verify_decode(cache_bytes: float, param_bytes: float = 0.0,
                  slots: Optional[int] = None,
                  max_len: Optional[int] = None,
                  replicas: int = 1,
                  budget_bytes: Optional[float] = None,
                  resource_spec=None) -> List[Diagnostic]:
    """ADT442 — does a continuous-batching decode engine's armed KV
    cache (``max_len x slots``, both halves, ``serving/decode.py``) plus
    the gathered full params the decode step holds fit the per-device
    HBM budget the ADT501 memory pass checks against? Run at engine
    construction, so an over-provisioned slot pool warns at deploy time
    instead of OOMing at the first full-occupancy step.

    ``cache_bytes`` is the GLOBAL cache allocation (k + v); the slot dim
    shards over ``replicas``, so the per-device share is
    ``cache_bytes / replicas``; params count whole (the step gathers
    them full). The budget comes from ``budget_bytes`` or
    ``resource_spec.chip_hbm_bytes()``; with neither there is nothing to
    project against and no diagnostic is emitted — a made-up default
    budget would fire on every CPU test."""
    from autodist_tpu.analysis.memory import GIB
    out: List[Diagnostic] = []
    budget = budget_bytes
    if budget is None and resource_spec is not None:
        budget = resource_spec.chip_hbm_bytes()
    if not budget or budget <= 0:
        return out
    per_device = cache_bytes / max(int(replicas), 1) + param_bytes
    if per_device > budget:
        geometry = ""
        if slots is not None and max_len is not None:
            geometry = " (%d slots x %d max_len)" % (slots, max_len)
        out.append(warning(
            "ADT442",
            "decode engine armed with %.2f GiB of KV cache%s + %.2f GiB "
            "params projects to %.2f GiB per device — past the %.2f GiB "
            "HBM budget (ADT501's bound): the first fully-occupied "
            "decode step OOMs, not the lint" % (
                cache_bytes / GIB, geometry, param_bytes / GIB,
                per_device / GIB, budget / GIB),
            fixit="shrink slots or max_len, serve a smaller model, or "
                  "spread the slot dim over more batch replicas"))
    return out


@rule
def _r_staleness_topology(ctx: Context) -> Iterable[Diagnostic]:
    if ctx.spec is None or not ctx.spec.is_single_node():
        return
    stale = sorted({owner for node in ctx.strategy.node_config
                    for owner, s in ctx.synchronizers(node)
                    if _is_ps(s) and s.sync and s.staleness > 0})
    if stale:
        yield info(
            "ADT404",
            "staleness window configured on a single-node spec — "
            "cross-process pacing is a no-op here (%d vars)" % len(stale))


@rule
def _r_topology_collectives(ctx: Context) -> Iterable[Diagnostic]:
    """ADT52x plan-level pass: delegated to analysis/topology.py and
    gated on the spec declaring a multi-level topology, so flat specs
    (the default — ``topology()`` is None) lint exactly as before."""
    if ctx.spec is None or not hasattr(ctx.spec, "topology"):
        return
    if ctx.spec.topology() is None:
        return
    from autodist_tpu.analysis.topology import verify_topology
    for d in verify_topology(ctx.strategy, ctx.var_infos, ctx.spec):
        yield d
