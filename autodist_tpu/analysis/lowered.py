"""Lint the *lowered* program (jaxpr / StableHLO text).

The plan-level rules (``rules.py``) prove the Strategy well-formed; this
second pass inspects what the lowering actually emitted — via
``Runner.lowered_text()`` (StableHLO from ``jax.jit(...).lower()``) or a
jaxpr pretty-print — for hazards no plan-level rule can see:

- ``ADT405``: an all-gather materializing the FULL value of a
  model-parallel (``mp_axes``) parameter. ZeRO-partitioned storage
  all-gathers by design; model-parallel compute must consume the local
  shard, so a full-shape gather means a sharding rule failed to
  propagate and the "parallel" run pays replicated bandwidth.
- ``ADT406``: host transfers on the hot path (infeed/outfeed,
  host memory-space annotations, send/recv custom calls) — each one
  serializes the step on PCIe.
- ``ADT407``: collectives under divergent control flow
  (``stablehlo.if``/``case`` branches, jaxpr ``cond``): if the predicate
  ever differs across replicas, the collective deadlocks — the
  mis-sharded-collective hang this framework's fault harness exists to
  catch at runtime, surfaced at lint time instead.
- ``ADT408``: a host transfer inside a loop body (``stablehlo.while``,
  jaxpr ``scan``/``while``) — in the fused multi-step program
  (``Runner.lowered_text(..., fuse_steps=k)``) the loop body IS the
  microstep, so one such transfer serializes every microstep on PCIe and
  undoes exactly the k× host-round-trip saving fusion exists for.
- ``ADT409``: the overlap schedule is armed (``overlap_armed=True``) but
  the program contains no ``optimization_barrier`` chain — the k-stage
  bucketed sync degenerated to a single sync unit, so XLA's collective
  combiner is free to merge every gradient reduce back into one epilogue
  and no communication hides behind the backward pass.

Text-based on purpose: it works on any ``as_text()`` dump (including ones
saved from a real TPU run) without re-lowering, and it has no opinion
about which JAX version produced the text.
"""
import re
from typing import Dict, List, Optional, Sequence, Tuple

from autodist_tpu.analysis.diagnostics import (Diagnostic, sort_diagnostics,
                                               warning)

# StableHLO / MHLO / jaxpr spellings of cross-replica collectives.
COLLECTIVE_TOKENS = (
    "all_gather", "all-gather",
    "all_reduce", "all-reduce",
    "reduce_scatter", "reduce-scatter",
    "collective_permute", "collective-permute",
    "all_to_all", "all-to-all",
    "psum", "psum_scatter", "ppermute", "pgather",
)

_GATHER_TOKENS = ("all_gather", "all-gather")

# substrings marking host traffic in StableHLO dumps
_HOST_TOKENS = ("infeed", "outfeed", "send_to_host", "recv_from_host",
                "SendToHost", "RecvFromHost", "pinned_host",
                "annotate_device_placement", "host_compute")

# result tensor type, e.g. tensor<128x512xf32>
_TENSOR_RE = re.compile(r"tensor<([0-9]+(?:x[0-9]+)*)x[a-z][a-z0-9]*>")
# StableHLO/MHLO region ops delimit their bodies with BRACES; jaxpr
# pretty-prints delimit the whole statement — params AND sub-jaxprs —
# with the op's square BRACKET (``scan[ ... jaxpr={...} ... ] a b``), so
# the two families need different span tracking. A jaxpr ``while[``
# carries TWO sub-jaxprs (cond_jaxpr + body_jaxpr) and nested scans
# re-open brackets inside the span, which is why brace-only tracking
# used to lose every region after the first (one level deep).
_BRANCH_BRACE_TOKENS = ("stablehlo.if", "stablehlo.case", "mhlo.if",
                        "mhlo.case")
_BRANCH_BRACKET_TOKENS = ("cond[",)
_LOOP_BRACE_TOKENS = ("stablehlo.while", "mhlo.while")
_LOOP_BRACKET_TOKENS = ("scan[", "while[")

# StableHLO / jaxpr spellings of the sequencing barrier the overlap
# schedule chains stages with (k stages emit k-1 of them)
_BARRIER_TOKENS = ("optimization_barrier", "opt-barrier")


def _line_tensor_shapes(line: str) -> List[Tuple[int, ...]]:
    return [tuple(int(x) for x in m.group(1).split("x"))
            for m in _TENSOR_RE.finditer(line)]


def lint_lowered_text(text: str,
                      mp_full_shapes: Optional[Dict[str, Sequence[int]]] = None,
                      overlap_armed: bool = False) -> List[Diagnostic]:
    """Scan a lowered-program dump for communication hazards.

    ``mp_full_shapes`` maps model-parallel variable names to their FULL
    (global) shapes; an all-gather whose result matches one of them is
    flagged as ADT405. Without it the all-gather check is skipped (there
    is no way to tell an accidental full gather from a legitimate one).
    ``overlap_armed`` says the plan lowered with the bucketed overlap
    schedule (``DistributedStep.metadata["overlap"]``); the ADT409 check
    then verifies the sequencing chain actually reached the program.
    """
    out: List[Diagnostic] = []
    full_shapes = {tuple(int(d) for d in shape): name
                   for name, shape in (mp_full_shapes or {}).items()}
    # StableHLO regions: depth of every open if/case (and while) region,
    # tracked by brace nesting; an opener whose braces land on a LATER
    # line is held pending (counted — two openers can be pending) until
    # its first ``{``. jaxpr statements: bracket-depth spans of every
    # open ``scan[``/``while[``/``cond[`` — the whole span (params and
    # every sub-jaxpr, however deeply nested) is the region.
    brace_depth = 0
    bracket_depth = 0
    branch_starts: List[int] = []
    loop_starts: List[int] = []
    branch_spans: List[int] = []
    loop_spans: List[int] = []
    pending_branch = 0
    pending_loop = 0
    flagged_branch = False
    seen_host: set = set()
    seen_loop_host: set = set()
    seen_gather: set = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        lowered_line = line.strip()
        is_branch_open = any(tok in line for tok in _BRANCH_BRACE_TOKENS)
        is_loop_open = any(tok in line for tok in _LOOP_BRACE_TOKENS)
        has_collective = any(tok in line for tok in COLLECTIVE_TOKENS)
        if any(tok in line for tok in _BRANCH_BRACKET_TOKENS):
            branch_spans.append(bracket_depth)
        if any(tok in line for tok in _LOOP_BRACKET_TOKENS):
            loop_spans.append(bracket_depth)
        in_branch = (branch_starts or pending_branch or is_branch_open
                     or branch_spans)
        in_loop = (loop_starts or pending_loop or is_loop_open
                   or loop_spans)
        if in_branch and has_collective and not flagged_branch:
            out.append(warning(
                "ADT407",
                "collective inside a conditional branch (line %d: %s) — "
                "if the predicate ever differs across replicas this "
                "deadlocks" % (lineno, lowered_line[:80]),
                fixit="hoist the collective out of the branch or prove "
                      "the predicate replica-uniform"))
            flagged_branch = True  # one finding per program is enough signal
        if has_collective and any(tok in line for tok in _GATHER_TOKENS):
            for shape in _line_tensor_shapes(line):
                name = full_shapes.get(shape)
                if name is not None and name not in seen_gather:
                    seen_gather.add(name)
                    out.append(warning(
                        "ADT405",
                        "all-gather materializes the full value of "
                        "model-parallel variable (shape %s, line %d) — "
                        "its compute should consume the local shard"
                        % (list(shape), lineno),
                        var=name,
                        fixit="check the model's mp_rules cover every "
                              "consumer of this variable"))
        for tok in _HOST_TOKENS:
            if tok not in line:
                continue
            if in_loop:
                # inside a while/scan body the transfer repeats PER
                # ITERATION — the more specific ADT408 supersedes ADT406
                # here (docs/linting.md). In the fused multi-step program
                # the loop body IS the microstep, so this is the exact
                # per-step host round-trip fusion exists to remove.
                if tok not in seen_loop_host:
                    seen_loop_host.add(tok)
                    out.append(warning(
                        "ADT408",
                        "host transfer inside a while/scan body (%s, line "
                        "%d) — it repeats every iteration; in a fused "
                        "multi-step program that is a per-microstep PCIe "
                        "round-trip, undoing the superstep fusion"
                        % (tok, lineno),
                        fixit="hoist the transfer out of the loop; in the "
                              "fused engine, pull PS values once per "
                              "superstep (the fused carry), never per "
                              "microstep"))
            elif tok not in seen_host:
                seen_host.add(tok)
                out.append(warning(
                    "ADT406",
                    "host transfer on the hot path (%s, line %d) — each "
                    "one serializes the step on PCIe" % (tok, lineno),
                    fixit="keep the step device-resident; host-PS pulls "
                          "belong in the store, not the compiled step"))
        opens = line.count("{")
        if opens > 0:
            if is_branch_open or pending_branch:
                branch_starts.append(brace_depth)
                pending_branch = max(pending_branch - 1, 0)
            if is_loop_open or pending_loop:
                loop_starts.append(brace_depth)
                pending_loop = max(pending_loop - 1, 0)
        else:
            if is_branch_open:
                pending_branch += 1  # braces arrive on a later line
            if is_loop_open:
                pending_loop += 1
        brace_depth += opens - line.count("}")
        while branch_starts and brace_depth <= branch_starts[-1]:
            branch_starts.pop()
        while loop_starts and brace_depth <= loop_starts[-1]:
            loop_starts.pop()
        bracket_depth += line.count("[") - line.count("]")
        while branch_spans and bracket_depth <= branch_spans[-1]:
            branch_spans.pop()
        while loop_spans and bracket_depth <= loop_spans[-1]:
            loop_spans.pop()
    if overlap_armed:
        barriers = sum(text.count(tok) for tok in _BARRIER_TOKENS)
        if barriers == 0:
            out.append(warning(
                "ADT409",
                "overlap schedule armed but the lowered program has no "
                "optimization_barrier chain — the bucketed sync "
                "degenerated to a single stage, so XLA may combine every "
                "gradient collective back into one serialized epilogue "
                "and nothing hides behind the backward pass",
                fixit="split the gradient sync into >= 2 stages: shrink "
                      "chunk_size (more, smaller buckets) or drop "
                      "overlap and keep the plain epilogue"))
    return sort_diagnostics(out)


def mp_full_shapes_of(distributed_step) -> Dict[str, Tuple[int, ...]]:
    """Full global shapes of the model-parallel variables of a compiled
    ``DistributedStep`` — the ``mp_full_shapes`` input of
    :func:`lint_lowered_text`."""
    infos = distributed_step.model_item.var_infos
    out: Dict[str, Tuple[int, ...]] = {}
    for name, layout in distributed_step.layouts.items():
        if getattr(layout, "mp_axes", ()):
            info_ = infos.get(name)
            if info_ is not None:
                out[name] = tuple(info_.shape)
    return out


def lint_runner(runner, batch, state=None,
                fuse_steps: int = 1) -> List[Diagnostic]:
    """Lower the runner's step for ``batch`` and lint the StableHLO.

    The single implementation behind ``Runner.lint_lowered`` — keep the
    two entry points from drifting. ``fuse_steps=k > 1`` lints the fused
    k-microstep scan program instead: its scan body is the microstep, so
    ADT408 findings there mean a per-microstep host round-trip survived
    the fusion. The ADT60x numerics dtype-flow pass
    (``analysis/numerics.py``) rides the same lowered text."""
    from autodist_tpu.analysis import numerics
    text = runner.lowered_text(batch, state, fuse_steps=fuse_steps)
    out = lint_lowered_text(
        text, mp_full_shapes_of(runner.distributed_step),
        overlap_armed=bool(
            runner.distributed_step.metadata.get("overlap", False)))
    out.extend(numerics.lint_text(text))
    return sort_diagnostics(out)
