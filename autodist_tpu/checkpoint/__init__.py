"""Checkpoint layer (reference ``autodist/checkpoint/``)."""
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.checkpoint.sharded import ShardedSaver
from autodist_tpu.checkpoint.saved_model_builder import (SavedModelBuilder,
                                                         export_for_serving)

__all__ = ["Saver", "ShardedSaver", "SavedModelBuilder", "export_for_serving"]
