"""Checkpoint layer (reference ``autodist/checkpoint/``)."""
from autodist_tpu.checkpoint import integrity
from autodist_tpu.checkpoint.integrity import CheckpointDamaged
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.checkpoint.sharded import ShardedSaver
from autodist_tpu.checkpoint.saved_model_builder import (SavedModelBuilder,
                                                         export_for_serving)


def latest_checkpoint(directory):
    """(step, saver) of the newest committed AND valid checkpoint in
    ``directory`` across BOTH formats (plain Saver and ShardedSaver), or
    (None, None) — ``latest()`` runs the fast integrity validation, so a
    torn or damaged newest step is skipped here, not discovered at
    restore time; checkpoints stamped ``healthy: false`` (committed under
    a bad sentinel verdict) are skipped the same way, so auto-resume and
    sentinel rollback never load a poisoned state. The single authority
    for "is there something to restore, and through which saver" —
    auto-resume (Runner.init), sentinel rollback
    (``runtime/sentinel.py``) and the sync-elastic restart gate
    (coordinator) must agree on the answer."""
    best = (None, None)
    for saver_cls in (Saver, ShardedSaver):
        try:
            saver = saver_cls(directory=directory)
            base = saver.latest()
        except OSError:
            continue
        if base is not None:
            step = int(base.rsplit("ckpt-", 1)[1])
            if best[0] is None or step > best[0]:
                best = (step, saver)
    return best


__all__ = ["Saver", "ShardedSaver", "SavedModelBuilder",
           "export_for_serving", "latest_checkpoint", "integrity",
           "CheckpointDamaged"]
