"""Checkpoint lifecycle CLI — ``python -m autodist_tpu.checkpoint``.

Three subcommands over a checkpoint directory (both formats — plain
:class:`Saver` and :class:`ShardedSaver` — are handled together):

- ``ls``    — every checkpoint step with its format, validity state
  (``committed`` / ``torn`` / ``corrupt``), file count and total bytes.
- ``fsck``  — FULL integrity verification: every recorded crc32 is
  re-computed from the bytes on disk (``integrity.scan(deep=True)``).
  Exit 1 when any committed checkpoint is corrupt (or, with
  ``--strict``, when torn save attempts are present); exit 0 on a clean
  directory.
- ``gc``    — prune: ``--keep N`` keeps the newest N committed
  checkpoints per format; ``--orphans`` removes failed-attempt debris
  (torn attempts, ``.tmp`` leftovers) — only run it when no save is in
  flight, it drops the newest-step safety guard the savers' automatic
  GC keeps; ``--damaged`` removes checkpoints fsck classifies corrupt
  (the fsck-found-damage → gc workflow — restore already refuses them,
  this stops every future resume from re-skipping the wreck).
  ``--dry-run`` prints what would go.

Exit codes: 0 ok, 1 damage found (fsck), 2 usage error.
"""
import argparse
import json
import os
import sys
from typing import List, Optional

from autodist_tpu import const
from autodist_tpu.checkpoint import integrity


def _human_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return ("%d %s" % (n, unit) if unit == "B"
                    else "%.1f %s" % (n, unit))
        n /= 1024.0
    return "%d B" % n


def _print_table(statuses: List[integrity.CheckpointStatus],
                 verbose: bool = True):
    if not statuses:
        print("(no checkpoints)")
        return
    print("%6s  %-8s %-10s %-7s %5s  %10s  %s"
          % ("STEP", "FORMAT", "STATE", "HEALTHY", "FILES", "BYTES",
             "PROBLEMS"))
    for s in statuses:
        problems = "-"
        if s.problems:
            problems = "; ".join(s.problems[:2 if verbose else 1])
            if len(s.problems) > 2:
                problems += " (+%d more)" % (len(s.problems) - 2)
        # the sentinel's stamp: yes / NO (saved under a bad verdict —
        # auto-resume skips it) / "?" for pre-stamp checkpoints
        # (healthy-unknown: resumable)
        healthy = {True: "yes", False: "NO"}.get(s.healthy, "?")
        print("%6d  %-8s %-10s %-7s %5d  %10s  %s"
              % (s.step, s.fmt, s.state, healthy, len(s.files),
                 _human_bytes(s.bytes), problems))


def _cmd_ls(args) -> int:
    statuses = integrity.scan(args.dir)
    if args.json:
        print(json.dumps([s.to_dict() for s in statuses], indent=2))
    else:
        _print_table(statuses)
    return 0


def _cmd_fsck(args) -> int:
    statuses = integrity.scan(args.dir, deep=True)
    if args.step is not None:
        statuses = [s for s in statuses if s.step == args.step]
        if not statuses:
            print("fsck: no checkpoint files for step %d in %s"
                  % (args.step, args.dir), file=sys.stderr)
            return 1
    if args.json:
        print(json.dumps([s.to_dict() for s in statuses], indent=2))
    else:
        _print_table(statuses)
    corrupt = [s for s in statuses if s.state == integrity.CORRUPT]
    torn = [s for s in statuses if s.state == integrity.TORN]
    if not args.json:
        print("fsck: %d checkpoint(s), %d committed, %d torn attempt(s), "
              "%d corrupt, %d stamped unhealthy"
              % (len(statuses),
                 sum(1 for s in statuses if s.committed),
                 len(torn), len(corrupt),
                 sum(1 for s in statuses if s.healthy is False)))
    if corrupt:
        return 1
    if torn and args.strict:
        return 1
    return 0


def _cmd_gc(args) -> int:
    if args.keep is None and not args.orphans and not args.damaged:
        print("gc: nothing to do — pass --keep N, --orphans and/or "
              "--damaged", file=sys.stderr)
        return 2
    removed: List[str] = []
    statuses = integrity.scan(args.dir)
    if args.keep is not None:
        if args.keep < 1:
            print("gc: --keep must be >= 1", file=sys.stderr)
            return 2
        for fmt in ("plain", "sharded"):
            committed = [s for s in statuses
                         if s.fmt == fmt and s.committed]
            for victim in committed[:-args.keep] if args.keep else []:
                removed.extend(victim.files)
    if args.orphans:
        victims, _ = integrity.gc_candidates(args.dir, "plain",
                                             force_orphans=True)
        removed.extend(victims)
        victims, _ = integrity.gc_candidates(args.dir, "sharded",
                                             force_orphans=True)
        removed.extend(victims)
    if args.damaged:
        # deep fsck pass so a crc-only mismatch is caught too — a step
        # restore would refuse must be removable without hand-rm
        for s in integrity.scan(args.dir, deep=True):
            if s.state == integrity.CORRUPT:
                removed.extend(s.files)
    removed = sorted(set(removed))
    for f in removed:
        print("%s %s" % ("would remove" if args.dry_run else "removed", f))
        if not args.dry_run:
            try:
                os.remove(os.path.join(args.dir, f))
            except FileNotFoundError:
                pass
    if not removed:
        print("gc: nothing to remove")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m autodist_tpu.checkpoint",
        description="Inspect, verify and prune autodist_tpu checkpoint "
                    "directories (both plain and sharded formats).")
    parser.add_argument("--dir", default=None,
                        help="checkpoint directory (default: ADT_CKPT_DIR)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_ls = sub.add_parser("ls", help="list checkpoints with validity state")
    p_ls.add_argument("--json", action="store_true")
    p_ls.set_defaults(fn=_cmd_ls)
    p_fsck = sub.add_parser(
        "fsck", help="full checksum verification; exit 1 on damage")
    p_fsck.add_argument("--step", type=int, default=None,
                        help="verify only this step")
    p_fsck.add_argument("--strict", action="store_true",
                        help="also fail (exit 1) on torn save attempts")
    p_fsck.add_argument("--json", action="store_true")
    p_fsck.set_defaults(fn=_cmd_fsck)
    p_gc = sub.add_parser("gc", help="prune checkpoints / failed attempts")
    p_gc.add_argument("--keep", type=int, default=None,
                      help="keep only the newest N committed checkpoints "
                           "per format")
    p_gc.add_argument("--orphans", action="store_true",
                      help="remove ALL failed-attempt debris (torn "
                           "attempts, .tmp files) — only when no save is "
                           "in flight")
    p_gc.add_argument("--damaged", action="store_true",
                      help="remove checkpoints a deep fsck classifies "
                           "corrupt (restore skips them anyway)")
    p_gc.add_argument("--dry-run", action="store_true")
    p_gc.set_defaults(fn=_cmd_gc)
    args = parser.parse_args(argv)
    if args.dir is None:
        args.dir = const.ENV.ADT_CKPT_DIR.val
    if not os.path.isdir(args.dir):
        print("checkpoint directory %s does not exist" % args.dir,
              file=sys.stderr)
        return 2
    return args.fn(args)


if __name__ == "__main__":  # pragma: no cover - exercised via __main__.py
    sys.exit(main())
