"""Sharded checkpoint save/restore — no process ever holds the full tree.

The plain :class:`~autodist_tpu.checkpoint.saver.Saver` gathers every
variable to one host before writing (the reference's original-layout
property) — correct, but it caps model size at one host's RAM. The
reference avoided that for partitioned variables by saving each shard as a
*slice* of the original tensor with ``SaveSliceInfo`` (reference
``autodist/kernel/partitioner.py:292-347``), so no process materialized the
full set. This module is the TPU-native equivalent:

- **save**: every process writes ONE npz holding exactly the array shards
  it owns — for each device leaf, the addressable shards with
  ``replica_id == 0`` (the unique-writer rule: every distinct slice of a
  sharded array has exactly one replica-0 holder across the whole mesh);
  for host-PS variables, the store shards this process owns (all of them
  on the chief in mirror mode, the owned groups in async serving mode).
  Peak host memory during save = this process's shards, never the tree.
- **commit**: a per-process index file lands next to each shard file; the
  chief waits for all of them (file barrier — the checkpoint directory
  must be SHARED across hosts, the same NFS assumption as the reference's
  chief-only saving, reference ``autodist/autodist.py:40-41``) and then
  writes the meta file. A checkpoint without its meta file is invisible.
- **restore**: same mesh topology required; each process reads back only
  the slices its own devices need (``Sharding.devices_indices_map``) and
  reassembles global arrays with
  ``jax.make_array_from_single_device_arrays`` — again never the full
  tree. Host-PS shards reload into the store.
- **export_full**: converts a sharded checkpoint into a plain
  :class:`Saver`-format one (original unpadded layout, ``numpy.load``-able
  with no framework) one LEAF at a time — the vanilla-reload property is
  preserved as an export, exactly as VERDICT r3 prescribed.

File layout for step N (all under ``directory``)::

    ckpt-N.shard-p<pid>.npz         this process's shards
    ckpt-N.shard-p<pid>.index.json  its key list (the barrier token)
    ckpt-N.shard-meta.json          chief-written commit point

npz keys: ``P|<var>|<a:b,c:d>`` (params), ``O|<leaf>|<...>`` (optimizer
state), ``S|<leaf>|<...>`` (sync/compressor state), ``H|<var>::<si>``
(host-PS shard value), ``Ho|<var>::<si>|<leaf>`` (host-PS shard optimizer
leaf). Slice tokens are in the PADDED global coordinates of the stored
array; the meta file records how to unpad.
"""
import json
import os
import threading
import time
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.checkpoint import integrity
from autodist_tpu.checkpoint.integrity import CheckpointDamaged
from autodist_tpu.checkpoint.saver import BackgroundWriter
from autodist_tpu.kernel.common import variable_utils
from autodist_tpu.runtime.faultinject import checkpoint_fault
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

_FORMAT = "autodist_tpu.sharded.v1"


# ----------------------------------------------------------------- tokens


def _index_token(index, shape) -> str:
    """Stable string for a shard's slice of the global array, with slice
    bounds made concrete (``slice(None)`` -> ``0:dim``)."""
    if not shape:
        return "-"
    parts = []
    for sl, dim in zip(index, shape):
        start, stop, _ = sl.indices(dim)
        parts.append("%d:%d" % (start, stop))
    return ",".join(parts)


def _token_slices(token: str) -> Tuple[slice, ...]:
    if token == "-":
        return ()
    return tuple(slice(*map(int, p.split(":"))) for p in token.split(","))


def _spec_to_json(spec: P) -> list:
    out = []
    for e in spec:
        out.append(list(e) if isinstance(e, (tuple, list)) else e)
    return out


def _spec_from_json(entries: list) -> P:
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


def _group_keys(meta: dict) -> Dict[str, List[str]]:
    """meta['keys'] grouped by their first two ``|`` segments ('P|emb',
    'Ho|emb::0', ...) — one pass, so restore/export look up each leaf's
    keys directly instead of scanning the whole key list per leaf."""
    out: Dict[str, List[str]] = {}
    for key in meta["keys"]:
        parts = key.split("|", 2)
        out.setdefault("|".join(parts[:2]), []).append(key)
    return out


def _leaf_unpad(name: str, shape, layouts) -> Optional[Tuple[int, int]]:
    """(axis, orig_dim) when the stored leaf carries partition padding the
    original layout does not have; None otherwise. ``layouts`` maps leaf
    names (variables, and optimizer leaves pre-resolved to their
    variable's layout by the caller) to VarLayout."""
    lay = layouts.get(name)
    if lay is None:
        return None
    if (lay.partitioned and lay.padded_dim != lay.orig_dim
            and len(shape) > lay.axis and shape[lay.axis] == lay.padded_dim):
        return (lay.axis, lay.orig_dim)
    return None


class _StreamingNpzWriter:
    """npz writer that streams one array at a time (zipfile + np.save), so
    peak memory while saving is a single shard, not the whole file.
    ``checksums`` maps each written key to ``[crc32, nbytes]`` of its
    serialized npy stream — recorded in the index file so fsck and the
    restore fallback can prove the bytes on disk are the bytes written."""

    def __init__(self, path: str):
        self._zf = zipfile.ZipFile(path, "w", zipfile.ZIP_STORED)
        self.checksums: Dict[str, list] = {}

    def write(self, key: str, arr: np.ndarray):
        with self._zf.open(key + ".npy", "w", force_zip64=True) as f:
            cf = integrity.Crc32Writer(f)
            np.save(cf, np.asarray(arr))
        self.checksums[key] = [cf.crc, cf.nbytes]

    def close(self):
        self._zf.close()


class ShardedSaver:
    """Save/restore distributed state with per-process shard files.

    Same call contract as :class:`Saver` — ``save()`` must run on EVERY
    process (each writes its own file); ``restore()`` likewise. The
    checkpoint ``directory`` must be shared across hosts (NFS/GCS —
    the reference's chief-only-on-NFS deployment assumption).

    ``async_save=True`` copies this process's shards to host synchronously
    (the step may donate the buffers right after) but moves file writes and
    the chief's commit wait to a background thread.
    """

    def __init__(self, directory: Optional[str] = None, max_to_keep: int = 5,
                 async_save: bool = False, barrier_timeout: float = 300.0):
        self.directory = directory or const.DEFAULT_CHECKPOINT_DIR
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.barrier_timeout = barrier_timeout
        self._writer = BackgroundWriter("adt-sharded-ckpt")
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------------ save

    @staticmethod
    def _mesh_suffix(dstep) -> str:
        """Device-key namespace. Global mesh (one SPMD program spanning
        processes): empty — the replica-0 rule gives each slice exactly one
        writer. Process-LOCAL mesh (between-graph mode, e.g. async PS):
        every process runs its own program with its own device state, so
        each process's device keys carry ``@p<pid>`` and restore reads its
        own."""
        if jax.process_count() == 1:
            return ""
        pid = jax.process_index()
        if all(d.process_index == pid
               for d in np.asarray(dstep.mesh.devices).flat):
            return "@p%d" % pid
        return ""

    def _device_tree_entries(self, kind: str, tree, collect, leaves_meta,
                             layouts, suffix: str):
        """Collect this process's replica-0 shards of every leaf. Replicated
        leaves have their single replica-0 shard on exactly one device
        globally, so exactly one process writes them."""
        names, leaves, _ = variable_utils.flatten_named(tree)
        for name, leaf in zip(names, leaves):
            if not isinstance(leaf, jax.Array):
                continue  # host-side scalar in a device tree: not ours
            shape = tuple(leaf.shape)
            leaves_meta["%s|%s" % (kind, name)] = {
                "shape": list(shape),
                "dtype": str(np.dtype(leaf.dtype)),
                "spec": _spec_to_json(leaf.sharding.spec),
                "unpad": _leaf_unpad(name, shape, layouts),
            }
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue
                key = "%s|%s|%s%s" % (kind, name,
                                      _index_token(shard.index, shape),
                                      suffix)
                collect(key, shard.data)

    def save(self, runner_or_step, state=None, step: Optional[int] = None
             ) -> Optional[str]:
        """Write this process's shard file; the chief commits the meta once
        every process's index file has landed. Returns the checkpoint base
        path."""
        if hasattr(runner_or_step, "distributed_step"):  # Runner
            dstep = runner_or_step.distributed_step
            state = state if state is not None else runner_or_step.state
        else:
            dstep = runner_or_step
        if state is None:
            raise ValueError("no state to save")
        from autodist_tpu.checkpoint.saver import (sentinel_health_stamp,
                                                   sentinel_save_vetoed)
        # epoch fence BEFORE any file write: a zombie's late shard save
        # must leave the checkpoint directory untouched (runtime/elastic.py)
        from autodist_tpu.runtime import elastic
        elastic.maybe_fence("ckpt.save")
        if sentinel_save_vetoed(runner_or_step):
            return None
        healthy = sentinel_health_stamp(runner_or_step)
        if step is None:
            step = int(jax.device_get(state.step))
        base = os.path.join(self.directory, "ckpt-%d" % step)
        pid = jax.process_index()
        nproc = jax.process_count()
        # a crash-resume can re-save the SAME step: this attempt's files
        # must never mix with a previous attempt's. Remove our own stale
        # index up front, and couple index<->npz with a per-process nonce
        # the commit verifies (stale index + replaced npz can't pair up).
        try:
            os.remove("%s.shard-p%d.index.json" % (base, pid))
        except FileNotFoundError:
            pass
        nonce = "%d-%d-%s" % (pid, os.getpid(), os.urandom(8).hex())

        # ---- collect this process's entries. Sync save streams: each
        # producer is materialized one at a time inside write() (peak = one
        # shard). Async save must copy up front — the caller may donate the
        # state's buffers the moment save() returns.
        entries: List[Tuple[str, Any]] = []
        leaves_meta: Dict[str, dict] = {}

        if self.async_save:
            def collect(key, data):
                entries.append((key, np.asarray(data)))
        else:
            def collect(key, data):
                entries.append((key, lambda d=data: np.asarray(d)))

        opt_layouts = dict(dstep.layouts)
        # optimizer leaves resolve to their variable's layout by name
        names_o, leaves_o, _ = variable_utils.flatten_named(state.opt_state)
        for n, l in zip(names_o, leaves_o):
            var = variable_utils.match_state_to_var(
                n, tuple(getattr(l, "shape", ())), dstep.model_item.var_infos,
                dstep.layouts)
            if var and var in dstep.layouts:
                opt_layouts[n] = dstep.layouts[var]
        suffix = self._mesh_suffix(dstep)
        with tel.span("ckpt.collect", "ckpt", step=int(step),
                      mode="async" if self.async_save else "sync"):
            self._device_tree_entries("P", state.params, collect,
                                      leaves_meta, dstep.layouts, suffix)
            self._device_tree_entries("O", state.opt_state, collect,
                                      leaves_meta, opt_layouts, suffix)
            self._device_tree_entries("S", state.sync_state, collect,
                                      leaves_meta, {}, suffix)
        checkpoint_fault("collect", step=int(step))

        ps_meta: Dict[str, dict] = {}
        store = dstep.ps_store
        if store is not None:
            dstep.flush_ps()  # in-flight pipelined push lands first
            store.drain()
            for name, plan in sorted(store.plans.items()):
                ranges = plan.shard_ranges() if plan.partitioned else None
                n_shards = len(ranges) if ranges else 1
                ps_meta[name] = {
                    "axis": plan.axis, "nshards": n_shards,
                    # explicit split-axis sizes so a restore under a
                    # DIFFERENT shard layout can re-slice without reading
                    # every saved shard just to learn its extent
                    "shard_sizes": ([hi - lo for lo, hi in ranges]
                                    if ranges else None),
                }
            for name, si in store.checkpoint_pairs(const.is_chief()):
                def ps_group(name=name, si=si):
                    value, opt_flat = store.shard_state(name, si)
                    out = [("H|%s::%d" % (name, si), value)]
                    out.extend(("Ho|%s::%d|%s" % (name, si, ln), arr)
                               for ln, arr in opt_flat.items())
                    return out
                if self.async_save:
                    for key, arr in ps_group():
                        entries.append((key, arr))
                else:
                    # one shard materialized at a time, atomically snapshot
                    # vs the async apply thread at write time
                    entries.append(ps_group)

        meta = {
            "format": _FORMAT, "step": int(step),
            "strategy_id": dstep.strategy.id, "healthy": healthy,
            "mesh": {"axes": list(dstep.mesh.axis_names),
                     "shape": [int(dstep.mesh.shape[a])
                               for a in dstep.mesh.axis_names]},
            "process_count": nproc,
            "leaves": leaves_meta,
            "ps": ps_meta,
        }

        def write(barrier=None):
            t_begin = time.monotonic()
            with tel.span("ckpt.write", "ckpt", step=int(step)):
                shard_path = "%s.shard-p%d.npz" % (base, pid)
                tmp = shard_path + ".tmp"
                w = _StreamingNpzWriter(tmp)
                w.write("__nonce__", np.frombuffer(nonce.encode(), np.uint8))
                written_keys: List[str] = []
                for item in entries:
                    if callable(item):  # per-shard group producer (PS)
                        for key, arr in item():
                            w.write(key, arr)
                            written_keys.append(key)
                    else:
                        key, arr = item
                        w.write(key, arr() if callable(arr) else arr)
                        written_keys.append(key)
                w.close()
                checkpoint_fault("write", path=tmp, step=int(step))
                os.replace(tmp, shard_path)
                checkpoint_fault("index", path=shard_path, step=int(step))
                index_path = "%s.shard-p%d.index.json" % (base, pid)
                tmp = index_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"pid": pid, "nonce": nonce,
                               "keys": written_keys,
                               "checksums": w.checksums}, f)
                os.replace(tmp, index_path)
                entries.clear()  # free host copies once they're on disk
            # pass the BASE so damage rules at this phase can target any
            # sibling file (shard npz / index), per the phase semantics
            checkpoint_fault("meta", path=base, step=int(step))
            if barrier is not None:
                t_bar = time.monotonic()
                with tel.span("ckpt.barrier", "ckpt", step=int(step),
                              kind="device"):
                    barrier()
                tel.counter_add("ckpt.barrier_s",
                                time.monotonic() - t_bar)
            if pid == 0:
                t_bar = time.monotonic()
                with tel.span("ckpt.barrier", "ckpt", step=int(step),
                              kind="index-files"):
                    key_owner = self._await_indexes(base, nproc)
                tel.counter_add("ckpt.barrier_s", time.monotonic() - t_bar)
                # re-fence at the COMMIT point: an epoch can change
                # between an async save's submit and the meta landing —
                # the shard debris stays un-committed (torn-attempt GC)
                elastic.maybe_fence("ckpt.commit")
                meta["keys"] = key_owner
                tmp = base + ".shard-meta.json.tmp"
                with open(tmp, "w") as f:
                    json.dump(meta, f)
                os.replace(tmp, base + ".shard-meta.json")
                checkpoint_fault("committed", path=base, step=int(step))
                with tel.span("ckpt.gc", "ckpt"):
                    self._gc()
                tel.counter_add("ckpt.saves")
                tel.hist_observe("ckpt.save_ms",
                                 (time.monotonic() - t_begin) * 1e3)
                logging.info("sharded checkpoint %s committed (step %d, "
                             "%d keys over %d processes)", base, step,
                             len(key_owner), nproc)

        if not self.async_save:
            # sync save on a global mesh: a REAL device barrier between the
            # per-process writes and the chief's commit — the commit can
            # then never pair this attempt's chief file with a previous
            # attempt's peer files (safe here on the main thread with no
            # step in flight; the nonce check is defense in depth, and the
            # only guard in async/between-graph modes)
            barrier = None
            if nproc > 1 and not suffix:
                from jax.experimental import multihost_utils

                def barrier():
                    multihost_utils.sync_global_devices(
                        "adt_sharded_ckpt_%d" % step)
            write(barrier)
            return base
        self._writer.submit(write)
        return base

    def _await_indexes(self, base: str, nproc: int) -> Dict[str, int]:
        """File barrier: the chief's commit waits until every process's
        index file exists, parses, and its nonce matches the one embedded
        in that process's npz (an index left by a crashed earlier attempt
        at the same step cannot pair with a fresh npz, or vice versa);
        returns the merged key->pid map."""
        deadline = time.monotonic() + self.barrier_timeout
        key_owner: Dict[str, int] = {}
        pending = set(range(nproc))
        laggard: Dict[int, str] = {}  # pid -> why its commit is incomplete
        while pending:
            for q in sorted(pending):
                path = "%s.shard-p%d.index.json" % (base, q)
                npz_path = "%s.shard-p%d.npz" % (base, q)
                try:
                    with open(path) as f:
                        idx = json.load(f)
                except FileNotFoundError:
                    laggard[q] = "index file %s not written" % (
                        os.path.basename(path))
                    continue
                except json.JSONDecodeError as e:
                    laggard[q] = "index file %s unreadable (%s)" % (
                        os.path.basename(path), e)
                    continue
                try:
                    with np.load(npz_path) as zf:
                        npz_nonce = bytes(zf["__nonce__"]).decode()
                except FileNotFoundError:
                    laggard[q] = "shard file %s not written" % (
                        os.path.basename(npz_path))
                    continue
                except (KeyError, zipfile.BadZipFile, OSError) as e:
                    laggard[q] = "shard file %s unreadable (%s)" % (
                        os.path.basename(npz_path), e)
                    continue
                if idx.get("nonce") != npz_nonce:
                    # torn pair from overlapping attempts
                    laggard[q] = ("index %s does not pair with %s (nonce "
                                  "mismatch — stale file from a crashed "
                                  "earlier attempt at this step)"
                                  % (os.path.basename(path),
                                     os.path.basename(npz_path)))
                    continue
                for k in idx["keys"]:
                    prev = key_owner.setdefault(k, q)
                    if prev != q:
                        raise ValueError(
                            "sharded checkpoint key %r written by both "
                            "process %d and %d — the replica-0 writer rule "
                            "was violated (mismatched mesh layouts between "
                            "processes?)" % (k, prev, q))
                pending.discard(q)
                laggard.pop(q, None)
            if pending:
                if time.monotonic() > deadline:
                    detail = "; ".join(
                        "p%d: %s" % (q, laggard.get(q, "no index file"))
                        for q in sorted(pending))
                    raise TimeoutError(
                        "sharded checkpoint commit: %d of %d processes "
                        "never wrote a valid index under %s within %.0fs "
                        "[%s] — is the checkpoint directory shared across "
                        "hosts?" % (len(pending), nproc, self.directory,
                                    self.barrier_timeout, detail))
                time.sleep(0.05)
        return key_owner

    def wait(self):
        """Join a pending async write; re-raises any writer error."""
        self._writer.wait()

    # ------------------------------------------------------------- discovery

    import re as _re
    _META_RE = _re.compile(r"^ckpt-(\d+)\.shard-meta\.json$")

    def _own_metas(self):
        from autodist_tpu.checkpoint.saver import scan_checkpoint_metas
        return scan_checkpoint_metas(self.directory, self._META_RE)

    def _gc(self):
        metas = self._own_metas()
        while len(metas) > self.max_to_keep:
            step, fname = metas.pop(0)
            base = "ckpt-%d" % step
            for f in os.listdir(self.directory):
                if f == fname or (f.startswith(base + ".shard-p")):
                    try:
                        os.remove(os.path.join(self.directory, f))
                        tel.counter_add("ckpt.gc_removed")
                    except FileNotFoundError:
                        pass
        # failed-attempt debris: shard/index/tmp files of attempts that
        # never committed, at steps below the newest commit — a resumed
        # run restarts past them, so they can only ever be dead weight
        victims, _ = integrity.gc_candidates(self.directory, "sharded")
        for f in victims:
            try:
                os.remove(os.path.join(self.directory, f))
                tel.counter_add("ckpt.gc_orphans")
            except FileNotFoundError:
                pass
        if victims:
            logging.info("sharded checkpoint gc: removed %d failed-attempt "
                         "files (%s)", len(victims), ", ".join(victims[:6]))

    def latest(self) -> Optional[str]:
        """Base path of the newest COMMITTED sharded checkpoint — fast
        validation (``integrity.validate_sharded``) skips torn attempts
        and structurally damaged steps, with a logged reason."""
        self.wait()
        from autodist_tpu.checkpoint.saver import _skip_unhealthy
        for status in integrity.committed_newest_first(self.directory,
                                                       "sharded"):
            if status.committed:
                if _skip_unhealthy(status):
                    continue
                return status.base
            logging.warning("sharded checkpoint step %d is %s, skipping: "
                            "%s", status.step, status.state,
                            "; ".join(status.problems[:3]))
        return None

    # --------------------------------------------------------------- restore

    class _ShardReader:
        """Lazy per-process npz handles + key->pid routing. Damage that
        surfaces at read time — a vanished shard file, a zip CRC mismatch
        on an entry (zipfile verifies every member against its stored
        CRC-32 as it streams) — raises :class:`CheckpointDamaged`, which
        the restore fallback loop catches to try the next-older
        checkpoint; anything else (a missing key = strategy mismatch)
        stays loud."""

        def __init__(self, base: str, meta: dict):
            self._base = base
            self._keys = meta["keys"]
            self._files: Dict[int, Any] = {}

        def __call__(self, key: str) -> np.ndarray:
            pid = self._keys.get(key)
            if pid is None:
                raise KeyError("checkpoint is missing key %r" % key)
            path = "%s.shard-p%d.npz" % (self._base, pid)
            try:
                zf = self._files.get(pid)
                if zf is None:
                    zf = np.load(path)
                    self._files[pid] = zf
                return zf[key]
            except (zipfile.BadZipFile, OSError, ValueError) as e:
                tel.counter_add("ckpt.corrupt_shards")
                raise CheckpointDamaged(
                    "shard file %s is damaged (reading key %r: %s)"
                    % (os.path.basename(path), key, e)) from e

        def close(self):
            for zf in self._files.values():
                zf.close()

    def _read_meta(self, path: str) -> dict:
        with open(path + ".shard-meta.json") as f:
            meta = json.load(f)
        if meta.get("format") != _FORMAT:
            raise ValueError("not a sharded checkpoint: %s" % path)
        return meta

    def _topology_matches(self, meta: dict, dstep) -> bool:
        want_axes = list(dstep.mesh.axis_names)
        want_shape = [int(dstep.mesh.shape[a]) for a in want_axes]
        have = meta["mesh"]
        return (have["axes"] == want_axes and have["shape"] == want_shape
                and meta["process_count"] == jax.process_count())

    def _flex_precheck(self, meta: dict, dstep, suffix: str):
        """Raise when a cross-topology restore is impossible. Flexible
        restore needs global-mesh checkpoints (one SPMD program at save
        AND restore — between-graph local-mesh keys are process-private
        views with no global slice identity) and every saved leaf's mesh
        axes present on the running mesh. Topology-independence is the
        reference's ``SaveSliceInfo`` property (reference
        ``autodist/kernel/partitioner.py:292-347``): keys carry global
        slice ranges, so any consumer topology can reassemble."""
        if suffix or any("@" in k for k in meta["keys"]):
            raise ValueError(
                "cross-topology sharded restore requires global-mesh "
                "checkpoints on both sides; this one involves a "
                "between-graph (process-local mesh) program. Convert with "
                "ShardedSaver.export_full() and restore through Saver.")
        mesh_axes = set(dstep.mesh.axis_names)
        for lkey, lm in meta["leaves"].items():
            for entry in lm["spec"]:
                for ax in (entry if isinstance(entry, list) else [entry]):
                    if ax is not None and ax not in mesh_axes:
                        raise ValueError(
                            "saved leaf %r is sharded over mesh axis %r, "
                            "absent from the running mesh %s — restore "
                            "under a strategy with the same axis names"
                            % (lkey, ax, sorted(mesh_axes)))
        logging.warning(
            "sharded restore across topologies: saved mesh %s=%s over %d "
            "processes -> running %s over %d processes; reassembling from "
            "global slice ranges",
            meta["mesh"]["axes"], meta["mesh"]["shape"],
            meta["process_count"],
            {a: int(dstep.mesh.shape[a]) for a in dstep.mesh.axis_names},
            jax.process_count())

    def _restore_device_tree(self, kind: str, template, meta, reader, mesh,
                             suffix: str, flex_layouts=None):
        """Rebuild one device tree: every leaf assembled from exactly the
        slices this process's devices need. With ``flex_layouts`` (leaf
        name -> VarLayout of the RUNNING program, or absent), the mesh may
        differ from the one the checkpoint was saved on: each needed slice
        is reassembled from the overlapping saved slices (cross-file
        reads), re-padding the split axis for the new layout."""
        names, leaves, treedef = variable_utils.flatten_named(template)
        groups = _group_keys(meta) if flex_layouts is not None else None
        out = []
        for name, _tmpl in zip(names, leaves):
            lm = meta["leaves"].get("%s|%s" % (kind, name))
            if lm is None:
                raise KeyError(
                    "checkpoint has no %s leaf %r — was it saved under a "
                    "different strategy?" % (kind, name))
            if flex_layouts is not None:
                out.append(self._flex_leaf(
                    kind, name, lm, reader, mesh,
                    flex_layouts.get(name),
                    groups.get("%s|%s" % (kind, name), [])))
                continue
            shape = tuple(lm["shape"])
            dtype = np.dtype(lm["dtype"])
            sharding = NamedSharding(mesh, _spec_from_json(lm["spec"]))
            imap = sharding.devices_indices_map(shape)
            bufs, seen = [], {}
            for d in sharding.addressable_devices:
                token = _index_token(imap[d], shape)
                data = seen.get(token)
                if data is None:
                    data = np.asarray(
                        reader("%s|%s|%s%s" % (kind, name, token, suffix)),
                        dtype=dtype)
                    seen[token] = data
                bufs.append(jax.device_put(data, d))
            out.append(jax.make_array_from_single_device_arrays(
                shape, sharding, bufs))
        return variable_utils.unflatten_named(treedef, out)

    def _flex_leaf(self, kind: str, name: str, lm: dict, reader, mesh,
                   layout, saved_keys: List[str]):
        """One leaf restored onto a mesh DIFFERENT from the save mesh.

        Coordinates: saved slice tokens are in the save-time PADDED frame;
        ``lm['unpad']`` recovers the original extent. The running program's
        padding (``layout.padded_dim``) generally differs — e.g. dim 10
        split 4 ways pads to 12, split 2 ways to 10 — so assembly goes
        saved-padded -> original -> new-padded. Pad regions are zeros in
        both frames (VarLayout.pad zero-pads), so only the original region
        is ever copied; memory peak per slice = the needed slice plus one
        overlapping saved slice."""
        saved_shape = tuple(lm["shape"])
        dtype = np.dtype(lm["dtype"])
        unpad = lm.get("unpad")
        orig_shape = list(saved_shape)
        if unpad:
            orig_shape[int(unpad[0])] = int(unpad[1])
        orig_shape = tuple(orig_shape)

        # the RUNNING program's layout decides the new shape and spec (the
        # saved spec reflects the save-time strategy compile, which can
        # differ — e.g. a dim-4 var partitions on a 2-device mesh but stays
        # replicated on 8); leaves without a layout (sync state, scalar
        # optimizer counts) keep the saved spec
        new_shape = list(orig_shape)
        if layout is not None:
            spec = layout.pspec
            if (layout.partitioned and len(orig_shape) > layout.axis
                    and orig_shape[layout.axis] == layout.orig_dim):
                new_shape[layout.axis] = layout.padded_dim
        else:
            spec = _spec_from_json(lm["spec"])
        new_shape = tuple(new_shape)
        if unpad and layout is not None and layout.partitioned \
                and int(unpad[0]) != layout.axis:
            raise ValueError(
                "leaf %s|%s: saved split axis %d != running split axis %d "
                "— cross-topology restore keeps the partition axis"
                % (kind, name, int(unpad[0]), layout.axis))

        # saved pieces: key -> its range per dim, CLIPPED to the original
        # extent (the clipped-off tail is save-time padding, all zeros)
        pieces = []
        for key in saved_keys:
            token = key.split("|", 2)[2]
            ranges = []
            for (lo, hi), odim in zip(
                    ((s.start, s.stop) for s in _token_slices(token))
                    if token != "-" else (), orig_shape):
                ranges.append((lo, min(hi, odim)))
            pieces.append((key, ranges))

        sharding = NamedSharding(mesh, spec)
        imap = sharding.devices_indices_map(new_shape)
        bufs, seen = [], {}
        for d in sharding.addressable_devices:
            token = _index_token(imap[d], new_shape)
            data = seen.get(token)
            if data is None:
                data = self._assemble_flex_slice(
                    _token_slices(token), new_shape, orig_shape, dtype,
                    pieces, reader)
                seen[token] = data
            bufs.append(jax.device_put(data, d))
        return jax.make_array_from_single_device_arrays(
            new_shape, sharding, bufs)

    @staticmethod
    def _assemble_flex_slice(need, new_shape, orig_shape, dtype, pieces,
                             reader) -> np.ndarray:
        """One needed slice (ranges in NEW-padded coords) filled from the
        overlapping saved pieces (ranges in original coords)."""
        if not new_shape:  # scalar: the single '-' piece is the value
            key = pieces[0][0]
            return np.asarray(reader(key), dtype=dtype)
        need_r = [(s.start, s.stop) for s in need]
        out = np.zeros([hi - lo for lo, hi in need_r], dtype)
        # the needed slice's overlap with the ORIGINAL region (identical
        # coordinates below the original extent; beyond it is new padding)
        need_orig = [(lo, min(hi, odim))
                     for (lo, hi), odim in zip(need_r, orig_shape)]
        if any(lo >= hi for lo, hi in need_orig):
            return out  # pure padding slice
        for key, pranges in pieces:
            ov = [(max(nl, pl), min(nh, ph))
                  for (nl, nh), (pl, ph) in zip(need_orig, pranges)]
            if any(lo >= hi for lo, hi in ov):
                continue
            arr = np.asarray(reader(key))
            src = tuple(slice(lo - pl, hi - pl)
                        for (lo, hi), (pl, _) in zip(ov, pranges))
            dst = tuple(slice(lo - nl, hi - nl)
                        for (lo, hi), (nl, _) in zip(ov, need_r))
            out[dst] = arr[src]
        return out

    def restore(self, runner, path: Optional[str] = None) -> Tuple[Any, int]:
        """Restore a Runner's state reading only this process's slices.
        Returns (state, step).

        The mesh/process topology may DIFFER from the save-time one
        (scale-down after losing a host, scale-up after adding some): npz
        keys carry global slice ranges, so each needed slice is
        reassembled from the overlapping saved slices — no process ever
        materializes a full leaf set in either direction (the reference's
        topology-independent ``SaveSliceInfo`` restore, reference
        ``autodist/kernel/partitioner.py:292-347``).

        **Last-good fallback**: with no explicit ``path``, checkpoints are
        tried newest-first; torn save attempts and checkpoints that fail
        validation (or whose damage only surfaces while reading) are
        skipped with a logged reason (counted in ``ckpt.fallback`` /
        ``ckpt.corrupt_shards``), and the call hard-fails only when NO
        valid checkpoint exists. An explicit ``path`` is validated and
        refused (``CheckpointDamaged``) when torn/corrupt — restore never
        loads a damaged checkpoint either way. Read-time fallback is
        single-process only: in a multi-process job a divergent per-process
        fallback choice would desynchronize the restore collectives, so
        read-time damage raises instead."""
        self.wait()
        if path is not None:
            # validate where the path POINTS — it need not live in this
            # saver's directory (restoring someone else's export)
            status = integrity.validate_sharded(*integrity.parse_base(path))
            if not status.committed:
                tel.counter_add("ckpt.corrupt_shards", len(status.damaged))
                raise CheckpointDamaged(
                    "sharded checkpoint %s is %s: %s" % (
                        path, status.state, "; ".join(status.problems[:5])))
            if status.healthy is False:
                # an EXPLICIT path is a human decision — honor it, loudly
                logging.warning("restoring %s despite its UNHEALTHY stamp "
                                "(explicit path overrides the quarantine)",
                                path)
            return self._restore_at(runner, path)
        from autodist_tpu.checkpoint.saver import _skip_unhealthy
        tried = 0
        for status in integrity.committed_newest_first(self.directory,
                                                       "sharded"):
            if not status.committed:
                logging.warning(
                    "sharded restore: skipping step %d (%s): %s",
                    status.step, status.state,
                    "; ".join(status.problems[:3]))
                tel.counter_add("ckpt.fallback")
                tel.counter_add("ckpt.corrupt_shards", len(status.damaged))
                continue
            if _skip_unhealthy(status):
                tel.counter_add("ckpt.fallback")
                continue
            tried += 1
            try:
                return self._restore_at(runner, status.base)
            except CheckpointDamaged as e:
                if jax.process_count() > 1:
                    # each process reads different slices: falling back
                    # independently would desynchronize the restore
                    raise
                logging.warning(
                    "sharded restore: step %d damaged mid-read (%s); "
                    "falling back to the previous checkpoint",
                    status.step, e)
                tel.counter_add("ckpt.fallback")
        raise FileNotFoundError(
            "no valid sharded checkpoint in %s (%d committed candidate(s) "
            "tried)" % (self.directory, tried))

    def _restore_at(self, runner, path: str) -> Tuple[Any, int]:
        """Restore from one specific, already-validated checkpoint base."""
        dstep = runner.distributed_step
        meta = self._read_meta(path)
        suffix = self._mesh_suffix(dstep)
        same = self._topology_matches(meta, dstep)
        if not same:
            self._flex_precheck(meta, dstep, suffix)
        if meta.get("strategy_id") != dstep.strategy.id:
            logging.warning(
                "sharded checkpoint %s was saved under strategy %s, "
                "restoring under %s — layouts must match or this will fail",
                path, meta.get("strategy_id"), dstep.strategy.id)
        reader = self._ShardReader(path, meta)
        try:
            item = dstep.model_item
            holed = dstep._holed_template
            # step_fn mode has no framework optimizer: the opaque state's
            # own moments live under P| and the O tree is empty.
            # ZeRO-sharded vars additionally have no O| slot — their
            # optimizer shards ride the S| (sync_state) tree.
            opt_basis = holed
            if getattr(dstep, "zero_syncs", None):
                from autodist_tpu.parallel import ps as ps_lib
                opt_basis = ps_lib.hole_out_params(
                    holed, frozenset(dstep.zero_syncs))
            opt_template = (jax.eval_shape(item.optimizer.init, opt_basis)
                            if item.optimizer is not None else {})
            p_flex = o_flex = None
            if not same:
                p_flex = dict(dstep.layouts)
                o_flex = dict(dstep.layouts)
                names_o, leaves_o, _ = variable_utils.flatten_named(
                    opt_template)
                for n, l in zip(names_o, leaves_o):
                    var = variable_utils.match_state_to_var(
                        n, tuple(getattr(l, "shape", ())),
                        item.var_infos, dstep.layouts)
                    if var and var in dstep.layouts:
                        o_flex[n] = dstep.layouts[var]
            params = self._restore_device_tree("P", holed, meta, reader,
                                               dstep.mesh, suffix, p_flex)
            opt_state = self._restore_device_tree("O", opt_template, meta,
                                                  reader, dstep.mesh, suffix,
                                                  o_flex)
            sync_template = dstep._sync_state_init()
            if same:
                sync_state = self._restore_device_tree(
                    "S", sync_template, meta, reader, dstep.mesh, suffix)
            else:
                # compressor state (error-feedback residuals, PowerSGD
                # factors) is PER-DEVICE — stored with a leading device
                # axis sized by the SAVE topology. Re-slicing it across a
                # different device count would silently assign residuals
                # to the wrong devices (or fail outright on scale-up), so
                # a cross-topology restore resets it to fresh init:
                # error feedback restarts from zero, a safe transient.
                # ZeRO-sharded optimizer shards are the exception: their
                # rows are GLOBAL flat slices of the variable, so they
                # re-lay-out exactly under the new replica count
                # (_flex_zero_sync below) — losing adam moments on a
                # shrink would not be a safe transient.
                host_sync = self._flex_zero_sync(sync_template, meta,
                                                 reader, dstep)
                sync_state = dstep.place_sync_state(host_sync)
            store = dstep.ps_store
            if store is not None:
                # a staged prefetch of pre-restore values must not survive
                dstep.invalidate_ps()
                groups = _group_keys(meta)
                if same:
                    def provider(name, si):
                        value = np.asarray(reader("H|%s::%d" % (name, si)))
                        prefix = "Ho|%s::%d|" % (name, si)
                        opt_flat = {k[len(prefix):]: np.asarray(reader(k))
                                    for k in groups.get(prefix[:-1], [])}
                        return value, opt_flat
                else:
                    provider = self._flex_ps_provider(meta, reader, groups,
                                                      store)
                store.load_shard_states(provider)
        finally:
            reader.close()
        step = int(meta["step"])
        from autodist_tpu.train_state import TrainState
        state = TrainState(
            step=dstep._put(np.asarray(step, np.int32), P()),
            params=params, opt_state=opt_state, sync_state=sync_state)
        runner.state = state
        notify = getattr(runner, "notify_state_restored", None)
        if callable(notify):
            notify()  # re-sync process-local sentinel LR scale
        tel.counter_add("ckpt.restores")
        logging.info("restored sharded checkpoint %s (step %d, local slices "
                     "only)", path, step)
        return state, step

    def _flex_zero_sync(self, sync_template, meta, reader, dstep):
        """Host sync_state for a cross-topology restore: ZeRO-sharded
        optimizer shards (``sync_state['zero']``) re-lay-out from the
        save topology's global flat slices onto the running replica
        count — concatenate the save-time per-data-index rows, re-pad to
        the new shard size, re-split — while every other per-device leaf
        (compressor residuals, sentinel LR scale) resets to the fresh
        template init (residuals are topology-bound transients)."""
        names, leaves, treedef = variable_utils.flatten_named(sync_template)
        zero_syncs = getattr(dstep, "zero_syncs", {}) or {}
        old_axes = list(meta["mesh"]["axes"])
        old_shape = [int(s) for s in meta["mesh"]["shape"]]
        data_axis = dstep.mesh_axis
        groups = _group_keys(meta)
        relaid, reset = [], []

        def owner_of(leaf_name):
            best = None
            for v in zero_syncs:
                if (leaf_name == "zero/%s" % v
                        or leaf_name.startswith("zero/%s/" % v)):
                    if best is None or len(v) > len(best):
                        best = v
            return best

        def read_full(leaf_name, lm):
            shape = tuple(lm["shape"])
            dtype = np.dtype(lm["dtype"])
            pieces = []
            for key in groups.get("S|%s" % leaf_name, []):
                token = key.split("|", 2)[2]
                ranges = ([(s.start, s.stop) for s in _token_slices(token)]
                          if token != "-" else [])
                pieces.append((key, ranges))
            if not pieces:
                return None
            return self._assemble_flex_slice(
                tuple(slice(0, d) for d in shape), shape, shape, dtype,
                pieces, reader)

        out = []
        for name, tmpl in zip(names, leaves):
            var = owner_of(name)
            lm = meta["leaves"].get("S|%s" % name)
            if var is None or lm is None or data_axis not in old_axes:
                out.append(tmpl)
                if name.startswith(("bucket/", "var/")) and lm is not None:
                    reset.append(name)
                continue
            saved = read_full(name, lm)
            if saved is None:
                out.append(tmpl)
                continue
            from autodist_tpu.kernel.synchronization.zero_synchronizer \
                import relayout_zero_sync_leaf
            tmpl_np = np.asarray(tmpl)
            full = relayout_zero_sync_leaf(saved, old_axes, old_shape,
                                           data_axis, zero_syncs[var],
                                           tmpl_np.shape, tmpl_np.dtype)
            if full is None:
                out.append(tmpl)
                reset.append(name)
                continue
            out.append(full)
            relaid.append(name)
        if relaid or reset:
            logging.warning(
                "cross-topology restore: %d ZeRO opt-state leaves "
                "re-laid-out onto %d replicas; %d per-device leaves "
                "(compressor residuals) reset to fresh init",
                len(relaid), int(dstep.mesh.shape[data_axis]), len(reset))
        return variable_utils.unflatten_named(treedef, out)

    def _flex_ps_provider(self, meta, reader, groups, store):
        """Provider for :meth:`PSStore.load_shard_states` when the RUNNING
        store's shard layout differs from the saved one (host count / load
        balance changed): each new shard's range re-slices the saved shards
        along the split axis, reading only the overlapping ones."""
        ps_meta = meta.get("ps", {})

        def gather_range(keys, lo, hi, axis, offs):
            """Saved shards ``keys`` (with cumulative ``offs`` along
            ``axis``) re-sliced to the half-open range [lo, hi); hi < 0
            means the full extent."""
            parts = []
            for s, k in enumerate(keys):
                if hi >= 0:
                    slo, shi = offs[s], offs[s + 1]
                    olo, ohi = max(lo, slo), min(hi, shi)
                    if olo >= ohi:
                        continue
                    arr = np.asarray(reader(k))
                    idx = [slice(None)] * arr.ndim
                    idx[axis] = slice(olo - slo, ohi - slo)
                    parts.append(arr[tuple(idx)])
                else:
                    parts.append(np.asarray(reader(k)))
            if not parts:
                raise ValueError("PS shard range [%d,%d) matches no saved "
                                 "shard" % (lo, hi))
            return (parts[0] if len(parts) == 1
                    else np.concatenate(parts, axis=axis))

        def provider(name, si):
            pm = ps_meta.get(name)
            if pm is None:
                raise KeyError("checkpoint has no host-PS var %r" % name)
            plan = store.plans[name]
            axis, nsaved = int(pm["axis"]), int(pm["nshards"])
            if plan.partitioned and plan.axis != axis:
                raise ValueError(
                    "PS var %r: saved split axis %d != running split axis "
                    "%d" % (name, axis, plan.axis))
            sizes = pm.get("shard_sizes")
            if not sizes:  # single saved shard, or a pre-shard_sizes meta
                sizes = [int(np.asarray(
                    reader("H|%s::%d" % (name, s))).shape[axis])
                    for s in range(nsaved)]
            offs = [0]
            for s in sizes:
                offs.append(offs[-1] + int(s))
            lo, hi = ((plan.shard_ranges()[si]) if plan.partitioned
                      else (0, -1))
            vkeys = ["H|%s::%d" % (name, s) for s in range(nsaved)]
            value = gather_range(vkeys, lo, hi, axis, offs)
            # optimizer leaves: var-shaped ones re-slice like the value;
            # shard-invariant ones (step counts, scalars, factored stats)
            # copy shard 0's. Var-shaped means FULL shape equality with
            # that saved shard's value — a per-column stats leaf whose one
            # extent happens to match the shard size must not be sliced
            # (same rule load_opt_from_full applies on the plain path).
            shard0_shape = tuple(np.asarray(reader(vkeys[0])).shape)
            opt_flat: Dict[str, np.ndarray] = {}
            leaf_names = sorted({
                k.split("|", 2)[2]
                for s in range(nsaved)
                for k in groups.get("Ho|%s::%d" % (name, s), [])})
            for ln in leaf_names:
                lkeys = ["Ho|%s::%d|%s" % (name, s, ln)
                         for s in range(nsaved)]
                probe = np.asarray(reader(lkeys[0]))
                if tuple(probe.shape) == shard0_shape:
                    opt_flat[ln] = gather_range(lkeys, lo, hi, axis, offs)
                else:
                    opt_flat[ln] = probe
            return value, opt_flat
        return provider

    # ---------------------------------------------------------------- export

    def export_full(self, path: Optional[str] = None,
                    out_dir: Optional[str] = None) -> str:
        """Convert a sharded checkpoint into a plain :class:`Saver`-format
        one (original unpadded layout — the vanilla ``numpy.load`` reload
        property), assembling ONE leaf at a time. Any single process can
        run it (typically the chief, offline). Returns the exported base
        path."""
        self.wait()
        path = path or self.latest()
        if path is None:
            raise FileNotFoundError("no sharded checkpoint in %s"
                                    % self.directory)
        meta = self._read_meta(path)
        out_dir = out_dir or self.directory
        os.makedirs(out_dir, exist_ok=True)
        base = os.path.join(out_dir, "ckpt-%d" % meta["step"])
        reader = self._ShardReader(path, meta)
        try:
            by_kind: Dict[str, List[str]] = {"P": [], "O": [], "S": []}
            for lkey in meta["leaves"]:
                kind, name = lkey.split("|", 1)
                by_kind[kind].append(name)
            groups = _group_keys(meta)
            ps_values, ps_opt = self._assemble_ps_full(meta, reader, groups)

            def write_kind(kind: str, out_path: str, extra: Dict[str, Any]):
                w = _StreamingNpzWriter(out_path + ".tmp")
                written = set()
                for name in sorted(by_kind[kind]):
                    w.write(name, self._assemble_leaf(kind, name, meta,
                                                      reader, groups))
                    written.add(name)
                for name in sorted(extra):
                    # shared leaves (optimizer step counts) can exist in both
                    # the device tree and a PS little-tree; one copy wins
                    if name not in written:
                        w.write(name, extra[name])
                w.close()
                os.replace(out_path + ".tmp", out_path)

            write_kind("P", base + ".params.npz", ps_values)
            write_kind("O", base + ".opt.npz", ps_opt)
            if by_kind["S"]:
                write_kind("S", base + ".sync.npz", {})
            with open(base + ".meta.json.tmp", "w") as f:
                json.dump({"step": meta["step"], "format": "autodist_tpu.v1",
                           "strategy_id": meta.get("strategy_id")}, f)
            os.replace(base + ".meta.json.tmp", base + ".meta.json")
        finally:
            reader.close()
        logging.info("exported sharded checkpoint %s -> full layout %s",
                     path, base)
        return base

    def _assemble_leaf(self, kind: str, name: str, meta, reader,
                       groups: Dict[str, List[str]]) -> np.ndarray:
        """One leaf reassembled from its slices and unpadded."""
        lm = meta["leaves"]["%s|%s" % (kind, name)]
        shape = tuple(lm["shape"])
        dtype = np.dtype(lm["dtype"])
        prefix = "%s|%s|" % (kind, name)
        full = np.zeros(shape, dtype)
        if not shape:
            try:
                return np.asarray(reader(prefix + "-"), dtype=dtype)
            except KeyError:
                # process-local-mesh checkpoint: export the chief's view
                return np.asarray(reader(prefix + "-@p0"), dtype=dtype)
        for key in groups.get(prefix[:-1], []):
            token = key[len(prefix):]
            token, _, pnum = token.partition("@")
            if pnum not in ("", "p0"):
                continue  # local-mesh checkpoints export the chief's view
            full[_token_slices(token)] = reader(key)
        unpad = lm.get("unpad")
        if unpad:
            axis, orig = unpad
            sl = [slice(None)] * len(shape)
            sl[axis] = slice(0, orig)
            full = full[tuple(sl)]
        return full

    def _assemble_ps_full(self, meta, reader, groups: Dict[str, List[str]]):
        """Host-PS values + optimizer leaves in full original layout
        (mirrors PSStore.full_values / full_opt_leaf naming: little-tree
        leaf ``0/mu/v`` becomes full leaf ``0/mu/<var>``)."""
        ps_values: Dict[str, np.ndarray] = {}
        ps_opt: Dict[str, np.ndarray] = {}
        for name, pm in meta.get("ps", {}).items():
            axis, n_shards = int(pm["axis"]), int(pm["nshards"])
            shards = [np.asarray(reader("H|%s::%d" % (name, si)))
                      for si in range(n_shards)]
            ps_values[name] = (shards[0] if n_shards == 1
                               else np.concatenate(shards, axis=axis))
            # per-slot: var-shaped leaves concatenate; others copy shard 0
            slot_leaves: Dict[str, List[np.ndarray]] = {}
            for si in range(n_shards):
                prefix = "Ho|%s::%d|" % (name, si)
                for key in groups.get(prefix[:-1], []):
                    slot_leaves.setdefault(key[len(prefix):], []).append(
                        np.asarray(reader(key)))
            for ln, pieces in slot_leaves.items():
                if ln.endswith("/v") or ln == "v":
                    full_name = ((ln[:-2] + "/" + name) if ln.endswith("/v")
                                 else name)
                    if (len(pieces) > 1 and pieces[0].ndim > axis
                            and sum(p.shape[axis] for p in pieces)
                            == ps_values[name].shape[axis]):
                        ps_opt[full_name] = np.concatenate(pieces, axis=axis)
                    else:
                        ps_opt[full_name] = pieces[0]
                else:
                    ps_opt.setdefault(ln, pieces[0])
        return ps_values, ps_opt
