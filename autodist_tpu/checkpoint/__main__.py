"""``python -m autodist_tpu.checkpoint`` — checkpoint lifecycle CLI."""
import sys

from autodist_tpu.checkpoint.cli import main

if __name__ == "__main__":
    sys.exit(main())
