"""Checkpoint integrity: classification of every on-disk checkpoint.

The savers' durability contract (docs/checkpointing.md) is built from two
mechanisms this module verifies:

- **Atomic visibility**: every final file (npz, index, meta) is written to
  a ``.tmp`` sibling and ``os.replace``'d into place; the meta file lands
  last, so a checkpoint is *committed* exactly when its meta exists. A
  crash at any instant leaves either a committed checkpoint or an
  invisible (meta-less) attempt — never a half-visible one.
- **Content checksums**: both savers record a crc32 + byte count for what
  they wrote (per npz entry in the sharded index files, per data file in
  the plain meta), so post-commit damage — bit rot, a torn write on a
  non-atomic filesystem, a truncated copy — is *detectable*, not silently
  loaded into a training run.

``validate_plain`` / ``validate_sharded`` classify one step; ``scan``
classifies a whole directory. Classification states:

- ``committed`` — meta present, every referenced file present and
  structurally sound (and, with ``deep=True``, every recorded checksum
  verified against the bytes on disk).
- ``torn``      — no meta: a save attempt that never committed (crash
  mid-save). Expected debris after a crash; restore skips it silently and
  GC prunes it.
- ``corrupt``   — meta present but the checkpoint is damaged: a referenced
  file is missing/unreadable, an index↔npz nonce pairing is stale, a size
  or checksum mismatches. Restore must *never* load it; ``fsck`` exits 1.

Fast (``deep=False``) validation is what ``restore()``/``latest()`` run
per candidate: file existence, zip central-directory readability, nonce
pairing, and recorded-size checks — no array data is read. ``deep=True``
(the ``fsck`` CLI) additionally streams every entry and verifies the
recorded crc32s.
"""
import json
import os
import re
import zipfile
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

COMMITTED = "committed"
TORN = "torn"
CORRUPT = "corrupt"

# every file either saver may leave behind, including crash debris (.tmp)
SHARDED_FILE_RE = re.compile(
    r"^ckpt-(\d+)\.shard-(?:p\d+\.(?:npz|index\.json)|meta\.json)"
    r"(\.tmp)?$")
PLAIN_FILE_RE = re.compile(
    r"^ckpt-(\d+)\.(?:(?:params|opt|sync)\.npz|meta\.json)(\.tmp)?$")

_FORMAT_RES = {"plain": PLAIN_FILE_RE, "sharded": SHARDED_FILE_RE}


class CheckpointDamaged(ValueError):
    """A checkpoint's bytes on disk do not match what was committed —
    raised by read paths when damage surfaces mid-restore (zip CRC /
    recorded-checksum mismatch, vanished file). Restore's fallback loop
    catches exactly this class: configuration errors (wrong strategy,
    missing mesh axis) stay loud."""


class CheckpointStatus:
    """Classification of one checkpoint step in one format."""

    __slots__ = ("directory", "step", "fmt", "state", "problems", "files",
                 "damaged", "bytes", "healthy")

    def __init__(self, directory: str, step: int, fmt: str):
        self.directory = directory
        self.step = step
        self.fmt = fmt
        self.state = COMMITTED
        self.problems: List[str] = []
        self.files: List[str] = []
        self.damaged: List[str] = []
        self.bytes = 0
        # the sentinel's health stamp from the meta: True (saved while
        # the run was judged healthy), False (saved despite a bad
        # verdict — auto-resume and rollback must never load it), or
        # None for pre-stamp checkpoints (healthy-UNKNOWN: resumable,
        # logged — an old checkpoint is not rejected for predating the
        # feature)
        self.healthy: Optional[bool] = None

    @property
    def committed(self) -> bool:
        return self.state == COMMITTED

    @property
    def base(self) -> str:
        return os.path.join(self.directory, "ckpt-%d" % self.step)

    def _flag(self, state: str, problem: str, damaged_file: Optional[str] = None):
        # corrupt dominates torn dominates committed
        if state == CORRUPT or self.state == COMMITTED:
            self.state = state
        self.problems.append(problem)
        if damaged_file is not None and damaged_file not in self.damaged:
            self.damaged.append(damaged_file)

    def to_dict(self) -> dict:
        return {"step": self.step, "format": self.fmt, "state": self.state,
                "files": list(self.files), "bytes": self.bytes,
                "problems": list(self.problems),
                "damaged": list(self.damaged), "healthy": self.healthy}

    def __repr__(self):
        return ("CheckpointStatus(step=%d, fmt=%r, state=%r, problems=%r)"
                % (self.step, self.fmt, self.state, self.problems))


def parse_base(path: str) -> Tuple[str, int]:
    """``(directory, step)`` of a checkpoint base path ``.../ckpt-N`` —
    what an explicit ``restore(path=...)`` hands the validators, so the
    checkpoint is validated where it LIVES, not in the saver's own
    directory."""
    base = os.path.basename(path.rstrip("/"))
    m = re.match(r"^ckpt-(\d+)$", base)
    if m is None:
        raise ValueError(
            "not a checkpoint base path (expected .../ckpt-<step>): %r"
            % path)
    return os.path.dirname(path.rstrip("/")) or ".", int(m.group(1))


class Crc32Writer:
    """Non-seekable write-through file proxy recording a crc32 + byte
    count of everything written — a saver records the content digest of
    what it streams with no second read pass. Deliberately NOT seekable:
    ``zipfile`` then writes in data-descriptor mode, never seeking back
    to patch headers, so the digest matches the final bytes on disk."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.nbytes = 0

    def write(self, data):
        self.crc = zlib.crc32(data, self.crc) & 0xFFFFFFFF
        self.nbytes += len(data)
        return self._f.write(data)

    def read(self, *_):  # np.savez file-object probe is hasattr("read")
        raise OSError("Crc32Writer is write-only")

    def readable(self) -> bool:
        return False

    def flush(self):
        self._f.flush()

    def tell(self) -> int:
        return self.nbytes

    def seekable(self) -> bool:
        return False

    def writable(self) -> bool:
        return True

    @property
    def digest(self) -> Dict[str, int]:
        return {"crc32": self.crc, "bytes": self.nbytes}


def file_digest(path: str, chunk: int = 1 << 20) -> Dict[str, int]:
    """Streaming ``{"crc32": ..., "bytes": ...}`` of a file — what the
    plain Saver records per data file in its meta."""
    crc, n = 0, 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
            n += len(block)
    return {"crc32": crc & 0xFFFFFFFF, "bytes": n}


def _group_files(directory: str, fmt: str) -> Dict[int, List[str]]:
    """step -> file basenames belonging to ``fmt`` in ``directory``."""
    pattern = _FORMAT_RES[fmt]
    out: Dict[int, List[str]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for f in names:
        m = pattern.match(f)
        if m:
            out.setdefault(int(m.group(1)), []).append(f)
    return out


def _sum_bytes(directory: str, files: List[str]) -> int:
    total = 0
    for f in files:
        try:
            total += os.path.getsize(os.path.join(directory, f))
        except OSError:
            pass
    return total


# ------------------------------------------------------------------ sharded


def _read_npz_nonce(zf: zipfile.ZipFile) -> Optional[str]:
    try:
        with zf.open("__nonce__.npy") as f:
            return bytes(np.lib.format.read_array(f)).decode()
    except (KeyError, OSError, ValueError, zipfile.BadZipFile):
        return None


def validate_sharded(directory: str, step: int, deep: bool = False,
                     files: Optional[List[str]] = None) -> CheckpointStatus:
    """Classify one sharded checkpoint step (see module docstring)."""
    status = CheckpointStatus(directory, step, "sharded")
    if files is None:
        files = _group_files(directory, "sharded").get(step, [])
    status.files = sorted(files)
    status.bytes = _sum_bytes(directory, files)
    meta_name = "ckpt-%d.shard-meta.json" % step
    if meta_name not in files:
        status._flag(TORN, "no %s — save attempt never committed"
                     % meta_name)
        return status
    try:
        with open(os.path.join(directory, meta_name)) as f:
            meta = json.load(f)
        key_owner = meta["keys"]
    except (OSError, json.JSONDecodeError, KeyError) as e:
        status._flag(CORRUPT, "meta unreadable: %s" % e, meta_name)
        return status
    if "healthy" in meta:
        status.healthy = bool(meta["healthy"])

    by_pid: Dict[int, List[str]] = {}
    for key, pid in key_owner.items():
        by_pid.setdefault(int(pid), []).append(key)
    for pid in sorted(by_pid):
        idx_name = "ckpt-%d.shard-p%d.index.json" % (step, pid)
        npz_name = "ckpt-%d.shard-p%d.npz" % (step, pid)
        try:
            with open(os.path.join(directory, idx_name)) as f:
                idx = json.load(f)
        except FileNotFoundError:
            status._flag(CORRUPT, "%s missing" % idx_name, idx_name)
            continue
        except (OSError, json.JSONDecodeError) as e:
            status._flag(CORRUPT, "%s unreadable: %s" % (idx_name, e),
                         idx_name)
            continue
        try:
            zf = zipfile.ZipFile(os.path.join(directory, npz_name))
        except FileNotFoundError:
            status._flag(CORRUPT, "%s missing" % npz_name, npz_name)
            continue
        except (OSError, zipfile.BadZipFile) as e:
            status._flag(CORRUPT, "%s unreadable (torn write?): %s"
                         % (npz_name, e), npz_name)
            continue
        with zf:
            _validate_shard_pair(status, zf, idx, by_pid[pid],
                                 idx_name, npz_name, deep)
    return status


def _validate_shard_pair(status: CheckpointStatus, zf: zipfile.ZipFile,
                         idx: dict, meta_keys: List[str], idx_name: str,
                         npz_name: str, deep: bool):
    npz_nonce = _read_npz_nonce(zf)
    if idx.get("nonce") != npz_nonce:
        status._flag(CORRUPT, "%s nonce does not match %s — stale "
                     "index/npz pairing from overlapping attempts"
                     % (idx_name, npz_name), npz_name)
        return
    names = set(zf.namelist())
    idx_keys = set(idx.get("keys", ()))
    for key in meta_keys:
        if key not in idx_keys:
            status._flag(CORRUPT, "meta key %r not in %s" % (key, idx_name),
                         idx_name)
    for key in idx_keys:
        if key + ".npy" not in names:
            status._flag(CORRUPT, "key %r listed in %s but absent from %s"
                         % (key, idx_name, npz_name), npz_name)
    checksums = idx.get("checksums") or {}
    for key, (crc, nbytes) in checksums.items():
        member = key + ".npy"
        if member not in names:
            continue  # already flagged above (or the nonce entry)
        info = zf.getinfo(member)
        if info.file_size != int(nbytes):
            status._flag(CORRUPT, "%s entry %r is %d bytes, index "
                         "recorded %d" % (npz_name, key, info.file_size,
                                          int(nbytes)), npz_name)
            continue
        if deep:
            try:
                with zf.open(member) as f:
                    got = zlib.crc32(f.read()) & 0xFFFFFFFF
            except (OSError, zipfile.BadZipFile) as e:
                status._flag(CORRUPT, "%s entry %r unreadable: %s"
                             % (npz_name, key, e), npz_name)
                continue
            if got != (int(crc) & 0xFFFFFFFF):
                status._flag(CORRUPT, "%s entry %r crc32 mismatch "
                             "(bit rot?)" % (npz_name, key), npz_name)


# -------------------------------------------------------------------- plain


def validate_plain(directory: str, step: int, deep: bool = False,
                   files: Optional[List[str]] = None) -> CheckpointStatus:
    """Classify one plain (Saver-format) checkpoint step."""
    status = CheckpointStatus(directory, step, "plain")
    if files is None:
        files = _group_files(directory, "plain").get(step, [])
    status.files = sorted(files)
    status.bytes = _sum_bytes(directory, files)
    meta_name = "ckpt-%d.meta.json" % step
    if meta_name not in files:
        status._flag(TORN, "no %s — save attempt never committed"
                     % meta_name)
        return status
    try:
        with open(os.path.join(directory, meta_name)) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        status._flag(CORRUPT, "meta unreadable: %s" % e, meta_name)
        return status
    if "healthy" in meta:
        status.healthy = bool(meta["healthy"])
    file_meta = meta.get("files")
    if file_meta is None:
        # legacy (pre-checksum) checkpoint: verify the standard files are
        # structurally readable; content checks are impossible — but the
        # params file at least must EXIST or restore fails at read time
        file_meta = {f: None for f in files
                     if f.endswith(".npz") and not f.endswith(".tmp")}
        params_name = "ckpt-%d.params.npz" % step
        if params_name not in file_meta:
            status._flag(CORRUPT, "%s missing (legacy checkpoint with no "
                         "recorded file list)" % params_name, params_name)
    for fname, digest in sorted(file_meta.items()):
        path = os.path.join(directory, fname)
        if not os.path.exists(path):
            status._flag(CORRUPT, "%s listed in meta but missing" % fname,
                         fname)
            continue
        if digest is not None and os.path.getsize(path) != digest["bytes"]:
            status._flag(CORRUPT, "%s is %d bytes, meta recorded %d"
                         % (fname, os.path.getsize(path), digest["bytes"]),
                         fname)
            continue
        if fname.endswith(".npz"):
            try:
                with zipfile.ZipFile(path) as zf:
                    if deep and zf.testzip() is not None:
                        status._flag(CORRUPT, "%s has a bad zip entry"
                                     % fname, fname)
            except (OSError, zipfile.BadZipFile) as e:
                status._flag(CORRUPT, "%s unreadable (torn write?): %s"
                             % (fname, e), fname)
                continue
        if deep and digest is not None:
            if file_digest(path)["crc32"] != (digest["crc32"] & 0xFFFFFFFF):
                status._flag(CORRUPT, "%s crc32 mismatch (bit rot?)"
                             % fname, fname)
    return status


# --------------------------------------------------------------- directory


_VALIDATORS = {"plain": validate_plain, "sharded": validate_sharded}


def scan(directory: str, fmt: Optional[str] = None, deep: bool = False
         ) -> List[CheckpointStatus]:
    """Classify every checkpoint in ``directory`` (both formats unless
    ``fmt`` narrows it); sorted by (step, format), oldest first."""
    out: List[CheckpointStatus] = []
    for f in (fmt,) if fmt else ("plain", "sharded"):
        for step, files in sorted(_group_files(directory, f).items()):
            out.append(_VALIDATORS[f](directory, step, deep=deep,
                                      files=files))
    return sorted(out, key=lambda s: (s.step, s.fmt))


def committed_newest_first(directory: str, fmt: str):
    """Lazily yield ``fmt``'s checkpoints newest step first — the restore
    fallback order. Fast validation runs per step AS CONSUMED, so
    ``latest()``/``restore()`` stopping at the first committed step pay
    one step's validation I/O, not the whole directory's (which matters
    on a networked checkpoint dir at startup). Callers decide what to do
    with the non-committed entries (skip + count, or just skip)."""
    groups = _group_files(directory, fmt)
    for step in sorted(groups, reverse=True):
        yield _VALIDATORS[fmt](directory, step, files=groups[step])


def gc_candidates(directory: str, fmt: str,
                  force_orphans: bool = False
                  ) -> Tuple[List[str], List[CheckpointStatus]]:
    """Failed-attempt debris safe to delete: files (basenames) of torn
    attempts at steps strictly below the newest committed step, plus
    ``.tmp`` leftovers below it. ``force_orphans`` (CLI ``gc --orphans``,
    caller asserts no save is in flight) drops the newest-step guard so
    debris at or above the newest commit goes too. Returns (filenames,
    statuses scanned)."""
    statuses = scan(directory, fmt=fmt)
    committed = [s.step for s in statuses if s.committed]
    newest = max(committed) if committed else None
    victims: List[str] = []
    for s in statuses:
        # never touch a committed step's final files; a torn attempt is
        # debris once a newer commit exists (resume starts past it)
        removable_step = (force_orphans or
                          (newest is not None and s.step < newest))
        if not removable_step:
            continue
        if s.state == TORN:
            victims.extend(s.files)
        else:
            victims.extend(f for f in s.files if f.endswith(".tmp"))
    return sorted(set(victims)), statuses
