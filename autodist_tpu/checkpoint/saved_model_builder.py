"""Serving export.

Analog of reference ``autodist/checkpoint/saved_model_builder.py:24-64``: a
SavedModel export of the *original* (untransformed) graph so the artifact
serves/fine-tunes without AutoDist. The JAX equivalent of "model for
serving" is (apply_fn, params): this builder writes the gathered
original-layout params plus a JSON model spec; a consumer reloads with
``numpy.load`` and its own apply function — no framework import required.
"""
import json
import os
from typing import Callable, Optional

from autodist_tpu.checkpoint.saver import Saver, _tree_to_flat
from autodist_tpu.utils import logging
import numpy as np


class SavedModelBuilder:
    def __init__(self, export_dir: str):
        self.export_dir = export_dir
        os.makedirs(export_dir, exist_ok=True)

    def save(self, runner, signature: Optional[dict] = None,
             apply_fn: Optional[Callable] = None) -> str:
        dstep = runner.distributed_step
        params = dstep.gather_params(runner.state)
        np.savez(os.path.join(self.export_dir, "params.npz"),
                 **_tree_to_flat(params))
        spec = dstep.model_item.to_spec_dict()
        spec["signature"] = signature or {}
        fn = apply_fn or dstep.model_item.apply_fn
        if fn is not None:
            spec["apply_fn"] = "%s.%s" % (getattr(fn, "__module__", "?"),
                                          getattr(fn, "__qualname__",
                                                  repr(fn)))
        with open(os.path.join(self.export_dir, "model_spec.json"), "w") as f:
            json.dump(spec, f, indent=1, sort_keys=True)
        logging.info("exported model to %s", self.export_dir)
        return self.export_dir


def export_for_serving(runner, export_dir: str,
                       apply_fn: Optional[Callable] = None) -> str:
    """Convenience wrapper mirroring the reference's usage pattern."""
    return SavedModelBuilder(export_dir).save(runner, apply_fn=apply_fn)
