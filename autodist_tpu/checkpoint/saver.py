"""Checkpoint saver — original-layout, framework-free restore.

Analog of reference ``autodist/checkpoint/saver.py:28-133``. The reference's
defining property (``saver.py:50-57``, ``docs/usage/tutorials/save-restore.md``):
checkpoints are written in the *original single-device namespace*, so they
load in vanilla TF with no AutoDist installed. Here the same contract:
``Saver.save`` gathers partitioned variables back to their full unpadded
shapes (``DistributedStep.gather_params``) and writes plain ``.npz`` files
keyed by the slash-joined variable names — loadable with ``numpy.load``
alone. Optimizer state is saved alongside (the reference saves slot
variables through the same saver), so training resumes exactly; a vanilla
consumer can ignore it.

Chief-only saving for shared filesystems mirrors the ``IS_AUTODIST_CHIEF``
gate (reference ``autodist/autodist.py:40-41``).
"""
import json
import os
import threading
import time
import zipfile
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from autodist_tpu import const
from autodist_tpu.checkpoint import integrity
from autodist_tpu.checkpoint.integrity import CheckpointDamaged
from autodist_tpu.kernel.common import variable_utils
from autodist_tpu.runtime.faultinject import checkpoint_fault
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging


def _tree_to_flat(tree) -> Dict[str, np.ndarray]:
    names, leaves, _ = variable_utils.flatten_named(tree)
    return {n: np.asarray(jax.device_get(l)) for n, l in zip(names, leaves)}


def _read_npz(path: str) -> Dict[str, np.ndarray]:
    """Fully read one npz, converting every read-path failure — vanished
    file, I/O error, zip/npy corruption — to :class:`CheckpointDamaged`,
    so the restore fallback loop can catch exactly that and configuration
    errors (template mismatch in ``_flat_to_tree``) stay loud. In
    particular a mid-read ``FileNotFoundError`` must NOT escape: the
    caller's no-valid-checkpoint sentinel shares that type, and
    ``Runner.init`` would misread the error as "start fresh"."""
    try:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    except (OSError, ValueError, zipfile.BadZipFile) as e:
        raise CheckpointDamaged("%s unreadable: %s" % (path, e)) from e


def _flat_to_tree(template, flat: Dict[str, np.ndarray]):
    names, leaves, treedef = variable_utils.flatten_named(template)
    out = []
    for n, leaf in zip(names, leaves):
        if n not in flat:
            raise KeyError("checkpoint missing variable %r" % n)
        arr = flat[n]
        want = tuple(getattr(leaf, "shape", ()))
        if tuple(arr.shape) != want:
            raise ValueError("checkpoint var %r has shape %s, model wants %s"
                             % (n, arr.shape, want))
        out.append(arr)
    return variable_utils.unflatten_named(treedef, out)


import re as _re


def _skip_unhealthy(status) -> bool:
    """Automatic restore paths (``latest()``, fallback ``restore()``,
    auto-resume) must never load a checkpoint stamped ``healthy: false``
    — it was committed while the sentinel's verdict was bad, i.e. it IS
    the poisoned state rollback exists to escape. Pre-stamp checkpoints
    (``healthy`` absent → None) stay resumable: healthy-unknown, logged."""
    if status.healthy is False:
        logging.warning("checkpoint step %d is stamped UNHEALTHY "
                        "(committed under a bad sentinel verdict); "
                        "skipping", status.step)
        tel.counter_add("ckpt.unhealthy_skipped")
        return True
    if status.healthy is None:
        logging.info("checkpoint step %d predates the health stamp "
                     "(healthy-unknown); treating as resumable",
                     status.step)
    return False


def scan_checkpoint_metas(directory: str, pattern) -> list:
    """Sorted (step, filename) pairs for meta files matching ``pattern``
    (a compiled regex whose group 1 is the step). Foreign files in a
    shared directory are ignored, not crashed on. Shared by
    :class:`Saver` and :class:`ShardedSaver` so retention/discovery
    semantics cannot drift apart."""
    out = []
    for f in os.listdir(directory):
        m = pattern.match(f)
        if m:
            out.append((int(m.group(1)), f))
    return sorted(out)


def sentinel_save_vetoed(runner_or_step) -> bool:
    """Quarantine gate shared by both savers: a Runner with an active
    sentinel vetoes saves while the health verdict is bad — the poisoned
    state must never become the newest committed checkpoint (it would be
    exactly what last-good fallback and auto-resume restore).

    The veto returns BEFORE the cross-process gather collectives, so it
    is only taken when every process provably reaches the same decision:
    in-graph verdicts are all-reduced, so guarded programs qualify; a
    LOSS-ONLY sentinel (step_fn mode, ADT420) watches user metrics that
    need not be replica-uniform, so in a multi-process job it must not
    veto — a divergent early return would strand the peers inside the
    gather. There the save proceeds and the ``healthy`` stamp (written
    by the chief alone, hence consistent) records the suspicion
    instead."""
    veto = getattr(runner_or_step, "sentinel_save_veto", None)
    if not (callable(veto) and veto()):
        return False
    if jax.process_count() > 1:
        dstep = getattr(runner_or_step, "distributed_step", None)
        metadata = getattr(dstep, "metadata", None) or {}
        if not metadata.get("sentinel_guards", False):
            logging.warning(
                "sentinel quarantine NOT vetoing this save: loss-only "
                "monitoring is not replica-uniform in a multi-process "
                "job (a divergent veto would deadlock the gather "
                "collectives) — the checkpoint will carry its honest "
                "healthy stamp instead")
            return False
    tel.counter_add("sentinel.save_vetoes")
    logging.warning("checkpoint save vetoed: sentinel quarantine "
                    "(health verdict is bad)")
    return True


def sentinel_health_stamp(runner_or_step) -> bool:
    """The ``healthy`` stamp this save should carry. True when no
    sentinel is armed (an unguarded run has no evidence of ill health —
    its checkpoints stay resumable); False only when a sentinel judged
    the state bad yet the save proceeded (quarantine disabled)."""
    fn = getattr(runner_or_step, "sentinel_healthy", None)
    return bool(fn()) if callable(fn) else True


class BackgroundWriter:
    """At most one background checkpoint write in flight. ``wait()`` joins
    the pending write and re-raises any error it hit — a failed checkpoint
    must never look like a success. Shared by :class:`Saver` and
    :class:`~autodist_tpu.checkpoint.sharded.ShardedSaver`."""

    def __init__(self, name: str):
        self._name = name
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def submit(self, fn):
        self.wait()  # serialize: at most one write in flight
        self._error = None

        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self._error = e

        self._thread = threading.Thread(target=run, name=self._name,
                                        daemon=False)
        self._thread.start()

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
            err, self._error = self._error, None
            if err is not None:
                raise err


class Saver:
    """Save/restore distributed training state in the original layout.

    ``async_save=True`` moves the file writes to a background thread: the
    collective gathers (which every process must join) still happen inside
    ``save()``, but the host-side npz serialization — the slow part for
    large models — overlaps subsequent training steps. At most one write is
    in flight; a new ``save()`` joins the previous one first, and
    ``wait()`` joins explicitly (call before reading ``latest()``)."""

    def __init__(self, directory: Optional[str] = None, max_to_keep: int = 5,
                 chief_only: bool = True, async_save: bool = False):
        self.directory = directory or const.DEFAULT_CHECKPOINT_DIR
        self.max_to_keep = max_to_keep
        self.chief_only = chief_only
        self.async_save = async_save
        self._writer = BackgroundWriter("adt-ckpt-writer")
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------------ save

    def save(self, runner_or_step, state=None, step: Optional[int] = None) -> Optional[str]:
        """Write a checkpoint. Accepts a Runner (uses its state) or a
        DistributedStep + explicit TrainState. The gathers are collectives —
        EVERY process must call save(); only the file writes are
        chief-gated."""
        if hasattr(runner_or_step, "distributed_step"):  # Runner
            dstep = runner_or_step.distributed_step
            state = state if state is not None else runner_or_step.state
        else:
            dstep = runner_or_step
        if state is None:
            raise ValueError("no state to save")
        # epoch fence BEFORE any work (and any file): a zombie worker's
        # late save must leave the checkpoint directory byte-identical to
        # a run where it never woke (runtime/elastic.py)
        from autodist_tpu.runtime import elastic
        elastic.maybe_fence("ckpt.save")
        if sentinel_save_vetoed(runner_or_step):
            return None
        healthy = sentinel_health_stamp(runner_or_step)
        # cross-process collectives: run on all processes before any gating
        with tel.span("ckpt.gather", "ckpt"):
            params = dstep.gather_params(state)
            opt_state_host = dstep.gather_opt_state(state)
            sync_state_host = dstep.gather_sync_state(state)
        if step is None:
            step = int(jax.device_get(state.step))
        checkpoint_fault("collect", step=step)
        if self.chief_only and not const.is_chief():
            return None
        path = os.path.join(self.directory, "ckpt-%d" % step)
        meta = {"step": step, "format": "autodist_tpu.v1",
                "strategy_id": dstep.strategy.id, "healthy": healthy}

        def write():
            t_begin = time.monotonic()
            with tel.span("ckpt.write", "ckpt", step=int(step)):
                trees = [(".params.npz", _tree_to_flat(params)),
                         (".opt.npz", _tree_to_flat(opt_state_host))]
                sync_flat = _tree_to_flat(sync_state_host)
                if sync_flat:
                    trees.append((".sync.npz", sync_flat))
                # every data file goes to a .tmp sibling first and is
                # os.replace'd into place — a crash mid-serialization can
                # never leave a truncated npz under the FINAL name (the
                # torn write numpy.load would fail on with no indication
                # of why); the meta records each file's crc32+bytes so
                # post-commit damage is detectable (integrity.py)
                file_meta: Dict[str, dict] = {}
                finals = []
                for suffix, flat in trees:
                    final = path + suffix
                    tmp = final + ".tmp"
                    with open(tmp, "wb") as f:
                        # the non-seekable proxy digests the stream as it
                        # is written (zipfile falls back to data-descriptor
                        # mode, so the digest IS the bytes on disk) — no
                        # second read pass over a multi-GB checkpoint
                        w = integrity.Crc32Writer(f)
                        np.savez(w, **flat)
                    file_meta[os.path.basename(final)] = w.digest
                    finals.append((tmp, final))
                checkpoint_fault("write", path=path, step=int(step))
                for tmp, final in finals:
                    os.replace(tmp, final)
                meta["files"] = file_meta
                # meta last, atomically: a checkpoint only becomes visible
                # to _own_metas / latest() once all its data files exist.
                # Re-fenced at the COMMIT point: an epoch can change
                # between an async save's submit and its write landing
                elastic.maybe_fence("ckpt.commit")
                checkpoint_fault("meta", path=path, step=int(step))
                with open(path + ".meta.json.tmp", "w") as f:
                    json.dump(meta, f)
                os.replace(path + ".meta.json.tmp", path + ".meta.json")
                checkpoint_fault("committed", path=path, step=int(step))
            with tel.span("ckpt.gc", "ckpt"):
                self._gc()
            tel.counter_add("ckpt.saves")
            tel.hist_observe("ckpt.save_ms",
                             (time.monotonic() - t_begin) * 1e3)
            logging.info("saved checkpoint %s (step %d)", path, step)

        if not self.async_save:
            write()
            return path
        self._writer.submit(write)
        return path

    def wait(self):
        """Join a pending async write; re-raises any error the writer hit —
        a failed checkpoint must not look like a success."""
        self._writer.wait()

    _META_RE = _re.compile(r"^ckpt-(\d+)\.meta\.json$")

    def _own_metas(self):
        return scan_checkpoint_metas(self.directory, self._META_RE)

    def _gc(self):
        metas = self._own_metas()
        while len(metas) > self.max_to_keep:
            _, fname = metas.pop(0)
            victim = fname.replace(".meta.json", "")
            for suffix in (".meta.json", ".params.npz", ".opt.npz", ".sync.npz"):
                try:
                    os.remove(os.path.join(self.directory, victim + suffix))
                except FileNotFoundError:
                    pass
        # failed-attempt debris (.tmp siblings, data files whose meta —
        # the commit point — never landed) below the newest commit
        victims, _ = integrity.gc_candidates(self.directory, "plain")
        for f in victims:
            try:
                os.remove(os.path.join(self.directory, f))
                tel.counter_add("ckpt.gc_orphans")
            except FileNotFoundError:
                pass
        if victims:
            logging.info("checkpoint gc: removed %d failed-attempt files "
                         "(%s)", len(victims), ", ".join(victims[:6]))

    # --------------------------------------------------------------- restore

    def latest(self) -> Optional[str]:
        """Base path of the newest COMMITTED checkpoint — fast validation
        skips torn save attempts and structurally damaged steps with a
        logged reason."""
        self.wait()  # an in-flight async write must be visible to readers
        for status in integrity.committed_newest_first(self.directory,
                                                       "plain"):
            if status.committed:
                if _skip_unhealthy(status):
                    continue
                return status.base
            logging.warning("checkpoint step %d is %s, skipping: %s",
                            status.step, status.state,
                            "; ".join(status.problems[:3]))
        return None

    def restore_params(self, params_template, path: Optional[str] = None):
        """Params pytree in the original layout — usable with or without the
        framework (the vanilla-restore property)."""
        self.wait()  # the path from an async save() is valid only post-write
        path = path or self.latest()
        if path is None:
            raise FileNotFoundError("no checkpoint in %s" % self.directory)
        flat = _read_npz(path + ".params.npz")
        return _flat_to_tree(params_template, flat)

    def restore(self, runner, path: Optional[str] = None) -> Tuple[Any, int]:
        """Restore a Runner's distributed state; returns (state, step).

        **Last-good fallback**: with no explicit ``path``, checkpoints are
        tried newest-first, skipping torn attempts and damaged steps (fast
        validation up front, read-time zip-CRC failures during the load)
        with a logged reason and ``ckpt.fallback``/``ckpt.corrupt_shards``
        counters; hard-fails only when no valid checkpoint exists. An
        explicit ``path`` is validated and refused when damaged."""
        self.wait()  # the path from an async save() is valid only post-write
        if path is not None:
            # validate where the path POINTS — it need not live in this
            # saver's directory (restoring someone else's export)
            status = integrity.validate_plain(*integrity.parse_base(path))
            if not status.committed:
                tel.counter_add("ckpt.corrupt_shards", len(status.damaged))
                raise CheckpointDamaged(
                    "checkpoint %s is %s: %s" % (
                        path, status.state, "; ".join(status.problems[:5])))
            if status.healthy is False:
                # an EXPLICIT path is a human decision — honor it, loudly
                logging.warning("restoring %s despite its UNHEALTHY stamp "
                                "(explicit path overrides the quarantine)",
                                path)
            return self._restore_at(runner, path)
        tried = 0
        for status in integrity.committed_newest_first(self.directory,
                                                       "plain"):
            if not status.committed:
                logging.warning("restore: skipping step %d (%s): %s",
                                status.step, status.state,
                                "; ".join(status.problems[:3]))
                tel.counter_add("ckpt.fallback")
                tel.counter_add("ckpt.corrupt_shards", len(status.damaged))
                continue
            if _skip_unhealthy(status):
                tel.counter_add("ckpt.fallback")
                continue
            tried += 1
            try:
                return self._restore_at(runner, status.base)
            except (CheckpointDamaged, zipfile.BadZipFile) as e:
                if jax.process_count() > 1:
                    raise  # peers must all restore the SAME step
                logging.warning("restore: step %d damaged mid-read (%s); "
                                "falling back", status.step, e)
                tel.counter_add("ckpt.fallback")
                tel.counter_add("ckpt.corrupt_shards")
        raise FileNotFoundError(
            "no valid checkpoint in %s (%d committed candidate(s) tried)"
            % (self.directory, tried))

    def _restore_at(self, runner, path: str) -> Tuple[Any, int]:
        dstep = runner.distributed_step
        params = self.restore_params(dstep.model_item.params, path)
        if dstep.model_item.optimizer is not None:
            opt_flat = _read_npz(path + ".opt.npz")
            opt_template = dstep.model_item.optimizer.init(
                dstep.model_item.params)
            opt_state = _flat_to_tree(opt_template, opt_flat)
        else:
            # step_fn mode: whatever optimizer state exists lives inside
            # the user's opaque state (saved under params)
            opt_state = {}
        sync_state = None
        if os.path.exists(path + ".sync.npz"):
            sync_flat = _read_npz(path + ".sync.npz")
            try:
                sync_state = _flat_to_tree(dstep._sync_state_init(), sync_flat)
            except (KeyError, ValueError) as e:
                logging.warning("sync state in checkpoint incompatible with "
                                "current strategy (%s); reinitializing", e)
        state = dstep.init_state(params, opt_state, sync_state)
        try:
            with open(path + ".meta.json") as f:
                step = json.load(f)["step"]
        except (OSError, json.JSONDecodeError, KeyError) as e:
            raise CheckpointDamaged(
                "%s.meta.json unreadable: %s" % (path, e)) from e
        # advance the step counter to the saved step
        from autodist_tpu.train_state import TrainState
        state = TrainState(step=dstep._put(np.asarray(step, np.int32),
                                           jax.sharding.PartitionSpec()),
                           params=state.params, opt_state=state.opt_state,
                           sync_state=state.sync_state)
        runner.state = state
        notify = getattr(runner, "notify_state_restored", None)
        if callable(notify):
            notify()  # re-sync process-local sentinel LR scale
        tel.counter_add("ckpt.restores")
        logging.info("restored checkpoint %s (step %d)", path, step)
        return state, step
