"""Checkpoint saver — original-layout, framework-free restore.

Analog of reference ``autodist/checkpoint/saver.py:28-133``. The reference's
defining property (``saver.py:50-57``, ``docs/usage/tutorials/save-restore.md``):
checkpoints are written in the *original single-device namespace*, so they
load in vanilla TF with no AutoDist installed. Here the same contract:
``Saver.save`` gathers partitioned variables back to their full unpadded
shapes (``DistributedStep.gather_params``) and writes plain ``.npz`` files
keyed by the slash-joined variable names — loadable with ``numpy.load``
alone. Optimizer state is saved alongside (the reference saves slot
variables through the same saver), so training resumes exactly; a vanilla
consumer can ignore it.

Chief-only saving for shared filesystems mirrors the ``IS_AUTODIST_CHIEF``
gate (reference ``autodist/autodist.py:40-41``).
"""
import json
import os
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from autodist_tpu import const
from autodist_tpu.kernel.common import variable_utils
from autodist_tpu.utils import logging


def _tree_to_flat(tree) -> Dict[str, np.ndarray]:
    names, leaves, _ = variable_utils.flatten_named(tree)
    return {n: np.asarray(jax.device_get(l)) for n, l in zip(names, leaves)}


def _flat_to_tree(template, flat: Dict[str, np.ndarray]):
    names, leaves, treedef = variable_utils.flatten_named(template)
    out = []
    for n, leaf in zip(names, leaves):
        if n not in flat:
            raise KeyError("checkpoint missing variable %r" % n)
        arr = flat[n]
        want = tuple(getattr(leaf, "shape", ()))
        if tuple(arr.shape) != want:
            raise ValueError("checkpoint var %r has shape %s, model wants %s"
                             % (n, arr.shape, want))
        out.append(arr)
    return variable_utils.unflatten_named(treedef, out)


import re as _re


def scan_checkpoint_metas(directory: str, pattern) -> list:
    """Sorted (step, filename) pairs for meta files matching ``pattern``
    (a compiled regex whose group 1 is the step). Foreign files in a
    shared directory are ignored, not crashed on. Shared by
    :class:`Saver` and :class:`ShardedSaver` so retention/discovery
    semantics cannot drift apart."""
    out = []
    for f in os.listdir(directory):
        m = pattern.match(f)
        if m:
            out.append((int(m.group(1)), f))
    return sorted(out)


class BackgroundWriter:
    """At most one background checkpoint write in flight. ``wait()`` joins
    the pending write and re-raises any error it hit — a failed checkpoint
    must never look like a success. Shared by :class:`Saver` and
    :class:`~autodist_tpu.checkpoint.sharded.ShardedSaver`."""

    def __init__(self, name: str):
        self._name = name
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def submit(self, fn):
        self.wait()  # serialize: at most one write in flight
        self._error = None

        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — re-raised in wait()
                self._error = e

        self._thread = threading.Thread(target=run, name=self._name,
                                        daemon=False)
        self._thread.start()

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
            err, self._error = self._error, None
            if err is not None:
                raise err


class Saver:
    """Save/restore distributed training state in the original layout.

    ``async_save=True`` moves the file writes to a background thread: the
    collective gathers (which every process must join) still happen inside
    ``save()``, but the host-side npz serialization — the slow part for
    large models — overlaps subsequent training steps. At most one write is
    in flight; a new ``save()`` joins the previous one first, and
    ``wait()`` joins explicitly (call before reading ``latest()``)."""

    def __init__(self, directory: Optional[str] = None, max_to_keep: int = 5,
                 chief_only: bool = True, async_save: bool = False):
        self.directory = directory or const.DEFAULT_CHECKPOINT_DIR
        self.max_to_keep = max_to_keep
        self.chief_only = chief_only
        self.async_save = async_save
        self._writer = BackgroundWriter("adt-ckpt-writer")
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------------ save

    def save(self, runner_or_step, state=None, step: Optional[int] = None) -> Optional[str]:
        """Write a checkpoint. Accepts a Runner (uses its state) or a
        DistributedStep + explicit TrainState. The gathers are collectives —
        EVERY process must call save(); only the file writes are
        chief-gated."""
        if hasattr(runner_or_step, "distributed_step"):  # Runner
            dstep = runner_or_step.distributed_step
            state = state if state is not None else runner_or_step.state
        else:
            dstep = runner_or_step
        if state is None:
            raise ValueError("no state to save")
        from autodist_tpu.telemetry import spans as tel
        # cross-process collectives: run on all processes before any gating
        with tel.span("ckpt.gather", "ckpt"):
            params = dstep.gather_params(state)
            opt_state_host = dstep.gather_opt_state(state)
            sync_state_host = dstep.gather_sync_state(state)
        if step is None:
            step = int(jax.device_get(state.step))
        if self.chief_only and not const.is_chief():
            return None
        path = os.path.join(self.directory, "ckpt-%d" % step)
        meta = {"step": step, "format": "autodist_tpu.v1",
                "strategy_id": dstep.strategy.id}

        def write():
            with tel.span("ckpt.write", "ckpt", step=int(step)):
                np.savez(path + ".params.npz", **_tree_to_flat(params))
                np.savez(path + ".opt.npz", **_tree_to_flat(opt_state_host))
                sync_flat = _tree_to_flat(sync_state_host)
                if sync_flat:
                    np.savez(path + ".sync.npz", **sync_flat)
                # meta last: a checkpoint only becomes visible to
                # _own_metas / latest() once all its data files exist
                with open(path + ".meta.json", "w") as f:
                    json.dump(meta, f)
            with tel.span("ckpt.gc", "ckpt"):
                self._gc()
            tel.counter_add("ckpt.saves")
            logging.info("saved checkpoint %s (step %d)", path, step)

        if not self.async_save:
            write()
            return path
        self._writer.submit(write)
        return path

    def wait(self):
        """Join a pending async write; re-raises any error the writer hit —
        a failed checkpoint must not look like a success."""
        self._writer.wait()

    _META_RE = _re.compile(r"^ckpt-(\d+)\.meta\.json$")

    def _own_metas(self):
        return scan_checkpoint_metas(self.directory, self._META_RE)

    def _gc(self):
        metas = self._own_metas()
        while len(metas) > self.max_to_keep:
            _, fname = metas.pop(0)
            victim = fname.replace(".meta.json", "")
            for suffix in (".meta.json", ".params.npz", ".opt.npz", ".sync.npz"):
                try:
                    os.remove(os.path.join(self.directory, victim + suffix))
                except FileNotFoundError:
                    pass

    # --------------------------------------------------------------- restore

    def latest(self) -> Optional[str]:
        self.wait()  # an in-flight async write must be visible to readers
        metas = self._own_metas()
        if not metas:
            return None
        return os.path.join(self.directory,
                            metas[-1][1].replace(".meta.json", ""))

    def restore_params(self, params_template, path: Optional[str] = None):
        """Params pytree in the original layout — usable with or without the
        framework (the vanilla-restore property)."""
        self.wait()  # the path from an async save() is valid only post-write
        path = path or self.latest()
        if path is None:
            raise FileNotFoundError("no checkpoint in %s" % self.directory)
        flat = dict(np.load(path + ".params.npz"))
        return _flat_to_tree(params_template, flat)

    def restore(self, runner, path: Optional[str] = None) -> Tuple[Any, int]:
        """Restore a Runner's distributed state; returns (state, step)."""
        self.wait()  # the path from an async save() is valid only post-write
        path = path or self.latest()
        if path is None:
            raise FileNotFoundError("no checkpoint in %s" % self.directory)
        dstep = runner.distributed_step
        params = self.restore_params(dstep.model_item.params, path)
        if dstep.model_item.optimizer is not None:
            opt_flat = dict(np.load(path + ".opt.npz"))
            opt_template = dstep.model_item.optimizer.init(
                dstep.model_item.params)
            opt_state = _flat_to_tree(opt_template, opt_flat)
        else:
            # step_fn mode: whatever optimizer state exists lives inside
            # the user's opaque state (saved under params)
            opt_state = {}
        sync_state = None
        if os.path.exists(path + ".sync.npz"):
            sync_flat = dict(np.load(path + ".sync.npz"))
            try:
                sync_state = _flat_to_tree(dstep._sync_state_init(), sync_flat)
            except (KeyError, ValueError) as e:
                logging.warning("sync state in checkpoint incompatible with "
                                "current strategy (%s); reinitializing", e)
        state = dstep.init_state(params, opt_state, sync_state)
        with open(path + ".meta.json") as f:
            step = json.load(f)["step"]
        # advance the step counter to the saved step
        from autodist_tpu.train_state import TrainState
        state = TrainState(step=dstep._put(np.asarray(step, np.int32),
                                           jax.sharding.PartitionSpec()),
                           params=state.params, opt_state=state.opt_state,
                           sync_state=state.sync_state)
        runner.state = state
        logging.info("restored checkpoint %s (step %d)", path, step)
        return state, step
