"""Tensor (model) parallelism primitives — Megatron-style sharded compute.

Beyond the reference, which shards only *storage* (its ``VariablePartitioner``
re-concatenates the full value for every consumer — reference
``docs/design/kernels.md:10-17`` "consumers read the re-concatenated value,
so compute is not model-parallel"). Here compute itself is sharded over the
``model`` mesh axis: column-parallel matmuls produce sharded activations with
no communication, row-parallel matmuls reduce partial products with one
``psum`` that XLA lowers to an ICI all-reduce, and embedding/softmax run
vocab-parallel (Shoeybi et al., Megatron-LM, arXiv 1909.08053).

All helpers are shape-polymorphic and no-op gracefully when the axis is not
bound, so ONE model definition serves single-device execution, tracing
outside shard_map (ModelItem capture), and sharded execution inside the
lowering — the same one-definition property ``parallel/sequence.py`` gives
sequence parallelism.

Gradient correctness: under ``shard_map`` the transpose of ``psum`` is
``psum``, so local autodiff computes exact derivatives of the
summed-over-devices loss; the lowering's uniform ``psum(complement)/N``
synchronization for mp-sharded variables (``kernel/graph_transformer.py``)
is exact against that convention — no f/g custom-vjp tricks needed.
"""
import jax
import jax.numpy as jnp

from autodist_tpu import const
from autodist_tpu.parallel.sequence import axis_bound


def reduce_model_parallel(x, axis_name: str = const.MODEL_AXIS):
    """All-reduce partial products over the model axis (the Megatron "g"
    in forward). No-op when unbound."""
    if not axis_bound(axis_name):
        return x
    return jax.lax.psum(x, axis_name)


def column_parallel_dense(x, kernel, bias=None):
    """Column-parallel matmul: kernel's OUTPUT dim is sharded over the model
    axis; the caller passes the local kernel shard and gets local (sharded)
    output columns. Pure local compute — no communication.

    kernel may have >2 dims ([d_model, heads_local, head_dim] for fused QKV
    projections); contraction is over x's last dim and kernel's first.
    """
    y = jnp.tensordot(x, kernel, axes=((x.ndim - 1,), (0,)))
    if bias is not None:
        y = y + bias
    return y


def row_parallel_dense(x, kernel, bias=None,
                       axis_name: str = const.MODEL_AXIS,
                       contract_dims: int = 1):
    """Row-parallel matmul: kernel's INPUT dim(s) are sharded over the model
    axis and x is the matching sharded activation; partial products are
    psum-reduced so every rank holds the full output. Bias is added AFTER the
    reduce (it is stored replicated).

    ``contract_dims``: how many leading kernel dims to contract (2 for
    attention out-projections [heads_local, head_dim, d_model]).
    """
    x_dims = tuple(range(x.ndim - contract_dims, x.ndim))
    k_dims = tuple(range(contract_dims))
    y = jnp.tensordot(x, kernel, axes=(x_dims, k_dims))
    y = reduce_model_parallel(y, axis_name)
    if bias is not None:
        y = y + bias
    return y


def vocab_parallel_embed(table, ids, axis_name: str = const.MODEL_AXIS,
                         name: str = "embed"):
    """Embedding lookup with the vocab dim of ``table`` sharded over the
    model axis: each rank looks up the ids it owns, others contribute zeros,
    one psum assembles the full embedding (Megatron VocabParallelEmbedding).

    When the model axis is UNBOUND (pp-only / dp-only configs) the lookup
    routes through ``ops.embedding.embedding_lookup(name=...)`` so the
    sparse-wire discovery sees it — for a tied table the discovery then
    deliberately keeps the dense sync (the output-head gradient is dense),
    but it decides that from evidence instead of warning about an
    un-routed gather."""
    from autodist_tpu.ops.embedding import embedding_lookup
    if not axis_bound(axis_name):
        return embedding_lookup(table, ids, name=name)
    rank = jax.lax.axis_index(axis_name)
    v_local = table.shape[0]
    local_ids = ids - rank * v_local
    ok = (local_ids >= 0) & (local_ids < v_local)
    # also via embedding_lookup: the sparse-wire discovery traces under
    # size-1 bound axes (where this branch runs) while the real program
    # may leave the axis unbound (pp-only) — both branches must present
    # the same named lookup or discovery misses it. On a truly
    # vocab-sharded table the var is mp-sharded, so no tap engages and
    # this is exactly jnp.take.
    emb = embedding_lookup(table, jnp.clip(local_ids, 0, v_local - 1),
                           name=name)
    emb = jnp.where(ok[..., None], emb, 0)
    out = jax.lax.psum(emb, axis_name)
    # an id owned by NO rank (out of range / negative) must not silently
    # embed as zeros while the single-device path NaNs loudly on the same
    # corrupt input — poison the row so the divergence cannot hide
    found = jax.lax.psum(ok.astype(out.dtype), axis_name)
    return jnp.where(found[..., None] > 0, out, jnp.nan)


def vocab_parallel_logits(x, table):
    """Output projection onto a vocab-sharded (tied) embedding table:
    logits columns stay sharded; pair with ``vocab_parallel_xent``."""
    return jnp.tensordot(x, table, axes=((x.ndim - 1,), (1,)))


def vocab_parallel_xent(logits, targets,
                        axis_name: str = const.MODEL_AXIS):
    """Per-token negative log-likelihood with the vocab (last) dim of
    ``logits`` sharded over the model axis. Numerically-stable global softmax
    via pmax/psum; the target logit is fetched from whichever rank owns it
    (Megatron vocab_parallel_cross_entropy). Returns nll with targets' shape.
    """
    # out-of-range targets (e.g. a -1 ignore sentinel) CLAMP to a valid
    # class in both branches — same contract as ops/xent.py. Without
    # this, the sharded path's target logit was owned by no rank and the
    # loss silently degraded to the bare lse with a garbage +softmax
    # gradient, diverging from single-device on the same data.
    if not axis_bound(axis_name):
        v_total = logits.shape[-1]
        targets = jnp.clip(targets, 0, v_total - 1)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    # the max offset cancels analytically in softmax, so it carries no
    # gradient (and pmax has no differentiation rule anyway) — stop the
    # gradient at the OPERAND so the pmax sees a zero tangent
    m = jax.lax.pmax(
        jnp.max(jax.lax.stop_gradient(logits), axis=-1), axis_name)
    e = jnp.exp(logits.astype(jnp.float32) - m[..., None])
    denom = jax.lax.psum(jnp.sum(e, axis=-1), axis_name)
    rank = jax.lax.axis_index(axis_name)
    v_local = logits.shape[-1]
    v_total = v_local * jax.lax.psum(1, axis_name)
    targets = jnp.clip(targets, 0, v_total - 1)
    local_t = targets - rank * v_local
    ok = (local_t >= 0) & (local_t < v_local)
    picked = jnp.take_along_axis(
        logits, jnp.clip(local_t, 0, v_local - 1)[..., None], axis=-1)[..., 0]
    picked = jnp.where(ok, picked.astype(jnp.float32), 0.0)
    target_logit = jax.lax.psum(picked, axis_name)
    return m + jnp.log(denom) - target_logit
