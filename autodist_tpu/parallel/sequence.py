"""Sequence/context-parallel helpers.

Long-context training support (absent in the reference — SURVEY §5): models
run inside the lowering's shard_map with the sequence dimension sharded over
the ``seq`` mesh axis, attending globally via ring or Ulysses attention
(``ops/attention.py``). These helpers give SP-aware models the pieces the
sharding takes away:

- ``position_offset``: global position of the local chunk's first token.
- ``shift_left``: the next chunk's first element, for next-token targets
  that cross shard boundaries.
- ``global_mean`` / ``global_weighted_mean``: loss reductions that are
  correct under sharding (a weighted mean of shard-weighted-means is NOT the
  global weighted mean; these psum numerator and denominator).

Each helper no-ops gracefully when the axis is not bound (single-device or
non-SP lowering), so one model definition serves both paths.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from autodist_tpu import const


def axis_bound(axis_name: str) -> bool:
    """True when running inside shard_map with this axis in scope."""
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def axis_size(axis_name: str) -> int:
    return jax.lax.psum(1, axis_name) if axis_bound(axis_name) else 1


def position_offset(local_seq_len: int, axis_name: str = const.SEQUENCE_AXIS):
    """Global position of local position 0 on this shard."""
    if not axis_bound(axis_name):
        return jnp.zeros((), jnp.int32)
    return jax.lax.axis_index(axis_name) * local_seq_len


def shift_left(x, axis_name: str = const.SEQUENCE_AXIS, axis: int = 1):
    """Shift a seq-sharded tensor left by one GLOBAL position: element i gets
    element i+1, with the boundary element fetched from the next shard (the
    last global position wraps; mask it out in the loss)."""
    local = jnp.roll(x, -1, axis=axis)
    if not axis_bound(axis_name):
        return local
    n = jax.lax.psum(1, axis_name)
    # next shard's first element arrives from rank r+1
    first = jax.lax.slice_in_dim(x, 0, 1, axis=axis)
    perm = [(i, (i - 1) % n) for i in range(n)]  # r receives from r+1
    incoming = jax.lax.ppermute(first, axis_name, perm)
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(-1, None)
    return jax.lax.dynamic_update_slice_in_dim(
        local, incoming, local.shape[axis] - 1, axis=axis)


def global_mean(x, axis_name: str = const.SEQUENCE_AXIS):
    """True global mean across shards — for METRICS. Do not use as a loss:
    the lowering already averages device losses/grads, so a loss should
    return the plain local ``jnp.mean`` (whose device-mean is the global
    mean for equal shards)."""
    if not axis_bound(axis_name):
        return jnp.mean(x)
    return jax.lax.pmean(jnp.mean(x), axis_name)


def global_weighted_mean(values, weights,
                         axis_name: str = const.SEQUENCE_AXIS):
    """SP-exact weighted-mean LOSS term: sum(v*w) / global_sum(w).

    Returns the device-local contribution scaled by the axis size, so that
    the lowering's mean-over-devices recovers exactly
    ``sum_all(v*w) / sum_all(w)`` — both the loss value (after the metrics
    pmean) and the gradients (after the grad psum/N) come out globally
    correct. (Returning an already-psum'd global value here would make the
    lowering's /N under-scale gradients by the shard count.)"""
    num = jnp.sum(values * weights)
    den = jnp.sum(weights)
    if not axis_bound(axis_name):
        return num / jnp.maximum(den, 1e-9)
    n = jax.lax.psum(1, axis_name)
    den_global = jax.lax.psum(den, axis_name)
    return n * num / jnp.maximum(den_global, 1e-9)
