"""Gradient bucketing for grouped all-reduce.

Analog of the reference's ScopedAllocator grouping (reference
``autodist/runner.py:40-46`` enables the grappler pass;
``strategy/all_reduce_strategy.py:60-67`` assigns group ids): small
gradients in the same strategy group are flattened, concatenated in
deterministic instance-key order (``collective_key.py``), all-reduced as one
payload (with the group's compressor applied to the concatenated vector),
then split back. XLA's all-reduce combiner does similar merging on its own;
explicit buckets additionally enable per-group compression and deterministic
payload layout across independently-compiled processes.
"""
import dataclasses
from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from autodist_tpu.kernel.synchronization.collective_key import CollectiveKey
from autodist_tpu.kernel.synchronization import compressor as compressor_lib

# compressors whose payload can be concatenated into one flat vector
_CONCATABLE = {"NoneCompressor", "HorovodCompressor", "HorovodCompressorEF",
               "BF16Compressor", "BF16CompressorEF"}


@dataclasses.dataclass
class Bucket:
    key: str
    var_names: List[str]            # deterministic order
    shapes: List[Tuple[int, ...]]
    sizes: List[int]
    dtype: str
    compressor_name: str

    @property
    def total_size(self) -> int:
        return sum(self.sizes)

    def make_compressor(self):
        return compressor_lib.create(self.compressor_name, self.key)


def make_buckets(ar_vars: Dict[str, object], var_infos) -> Tuple[List[Bucket], Dict[str, str]]:
    """Group unpartitioned AllReduce vars into buckets.

    ``ar_vars`` maps var_name -> AllReduceSynchronizer kernel. Returns
    (buckets, per_var) where ``per_var`` maps vars that must sync
    individually (non-concatable compressors like PowerSGD) to their
    compressor name."""
    groups: Dict[Tuple, List[str]] = {}
    per_var: Dict[str, str] = {}
    for name, sync in ar_vars.items():
        comp = sync.compressor.name
        if comp not in _CONCATABLE:
            per_var[name] = comp
            continue
        dtype = var_infos[name].dtype
        groups.setdefault((sync.group, comp, dtype), []).append(name)
    buckets = []
    for (gid, comp, dtype), names in sorted(groups.items(), key=lambda kv: kv[0][:2]):
        # deterministic in-bucket order by md5 instance key (reference parity)
        names = sorted(names, key=CollectiveKey.instance_key)
        shapes = [tuple(var_infos[n].shape) for n in names]
        sizes = [int(np.prod(s or (1,))) for s in shapes]
        buckets.append(Bucket(
            key="g%d_%s_%s" % (gid, comp, dtype), var_names=names,
            shapes=shapes, sizes=sizes, dtype=dtype, compressor_name=comp))
    return buckets, per_var


def bucket_reduce(bucket: Bucket, grads: Dict[str, jnp.ndarray], state, psum,
                  num_replicas: int):
    """Concat -> compress+psum -> mean -> split. Returns (synced dict, state)."""
    flat = jnp.concatenate([grads[n].reshape(-1) for n in bucket.var_names])
    comp = bucket.make_compressor()
    reduced, new_state = comp.reduce(flat, state, psum)
    reduced = reduced / num_replicas
    out = {}
    offset = 0
    for n, shape, size in zip(bucket.var_names, bucket.shapes, bucket.sizes):
        out[n] = reduced[offset:offset + size].reshape(shape)
        offset += size
    return out, new_state
