"""Gradient bucketing for grouped all-reduce.

Analog of the reference's ScopedAllocator grouping (reference
``autodist/runner.py:40-46`` enables the grappler pass;
``strategy/all_reduce_strategy.py:60-67`` assigns group ids): small
gradients in the same strategy group are flattened, concatenated in
deterministic instance-key order (``collective_key.py``), all-reduced as one
payload (with the group's compressor applied to the concatenated vector),
then split back. XLA's all-reduce combiner does similar merging on its own;
explicit buckets additionally enable per-group compression and deterministic
payload layout across independently-compiled processes.
"""
import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.kernel.synchronization.collective_key import CollectiveKey
from autodist_tpu.kernel.synchronization import compressor as compressor_lib

# compressors whose payload can be concatenated into one flat vector
_CONCATABLE = {"NoneCompressor", "HorovodCompressor", "HorovodCompressorEF",
               "BF16Compressor", "BF16CompressorEF",
               "Int8Compressor", "Int8CompressorEF"}


@dataclasses.dataclass
class Bucket:
    key: str
    var_names: List[str]            # deterministic order
    shapes: List[Tuple[int, ...]]
    sizes: List[int]
    dtype: str
    compressor_name: str
    spec: str = "AUTO"              # AUTO | ICI | DCN communication hint

    @property
    def total_size(self) -> int:
        return sum(self.sizes)

    def make_compressor(self):
        return compressor_lib.create(self.compressor_name, self.key)


def make_buckets(ar_vars: Dict[str, object], var_infos) -> Tuple[List[Bucket], Dict[str, str]]:
    """Group unpartitioned AllReduce vars into buckets.

    ``ar_vars`` maps var_name -> AllReduceSynchronizer kernel. Returns
    (buckets, per_var) where ``per_var`` maps vars that must sync
    individually (non-concatable compressors like PowerSGD) to their
    compressor name."""
    groups: Dict[Tuple, List[str]] = {}
    per_var: Dict[str, str] = {}
    for name, sync in ar_vars.items():
        comp = sync.compressor.name
        if comp not in _CONCATABLE:
            per_var[name] = comp
            continue
        dtype = var_infos[name].dtype
        spec = getattr(sync, "spec", "AUTO")
        groups.setdefault((sync.group, comp, dtype, spec), []).append(name)
    buckets = []
    for (gid, comp, dtype, spec), names in sorted(groups.items(),
                                                  key=lambda kv: kv[0][:2] + kv[0][3:]):
        # deterministic in-bucket order by md5 instance key (reference parity)
        names = sorted(names, key=CollectiveKey.instance_key)
        shapes = [tuple(var_infos[n].shape) for n in names]
        sizes = [int(np.prod(s or (1,))) for s in shapes]
        buckets.append(Bucket(
            key="g%d_%s_%s_%s" % (gid, comp, dtype, spec), var_names=names,
            shapes=shapes, sizes=sizes, dtype=dtype, compressor_name=comp,
            spec=spec))
    return buckets, per_var


def bucket_reduce(bucket: Bucket, grads: Dict[str, jnp.ndarray], state, psum,
                  num_replicas: int, ring_axes: Tuple[Tuple[str, int], ...] = ()):
    """Concat -> compress+psum -> mean -> split. Returns (synced dict, state).
    ``ring_axes`` — ((axis_name, size), ...) — arms int8 compressors'
    explicit quantized ring; multi-axis reductions run one ring per axis
    sequentially, keeping the 4x wire compression on dp x sp / dp x tp
    meshes."""
    flat = jnp.concatenate([grads[n].reshape(-1) for n in bucket.var_names])
    comp = bucket.make_compressor()
    if isinstance(comp, compressor_lib.Int8Compressor) and ring_axes:
        comp.ring_axes = tuple((a, n) for a, n in ring_axes if n > 1)
    reduced, new_state = comp.reduce(flat, state, psum)
    reduced = reduced / num_replicas
    out = {}
    offset = 0
    for n, shape, size in zip(bucket.var_names, bucket.shapes, bucket.sizes):
        out[n] = reduced[offset:offset + size].reshape(shape)
        offset += size
    return out, new_state


# --------------------------------------------------- quantized ring all-reduce


def _quant_i8(c):
    """Symmetric per-tensor int8 quantization: (q, scale). A non-finite
    input poisons the scale (NaN) so divergence propagates to the output
    like every other reduction path, instead of being silently zeroed."""
    absmax = jnp.max(jnp.abs(c))
    scale = jnp.where(jnp.isfinite(absmax),
                      jnp.maximum(absmax, 1e-30), jnp.nan) / 127.0
    safe = jnp.where(jnp.isfinite(scale), scale, 1.0)  # keep the i8 cast defined
    q = jnp.clip(jnp.round(c / safe), -127, 127).astype(jnp.int8)
    return q, scale


def _dequant_i8(q, scale):
    return q.astype(jnp.float32) * scale


def int8_ring_all_reduce(x, axis_name: str, n: int):
    """Sum a flat f32 vector over ``axis_name`` with an int8 wire payload
    (EQuARX-style quantized all-reduce, arXiv 2506.17615's setting).

    XLA's all-reduce cannot accumulate int8 without overflow, so the 4x
    wire compression needs an explicit ring: a reduce-scatter of n-1
    ppermute hops (each hop ships one int8-quantized chunk + its f32
    scale; accumulation stays f32 locally), then an all-gather of the
    completed chunks, quantized once. Requantization noise is bounded by
    ~1/254 of each hop's partial-sum magnitude; pair with error feedback
    (Int8CompressorEF) for training.

    Must run inside shard_map with ``axis_name`` bound and size ``n``.
    """
    L = x.shape[0]
    chunk = -(-L // n)
    xp = jnp.pad(x, (0, n * chunk - L)).reshape(n, chunk)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def rs_body(t, acc):
        send_idx = (idx - t) % n
        q, s = _quant_i8(acc[send_idx])
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        recv_idx = (idx - t - 1) % n
        return acc.at[recv_idx].add(_dequant_i8(q, s))

    acc = jax.lax.fori_loop(0, n - 1, rs_body, xp)
    own = (idx + 1) % n  # this replica's fully-reduced chunk

    def ag_body(t, carry):
        out, q, s = carry
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        return out.at[(own - t) % n].set(_dequant_i8(q, s)), q, s

    q0, s0 = _quant_i8(acc[own])
    # the owner uses its own quantized broadcast, not the f32 original:
    # every replica must hold BIT-IDENTICAL reduced values or SPMD param
    # copies drift apart step by step
    out0 = jnp.zeros_like(xp).at[own].set(_dequant_i8(q0, s0))
    out, _, _ = jax.lax.fori_loop(1, n, ag_body, (out0, q0, s0))
    return out.reshape(-1)[:L]


def int8_multi_axis_all_reduce(x, axes_sizes):
    """Sum a flat f32 vector over MULTIPLE mesh axes with int8 wire payload:
    one quantized ring per axis, sequentially — ring over axis 1 reduces
    within each axis-2 fiber, then ring over axis 2 combines the partials
    (the standard decomposition of a multi-axis all-reduce). Requantization
    noise accumulates once per stage; pair with error feedback for training.
    This is what keeps AutoStrategy's int8 candidate honest on dp x sp /
    dp x tp meshes instead of silently degrading to bf16."""
    for axis, n in axes_sizes:
        if n > 1:
            x = int8_ring_all_reduce(x, axis, n)
    return x


# ----------------------------------------------- hierarchical (DCN) psum


def hierarchical_psum(x, ici_axes, dcn_axes):
    """Bandwidth-hierarchy-aware sum: reduce-scatter over the fast ICI
    axes, all-reduce only the 1/N_ici shard over the slow DCN axes, then
    all-gather over ICI — the cross-slice wire carries 1/N_ici of the
    payload instead of all of it. This is what the strategy's ``spec=DCN``
    hint lowers to (the reference consumed its AUTO/NCCL/RING equivalent
    server-side, ``proto/synchronizers.proto:37-44``)."""
    ici_axes = tuple(ici_axes)
    dcn_axes = tuple(dcn_axes)
    if not dcn_axes:
        return jax.lax.psum(x, ici_axes)
    if not ici_axes:
        return jax.lax.psum(x, dcn_axes)
    n_ici = 1
    for a in ici_axes:
        # axis_size only exists on newer jax; psum of the constant 1 is
        # the classic spelling and folds to the same static size
        n_ici *= (jax.lax.axis_size(a) if hasattr(jax.lax, "axis_size")
                  else int(jax.lax.psum(1, a)))
    shape = x.shape
    flat = x.reshape(-1)
    L = flat.shape[0]
    pad = (-L) % n_ici
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = jax.lax.psum_scatter(flat, ici_axes, scatter_dimension=0,
                                 tiled=True)
    shard = jax.lax.psum(shard, dcn_axes)
    full = jax.lax.all_gather(shard, ici_axes, axis=0, tiled=True)
    return full[:L].reshape(shape)
