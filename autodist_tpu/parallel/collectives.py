"""Gradient bucketing for grouped all-reduce.

Analog of the reference's ScopedAllocator grouping (reference
``autodist/runner.py:40-46`` enables the grappler pass;
``strategy/all_reduce_strategy.py:60-67`` assigns group ids): small
gradients in the same strategy group are flattened, concatenated in
deterministic instance-key order (``collective_key.py``), all-reduced as one
payload (with the group's compressor applied to the concatenated vector),
then split back. XLA's all-reduce combiner does similar merging on its own;
explicit buckets additionally enable per-group compression and deterministic
payload layout across independently-compiled processes.
"""
import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.kernel.synchronization.collective_key import CollectiveKey
from autodist_tpu.kernel.synchronization import compressor as compressor_lib

# compressors whose payload can be concatenated into one flat vector
_CONCATABLE = {"NoneCompressor", "HorovodCompressor", "HorovodCompressorEF",
               "BF16Compressor", "BF16CompressorEF",
               "Int8Compressor", "Int8CompressorEF"}


@dataclasses.dataclass
class Bucket:
    key: str
    var_names: List[str]            # deterministic order
    shapes: List[Tuple[int, ...]]
    sizes: List[int]
    dtype: str
    compressor_name: str
    spec: str = "AUTO"              # AUTO | ICI | DCN communication hint
    schedule: str = "auto"          # auto | ring | rhd | hier algorithm knob

    @property
    def total_size(self) -> int:
        return sum(self.sizes)

    def make_compressor(self):
        return compressor_lib.create(self.compressor_name, self.key)


def make_buckets(ar_vars: Dict[str, object], var_infos) -> Tuple[List[Bucket], Dict[str, str]]:
    """Group unpartitioned AllReduce vars into buckets.

    ``ar_vars`` maps var_name -> AllReduceSynchronizer kernel. Returns
    (buckets, per_var) where ``per_var`` maps vars that must sync
    individually (non-concatable compressors like PowerSGD) to their
    compressor name."""
    groups: Dict[Tuple, List[str]] = {}
    per_var: Dict[str, str] = {}
    for name, sync in ar_vars.items():
        comp = sync.compressor.name
        if comp not in _CONCATABLE:
            per_var[name] = comp
            continue
        dtype = var_infos[name].dtype
        spec = getattr(sync, "spec", "AUTO")
        sched = (getattr(sync, "schedule", "auto") or "auto").lower()
        groups.setdefault((sync.group, comp, dtype, spec, sched),
                          []).append(name)
    buckets = []
    for (gid, comp, dtype, spec, sched), names in sorted(
            groups.items(), key=lambda kv: kv[0][:2] + kv[0][3:]):
        # deterministic in-bucket order by md5 instance key (reference parity)
        names = sorted(names, key=CollectiveKey.instance_key)
        shapes = [tuple(var_infos[n].shape) for n in names]
        sizes = [int(np.prod(s or (1,))) for s in shapes]
        key = "g%d_%s_%s_%s" % (gid, comp, dtype, spec)
        if sched != "auto":
            # schedule-pinned buckets key separately — the bucket psum
            # lowers per algorithm, so mixing schedules in one bucket
            # would silently drop the pin for all but one member
            key += "_%s" % sched
        buckets.append(Bucket(
            key=key, var_names=names, shapes=shapes, sizes=sizes,
            dtype=dtype, compressor_name=comp, spec=spec, schedule=sched))
    return buckets, per_var


def bucket_reduce(bucket: Bucket, grads: Dict[str, jnp.ndarray], state, psum,
                  num_replicas: int, ring_axes: Tuple[Tuple[str, int], ...] = ()):
    """Concat -> compress+psum -> mean -> split. Returns (synced dict, state).
    ``ring_axes`` — ((axis_name, size), ...) — arms int8 compressors'
    explicit quantized ring; multi-axis reductions run one ring per axis
    sequentially, keeping the 4x wire compression on dp x sp / dp x tp
    meshes."""
    flat = jnp.concatenate([grads[n].reshape(-1) for n in bucket.var_names])
    comp = bucket.make_compressor()
    if isinstance(comp, compressor_lib.Int8Compressor) and ring_axes:
        comp.ring_axes = tuple((a, n) for a, n in ring_axes if n > 1)
    reduced, new_state = comp.reduce(flat, state, psum)
    reduced = reduced / num_replicas
    out = {}
    offset = 0
    for n, shape, size in zip(bucket.var_names, bucket.shapes, bucket.sizes):
        out[n] = reduced[offset:offset + size].reshape(shape)
        offset += size
    return out, new_state


# ----------------------------------------------- collective-schedule IR


VALID_OP_KINDS = ("reduce", "reduce_scatter", "all_gather")


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in the gradient-sync schedule: ``kind`` over the
    named mesh ``axes``, reducing/gathering the sync unit ``unit`` (a
    bucket key, ``var:<name>`` or ``zero:<name>``)."""
    kind: str                       # reduce | reduce_scatter | all_gather
    unit: str
    axes: Tuple[str, ...]
    var_names: Tuple[str, ...] = ()
    payload_elems: int = 0
    wire_dtype: str = "fp32"


@dataclasses.dataclass(frozen=True)
class ScheduleStage:
    """An ordered stage of the schedule. ``ready_rank`` is the position
    in the backward pass (max var index of the unit's gradients, in
    params-flatten order) after which every op in the stage is launchable
    — stages are emitted in DESCENDING ready_rank, i.e. reverse layer
    order, because later layers' gradients materialize first in the
    backward sweep. ``deps`` names earlier stage indices that must
    complete before this stage launches (the lowering realizes them as an
    ``optimization_barrier`` chain)."""
    index: int
    ops: Tuple[CollectiveOp, ...]
    ready_rank: int = 0
    deps: Tuple[int, ...] = ()

    @property
    def var_names(self) -> Tuple[str, ...]:
        return tuple(n for op in self.ops for n in op.var_names)


@dataclasses.dataclass(frozen=True)
class GradSyncSchedule:
    """The gradient-synchronization schedule the overlapped lowering
    executes: ordered stages of collectives with explicit ready
    dependencies. ``validate()`` is the IR's one structural contract —
    the lowering, the lint, and the cost model all consume a schedule
    that passed it."""
    stages: Tuple[ScheduleStage, ...]

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    @property
    def num_collectives(self) -> int:
        return sum(len(st.ops) for st in self.stages)

    def validate(self) -> None:
        seen_units = set()
        for pos, st in enumerate(self.stages):
            if st.index != pos:
                raise ValueError(
                    "schedule stage %d carries index %d — stages must be "
                    "densely numbered in emission order" % (pos, st.index))
            if not st.ops:
                raise ValueError("schedule stage %d has no ops" % pos)
            for dep in st.deps:
                if not 0 <= dep < pos:
                    raise ValueError(
                        "stage %d depends on stage %d which does not "
                        "precede it" % (pos, dep))
            for op in st.ops:
                if op.kind not in VALID_OP_KINDS:
                    raise ValueError("unknown collective kind %r (stage %d)"
                                     % (op.kind, pos))
                if not op.axes:
                    raise ValueError("op %r reduces over no mesh axes"
                                     % (op.unit,))
                if (op.kind, op.unit) in seen_units:
                    raise ValueError("unit %r scheduled twice for %s"
                                     % (op.unit, op.kind))
                seen_units.add((op.kind, op.unit))
        ranks = [st.ready_rank for st in self.stages]
        if ranks != sorted(ranks, reverse=True):
            raise ValueError(
                "stages are not in reverse-readiness order (ready_rank "
                "must be non-increasing): %r" % (ranks,))

    def describe(self) -> str:
        lines = []
        for st in self.stages:
            ops = ", ".join("%s(%s%s)" % (
                op.kind, op.unit,
                ", int8" if op.wire_dtype == "int8" else "")
                for op in st.ops)
            dep = (" after %s" % (",".join(map(str, st.deps)))
                   if st.deps else "")
            lines.append("stage %d [ready@%d]%s: %s"
                         % (st.index, st.ready_rank, dep, ops))
        return "\n".join(lines)


def build_grad_sync_schedule(units, var_positions) -> GradSyncSchedule:
    """Order gradient-sync units into a :class:`GradSyncSchedule`.

    ``units`` — iterable of ``(unit_id, kind, var_names, payload_elems,
    wire_dtype, axes)`` — one entry per sync unit the lowering would
    execute (a concat bucket, a per-var sync, a ZeRO reduce-scatter).
    ``var_positions`` maps var_name -> index in params-flatten order.

    Stages are emitted one unit each, sorted by DESCENDING max var
    position (reverse layer order): in the backward sweep the LAST
    layer's gradients are produced first, so its stage launches first and
    overlaps with the remaining backward compute. Each stage depends on
    its predecessor — the serialized launch chain keeps XLA's all-reduce
    combiner from re-merging the collectives into one epilogue payload
    while leaving each free to overlap with compute."""
    entries = []
    for unit_id, kind, var_names, payload, wire_dtype, axes in units:
        if kind not in VALID_OP_KINDS:
            raise ValueError("unknown unit kind %r" % (kind,))
        rank = max((int(var_positions.get(n, 0)) for n in var_names),
                   default=0)
        entries.append((rank, unit_id, kind, tuple(var_names),
                        int(payload), wire_dtype, tuple(axes)))
    # descending readiness rank; unit_id tie-break keeps emission stable
    entries.sort(key=lambda e: (-e[0], e[1]))
    stages = []
    for i, (rank, unit_id, kind, names, payload, wire, axes) in enumerate(
            entries):
        op = CollectiveOp(kind=kind, unit=unit_id, axes=axes,
                          var_names=names, payload_elems=payload,
                          wire_dtype=wire)
        stages.append(ScheduleStage(index=i, ops=(op,), ready_rank=rank,
                                    deps=(i - 1,) if i else ()))
    sched = GradSyncSchedule(stages=tuple(stages))
    sched.validate()
    return sched


def overlap_token(tree):
    """Chain token for the overlapped lowering: a 1-element data-dependent
    view of a unit's reduced output. Deliberately NOT an arithmetic zero —
    XLA folds ``x * 0`` and would sever the dependency the barrier chain
    exists to create."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return None
    return jnp.ravel(leaves[0])[:1].astype(jnp.float32)


def barrier_chain(tree, token):
    """Identity on ``tree`` that XLA cannot reorder before ``token``'s
    producers: ``optimization_barrier`` over (leaves..., token). This is
    the sequencing primitive the overlapped lowering threads between sync
    units — values are bit-identical to the unchained program (the
    barrier is an identity op), but the schedule's stage order becomes a
    real data dependence, so the all-reduce combiner cannot merge the
    per-stage collectives back into one epilogue reduce and the
    latency-hiding scheduler can hide each under remaining backward
    compute. Returns ``(tree, token)`` unchanged when ``token`` is None
    (first stage — nothing to order after)."""
    if token is None:
        return tree, token
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree, token
    out = jax.lax.optimization_barrier(tuple(leaves) + (token,))
    return jax.tree_util.tree_unflatten(treedef, out[:-1]), out[-1]


# --------------------------------------------------- quantized wire codec


def wire_block_size() -> int:
    """Elements per absmax-scale block for the int8 wire codec
    (``ADT_WIRE_BLOCK``; floor-clamped to 8 — below that the f32 sidecar
    cancels the payload saving)."""
    from autodist_tpu import const as _const
    return max(int(_const.ENV.ADT_WIRE_BLOCK.val), 8)


def _quant_i8(c):
    """Symmetric per-tensor int8 quantization: (q, scale). A non-finite
    input poisons the scale (NaN) so divergence propagates to the output
    like every other reduction path, instead of being silently zeroed."""
    absmax = jnp.max(jnp.abs(c))
    scale = jnp.where(jnp.isfinite(absmax),
                      jnp.maximum(absmax, 1e-30), jnp.nan) / 127.0
    safe = jnp.where(jnp.isfinite(scale), scale, 1.0)  # keep the i8 cast defined
    q = jnp.clip(jnp.round(c / safe), -127, 127).astype(jnp.int8)
    return q, scale


def _dequant_i8(q, scale):
    return q.astype(jnp.float32) * scale


def quant_i8_block(x, block: int = 0):
    """Blockwise-scaled symmetric int8 quantization of a flat f32 vector
    (EQuARX's wire format, arXiv 2506.17615): pad to a block multiple,
    one absmax scale per ``block`` elements. Returns ``(q, s)`` with
    ``q: int8 [nb, block]`` and ``s: f32 [nb]``. Like :func:`_quant_i8`,
    a non-finite block poisons its scale (NaN) so divergence propagates
    instead of clipping away."""
    block = block or wire_block_size()
    L = x.shape[0]
    nb = max(-(-L // block), 1)
    xp = jnp.pad(x.astype(jnp.float32), (0, nb * block - L)).reshape(nb, block)
    absmax = jnp.max(jnp.abs(xp), axis=1)
    scale = jnp.where(jnp.isfinite(absmax),
                      jnp.maximum(absmax, 1e-30), jnp.nan) / 127.0
    safe = jnp.where(jnp.isfinite(scale), scale, 1.0)
    q = jnp.clip(jnp.round(xp / safe[:, None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequant_i8_block(q, s, length: int):
    """Inverse of :func:`quant_i8_block`: flat f32 vector of ``length``."""
    out = (q.astype(jnp.float32) * s.astype(jnp.float32)[:, None])
    return out.reshape(-1)[:length]


def quant_wire(arr, block: int = 0):
    """Any-shape array -> the wire container the quantized PS path ships:
    ``{"q": int8 [nb, block], "s": f32 [nb]}`` (flattened blockwise). The
    original shape is NOT carried — both endpoints know it statically
    (var_infos / PSVarPlan)."""
    flat = jnp.asarray(arr).astype(jnp.float32).reshape(-1)
    q, s = quant_i8_block(flat, block)
    return {"q": q, "s": s}


def dequant_wire(wire, shape, dtype=jnp.float32):
    """Inverse of :func:`quant_wire` given the variable's static shape."""
    length = int(np.prod(tuple(shape) or (1,)))
    return dequant_i8_block(wire["q"], wire["s"],
                            length).reshape(tuple(shape)).astype(dtype)


def quant_wire_np(arr, block: int = 0):
    """Host-side (numpy) mirror of :func:`quant_wire` — the PS store
    quantizes pulls on the host without paying a jit dispatch. Same
    round-half-to-even rounding as the jnp codec."""
    block = block or wire_block_size()
    flat = np.asarray(arr, np.float32).reshape(-1)
    L = flat.shape[0]
    nb = max(-(-L // block), 1)
    xp = np.pad(flat, (0, nb * block - L)).reshape(nb, block)
    absmax = np.max(np.abs(xp), axis=1)
    with np.errstate(invalid="ignore"):
        scale = np.where(np.isfinite(absmax),
                         np.maximum(absmax, 1e-30), np.nan) / 127.0
    safe = np.where(np.isfinite(scale), scale, 1.0)
    q = np.clip(np.round(xp / safe[:, None]), -127, 127).astype(np.int8)
    return {"q": q, "s": scale.astype(np.float32)}


def dequant_wire_np(wire, shape, dtype=np.float32):
    """Host-side mirror of :func:`dequant_wire` (store-boundary dequant)."""
    length = int(np.prod(tuple(shape) or (1,)))
    q = np.asarray(wire["q"], np.float32)
    s = np.asarray(wire["s"], np.float32)
    out = (q * s[:, None]).reshape(-1)[:length]
    return out.reshape(tuple(shape)).astype(dtype)


def wire_avals(shape, block: int = 0):
    """ShapeDtypeStructs matching :func:`quant_wire`'s output for a
    variable of ``shape`` — the lowering's aval stand-in for a quantized
    PS value (must never cost a real pull)."""
    import jax as _jax
    block = block or wire_block_size()
    length = int(np.prod(tuple(shape) or (1,)))
    nb = max(-(-length // block), 1)
    return {"q": _jax.ShapeDtypeStruct((nb, block), np.int8),
            "s": _jax.ShapeDtypeStruct((nb,), np.float32)}


def wire_quantizable(info, min_block: bool = False) -> bool:
    """The ONE eligibility gate for the int8 wire codec, shared by the
    builders, the host-PS planner, the search space, and the cost model
    (five hand-rolled copies would drift). Dense float only — sparse
    (ids, values) pairs have no absmax blocks, integer values no scale
    (the linter's ADT310). ``min_block=True`` additionally requires at
    least one scale block (the ADT311 *policy* gate the builders and the
    searcher apply; the planner and cost model stay permissive because
    the lowering quantizes whatever the plan says)."""
    if info is None or getattr(info, "sparse", False):
        return False
    if not str(getattr(info, "dtype", "float32")).startswith(
            ("float", "bfloat")):
        return False
    if min_block and getattr(info, "num_elements", 0) < wire_block_size():
        return False
    return True


def int8_wire_payload_bytes(num_elements: int, itemsize: int = 4,
                            block: int = 0):
    """(quantized_bytes, full_width_bytes) for one wire crossing of a
    ``num_elements`` payload: int8 body padded to a block multiple PLUS
    the f32 scale sidecar, vs the uncompressed ``itemsize``-wide payload.
    The ONE byte-accounting formula shared by the cost model, the
    telemetry counters, and the drift tests — they can never disagree."""
    block = block or wire_block_size()
    nb = max(-(-int(num_elements) // block), 1)
    return nb * block + nb * 4, int(num_elements) * int(itemsize)


def int8_ring_all_reduce(x, axis_name: str, n: int):
    """Sum a flat f32 vector over ``axis_name`` with an int8 wire payload
    (EQuARX-style quantized all-reduce, arXiv 2506.17615's setting).

    XLA's all-reduce cannot accumulate int8 without overflow, so the 4x
    wire compression needs an explicit ring: a reduce-scatter of n-1
    ppermute hops (each hop ships one int8-quantized chunk + its f32
    scale; accumulation stays f32 locally), then an all-gather of the
    completed chunks, quantized once. Requantization noise is bounded by
    ~1/254 of each hop's partial-sum magnitude; pair with error feedback
    (Int8CompressorEF) for training.

    Must run inside shard_map with ``axis_name`` bound and size ``n``.
    """
    L = x.shape[0]
    chunk = -(-L // n)
    xp = jnp.pad(x, (0, n * chunk - L)).reshape(n, chunk)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def rs_body(t, acc):
        send_idx = (idx - t) % n
        q, s = _quant_i8(acc[send_idx])
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        recv_idx = (idx - t - 1) % n
        return acc.at[recv_idx].add(_dequant_i8(q, s))

    acc = jax.lax.fori_loop(0, n - 1, rs_body, xp)
    own = (idx + 1) % n  # this replica's fully-reduced chunk

    def ag_body(t, carry):
        out, q, s = carry
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        return out.at[(own - t) % n].set(_dequant_i8(q, s)), q, s

    q0, s0 = _quant_i8(acc[own])
    # the owner uses its own quantized broadcast, not the f32 original:
    # every replica must hold BIT-IDENTICAL reduced values or SPMD param
    # copies drift apart step by step
    out0 = jnp.zeros_like(xp).at[own].set(_dequant_i8(q0, s0))
    out, _, _ = jax.lax.fori_loop(1, n, ag_body, (out0, q0, s0))
    return out.reshape(-1)[:L]


def int8_block_all_reduce(x, axis_name: str, n: int, block: int = 0):
    """Sum a flat f32 vector over ``axis_name`` with a blockwise-scaled
    int8 wire payload in the EQuARX two-phase shape (arXiv 2506.17615):

    1. **quantize -> reduce-scatter on the int8 payload**: each device
       blockwise-quantizes all ``n`` peer chunks and ships them in ONE
       ``all_to_all`` (int8 body + f32 scale sidecar — a reduce-scatter
       whose summation is deferred to the receiver);
    2. **local dequant-accumulate**: the received chunks dequantize and
       sum in f32 locally, so accumulation never overflows int8;
    3. **quantize -> all-gather**: the completed chunk re-quantizes once
       and all-gathers (int8 + scales); every replica dequantizes the
       SAME bytes, so reduced values are bit-identical across replicas
       (the SPMD invariant that keeps param copies from drifting).

    Two collectives total (vs the ring's 2(n-1) ppermute hops) and
    exactly two quantizations of any element; pair with error feedback
    (``Int8CompressorEF``) for training. Must run inside shard_map with
    ``axis_name`` bound at size ``n``.
    """
    block = block or wire_block_size()
    if n <= 1:
        return x
    L = x.shape[0]
    # chunk per device, rounded up to whole scale blocks so every chunk's
    # scales are self-contained
    chunk = -(-(-(-L // n)) // block) * block
    nb = chunk // block
    xp = jnp.pad(x.astype(jnp.float32),
                 (0, n * chunk - L)).reshape(n, nb, block)
    # phase 1: blockwise-quantize every peer chunk, one all_to_all for the
    # int8 body and one for the f32 scales (the reduce-scatter wire)
    absmax = jnp.max(jnp.abs(xp), axis=2)
    scale = jnp.where(jnp.isfinite(absmax),
                      jnp.maximum(absmax, 1e-30), jnp.nan) / 127.0
    safe = jnp.where(jnp.isfinite(scale), scale, 1.0)
    q = jnp.clip(jnp.round(xp / safe[:, :, None]), -127, 127).astype(jnp.int8)
    q = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s = jax.lax.all_to_all(scale.astype(jnp.float32), axis_name,
                           split_axis=0, concat_axis=0)
    # phase 2: dequant-accumulate locally in f32, re-quantize the reduced
    # chunk, all-gather body + scales, dequantize the shared bytes
    acc = jnp.sum(q.astype(jnp.float32) * s[:, :, None], axis=0)  # [nb, block]
    q2, s2 = quant_i8_block(acc.reshape(-1), block)
    q2g = jax.lax.all_gather(q2, axis_name, axis=0)               # [n, nb, block]
    s2g = jax.lax.all_gather(s2, axis_name, axis=0)               # [n, nb]
    out = q2g.astype(jnp.float32) * s2g[:, :, None]
    return out.reshape(-1)[:L]


def int8_block_reduce_scatter(x, axis_name: str, n: int, block: int = 0):
    """Reduce-scatter a flat f32 vector over ``axis_name`` with a
    blockwise int8 wire payload — phases 1+2 of the EQuARX two-phase
    all-reduce (:func:`int8_block_all_reduce`), stopping before the
    all-gather: each device blockwise-quantizes all ``n`` peer chunks,
    ships them in ONE ``all_to_all`` (int8 body + f32 scale sidecar),
    then dequant-accumulates its own chunk locally in f32 (accumulation
    never overflows int8). Returns this device's summed chunk of
    ``ceil-to-block(ceil(L/n))`` elements; chunk ``i`` lands on the
    device at axis position ``i`` (matching ``lax.all_gather`` order).
    This is the gradient wire of the ZeRO-sharded update
    (``kernel/synchronization/zero_synchronizer.py``). Must run inside
    shard_map with ``axis_name`` bound at size ``n``."""
    block = block or wire_block_size()
    L = x.shape[0]
    chunk = -(-(-(-L // n)) // block) * block
    nb = chunk // block
    if n <= 1:
        return jnp.pad(x.astype(jnp.float32), (0, chunk - L))
    xp = jnp.pad(x.astype(jnp.float32),
                 (0, n * chunk - L)).reshape(n, nb, block)
    absmax = jnp.max(jnp.abs(xp), axis=2)
    scale = jnp.where(jnp.isfinite(absmax),
                      jnp.maximum(absmax, 1e-30), jnp.nan) / 127.0
    safe = jnp.where(jnp.isfinite(scale), scale, 1.0)
    q = jnp.clip(jnp.round(xp / safe[:, :, None]), -127, 127).astype(jnp.int8)
    q = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s = jax.lax.all_to_all(scale.astype(jnp.float32), axis_name,
                           split_axis=0, concat_axis=0)
    acc = jnp.sum(q.astype(jnp.float32) * s[:, :, None], axis=0)  # [nb, block]
    return acc.reshape(-1)


def int8_block_all_gather(x, axis_name: str, n: int, block: int = 0):
    """All-gather a flat f32 chunk over ``axis_name`` with a blockwise
    int8 wire payload: quantize the local chunk once, all-gather body +
    scales, and dequantize the SHARED bytes — every replica (including
    the chunk's owner) reconstructs from the same int8 image, so the
    result is bit-identical across replicas (the SPMD invariant). Pads
    the chunk to a whole number of scale blocks; returns the
    ``[n * padded_chunk]`` concatenation in axis order. This is the
    update wire of the ZeRO-sharded weight update."""
    block = block or wire_block_size()
    if n <= 1:
        return x.astype(jnp.float32)
    q, s = quant_i8_block(x.astype(jnp.float32).reshape(-1), block)
    qg = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)   # [n*nb, block]
    sg = jax.lax.all_gather(s, axis_name, axis=0, tiled=True)   # [n*nb]
    return (qg.astype(jnp.float32) * sg[:, None]).reshape(-1)


def int8_multi_axis_all_reduce(x, axes_sizes, block: int = 0):
    """Sum a flat f32 vector over MULTIPLE mesh axes with int8 wire
    payload: one two-phase quantized all-reduce per axis, sequentially —
    the reduction over axis 1 completes within each axis-2 fiber, then
    axis 2 combines the partials (the standard decomposition of a
    multi-axis all-reduce). Requantization noise accumulates once per
    stage; pair with error feedback for training. This is what keeps the
    int8 wire honest on dp x sp / dp x tp meshes instead of silently
    degrading to bf16."""
    for axis, n in axes_sizes:
        if n > 1:
            x = int8_block_all_reduce(x, axis, n, block)
    return x


# ----------------------------------------------- hierarchical (DCN) psum


def hierarchical_psum(x, ici_axes, dcn_axes):
    """Bandwidth-hierarchy-aware sum: reduce-scatter over the fast ICI
    axes, all-reduce only the 1/N_ici shard over the slow DCN axes, then
    all-gather over ICI — the cross-slice wire carries 1/N_ici of the
    payload instead of all of it. This is what the strategy's ``spec=DCN``
    hint lowers to (the reference consumed its AUTO/NCCL/RING equivalent
    server-side, ``proto/synchronizers.proto:37-44``)."""
    ici_axes = tuple(ici_axes)
    dcn_axes = tuple(dcn_axes)
    if not dcn_axes:
        return jax.lax.psum(x, ici_axes)
    if not ici_axes:
        return jax.lax.psum(x, dcn_axes)
    n_ici = 1
    for a in ici_axes:
        # axis_size only exists on newer jax; psum of the constant 1 is
        # the classic spelling and folds to the same static size
        n_ici *= (jax.lax.axis_size(a) if hasattr(jax.lax, "axis_size")
                  else int(jax.lax.psum(1, a)))
    shape = x.shape
    flat = x.reshape(-1)
    L = flat.shape[0]
    pad = (-L) % n_ici
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = jax.lax.psum_scatter(flat, ici_axes, scatter_dimension=0,
                                 tiled=True)
    shard = jax.lax.psum(shard, dcn_axes)
    full = jax.lax.all_gather(shard, ici_axes, axis=0, tiled=True)
    return full[:L].reshape(shape)


# ------------------------------------------ synthesized collective schedules


# The per-sync-op schedule algorithms the searcher may pick and the cost
# model prices per topology level:
#   ring — one fused all-reduce (XLA's default ring): 2(n-1)/n of the
#          payload per link, 2(n-1) hops;
#   rhd  — recursive halving/doubling, realized as reduce-scatter +
#          all-gather over the same axes: identical per-link bytes, but
#          ~2*log2(n) latency hops instead of 2(n-1);
#   hier — hierarchical two-level: reduce-scatter over the intra-host
#          axes at fast bandwidth, all-reduce the 1/c shard over the
#          per-host leaders, all-gather back over intra-host — the slow
#          inter-host links carry 1/c of the payload.
SCHEDULE_ALGORITHMS = ("ring", "rhd", "hier")


def rhd_psum(x, axes):
    """Recursive-halving/doubling all-reduce over ``axes``, realized as
    the reduce-scatter + all-gather composition (halving = psum_scatter,
    doubling = all_gather). Exactly the same summation as ``psum`` —
    every element is reduced once by the scatter phase and broadcast
    bit-identically by the gather — so replicated param copies cannot
    drift. Must run inside shard_map with ``axes`` bound."""
    axes = tuple(axes)
    if not axes:
        return x
    n = 1
    for a in axes:
        n *= (jax.lax.axis_size(a) if hasattr(jax.lax, "axis_size")
              else int(jax.lax.psum(1, a)))
    if n <= 1:
        return x
    shape = x.shape
    flat = x.reshape(-1)
    L = flat.shape[0]
    pad = (-L) % n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    shard = jax.lax.psum_scatter(flat, axes, scatter_dimension=0,
                                 tiled=True)
    full = jax.lax.all_gather(shard, axes, axis=0, tiled=True)
    return full[:L].reshape(shape)


def synthesize_collective_candidates(unit: str, axes, intra_axes=(),
                                     inter_axes=(), payload_elems: int = 0,
                                     wire_dtype: str = "fp32",
                                     var_names=()):
    """Synthesize the candidate stage compositions for one ``reduce``
    sync unit over named mesh ``axes`` — the TACCL-style sketch
    expansion (arXiv 2111.04867) restricted to the three algorithms the
    lowering can execute. Returns ``{algorithm: (CollectiveOp, ...)}``;
    the ``hier`` candidate exists only when both an intra- and an
    inter-host axis are named (the multi-level reduction of arXiv
    2110.10548 needs two levels to place onto). Every candidate is
    reduction-equivalent to the flat reduce it replaces — asserted by
    :func:`reduction_equivalent`, which the ADT522 lint re-checks."""
    axes = tuple(axes)
    intra = tuple(a for a in (intra_axes or ()) if a in axes)
    inter = tuple(a for a in (inter_axes or ()) if a in axes)
    names = tuple(var_names)

    def op(kind, over, elems=payload_elems):
        return CollectiveOp(kind=kind, unit=unit, axes=tuple(over),
                            var_names=names, payload_elems=int(elems),
                            wire_dtype=wire_dtype)

    out = {
        "ring": (op("reduce", axes),),
        "rhd": (op("reduce_scatter", axes),
                op("all_gather", axes)),
    }
    if intra and inter:
        out["hier"] = (op("reduce_scatter", intra),
                       op("reduce", inter),
                       op("all_gather", intra))
    return out


def reduction_equivalent(stages, target) -> bool:
    """True when a synthesized stage composition computes exactly the
    reduction ``target`` does — the ADT522 contract. A composition is
    equivalent iff (a) it reduces over exactly the target's axes, each
    axis exactly once, (b) every reduce_scatter is matched by a later
    all_gather over the SAME axes (the shard comes back), and (c)
    nothing else is interleaved. ``target`` is a ``reduce``
    :class:`CollectiveOp` (or anything with ``.axes``)."""
    want = tuple(target.axes)
    ops = tuple(stages)
    if not ops:
        return False
    reduced = []           # axes whose reduction has been applied
    open_scatters = []     # reduce_scatter axes awaiting their all_gather
    for op in ops:
        if op.kind == "reduce":
            reduced.extend(op.axes)
        elif op.kind == "reduce_scatter":
            reduced.extend(op.axes)
            open_scatters.append(tuple(op.axes))
        elif op.kind == "all_gather":
            if not open_scatters or open_scatters[-1] != tuple(op.axes):
                return False  # gathers a shard nothing scattered
            open_scatters.pop()
        else:
            return False
    if open_scatters:
        return False  # a shard never came back: not an all-reduce
    return sorted(reduced) == sorted(want) and len(set(reduced)) == len(
        reduced)
