"""Deterministic device-mesh construction.

The TPU-native replacement for the reference's TF ClusterSpec + deterministic
ip:port ordering (reference ``autodist/cluster.py:70-82``): every process must
independently build the *same* mesh so that independently-lowered programs
agree on collective participants — the analog of the reference's
deterministic collective key generation
(``kernel/synchronization/collective_key.py:43-70``).

Devices are ordered by (process_index, device id), which is stable across
all processes of one jax.distributed job.
"""
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from autodist_tpu import const
from autodist_tpu.utils import logging


def ordered_devices(n: Optional[int] = None, backend: Optional[str] = None) -> List:
    devs = sorted(jax.devices(backend) if backend else jax.devices(),
                  key=lambda d: (d.process_index, d.id))
    if n is not None:
        if len(devs) < n:
            raise ValueError("need %d devices, runtime has %d" % (n, len(devs)))
        devs = devs[:n]
    return devs


def build_mesh(num_devices: Optional[int] = None,
               axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence] = None,
               backend: Optional[str] = None) -> Mesh:
    """Build a Mesh with named axes.

    ``axes`` maps axis name -> size, in major-to-minor order; sizes must
    multiply to the device count. Default: a 1-D data-parallel mesh over all
    devices. Axis order convention (outer->inner): pipe, data, expert, seq,
    model — inner axes get the fastest ICI links (nearest-neighbor), which is
    where tensor-parallel collectives belong.
    """
    if devices is None:
        devices = ordered_devices(num_devices, backend)
    devices = list(devices)
    if not axes:
        axes = {const.DATA_AXIS: len(devices)}
    sizes = list(axes.values())
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError("mesh axes %s don't cover %d devices" % (axes, len(devices)))
    arr = np.array(devices, dtype=object).reshape(sizes)
    mesh = Mesh(arr, tuple(axes.keys()))
    logging.debug("built mesh %s over %d devices", dict(axes), len(devices))
    return mesh


def host_to_mesh(mesh: Mesh, value, pspec) -> jax.Array:
    """Place a value onto the mesh with the given PartitionSpec.
    Works single- and multi-process (every process provides its addressable
    shards from the same host-global value).

    On a single-process mesh, already-device-resident values take the
    ``device_put`` path: XLA reshards on device (a no-op when the sharding
    already matches). ``np.asarray`` on a jax.Array would DOWNLOAD it to
    host and re-upload — invisible over PCIe, but a 220 MB parameter tree
    over a slow host<->device link pays minutes for nothing. Multi-process
    meshes stay on the callback path: ``device_put`` cannot retarget a
    committed process-local array onto a mesh this process only partly
    owns, and for uncommitted arrays it inserts per-leaf cross-host
    equality collectives — each-process-provides-its-shards is the
    multi-process contract here."""
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh, pspec)
    if isinstance(value, jax.Array) and jax.process_count() == 1:
        return jax.device_put(value, sharding)
    arr = np.asarray(value)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def tree_to_mesh(mesh: Mesh, tree, pspec):
    """Place a whole pytree onto the mesh with ONE shared PartitionSpec.
    Single-process meshes take the batched ``device_put`` fast path (one
    dispatch for the whole tree, not one per leaf — the per-step PS pull of
    a 100-variable model is 100x fewer host round-trips); multi-process
    falls back to the per-leaf host-global placement."""
    from jax.sharding import NamedSharding
    if jax.process_count() == 1:
        return jax.device_put(tree, NamedSharding(mesh, pspec))
    return jax.tree_util.tree_map(
        lambda leaf: host_to_mesh(mesh, leaf, pspec), tree)


def dcn_axes(mesh: Mesh) -> tuple:
    """Mesh axes that cross process (host) boundaries — the axes whose
    collectives ride DCN rather than ICI. Detected from the device layout
    (process_index varies along the axis); ``ADT_DCN_AXES`` (comma list)
    overrides for single-process tests and exotic topologies."""
    ov = const.ENV.ADT_DCN_AXES.val
    if ov:
        names = [a.strip() for a in ov.split(",") if a.strip()]
        return tuple(a for a in names if a in mesh.axis_names)
    procs = np.vectorize(lambda d: d.process_index)(mesh.devices)
    out = []
    for i, name in enumerate(mesh.axis_names):
        if procs.min(axis=i).tolist() != procs.max(axis=i).tolist():
            out.append(name)
    return tuple(out)


def local_mesh(backend: Optional[str] = None,
               axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Mesh over THIS process's devices only — the between-graph replication
    substrate for async PS (no cross-process collectives; processes couple
    only through the parameter service, reference
    ``ps_synchronizer.py:556-633`` semantics)."""
    devs = sorted(jax.local_devices(backend=backend) if backend
                  else jax.local_devices(), key=lambda d: d.id)
    return build_mesh(devices=devs, axes=axes)


def mesh_from_strategy(strategy, resource_spec=None, backend: Optional[str] = None) -> Mesh:
    """Mesh for a compiled Strategy: replicas define the data axis; the
    optional ``mesh_shape`` extension adds model/pipeline/sequence axes."""
    n = len(strategy.graph_config.replicas)
    shape = strategy.graph_config.mesh_shape
    if shape:
        return build_mesh(axes=dict(shape), backend=backend)
    return build_mesh(num_devices=n or None, backend=backend)
