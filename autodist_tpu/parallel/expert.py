"""Expert parallelism — Mixture-of-Experts with all_to_all token routing.

Beyond the reference (data-parallel only, reference
``docs/design/architecture.rst:46-48``). Experts are stacked on a leading
dim sharded over the ``expert`` mesh axis (``VarConfig.mp_axes = {0:
'expert'}``); tokens are routed to their expert's owning device with one
``lax.all_to_all`` each way (GShard, arXiv 2006.16668; Switch Transformer,
arXiv 2101.03961). Static shapes throughout — the MXU-hostile part of MoE
(data-dependent routing) is expressed as dense one-hot dispatch/combine
einsums with a fixed per-expert capacity, which is the idiomatic TPU
formulation (dynamic scatter would defeat XLA tiling).

All helpers degrade gracefully when the axis is unbound: single-device
execution computes every expert locally — one model definition for both
paths, as with ``parallel/tensor.py`` / ``parallel/pipeline.py``.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu import const
from autodist_tpu.parallel.sequence import axis_bound


def top1_dispatch(router_probs, capacity: int):
    """Top-1 gating with capacity (Switch). router_probs [T, E] ->
    (dispatch [T, E, C] one-hot, combine [T, E, C] gated, aux_loss scalar).

    Tokens beyond an expert's capacity are dropped (their combine weights
    are zero -> they pass through the residual connection only).
    """
    T, E = router_probs.shape
    expert_idx = jnp.argmax(router_probs, axis=-1)               # [T]
    gate = jnp.take_along_axis(router_probs, expert_idx[:, None], 1)[:, 0]
    onehot = jax.nn.one_hot(expert_idx, E, dtype=router_probs.dtype)  # [T, E]
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0              # [T, E]
    keep = (pos >= 0) & (pos < capacity)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=router_probs.dtype)            # [T, E, C]
    dispatch = pos_oh * keep.astype(router_probs.dtype)[..., None]
    combine = dispatch * gate[:, None, None]
    # Switch aux load-balance loss: E * sum_e fraction_dispatched * mean_prob
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(router_probs, axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


def _dispatch_a2a(x_ecd, axis_name):
    """[E, C, d] (inputs for every global expert, from local tokens) ->
    [E_local, N*C, d] (this rank's experts' inputs from every rank)."""
    n = jax.lax.psum(1, axis_name)
    E, C, d = x_ecd.shape
    x = x_ecd.reshape(n, E // n, C, d)
    # tiled a2a on dim 0: rank r keeps expert-group r from EVERY source
    # rank; dim 0 of the result indexes the source rank
    x = jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                           tiled=True)                       # [n, E_local, C, d]
    x = x.transpose(1, 0, 2, 3)                              # [E_local, n, C, d]
    return x.reshape(E // n, n * C, d)


def _combine_a2a(y_elcd, axis_name, E: int):
    """Inverse of ``_dispatch_a2a``: [E_local, N*C, d] -> [E, C, d]."""
    n = jax.lax.psum(1, axis_name)
    E_local, NC, d = y_elcd.shape
    C = NC // n
    y = y_elcd.reshape(E_local, n, C, d).transpose(1, 0, 2, 3)  # [n, E_local, C, d]
    y = jax.lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                           tiled=True)                       # [n, E_local, C, d]
    return y.reshape(E, C, d)


def moe_ffn(x, router_w, w1, b1, w2, b2,
            capacity_factor: float = 2.0,
            axis_name: str = const.EXPERT_AXIS,
            dtype=None) -> Tuple[jax.Array, jax.Array]:
    """Top-1 MoE feed-forward. Returns (output with x's shape, aux loss).

    - ``x``: [..., d] local activations; flattened to tokens internally.
    - ``router_w``: [d, E] (replicated).
    - ``w1``/``b1``/``w2``/``b2``: expert-stacked [E(, ...)] — pass the LOCAL
      shard inside the lowering ([E_local, ...]) or the full stack outside.
    - capacity C = ceil(T_local/E * capacity_factor) tokens per expert per
      rank (static).
    """
    dt = dtype or x.dtype
    d = x.shape[-1]
    lead = x.shape[:-1]
    tokens = x.reshape(-1, d)
    T = tokens.shape[0]
    bound = axis_bound(axis_name)
    n = jax.lax.psum(1, axis_name) if bound else 1
    E_local = w1.shape[0]
    E = E_local * n
    capacity = int(np.ceil(T / E * capacity_factor))

    logits = tokens.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits)
    dispatch, combine, aux = top1_dispatch(probs, capacity)
    dispatch = dispatch.astype(dt)
    combine = combine.astype(dt)

    x_ecd = jnp.einsum("td,tec->ecd", tokens, dispatch)      # [E, C, d]
    if bound:
        x_in = _dispatch_a2a(x_ecd, axis_name)               # [E_local, nC, d]
    else:
        x_in = x_ecd
    h = jnp.einsum("ecd,edf->ecf", x_in, w1.astype(dt)) + b1.astype(dt)[:, None]
    h = jax.nn.gelu(h)
    y = jnp.einsum("ecf,efd->ecd", h, w2.astype(dt)) + b2.astype(dt)[:, None]
    if bound:
        y = _combine_a2a(y, axis_name, E)                    # [E, C, d]
    out = jnp.einsum("tec,ecd->td", combine, y)
    return out.reshape(lead + (d,)), aux.astype(jnp.float32)
