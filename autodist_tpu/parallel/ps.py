"""Host-offloaded parameter-server data path.

Analog of the reference's between-graph PS placement: the reference places
each PS variable and its update op ON the parameter-server device — a host
CPU device — and workers read/write it over the wire every step
(reference ``autodist/kernel/synchronization/ps_synchronizer.py:171-176``,
task placement ``:636-762``). The TPU-native equivalent keeps PS variables
(and their optimizer state — the Adam moments are usually 2x the weights)
resident in **host memory**, off the HBM:

- at step start the store **pulls**: PS values transfer host -> device and
  enter the SPMD step replicated (the reference's workers reading from the
  PS over gRPC);
- the step returns the mean-psum'd gradient for every PS variable instead
  of updating it on device (the reference's grad push to the PS
  accumulator);
- the store **pushes**: gradients transfer device -> host, are split by
  true shard ranges (honoring *uneven* ``shard_sizes`` exactly — host
  arrays need no XLA padding, reference
  ``strategy/uneven_partition_ps_strategy.py:128-137``), and the optimizer
  update is applied **on the host CPU** per shard (the reference's update
  op placed on the PS device).

The strategy's ``local_replication`` knob therefore changes the program:
``True`` (proxy, reference ``common/proxy_variable.py:74-191``) keeps the
variable device-resident and updates it on device — no per-step parameter
traffic; ``False`` routes it through this host path — 1/HBM residency in
exchange for PCIe traffic every step. ``reduction_destination`` assigns the
owning host; in synchronous mode every process holds a deterministic mirror
(the psum'd gradient is bit-identical everywhere, so replaying the update
locally IS the reference's "every worker transforms its own graph"
architecture with zero serving traffic), and the owner is the one whose
copy is authoritative for checkpoints and async serving.

Mechanically, PS variables are carved out of the device ``TrainState`` as
**holes** — empty pytree nodes that keep the tree structure (so optax
transformations, tree specs and donation all compose) while contributing no
device arrays.
"""
import dataclasses
import functools
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu.model_item import _normalize_path
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging


# ------------------------------------------------------------------- holes


class PSHole:
    """An empty pytree node standing where a host-resident PS variable
    would be: flattening yields no leaves, so jit/optax/shard_map treat it
    as pure structure. The variable's flattened name rides in the treedef
    (aux data), so two states with the same PS plan unify under jit."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return "PSHole(%s)" % self.name


jax.tree_util.register_pytree_node(
    PSHole, lambda h: ((), h.name), lambda name, _: PSHole(name))


def _is_hole(x) -> bool:
    return isinstance(x, PSHole)


def hole_out_params(params, ps_names) -> Any:
    """Replace leaves named in ``ps_names`` with PSHole nodes."""
    def repl(path, leaf):
        name = _normalize_path(path)
        return PSHole(name) if name in ps_names else leaf
    return jax.tree_util.tree_map_with_path(repl, params)


def fill_holes(tree, values: Dict[str, Any]) -> Any:
    """Replace every PSHole with ``values[hole.name]``."""
    return jax.tree_util.tree_map(
        lambda x: values[x.name] if _is_hole(x) else x, tree, is_leaf=_is_hole)


def fill_holes_with_path(tree, provider: Callable[[str, str], Any]) -> Any:
    """Replace every PSHole with ``provider(path, var_name)`` — used for
    optimizer-state reconstruction where the hole's tree position (the
    optimizer slot) matters."""
    def repl(path, x):
        if _is_hole(x):
            return provider(_normalize_path(path), x.name)
        return x
    return jax.tree_util.tree_map_with_path(repl, tree, is_leaf=_is_hole)


def hole_like(template, full):
    """Structure-align ``full`` to a holed ``template``: wherever the
    template has a PSHole, the corresponding subtree of ``full`` is dropped
    and the hole kept; everywhere else ``full``'s leaves win."""
    return jax.tree_util.tree_map(
        lambda t, f: t if _is_hole(t) else f, template, full, is_leaf=_is_hole)


def extract_holes(template, full) -> Dict[Tuple[str, str], Any]:
    """Inverse of :func:`hole_like`: ``{(hole_path, var_name): subtree}``
    for every hole position, pulling the subtree out of ``full``."""
    out: Dict[Tuple[str, str], Any] = {}

    def visit(path, t, f):
        if _is_hole(t):
            out[(_normalize_path(path), t.name)] = f
        return t
    jax.tree_util.tree_map_with_path(visit, template, full, is_leaf=_is_hole)
    return out


def holes_of(tree) -> List[str]:
    """Names of all PSHoles in a tree."""
    found: List[str] = []
    jax.tree_util.tree_map(
        lambda x: found.append(x.name) if _is_hole(x) else None,
        tree, is_leaf=_is_hole)
    return found


# -------------------------------------------------------------------- plans


@dataclasses.dataclass(frozen=True)
class PSVarPlan:
    """Host-residency plan for one PS variable.

    ``destinations`` has one owner device string per shard (length 1 for
    unpartitioned vars); ``shard_sizes`` are the TRUE sizes along ``axis``
    (uneven allowed — host storage is ragged, never padded).

    ``wire_dtype="int8"`` quantizes the host<->device step wire: pulls
    ship the value as blockwise int8 + f32 scales (dequantized in-graph),
    pushes ship the reduced gradient the same way (dequantized at the
    store boundary before the optimizer apply). The store itself always
    holds exact fp32 — only the wire is lossy."""
    var_name: str
    destinations: Tuple[str, ...]
    shard_sizes: Optional[Tuple[int, ...]] = None   # None = unpartitioned
    axis: int = 0
    sync: bool = True
    staleness: int = 0
    sparse: bool = False
    wire_dtype: str = "fp32"

    @property
    def partitioned(self) -> bool:
        return self.shard_sizes is not None and len(self.shard_sizes) > 1

    def shard_ranges(self) -> List[Tuple[int, int]]:
        if not self.shard_sizes:
            return [(0, -1)]
        ranges, off = [], 0
        for s in self.shard_sizes:
            ranges.append((off, off + s))
            off += s
        return ranges


def _even_or_given_sizes(node, info) -> Tuple[int, ...]:
    if node.shard_sizes:
        return tuple(node.shard_sizes)
    n = node.num_shards
    axis = node.partition_axis or 0
    dim = info.shape[axis]
    base, rem = divmod(dim, n)
    return tuple(base + (1 if i < rem else 0) for i in range(n))


def plan_host_ps(strategy, var_infos) -> Dict[str, PSVarPlan]:
    """Decide which variables are host-resident, from the compiled strategy.

    A variable routes to the host PS path when it is PS-synchronized with
    ``local_replication=False`` (no proxy — the reference's default, where
    every read hits the PS). Proxied PS vars stay device-resident; AllReduce
    vars never come here. The cached-vs-resident decision itself is owned by
    ``ProxyVariable.plan`` (single source — this function adds only the
    eligibility gating: trainable, non-model-parallel, uniform shard
    configs)."""
    from autodist_tpu.kernel.common.proxy_variable import ProxyVariable
    from autodist_tpu.strategy.base import PSSynchronizer as PSConfig

    def cached(cfg) -> bool:
        return ProxyVariable.plan("", cfg, None).cached

    def wire_for(info, syncs) -> str:
        """The plan's host-wire format: int8 only when EVERY shard config
        asks for it AND the variable is dense float — the same guard the
        linter enforces as ADT310 (sparse grads ship (ids, values) pairs,
        integer values have no absmax scale). No block-size floor here:
        the planner does what the plan says; ADT311 is the linter's
        advisory."""
        from autodist_tpu.parallel.collectives import wire_quantizable
        if not wire_quantizable(info):
            return "fp32"
        if all((getattr(s, "wire_dtype", "fp32") or "fp32") == "int8"
               for s in syncs):
            return "int8"
        return "fp32"

    plans: Dict[str, PSVarPlan] = {}
    for node in strategy.node_config:
        info = var_infos.get(node.var_name)
        if info is None or not info.trainable:
            continue
        if node.mp_axes:
            continue  # model-parallel storage owns these
        sync_cfg = node.synchronizer
        part_syncs = [p.synchronizer for p in node.part_configs
                      if p.synchronizer is not None]
        if node.partitioner and part_syncs:
            if not all(isinstance(s, PSConfig) for s in part_syncs):
                continue
            if any(cached(s) for s in part_syncs):
                continue  # proxied: device ZeRO path
            sizes = _even_or_given_sizes(node, info)
            plans[node.var_name] = PSVarPlan(
                var_name=node.var_name,
                destinations=tuple(s.reduction_destination for s in part_syncs),
                shard_sizes=sizes,
                axis=node.partition_axis or 0,
                sync=all(s.sync for s in part_syncs),
                staleness=max(s.staleness for s in part_syncs),
                sparse=info.sparse,
                wire_dtype=wire_for(info, part_syncs))
        elif isinstance(sync_cfg, PSConfig):
            if cached(sync_cfg):
                continue  # proxied: device-resident (cached) path
            plans[node.var_name] = PSVarPlan(
                var_name=node.var_name,
                destinations=(sync_cfg.reduction_destination,),
                sync=sync_cfg.sync,
                staleness=sync_cfg.staleness,
                sparse=info.sparse,
                wire_dtype=wire_for(info, [sync_cfg]))
    return plans


# -------------------------------------------------------------------- store


class PSStore:
    """Host-memory parameter server: values + optimizer state per shard.

    The store is the PS device of the reference — parameters rest here, the
    update op runs here (on the host CPU), and the training step only ever
    sees pulled copies. Updates run through the SAME optax optimizer the
    device path uses, one subtree per shard (the reference's per-PS
    optimizer placement; cross-variable optimizer coupling such as global
    gradient clipping decouples between the PS set and the device set,
    exactly as it did across reference PS shards).

    ``stats`` counts the wire: pulls/pushes and their bytes — the honest
    cost of the no-proxy PS path that tests and the simulator can assert
    on."""

    def __init__(self, plans: Dict[str, PSVarPlan], var_infos, optimizer):
        self.plans = dict(plans)
        self._var_infos = var_infos
        self._optimizer = optimizer
        # vars whose host<->device step wire ships blockwise int8 + scales
        # (PSVarPlan.wire_dtype): quantized at this store's boundary on
        # pull, dequantized at it on push — resident values stay exact f32
        self.wire_quant = sorted(n for n, p in self.plans.items()
                                 if p.wire_dtype == "int8")
        self._values: Dict[str, List[np.ndarray]] = {}
        self._opt: Dict[str, List[Any]] = {}
        self._cpu = jax.local_devices(backend="cpu")[0]
        self.stats = {"pulls": 0, "pushes": 0, "applies": 0,
                      "bytes_pulled": 0, "bytes_pushed": 0,
                      "degraded_pulls": 0}
        self._serve_groups: Optional[Dict[str, dict]] = None
        self._serve_config = None
        self._my_pushes = 0
        self._warned_sync_fallback = False
        # effective-LR scale applied to every optimizer update (sentinel
        # escalation ladder, runtime/sentinel.py): passed into the jitted
        # apply as an ARRAY argument, so changing it never retraces
        self.update_scale = 1.0
        # guards value/opt swaps vs concurrent reads: the async apply
        # thread must never expose a var whose shards span two versions
        import threading
        self._lock = threading.Lock()
        # ALL shards' updates traced into ONE program — one dispatch per
        # step instead of one per shard (a 100-var model pays ~100x less
        # host-dispatch latency). Compiled for CPU so PS updates never
        # touch HBM. NO donation: checkpoint readers (full_opt_leaf /
        # full_values) may hold references to the stored buffers while the
        # async apply thread runs; donating would invalidate them mid-read.
        self._apply_batch = jax.jit(self._apply_batch_impl)
        # shard updates are independent, so the apply fans out over a
        # thread pool (DLRM-scale tables: one CPU core running the whole
        # optimizer pass leaves the rest of the host idle). Deterministic
        # round-robin grouping -> stable jit cache AND bit-exact results.
        from autodist_tpu import const as _const
        n = _const.ENV.ADT_PS_APPLY_THREADS.val
        if n <= 0:
            n = min(4, os.cpu_count() or 1)
        self._apply_threads = n
        self._apply_pool = None  # lazily built on first parallel apply

    # ------------------------------------------------------------ lifecycle

    def _apply_impl(self, shard, opt_state, grad, scale=None):
        updates, new_opt = self._optimizer.update(
            {"v": grad}, opt_state, {"v": shard})
        if scale is not None:
            # sentinel LR escalation: exact lr semantics for linear-in-lr
            # transforms; `scale` is a traced array — no retrace on change
            updates = jax.tree_util.tree_map(
                lambda u: (u * scale).astype(u.dtype), updates)
        return optax.apply_updates({"v": shard}, updates)["v"], new_opt

    def _apply_batch_impl(self, shards, opt_states, grads, scale):
        """One traced program covering every (var, shard): per-key
        optimizer semantics identical to :meth:`_apply_impl` (each shard
        keeps its own little opt-state tree)."""
        new_vals, new_opts = {}, {}
        for key in shards:
            new_vals[key], new_opts[key] = self._apply_impl(
                shards[key], opt_states[key], grads[key], scale)
        return new_vals, new_opts

    def _apply_sharded(self, shards, opts, gshards):
        """Dispatch the per-shard updates — one jitted program when the
        pool is disabled or there is a single shard, else round-robin
        groups over the thread pool. Grouping is deterministic (sorted
        keys, fixed stride), so the jit cache is stable across steps and
        the per-shard math — hence the result — is identical to the
        single-dispatch baseline."""
        keys = sorted(shards)
        scale = jnp.float32(self.update_scale)
        n = min(self._apply_threads, len(keys))
        if n <= 1:
            return self._apply_batch(shards, opts, gshards, scale)
        if self._apply_pool is None:
            import concurrent.futures
            self._apply_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self._apply_threads,
                thread_name_prefix="adt-ps-apply")
        groups = [keys[i::n] for i in range(n)]

        def run(group):
            # jax.default_device is THREAD-local: without re-entering it,
            # pool workers would dispatch the host update onto the
            # accelerator (observed: 250x slower through a TPU tunnel)
            with jax.default_device(self._cpu):
                return self._apply_batch({k: shards[k] for k in group},
                                         {k: opts[k] for k in group},
                                         {k: gshards[k] for k in group},
                                         scale)
        futures = [self._apply_pool.submit(run, g) for g in groups]
        new_vals, new_opts = {}, {}
        for f in futures:
            nv, no = f.result()
            new_vals.update(nv)
            new_opts.update(no)
        return new_vals, new_opts

    @staticmethod
    def _shard_slice(plan: PSVarPlan, si: int, full: np.ndarray) -> np.ndarray:
        """One shard's slice of a full array along the plan axis."""
        lo, hi = plan.shard_ranges()[si]
        idx = [slice(None)] * full.ndim
        idx[plan.axis] = slice(lo, hi)
        return np.ascontiguousarray(full[tuple(idx)])

    def _split(self, plan: PSVarPlan, full: np.ndarray) -> List[np.ndarray]:
        if not plan.partitioned:
            return [np.asarray(full)]
        return [self._shard_slice(plan, si, full)
                for si in range(len(plan.shard_ranges()))]

    def init_params(self, full_params) -> None:
        """Take ownership of the PS leaves of a host params tree."""
        from autodist_tpu.kernel.common import variable_utils
        names, leaves, _ = variable_utils.flatten_named(full_params)
        by_name = dict(zip(names, leaves))
        with jax.default_device(self._cpu):
            for name, plan in self.plans.items():
                full = np.asarray(jax.device_get(by_name[name]))
                self._values[name] = self._split(plan, full)
                self._opt[name] = [
                    self._optimizer.init({"v": jnp.asarray(s)})
                    for s in self._values[name]]
        if self._serve_config is not None:
            self._start_serving()

    def load_opt_from_full(self, full_opt_tree) -> None:
        """Rebuild per-shard optimizer state from a full-layout opt tree
        (checkpoint restore). Var-shaped leaves are sliced by shard range;
        everything else (step counts, factored-state leaves not along the
        split axis) is copied whole per shard."""
        from autodist_tpu.kernel.common import variable_utils
        flat_full = {}
        names, leaves, _ = variable_utils.flatten_named(full_opt_tree)
        for n, l in zip(names, leaves):
            flat_full[n] = np.asarray(jax.device_get(l))
        with jax.default_device(self._cpu):
            for name, plan in self.plans.items():
                info = self._var_infos[name]
                new_states = []
                for si in range(len(plan.shard_ranges())):
                    template = self._optimizer.init(
                        {"v": jnp.asarray(self._values[name][si])})
                    t_names, t_leaves, t_def = variable_utils.flatten_named(template)
                    out = []
                    for tn, tl in zip(t_names, t_leaves):
                        # little-tree leaf "0/mu/v" <-> full leaf "0/mu/<var>"
                        if tn.endswith("/v") or tn == "v":
                            src_name = (tn[:-2] + "/" + name) if tn.endswith("/v") else name
                        else:
                            src_name = tn
                        src = flat_full.get(src_name)
                        if src is None:
                            logging.warning(
                                "PS restore: opt leaf %r for %s not in "
                                "checkpoint; keeping fresh init", tn, name)
                            out.append(tl)
                            continue
                        if (plan.partitioned and src.ndim > plan.axis
                                and src.shape[plan.axis] == info.shape[plan.axis]):
                            src = self._shard_slice(plan, si, src)
                        out.append(jnp.asarray(src))
                    new_states.append(variable_utils.unflatten_named(t_def, out))
                self._opt[name] = new_states

    # ------------------------------------------------------------- step i/o

    def _local_full(self, names=None) -> Dict[str, np.ndarray]:
        out = {}
        for name in (names if names is not None else self.plans):
            plan = self.plans[name]
            with self._lock:
                shards = list(self._values[name])
            out[name] = (np.asarray(shards[0]) if len(shards) == 1
                         else np.concatenate([np.asarray(s) for s in shards],
                                             axis=plan.axis))
        return out

    def pull(self, wire: bool = True) -> Dict[str, np.ndarray]:
        """Current full values, host-side (the workers' per-step PS read).
        In serving (async) mode, values of groups owned by OTHER processes
        are fetched from the service — the latest published version, no
        barrier (the reference's async read-from-PS).

        ``wire=True`` (the step path) ships ``wire_dtype="int8"`` vars as
        their quantized wire container ``{"q", "s"}`` — the H2D transfer
        carries int8 + scales; the lowering dequantizes in-graph.
        ``wire=False`` (fused carry pull, checkpoints) returns exact f32;
        the fused scan body applies the codec per microstep itself, so
        its numerics still match the per-step wire exactly."""
        # step arg = this store's pull sequence: on a merged cluster
        # timeline the per-worker PS-wire spans line up per step, so
        # wire-time skew is visible per step, not just per run
        with tel.span("ps.pull", "ps",
                      serving=self._serve_groups is not None,
                      step=self.stats["pulls"]):
            out = self._pull_impl(wire=wire)
        tel.counter_add("ps.pulls")
        return out

    def _quantize_pull(self, out: Dict[str, np.ndarray],
                       count_bytes: bool) -> Dict[str, Any]:
        """Swap wire-quantized vars' values for their int8+scales wire
        containers, crediting the telemetry wire counters (and, on the
        mirror path, counting the TRUE wire bytes into ``bytes_pulled``
        — the serving path already counted its network blobs). Runs on
        whatever values the pull assembled — including a degraded pull's
        last-good snapshot, which therefore dequantizes on device exactly
        like a healthy one."""
        from autodist_tpu.parallel import collectives
        for name in self.wire_quant:
            full = np.asarray(out[name])
            w = collectives.quant_wire_np(full)
            qb = int(w["q"].nbytes + w["s"].nbytes)
            if count_bytes:
                self.stats["bytes_pulled"] += qb
            tel.counter_add("wire.bytes_quantized", qb)
            tel.counter_add("wire.bytes_saved", full.nbytes - qb)
            out[name] = w
        return out

    def _pull_impl(self, wire: bool = False) -> Dict[str, np.ndarray]:
        bytes0 = self.stats["bytes_pulled"]
        quant = frozenset(self.wire_quant) if wire else frozenset()
        if self._serve_groups is None:
            out = self._local_full()
            for name in out:
                if name in quant:
                    continue  # counted at its true wire width below
                self.stats["bytes_pulled"] += out[name].nbytes
        else:
            shard_vals: Dict[str, Dict[int, np.ndarray]] = {}
            for host, grp in self._serve_groups.items():
                if grp["owned"]:
                    blobs = self._local_shard_blobs(grp["pairs"])
                else:
                    from autodist_tpu.runtime import ps_service as pss
                    res, fetch_err = None, None
                    try:
                        deadline = time.monotonic() + 60.0
                        res = grp["service"].fetch()
                        while res is None:  # owner hasn't published yet
                            if time.monotonic() > deadline:
                                break
                            time.sleep(0.002)
                            res = grp["service"].fetch()
                    except OSError as e:
                        fetch_err = e
                    if fetch_err is not None:
                        # transport failure — the degraded-serve window
                        blobs = self._serve_stale(host, grp, fetch_err)
                        if blobs is None:
                            raise RuntimeError(
                                "async PS: owner %s unreachable and the "
                                "degraded-serve window is exhausted — "
                                "aborting instead of training on "
                                "unboundedly stale values (%s)"
                                % (host, fetch_err)) from fetch_err
                    elif res is None:
                        # service reachable but the owner never published:
                        # NOT a transport error — stale serving would hide
                        # a wedged owner behind frozen parameters
                        raise TimeoutError(
                            "async PS: owner %s never published" % host)
                    else:
                        _version, blob = res
                        blobs = pss.unpack_arrays(blob)
                        self.stats["bytes_pulled"] += len(blob)
                        # keep the last good fetch: the degraded-serve
                        # fallback for a transient service blip
                        grp["last_fetch"] = blobs
                        grp["degraded"] = 0
                for key, arr in blobs.items():
                    if "!" in key:
                        continue  # opt-state leaf (checkpoint wire)
                    name, si = key.rsplit("::", 1)
                    shard_vals.setdefault(name, {})[int(si)] = arr
            out = self._assemble(shard_vals)
        if wire and self.wire_quant:
            out = self._quantize_pull(out,
                                      count_bytes=self._serve_groups is None)
        self.stats["pulls"] += 1
        tel.counter_add("ps.bytes_pulled",
                        self.stats["bytes_pulled"] - bytes0)
        return out

    def _degraded_bound(self) -> int:
        """How many consecutive pulls may serve from the last fetch while
        the owner is unreachable: the strategy's staleness bound when one
        is declared, else the async pacing lag (``ADT_PS_MAX_LAG``) —
        past it the values are staler than anything the strategy ever
        promised, and the pull must fail instead."""
        from autodist_tpu import const as _const
        return max(self.max_staleness(), _const.ENV.ADT_PS_MAX_LAG.val)

    def _serve_stale(self, host: str, grp: dict, err: OSError):
        """Graceful degradation for a worker that cannot reach an owner:
        serve the LAST fetched values for up to ``_degraded_bound()``
        consecutive pulls — a service blip shorter than the window is
        invisible to training, and the resilient client reconnects on
        its own schedule. None = window exhausted (caller fails
        loudly)."""
        bound = self._degraded_bound()
        cached = grp.get("last_fetch")
        used = grp.get("degraded", 0)
        if cached is None or used >= bound:
            return None
        grp["degraded"] = used + 1
        self.stats["degraded_pulls"] += 1
        tel.counter_add("ps.degraded_pulls")
        tel.instant("ps.degraded_pull", "ps", host=host,
                    used=used + 1, bound=bound)
        # no service.reconnect() here: the resilient client reconnects
        # internally, and dropping it would discard its circuit-breaker
        # state — every degraded pull would re-pay the full retry budget
        # instead of failing fast into this window
        logging.warning(
            "async PS: owner %s unreachable (%s); serving last-fetched "
            "values (degraded pull %d/%d)", host, err, used + 1, bound)
        return cached

    def _assemble(self, shard_vals: Dict[str, Dict[int, np.ndarray]]
                  ) -> Dict[str, np.ndarray]:
        """Reassemble full variables from per-shard pieces (possibly
        published by different owners), in plan shard order. Missing
        shards fall back to the local mirror (pre-publish window)."""
        out = {}
        for name, plan in self.plans.items():
            n_shards = len(plan.shard_ranges()) if plan.partitioned else 1
            pieces = []
            for si in range(n_shards):
                arr = shard_vals.get(name, {}).get(si)
                if arr is None:
                    with self._lock:
                        arr = np.asarray(self._values[name][si])
                pieces.append(np.asarray(arr))
            out[name] = (pieces[0] if n_shards == 1
                         else np.concatenate(pieces, axis=plan.axis))
        return out

    def push(self, grads: Dict[str, Any]) -> None:
        """Hand mean-reduced gradients to the PS. Mirror (sync) mode applies
        locally — every process replays the identical deterministic update.
        Serving (async) mode packs each owner group's gradients into a blob
        and enqueues it on the owner's queue; the owner's apply thread
        applies gradients one at a time (no barrier)."""
        # epoch fence at the STORE boundary (runtime/elastic.py) — before
        # any D2H work, so a zombie's push is rejected at zero cost and
        # never reaches an owner queue its replacement is draining
        from autodist_tpu.runtime import elastic
        elastic.maybe_fence("ps.push")
        with tel.span("ps.push", "ps",
                      serving=self._serve_groups is not None,
                      step=self.stats["pushes"]):
            self._push_impl(grads)
        tel.counter_add("ps.pushes")

    def _grad_to_host(self, name: str, g, count_bytes: bool = True):
        """D2H one pushed gradient at the store boundary. Dense arrays and
        sparse (ids, values) pairs pass through; a wire-quantized gradient
        arrives as its ``{"q", "s"}`` container (int8 + scales — the D2H
        transfer the push actually paid), is counted at its true wire
        width, and dequantizes HERE — the store never sees int8."""
        if isinstance(g, dict):
            from autodist_tpu.parallel import collectives
            w = {k: np.asarray(jax.device_get(v)) for k, v in g.items()}
            qb = int(w["q"].nbytes + w["s"].nbytes)
            info = self._var_infos[name]
            host = collectives.dequant_wire_np(w, tuple(info.shape),
                                               np.dtype(info.dtype))
            if count_bytes:
                self.stats["bytes_pushed"] += qb
            tel.counter_add("wire.bytes_quantized", qb)
            tel.counter_add("wire.bytes_saved", host.nbytes - qb)
            return host
        if isinstance(g, tuple):
            pair = tuple(np.asarray(jax.device_get(x)) for x in g)
            if count_bytes:
                self.stats["bytes_pushed"] += sum(x.nbytes for x in pair)
            return pair
        arr = np.asarray(jax.device_get(g))
        if count_bytes:
            self.stats["bytes_pushed"] += arr.nbytes
        return arr

    def _push_impl(self, grads: Dict[str, Any]) -> None:
        bytes0 = self.stats["bytes_pushed"]
        drops0 = self.stats.get("dropped_pushes", 0)
        if self._serve_groups is None:
            if self.any_async() and not self._warned_sync_fallback:
                self._warned_sync_fallback = True
                logging.warning(
                    "async PS (sync=False) requested but serving is not "
                    "wired (no AutoDist async build); applying synchronously")
            host_grads = {name: self._grad_to_host(name, g)
                          for name, g in grads.items()}
            self.apply_local(host_grads)
        else:
            from autodist_tpu.runtime import ps_service as pss
            host_grads: Dict[str, Any] = {}  # one D2H transfer per var

            def fetch(name):
                if name not in host_grads:
                    # serving counts its network blobs below; the D2H leg
                    # only credits the wire counters
                    host_grads[name] = self._grad_to_host(
                        name, grads[name], count_bytes=False)
                return host_grads[name]

            for host, grp in self._serve_groups.items():
                payload = {}
                for name, si in grp["pairs"]:
                    if name not in grads:
                        continue
                    g = fetch(name)
                    plan = self.plans[name]
                    if isinstance(g, tuple):
                        # sparse (ids, values): one whole pair per owner
                        # group — the owner scatter-applies only into its
                        # own shard index ranges (shard_filter)
                        payload[name + "#idx"] = g[0]
                        payload[name + "#vals"] = g[1]
                    elif plan.partitioned:
                        # ship only this owner's slice of the gradient
                        payload["%s::%d" % (name, si)] = self._shard_slice(
                            plan, si, g)
                    else:
                        payload["%s::0" % name] = g
                if not payload:
                    continue
                blob = pss.pack_arrays(payload)
                # backpressure BEFORE the push: an unbounded queue lets a
                # fast worker stack gradients computed at ever-staler values
                # (and diverge), and a dead owner would grow its queue
                # without bound. The reference's async apply sat in the
                # step's critical path; here the bound is explicit: at most
                # ADT_PS_MAX_LAG blobs in flight (0 = unbounded, pure
                # async). On timeout the push is DROPPED (counted in
                # stats["dropped_pushes"]) — the watchdog/DEADLIST plane is
                # what kills the job if the owner is really gone.
                from autodist_tpu import const as _const
                max_lag = _const.ENV.ADT_PS_MAX_LAG.val
                try:
                    if max_lag > 0:
                        deadline = time.monotonic() + 60.0
                        stuck = False
                        while grp["service"].pending_grads() >= max_lag:
                            if time.monotonic() > deadline:
                                logging.warning(
                                    "async PS: owner %s queue stuck at max "
                                    "lag; dropping this push", host)
                                stuck = True
                                break
                            time.sleep(0.001)
                        if stuck:
                            self.stats["dropped_pushes"] = (
                                self.stats.get("dropped_pushes", 0) + 1)
                            continue
                    grp["service"].push_grads(blob)
                except OSError as e:
                    # transport blip: a dropped async gradient is legal
                    # (same semantics as backpressure drops) — but only
                    # within the degraded window; past it the owner is
                    # gone for real and the job must fail loudly
                    used = grp.get("push_failures", 0) + 1
                    bound = self._degraded_bound()
                    if used > bound:
                        raise RuntimeError(
                            "async PS: pushes to owner %s failed %d "
                            "consecutive times — aborting instead of "
                            "silently training without gradient exchange "
                            "(%s)" % (host, used, e)) from e
                    grp["push_failures"] = used
                    self.stats["dropped_pushes"] = (
                        self.stats.get("dropped_pushes", 0) + 1)
                    # no reconnect() kick: see _serve_stale — it would
                    # reset the resilient client's circuit breaker
                    logging.warning(
                        "async PS: push to owner %s failed (%s); dropped "
                        "this gradient (consecutive failure %d/%d)",
                        host, e, used, bound)
                    continue
                grp["push_failures"] = 0
                self.stats["bytes_pushed"] += len(blob)
            self._my_pushes += 1
        self.stats["pushes"] += 1
        tel.counter_add("ps.bytes_pushed",
                        self.stats["bytes_pushed"] - bytes0)
        dropped = self.stats.get("dropped_pushes", 0) - drops0
        if dropped:
            tel.counter_add("ps.dropped_pushes", dropped)

    def apply_local(self, grads: Dict[str, Any], shard_filter=None) -> None:
        """The PS-side update op: apply gradients to the resident shards
        through the optimizer, on the host CPU. Gradients arrive as full
        dense arrays (mirror mode), pre-sliced ``name::si`` shard slices
        (per-shard serving pushes), or sparse ``(indices, values)`` pairs
        — also their packed ``name#idx``/``name#vals`` wire form —
        scatter-added into the shard's index range (the reference's
        IndexedSlices split, ``kernel/partitioner.py:660-684``).
        ``shard_filter`` restricts the apply to the given (name, si) set
        — an owner loop touches only the shards it owns."""
        items: Dict[str, Any] = {}
        slices: Dict[str, Dict[int, Any]] = {}
        for name, g in grads.items():
            if name.endswith("#idx"):
                base = name[:-4]
                items[base] = (g, grads[base + "#vals"])
            elif name.endswith("#vals"):
                continue
            elif ("::" in name and name not in self.plans
                  and name.rsplit("::", 1)[0] in self.plans
                  and name.rsplit("::", 1)[1].isdigit()):
                # wire shard-slice key; a real variable literally named
                # "w::1" is in self.plans itself and takes the dense branch
                base, si = name.rsplit("::", 1)
                slices.setdefault(base, {})[int(si)] = g
            else:
                items[name] = g
        with jax.default_device(self._cpu):
            # collect every (var, shard) then apply in ONE jitted dispatch
            shards, opts, gshards, order = {}, {}, {}, []

            def add(name, si, gs):
                key = "%s::%d" % (name, si)
                shards[key] = jnp.asarray(self._values[name][si])
                opts[key] = self._opt[name][si]
                gshards[key] = jnp.asarray(gs)
                order.append((name, si, key))

            for name, g in items.items():
                plan = self.plans[name]
                if isinstance(g, tuple):
                    g = self._densify(name, plan, g)
                else:
                    g = np.asarray(g)
                for si in range(len(plan.shard_ranges())):
                    if shard_filter is not None \
                            and (name, si) not in shard_filter:
                        continue
                    gs = (self._shard_slice(plan, si, g)
                          if plan.partitioned else g)
                    add(name, si, gs)
            for name, by_si in slices.items():
                for si, gs in sorted(by_si.items()):
                    if shard_filter is not None \
                            and (name, si) not in shard_filter:
                        continue
                    add(name, si, np.asarray(gs))
            if not order:
                return
            with tel.span("ps.apply", "ps", shards=len(order)):
                new_vals, new_opts = self._apply_sharded(shards, opts,
                                                         gshards)
            tel.counter_add("ps.applies", len(order))
            per_var: Dict[str, Dict[int, Tuple]] = {}
            for name, si, key in order:
                per_var.setdefault(name, {})[si] = (
                    np.asarray(new_vals[key]), new_opts[key])
            for name, by_si in per_var.items():
                # swap the var's updated shards in one locked mutation;
                # shards owned by OTHER processes are left untouched
                # (per-shard ownership — their owners update them)
                with self._lock:
                    vlist = list(self._values[name])
                    olist = list(self._opt[name])
                    for si, (v, o) in by_si.items():
                        vlist[si], olist[si] = v, o
                    self._values[name] = vlist
                    self._opt[name] = olist
                self.stats["applies"] += 1

    # ---------------------------------------------------- async PS serving

    def enable_serving(self, service_for_host, my_host: str) -> None:
        """Switch to serving (async) mode: variables are grouped by owner
        host (``reduction_destination``); this process runs an apply loop
        for the groups it owns and fetches the rest over the service — the
        reference's sharded-PS deployment (one PS task per destination,
        ``ps_synchronizer.py:636-762``). May be called before
        ``init_params``; owner loops start once values exist."""
        self._serve_config = (service_for_host, my_host)
        if self._values:
            self._start_serving()

    def _start_serving(self) -> None:
        """Group by owner host PER SHARD (``reduction_destination`` is
        per-shard in the plan): a partitioned variable's shards can be
        owned — stored, applied, published — by different hosts, exactly
        the reference's sharded-PS task placement
        (``ps_synchronizer.py:636-762``). Pulls reassemble each variable
        across its owners' published blobs."""
        from autodist_tpu.runtime import ps_service as pss
        service_for_host, my_host = self._serve_config
        if self._serve_groups is not None:  # re-init: restart owner loops
            self.close()
        groups: Dict[str, list] = {}
        for name, plan in sorted(self.plans.items()):
            for si, dest in enumerate(plan.destinations):
                host = dest.split(":")[0] if dest else my_host
                groups.setdefault(host, []).append((name, si))
        self._serve_groups = {}
        for host, pairs in sorted(groups.items()):
            svc = service_for_host(host)
            owned = (host == my_host)
            grp = {"pairs": sorted(pairs), "service": svc, "owned": owned,
                   "worker": None}
            if owned:
                shard_set = frozenset(grp["pairs"])
                # values ride the HOT channel (fetched by every worker's
                # per-step pull); the optimizer moments publish on the
                # side channel, fetched only at checkpoint time — under
                # Adam this cuts the per-step serving wire ~3x
                grp["worker"] = pss.AsyncPSWorker(
                    svc,
                    functools.partial(self.apply_local,
                                      shard_filter=shard_set),
                    functools.partial(self._local_shard_blobs,
                                      grp["pairs"]),
                    opt_fn=functools.partial(self._local_opt_blobs,
                                             grp["pairs"])).start()
            self._serve_groups[host] = grp
        logging.info("async PS serving: %d owner groups, this process (%s) "
                     "owns %s", len(self._serve_groups), my_host,
                     [h for h, g in self._serve_groups.items() if g["owned"]])

    def _local_shard_blobs(self, pairs,
                           with_opt: bool = False) -> Dict[str, np.ndarray]:
        """{'name::si': shard value} for the given (name, si) pairs — the
        owner's publish payload (only the shards it owns). With
        ``with_opt``, the shard's optimizer-state leaves ride along as
        ``name::si!<leaf>`` (single-blob form; serving publishes them on
        the separate opt channel instead, see ``_local_opt_blobs``)."""
        from autodist_tpu.kernel.common import variable_utils
        out = {}
        with self._lock:
            for name, si in pairs:
                key = "%s::%d" % (name, si)
                out[key] = np.asarray(self._values[name][si])
                if with_opt:
                    names, leaves, _ = variable_utils.flatten_named(
                        self._opt[name][si])
                    for ln, leaf in zip(names, leaves):
                        out["%s!%s" % (key, ln)] = np.asarray(leaf)
        return out

    def _local_opt_blobs(self, pairs) -> Dict[str, np.ndarray]:
        """{'name::si!leaf': opt leaf} for the owned (name, si) pairs —
        the optimizer-state side channel a chief-side checkpoint reads to
        reconstruct a COMPLETE opt state for shards it does not own
        (per-shard ownership means no single process applies to every
        shard — without the wire, peer shards' moments would silently
        checkpoint as their frozen local init)."""
        from autodist_tpu.kernel.common import variable_utils
        out = {}
        with self._lock:
            for name, si in pairs:
                key = "%s::%d" % (name, si)
                names, leaves, _ = variable_utils.flatten_named(
                    self._opt[name][si])
                for ln, leaf in zip(names, leaves):
                    out["%s!%s" % (key, ln)] = np.asarray(leaf)
        return out

    @property
    def serving(self) -> bool:
        return self._serve_groups is not None

    def owner_health_errors(self) -> List[Tuple[str, str]]:
        """(host, error) for every owner apply loop of THIS process that
        is dead or past its reconnect budget. Non-empty means gradients
        pushed to those groups are never applied again — the Runner
        checks this every step and fails the job loudly (the silent-stall
        alternative is the one forbidden outcome)."""
        out: List[Tuple[str, str]] = []
        if self._serve_groups is None:
            return out
        for host, grp in self._serve_groups.items():
            w = grp["worker"]
            if w is not None and not w.healthy:
                out.append((host, str(w.last_error or
                                      "apply thread died unexpectedly")))
        return out

    def applied_total(self) -> int:
        """Gradient blobs applied by this process's owner loops."""
        if self._serve_groups is None:
            return self.stats["applies"]
        return sum(g["worker"].applied for g in self._serve_groups.values()
                   if g["worker"] is not None)

    def drain(self, timeout: float = 30.0) -> None:
        """Wait for this process's owner queues to empty (checkpoints)."""
        if self._serve_groups is None:
            return
        for grp in self._serve_groups.values():
            if grp["worker"] is not None:
                grp["worker"].drain(timeout)

    def close(self) -> None:
        # stop the owner apply loops BEFORE shutting the apply pool: a
        # still-running worker mid-apply_local would lazily rebuild a
        # fresh pool after its shutdown, leaking threads forever
        if self._serve_groups is not None:
            for grp in self._serve_groups.values():
                stopped = True
                if grp["worker"] is not None:
                    stopped = grp["worker"].stop()
                if stopped:
                    grp["service"].close()
                else:
                    # the apply thread is wedged (slow apply / stalled
                    # recv); leaking its socket beats yanking it out from
                    # under a live thread mid-publish
                    logging.warning("PS owner apply thread did not stop; "
                                    "leaving its service open")
        if self._apply_pool is not None:
            self._apply_pool.shutdown(wait=True)
            self._apply_pool = None

    def _densify(self, name: str, plan: PSVarPlan, pair) -> np.ndarray:
        """(indices, values) -> dense mean gradient for the full var.
        Wire accounting happens at the push site (idx+vals are what crossed
        the wire), not here."""
        idx, vals = pair
        idx = np.asarray(jax.device_get(idx)).reshape(-1)
        vals = np.asarray(jax.device_get(vals))
        vals = vals.reshape(idx.shape[0], -1)
        shape = tuple(self._var_infos[name].shape)
        dense = np.zeros(shape, vals.dtype).reshape(shape[0], -1)
        np.add.at(dense, idx, vals)
        return dense.reshape(shape)

    # ---------------------------------------------------------- checkpoints

    def full_values(self) -> Dict[str, np.ndarray]:
        """Like :meth:`pull` but for checkpoints — does not count as wire.
        In serving mode, non-owned groups come from the owner's latest
        published version (the authoritative copy); the local stale mirror
        is only the fallback when the owner has not published."""
        if self._serve_groups is None:
            return self._local_full()
        from autodist_tpu.runtime import ps_service as pss
        shard_vals: Dict[str, Dict[int, np.ndarray]] = {}
        for host, grp in self._serve_groups.items():
            if grp["owned"]:
                blobs = self._local_shard_blobs(grp["pairs"])
            else:
                res = grp["service"].fetch()
                if res is None:
                    continue  # pre-publish: _assemble falls back to mirror
                blobs = pss.unpack_arrays(res[1])
            for key, arr in blobs.items():
                if "!" in key:
                    continue  # opt-state leaf (checkpoint wire)
                name, si = key.rsplit("::", 1)
                shard_vals.setdefault(name, {})[int(si)] = arr
        return self._assemble(shard_vals)

    def checkpoint_pairs(self, is_chief: bool) -> List[Tuple[str, int]]:
        """(var, shard) pairs THIS process writes in a sharded checkpoint.
        Serving (async) mode: the shards this process owns — its local
        state is the authoritative copy for exactly those. Mirror (sync)
        mode: every process holds identical state, so the chief writes all
        of them and everyone else none."""
        if self._serve_groups is not None:
            out: List[Tuple[str, int]] = []
            for grp in self._serve_groups.values():
                if grp["owned"]:
                    out.extend(grp["pairs"])
            return sorted(out)
        if not is_chief:
            return []
        out = []
        for name, plan in sorted(self.plans.items()):
            n = len(plan.shard_ranges()) if plan.partitioned else 1
            out.extend((name, si) for si in range(n))
        return out

    def shard_state(self, name: str, si: int
                    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """(value, flattened opt-state leaves) of one shard — an atomic
        snapshot vs the async apply thread."""
        from autodist_tpu.kernel.common import variable_utils
        with self._lock:
            value = np.asarray(self._values[name][si])
            names, leaves, _ = variable_utils.flatten_named(
                self._opt[name][si])
            opt_flat = {n: np.asarray(l) for n, l in zip(names, leaves)}
        return value, opt_flat

    def load_shard_states(self, provider) -> None:
        """Reload every shard from ``provider(name, si) -> (value,
        opt_flat)`` — the sharded-checkpoint restore. All shards load in
        every process (owned ones authoritative; the rest seed the mirror
        that pre-publish pulls fall back to). Unknown opt leaves keep the
        fresh init with a warning, matching :meth:`load_opt_from_full`.

        In serving mode the owner apply loops are PAUSED across the swap:
        an apply interleaved with the reload would mutate a mix of
        restored and pre-restore shards. Gradients queued meanwhile stay
        queued and land after resume — stale-but-legal async grads."""
        from autodist_tpu.kernel.common import variable_utils
        workers = []
        if self._serve_groups is not None:
            workers = [g["worker"] for g in self._serve_groups.values()
                       if g["worker"] is not None]
        for w in workers:
            w.pause()
        try:
            with jax.default_device(self._cpu):
                for name, plan in sorted(self.plans.items()):
                    n = len(plan.shard_ranges()) if plan.partitioned else 1
                    new_vals, new_opts = [], []
                    for si in range(n):
                        value, opt_flat = provider(name, si)
                        value = np.asarray(value)
                        template = self._optimizer.init(
                            {"v": jnp.asarray(value)})
                        t_names, t_leaves, t_def = (
                            variable_utils.flatten_named(template))
                        out = []
                        for tn, tl in zip(t_names, t_leaves):
                            src = opt_flat.get(tn)
                            if src is None:
                                logging.warning(
                                    "PS sharded restore: opt leaf %r for "
                                    "%s[%d] not in checkpoint; keeping "
                                    "fresh init", tn, name, si)
                                out.append(tl)
                            else:
                                out.append(jnp.asarray(np.asarray(src)))
                        new_vals.append(value)
                        new_opts.append(
                            variable_utils.unflatten_named(t_def, out))
                    with self._lock:
                        self._values[name] = new_vals
                        self._opt[name] = new_opts
            # republish so peers' first post-restore pull sees the restored
            # values instead of the owner's pre-restore published blob
            for w in workers:
                w.publish_now()
        finally:
            for w in workers:
                w.resume()
        if self._serve_config is not None and self._serve_groups is None:
            # serving was requested before any values existed (the
            # ADT_AUTO_RESUME path restores through the sharded format
            # BEFORE init_params ever runs): activate it now, or the job
            # would silently train disconnected local mirrors — no owner
            # loops, no cross-process exchange — with only the
            # "serving is not wired" warning as a symptom
            self._start_serving()

    def full_little_opt(self, name: str):
        """One variable's optimizer state as a FULL-variable little tree
        (the ``optimizer.init({'v': full_value})`` structure) assembled
        from the per-shard states: var-shaped leaves concatenate along the
        plan axis, shared (count-like) leaves come from shard 0. This is
        the fused engine's device carry — the inverse direction of
        :meth:`absorb_device_state`."""
        plan = self.plans[name]
        with self._lock:  # atomic snapshot vs the apply thread's swap
            states = list(self._opt[name])
        if not plan.partitioned:
            return jax.tree_util.tree_map(np.asarray, states[0])
        shard_dims = plan.shard_sizes

        def merge(*leaves):
            arrs = [np.asarray(l) for l in leaves]
            if (arrs[0].ndim > plan.axis
                    and tuple(a.shape[plan.axis] for a in arrs) == shard_dims):
                return np.concatenate(arrs, axis=plan.axis)
            return arrs[0]
        return jax.tree_util.tree_map(merge, *states)

    def absorb_device_state(self, values: Dict[str, Any],
                            opt_states: Dict[str, Any]) -> None:
        """Take ownership of post-superstep state computed ON DEVICE by the
        fused multi-step engine: full values split by true shard ranges,
        full little-tree optimizer states sliced per shard (var-shaped
        leaves along the plan axis; shared leaves copied whole — the same
        slicing rule as :meth:`load_opt_from_full`). One writeback replaces
        k per-microstep pushes; the wire accounting reflects that."""
        bytes0 = self.stats["bytes_pushed"]
        with tel.span("ps.absorb", "ps", vars=len(values)), \
                jax.default_device(self._cpu):
            for name, full in values.items():
                plan = self.plans[name]
                info = self._var_infos[name]
                full = np.asarray(jax.device_get(full))
                new_vals = self._split(plan, full)
                self.stats["bytes_pushed"] += full.nbytes
                new_opts = []
                for si in range(len(plan.shard_ranges())):
                    def slice_leaf(leaf, _si=si):
                        a = np.asarray(jax.device_get(leaf))
                        if (plan.partitioned and a.ndim > plan.axis
                                and a.shape[plan.axis]
                                == info.shape[plan.axis]):
                            a = self._shard_slice(plan, _si, a)
                        return jnp.asarray(a)
                    new_opts.append(jax.tree_util.tree_map(
                        slice_leaf, opt_states[name]))
                with self._lock:
                    self._values[name] = new_vals
                    self._opt[name] = new_opts
                self.stats["applies"] += 1
        if values:
            self.stats["pushes"] += 1
            tel.counter_add("ps.pushes")
            tel.counter_add("ps.bytes_pushed",
                            self.stats["bytes_pushed"] - bytes0)

    def full_opt_leaf(self, slot_path: str, var_name: str):
        """Reconstruct one optimizer-state subtree in the var's full layout
        (for original-layout checkpoints): concat var-sliced leaves across
        shards, take shard 0 for shared leaves. ``slot_path`` is the hole's
        position in the full opt tree, e.g. ``0/mu/<var_name>``."""
        plan = self.plans[var_name]
        with self._lock:  # atomic snapshot vs the apply thread's swap
            states = list(self._opt[var_name])
        if self._serve_groups is not None:
            # per-shard ownership: this process's local opt state is only
            # authoritative for the shards it owns; peer-owned shards'
            # moments come off the owner's opt side channel (the
            # ::si!leaf keys published with every apply, fetched only
            # here — never by the per-step value pulls)
            states = [self._remote_opt_state(var_name, si, st)
                      for si, st in enumerate(states)]
        # the per-shard little trees hold the same subtree under ".../v"
        prefix = slot_path[: -len(var_name)].rstrip("/")
        sub0 = self._subtree_at(states[0], prefix)
        if sub0 is None:
            raise KeyError("PS store has no opt slot %r for %s"
                           % (slot_path, var_name))
        if not plan.partitioned:
            return jax.tree_util.tree_map(lambda x: np.asarray(x), sub0)
        subs = [self._subtree_at(s, prefix) for s in states]
        shard_dims = plan.shard_sizes

        def merge(*leaves):
            arrs = [np.asarray(l) for l in leaves]
            a0 = arrs[0]
            if (a0.ndim > plan.axis
                    and tuple(a.shape[plan.axis] for a in arrs) == shard_dims):
                return np.concatenate(arrs, axis=plan.axis)
            return a0  # shared (count-like) leaf
        return jax.tree_util.tree_map(merge, *subs)

    def _remote_opt_state(self, var_name: str, si: int, local_state):
        """The authoritative little-tree opt state for one shard: local
        when this process owns the shard, else rebuilt from the owner's
        latest published ``name::si!leaf`` entries (falling back to the
        local state pre-publish). The local state provides the tree
        structure; leaves are filled by flattened name."""
        from autodist_tpu.kernel.common import variable_utils
        from autodist_tpu.runtime import ps_service as pss
        for grp in self._serve_groups.values():
            if (var_name, si) not in grp["pairs"]:
                continue
            if grp["owned"]:
                return local_state
            res = grp["service"].fetch_opt()
            if res is None:
                return local_state  # owner pre-publish
            blobs = pss.unpack_arrays(res[1])
            want = "%s::%d!" % (var_name, si)
            remote = {k[len(want):]: v for k, v in blobs.items()
                      if k.startswith(want)}
            if not remote:
                return local_state  # older publish without opt leaves
            names, leaves, treedef = variable_utils.flatten_named(local_state)
            filled = [remote.get(n, leaf) for n, leaf in zip(names, leaves)]
            return variable_utils.unflatten_named(treedef, filled)
        return local_state

    @staticmethod
    def _subtree_at(little_tree, slot_prefix: str):
        """The subtree of a per-shard opt state at a slot path, where the
        little tree's var key is ``v``. slot_prefix '' means the leaf 'v'
        itself (optimizers whose whole state is var-shaped)."""
        from autodist_tpu.kernel.common import variable_utils
        # collect (name, leaf) then rebuild the subtree under prefix + "/v"
        target = (slot_prefix + "/v") if slot_prefix else "v"
        names, leaves, _ = variable_utils.flatten_named(little_tree)
        # exact leaf hit
        for n, l in zip(names, leaves):
            if n == target:
                return l
        # subtree hit: leaves under target/
        picked = [(n[len(target) + 1:], l) for n, l in zip(names, leaves)
                  if n.startswith(target + "/")]
        if not picked:
            return None
        return {n: l for n, l in picked}

    # ------------------------------------------------------------ accounting

    def mirror_digest(self) -> str:
        """Digest of all resident values — the sync multi-process
        consistency check. Every process's mirror must stay bit-identical
        (deterministic jitted CPU applies of the identical psum'd
        gradient); the Runner compares digests across processes via the
        coordination service every ``ADT_PS_MIRROR_CHECK_EVERY`` steps and
        fails fast on divergence (heterogeneous host codegen would
        otherwise silently fork the replicas). Mirror mode only: a serving
        store has one authoritative owner copy, so there is nothing to
        cross-check (and no consistent snapshot to hash under the apply
        thread)."""
        if self.serving:  # not an assert: must hold under python -O too
            raise RuntimeError("mirror_digest is for sync (mirror) mode")
        import hashlib
        h = hashlib.md5()
        for name in sorted(self._values):
            h.update(name.encode())
            for s in self._values[name]:
                h.update(np.ascontiguousarray(s).tobytes())
        return h.hexdigest()

    def resident_bytes(self) -> int:
        """Host bytes resident in this store (values only)."""
        return sum(int(s.nbytes) for shards in self._values.values()
                   for s in shards)

    def resident_bytes_by_destination(self) -> Dict[str, int]:
        """Per-owner byte loads (the PS load-balancing accounting)."""
        out: Dict[str, int] = {}
        for name, plan in self.plans.items():
            for dest, shard in zip(plan.destinations, self._values[name]):
                out[dest] = out.get(dest, 0) + int(shard.nbytes)
        return out

    @property
    def var_names(self):
        return sorted(self.plans)

    def max_staleness(self) -> int:
        return max((p.staleness for p in self.plans.values()), default=0)

    def any_async(self) -> bool:
        return any(not p.sync for p in self.plans.values())


# ----------------------------------------------------------------- pipeline


class PSPipeline:
    """Overlap the host-PS data path with compute (a TPU-native stand-in
    for the reference's TF dataflow runtime, which scheduled PS send/recv
    against compute implicitly, ``ps_synchronizer.py:171-176``).

    The serial baseline runs pull -> step -> device_get(grads) -> host
    apply, so a transfer-bound config pays compute + 2x PCIe per step.
    Here the push (D2H + optimizer apply) and the NEXT step's pull staging
    (H2D) run on one background worker:

    - **sync PS (exact)**: each step's job is get -> apply -> prefetch, and
      the next step's :meth:`values` waits for it — numerics are
      bit-identical to the serial path (same calls, same order). The whole
      job overlaps the main thread's dispatch latency, feed building, and
      user host code.
    - **staleness >= 1 or async serving**: the prefetch is issued BEFORE
      the apply, so the H2D rides alongside this step's compute and the
      apply + D2H ride alongside the next step's: step time ~=
      max(compute, transfer). Reads lag applies by exactly one — inside
      the declared staleness bound (and unordered-by-design under async).

    ``ADT_PS_OVERLAP=0`` restores the serial path.
    """

    def __init__(self, store: PSStore, mesh, stale_ok: bool):
        import concurrent.futures
        self._store = store
        self._mesh = mesh
        self._stale_ok = stale_ok
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="adt-ps-pipe")
        # stale mode runs pulls on their OWN lane so the next step's H2D
        # overlaps the previous push's D2H+apply (max(pull, push) instead
        # of pull+push); exact mode keeps one lane (strict order)
        self._pull_exec = (concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="adt-ps-pull")
            if stale_ok else self._exec)
        self._pending = None  # Future -> staged device values for next step
        self._push_pending = None  # stale mode: the push/apply future
        # staleness window: a read may lag at most this many applies (the
        # pull for step N+1 waits for push N-s before reading)
        self._window = max(1, store.max_staleness())
        import collections
        self._push_hist = collections.deque(maxlen=max(self._window, 1))

    def _pull_staged(self):
        from autodist_tpu.parallel.mesh import tree_to_mesh
        from jax.sharding import PartitionSpec as P
        return tree_to_mesh(self._mesh, self._store.pull(), P())

    def values(self):
        """Device-staged PS values for the step about to run. Consumes the
        prefetch when one is pending (in exact mode the prefetch job also
        carries the push, so waiting keeps sync semantics exact); cold
        start / post-eval does a fresh pull."""
        if self._pending is None:
            return self._pull_staged()
        fut, self._pending = self._pending, None
        return fut.result()

    def submit(self, ps_grads: Dict[str, Any], ok=None) -> None:
        """Queue this step's push and the next step's pull.

        Exact (sync) mode: one job, get -> apply -> prefetch, and the next
        ``values()`` waits for all of it — bit-identical to serial.

        Stale mode (staleness >= 1 / async serving): the pull rides its own
        lane and may read PRE-apply values (stale-by-one, and per-variable
        rather than tree-atomic — the store's per-var lock means a pull
        concurrent with an apply can see var A pre-apply and var B post-
        apply, exactly the per-variable consistency the reference's
        per-var PS queues gave).

        ``ok`` is the sentinel verdict device scalar riding the same
        dispatch as ``ps_grads``: the push job reads it (the one D2H a
        push pays anyway, in the worker thread — never blocking the main
        thread) and SUPPRESSES the apply when the step was judged
        unhealthy, so a poisoned gradient never reaches the store."""

        def _push_allowed() -> bool:
            if ok is None:
                return True
            if bool(np.asarray(jax.device_get(ok))):
                return True
            tel.counter_add("sentinel.ps_suppressed")
            logging.warning("sentinel: PS push suppressed (bad verdict)")
            return False

        if self._stale_ok:
            # bounded lag: the prefetched read may trail the newest apply
            # by at most the staleness window — the pull waits for the
            # push submitted `window` steps ago (None in the ramp-up)
            barrier = (self._push_hist[0]
                       if len(self._push_hist) >= self._window else None)

            def pull_job():
                if barrier is not None:
                    barrier.result()
                return self._pull_staged()
            self._pending = self._pull_exec.submit(pull_job)
            prev = self._push_pending

            def push_job():
                if prev is not None:
                    prev.result()        # pushes stay ordered
                if _push_allowed():
                    self._store.push(ps_grads)
            self._push_pending = self._exec.submit(push_job)
            self._push_hist.append(self._push_pending)
        else:
            def job():
                if _push_allowed():
                    self._store.push(ps_grads)
                return self._pull_staged()
            self._pending = self._exec.submit(job)

    def flush(self) -> None:
        """Wait for the in-flight push (checkpoints / gathers / digests
        read the store and must see every submitted gradient applied).
        The staged values stay pending for the next :meth:`values`."""
        if self._push_pending is not None:
            self._push_pending.result()
        if self._pending is not None and not self._stale_ok:
            self._pending.result()

    def invalidate(self) -> None:
        """Flush, then DISCARD the staged prefetch — the store's state was
        replaced out of band (checkpoint restore / re-init) and the staged
        values no longer reflect it."""
        self.flush()
        if self._pending is not None:
            self._pending.result()  # never abandon a running pull mid-flight
        self._pending = None

    def close(self) -> None:
        self.flush()
        self._exec.shutdown(wait=True)
        if self._pull_exec is not self._exec:
            self._pull_exec.shutdown(wait=True)
