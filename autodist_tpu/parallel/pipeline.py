"""Pipeline parallelism — GPipe-style SPMD microbatch pipelining.

Beyond the reference, which has no pipeline axis ("Currently, AutoDist only
supports data-parallel distribution", reference
``docs/design/architecture.rst:46-48``). On TPU the pipeline is expressed
INSIDE the lowering's shard_map: layer-stacked parameters are sharded over
the ``pipe`` mesh axis (``VarConfig.mp_axes = {0: 'pipe'}``), every pipe
rank runs the same program, and activations flow rank-to-rank with
``lax.ppermute`` over nearest-neighbor ICI links. The schedule is GPipe
(Huang et al., arXiv 1811.06965): M microbatches stream through S stages in
M + S - 1 ticks, implemented as one ``lax.scan`` so XLA compiles a single
fused loop; reverse-mode AD through ppermute/scan yields the exact backward
schedule automatically.

Gradient correctness needs no special-casing: the loss is made uniform
across pipe ranks with a psum broadcast, whose transpose gives every rank
the summed cotangent; the lowering's ``psum(complement)/N`` sync for
pipe-sharded vars and ``psum(all)/N`` for replicated vars are exact against
that convention (same algebra as tensor parallelism — see
``parallel/tensor.py`` and ``kernel/graph_transformer.py``).

Composes with tensor parallelism: stack dim 0 over ``pipe`` and head/hidden
dims over ``model`` in the same ``mp_axes`` spec, and use
``parallel/tensor.py`` ops inside the stage body.
"""
from typing import Callable

import jax
import jax.numpy as jnp

from autodist_tpu import const
from autodist_tpu.parallel.sequence import axis_bound


def num_stages(axis_name: str = const.PIPELINE_AXIS) -> int:
    return jax.lax.psum(1, axis_name) if axis_bound(axis_name) else 1


def pipeline_apply(stage_fn: Callable, stage_params, x,
                   n_microbatches: int,
                   axis_name: str = const.PIPELINE_AXIS):
    """Run ``x`` through the full layer stack, pipelined over ``axis_name``.

    - ``stage_fn(stage_params, h) -> h``: applies this rank's layer chunk;
      ``stage_params`` leaves are stacked [stages_per_device, ...] shards
      (apply them sequentially inside). Activation shape must be uniform
      across stages (the transformer-block invariant).
    - ``x``: local activations [B, ...] (replicated over the pipe axis; B is
      the per-data-shard batch). Split into ``n_microbatches`` along dim 0.
    - Returns the final stage's output for the whole batch, broadcast to all
      pipe ranks (so the loss/head computes identically everywhere).

    Outside shard_map (single device / capture tracing) this degenerates to
    a plain sequential apply — one model definition serves both paths.
    """
    if not axis_bound(axis_name):
        return stage_fn(stage_params, x)

    S = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    M = n_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError("batch %d not divisible by %d microbatches" % (B, M))
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    # stage r receives from r-1; rank 0 reads microbatches, rank S-1's
    # output is collected (no wraparound send)
    perm = [(i, i + 1) for i in range(S - 1)]
    state0 = jnp.zeros_like(x_mb[0])
    outs0 = jnp.zeros_like(x_mb)

    def tick(carry, t):
        state, outs = carry
        inp = jnp.where(rank == 0,
                        jax.lax.dynamic_index_in_dim(
                            x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False),
                        state)
        out = stage_fn(stage_params, inp)
        # the last rank finishes microbatch t-(S-1) at tick t
        idx = t - (S - 1)
        valid = (idx >= 0) & (rank == S - 1)
        written = jax.lax.dynamic_update_slice_in_dim(
            outs, out[None], jnp.clip(idx, 0, M - 1), 0)
        outs = jnp.where(valid, written, outs)
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, outs), None

    (_, outs), _ = jax.lax.scan(tick, (state0, outs0), jnp.arange(M + S - 1))
    # broadcast the last rank's collected outputs to every pipe rank
    outs = jax.lax.psum(
        jnp.where(rank == S - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs.reshape((B,) + x.shape[1:])


def _chunk(tree, c, V):
    """Chunk ``c`` of a rank-local layer stack: leading dim L_local splits
    into [V, L_local/V]; works with a traced ``c`` (dynamic index)."""
    def take(a):
        sub = a.reshape((V, a.shape[0] // V) + a.shape[1:])
        return jax.lax.dynamic_index_in_dim(sub, c, 0, keepdims=False)
    return jax.tree_util.tree_map(take, tree)


def pipeline_apply_interleaved(stage_fn: Callable, stage_params, x,
                               n_microbatches: int, virtual_stages: int,
                               axis_name: str = const.PIPELINE_AXIS,
                               pp_shards_hint: int = 0,
                               remat_chunks: bool = False):
    """Interleaved (virtual-stage) pipeline schedule — Megatron-LM's
    bubble-cutting variant (Narayanan et al. 2104.04473): each rank holds
    ``V = virtual_stages`` layer CHUNKS instead of one contiguous block,
    and microbatches visit rank r's chunk c as virtual stage
    ``s = c*S + r``. Per-rank work slots go from M (GPipe, V-sized
    chunks) to M*V (1/V-sized chunks) while the fill/drain bubble stays
    S-1 slots — the bubble FRACTION shrinks from (S-1)/M to (S-1)/(V*M).

    Model definition: physical stack position ``r*V + c`` (rank-major
    chunk grid) holds logical stage ``c*S + r``; the unbound degenerate
    path below applies the same logical order, so single-device traces
    and the pipelined program compute identical math.

    Slot schedule (forward; AD derives the backward through scan/ppermute
    exactly as for GPipe): stage s of microbatch m runs at slot
    ``u = (s mod S) + (s//S)*S + (m mod S) + (m//S)*V*S`` — consecutive
    stages always land on consecutive slots on ring-adjacent ranks, so
    the wire is ONE full-ring ppermute per slot (the wraparound edge
    S-1 -> 0 carries chunk-boundary hops; GPipe's chain never uses it).
    Needs ``M % S == 0`` (the standard interleaved-schedule constraint)
    and ``L_local % V == 0``.

    ``remat_chunks=True`` wraps each slot's chunk application in
    ``jax.checkpoint``: AD then stashes only the slot INPUT per tick and
    recomputes the chunk forward inside the backward — activation
    residency drops from every intra-chunk layer activation across all
    M*V slots to one microbatch activation per slot (the same
    FLOPs-for-HBM trade the 1F1B schedule makes per microbatch), with
    bit-identical numerics.
    """
    V = int(virtual_stages)
    if V < 1:
        raise ValueError("virtual_stages must be >= 1")
    if not axis_bound(axis_name):
        # Degenerate path: single-device traces (capture, references) see
        # the FULL stack. The logical network visits physical chunk-grid
        # position (s % S)*V + s//S for s = 0..S*V-1, so with the
        # intended stage count as a hint the emulation applies the SAME
        # permuted order the pipelined program computes; without a hint
        # (S unknowable) it falls back to the plain sequential stack
        # (exact only for S == 1).
        S_hint = int(pp_shards_hint)
        if S_hint > 1:
            h = x
            for s in range(S_hint * V):
                g = (s % S_hint) * V + (s // S_hint)
                h = stage_fn(
                    jax.tree_util.tree_map(
                        lambda a, g=g: a.reshape(
                            (S_hint * V, a.shape[0] // (S_hint * V))
                            + a.shape[1:])[g],
                        stage_params), h)
            return h
        return stage_fn(stage_params, x)

    S = jax.lax.psum(1, axis_name)
    S_int = int(S)
    rank = jax.lax.axis_index(axis_name)
    M = n_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError("batch %d not divisible by %d microbatches" % (B, M))
    if M % S_int != 0:
        raise ValueError(
            "interleaved schedule needs n_microbatches (%d) divisible by "
            "pipeline stages (%d)" % (M, S_int))
    x_mb = x.reshape((M, B // M) + x.shape[1:])
    ring = [(i, (i + 1) % S_int) for i in range(S_int)]

    apply_chunk = (jax.checkpoint(stage_fn) if remat_chunks else stage_fn)

    state0 = jnp.zeros_like(x_mb[0])
    outs0 = jnp.zeros_like(x_mb)

    def tick(carry, t):
        state, outs = carry
        q = t - rank                       # this rank's work-slot index
        on = (q >= 0) & (q < M * V)
        blk = jnp.clip(q, 0, M * V - 1) % (V * S_int)
        c = jnp.clip(blk // S_int, 0, V - 1)       # chunk = virtual row
        j = blk % S_int
        k = jnp.clip(q, 0, M * V - 1) // (V * S_int)
        m = jnp.clip(k * S_int + j, 0, M - 1)      # microbatch index
        first = (rank == 0) & (c == 0)             # virtual stage 0
        inp = jnp.where(first,
                        jax.lax.dynamic_index_in_dim(x_mb, m, 0,
                                                     keepdims=False),
                        state)
        out = apply_chunk(_chunk(stage_params, c, V), inp)
        out = jnp.where(on, out, jnp.zeros_like(out))
        # virtual stage V*S-1 = rank S-1's chunk V-1 finishes microbatch m
        done = on & (rank == S - 1) & (c == V - 1)
        written = jax.lax.dynamic_update_slice_in_dim(outs, out[None], m, 0)
        outs = jnp.where(done, written, outs)
        state = jax.lax.ppermute(out, axis_name, ring)
        return (state, outs), None

    T = M * V + S_int - 1
    (_, outs), _ = jax.lax.scan(tick, (state0, outs0), jnp.arange(T))
    outs = jax.lax.psum(
        jnp.where(rank == S - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs.reshape((B,) + x.shape[1:])


def stacked_scan(block_fn: Callable, stacked_params, h):
    """Apply ``block_fn(params_i, h) -> h`` for each leading-dim slice of
    ``stacked_params`` via ``lax.scan`` (compile-time-friendly for deep
    stacks; the standard stage body for ``pipeline_apply``)."""
    def body(carry, p):
        return block_fn(p, carry), None
    out, _ = jax.lax.scan(body, h, stacked_params)
    return out


# --------------------------------------------------------------------- 1F1B


def _run_1f1b(stage_fn, head_fn, stage_params, head_params, x, y,
              n_microbatches: int, axis_name: str):
    """The fused 1F1B schedule: loss AND grads in ONE interleaved scan.

    Schedule (classic non-interleaved 1F1B, Narayanan et al. PipeDream /
    Megatron): with S stages and M microbatches over global half-ticks,
    rank ``r`` runs fwd(m) at tick ``r + 2m`` and bwd(m) at tick
    ``2S-1-r + 2m``. The two live on opposite tick parities, so each rank
    does at most one forward and one backward per tick, activations flow
    down (ppermute +1) and cotangents up (ppermute -1) every tick, and a
    stashed microbatch INPUT lives only ``2(S-r)-1`` ticks — so a
    circular stash of ``S`` slots bounds activation residency at S
    microbatches (vs GPipe's all-M residency). The backward recomputes
    the stage forward from the stashed input (per-microbatch remat).

    Gradient conventions match the GPipe path exactly (the lowering's
    psum(complement)/N for pipe-sharded vars and psum(all)/N for
    replicated vars assume the broadcast-loss inflation — see
    tests/test_pipeline_parallel.py): stage grads come back S-inflated,
    dx is S-inflated and nonzero only on rank 0, head grads uniform.

    Returns (loss, dstage_params, dhead_params, dx).
    """
    S = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    M = n_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError("batch %d not divisible by %d microbatches" % (B, M))
    x_mb = x.reshape((M, B // M) + x.shape[1:])
    y_mb = y.reshape((M, B // M) + y.shape[1:])
    S_int = int(S)  # mesh axis sizes are static under shard_map

    fwd_perm = [(i, i + 1) for i in range(S_int - 1)]
    bwd_perm = [(i + 1, i) for i in range(S_int - 1)]

    zeros_mb = jnp.zeros_like(x_mb[0])
    carry0 = {
        "fwd_in": zeros_mb,                       # activation from upstream
        "bwd_in": zeros_mb,                       # cotangent from downstream
        "stash": jnp.zeros((S_int,) + zeros_mb.shape, zeros_mb.dtype),
        "gacc": jax.tree_util.tree_map(jnp.zeros_like, stage_params),
        "hacc": jax.tree_util.tree_map(jnp.zeros_like, head_params),
        "dx": jnp.zeros_like(x_mb),
        "loss": jnp.zeros((), jnp.float32),
    }

    zeros_stage = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    zeros_head = jax.tree_util.tree_map(jnp.zeros_like, head_params)

    def tick(carry, t):
        f2 = t - rank                      # fwd(m) at t = r + 2m
        b2 = t - (2 * S - 1 - rank)        # bwd(m) at t = 2S-1-r + 2m
        fwd_on = (f2 >= 0) & (f2 % 2 == 0) & (f2 // 2 < M)
        bwd_on = (b2 >= 0) & (b2 % 2 == 0) & (b2 // 2 < M)
        fi = jnp.clip(f2 // 2, 0, M - 1)
        bi = jnp.clip(b2 // 2, 0, M - 1)
        is_last = rank == S - 1

        # fwd and bwd live on opposite tick parities, so each rank runs
        # exactly ONE of the branches per tick — lax.cond, not
        # where-predication, so the idle half is not computed. In-branch
        # collectives (model-axis psums under tp) stay matched: the
        # parity predicate depends only on the pipe coordinate, so every
        # model/data-axis peer takes the same branch.
        # Branch outputs: (fwd_payload, dh, stash, dstage_mb, dhead_mb,
        # loss_mb) — dh doubles as the upstream ppermute payload AND the
        # dx-gradient source
        def fwd_branch(_):
            inp = jnp.where(rank == 0,
                            jax.lax.dynamic_index_in_dim(x_mb, fi, 0,
                                                         keepdims=False),
                            carry["fwd_in"])
            out = stage_fn(stage_params, inp)
            stash = jnp.where(
                fwd_on,
                jax.lax.dynamic_update_slice_in_dim(
                    carry["stash"], inp[None], fi % S_int, 0),
                carry["stash"])
            return (jnp.where(fwd_on, out, jnp.zeros_like(out)),
                    jnp.zeros_like(carry["bwd_in"]), stash,
                    zeros_stage, zeros_head,
                    jnp.zeros((), jnp.float32))

        def bwd_branch(_):
            h_in = jax.lax.dynamic_index_in_dim(carry["stash"],
                                                bi % S_int, 0,
                                                keepdims=False)
            s_out, stage_vjp = jax.vjp(stage_fn, stage_params, h_in)
            yb = jax.lax.dynamic_index_in_dim(y_mb, bi, 0, keepdims=False)
            loss_mb, head_vjp = jax.vjp(head_fn, head_params, s_out, yb)
            dhead_mb, dout_head, _ = head_vjp(jnp.ones((), loss_mb.dtype))
            dout = jnp.where(is_last, dout_head, carry["bwd_in"])
            dstage_mb, dh = stage_vjp(dout)
            gate = lambda on, tree: jax.tree_util.tree_map(  # noqa: E731
                lambda d: jnp.where(on, d, jnp.zeros_like(d)), tree)
            return (jnp.zeros_like(carry["fwd_in"]),
                    jnp.where(bwd_on, dh, jnp.zeros_like(dh)),
                    carry["stash"],
                    gate(bwd_on, dstage_mb),
                    gate(bwd_on & is_last, dhead_mb),
                    jnp.where(bwd_on & is_last,
                              loss_mb.astype(jnp.float32), 0.0))

        # bwd-parity ticks run the backward branch (cooldown ticks where
        # bwd_on is False just compute gated-to-zero deltas)
        (fwd_payload, dh, stash, dstage_mb, dhead_mb,
         loss_mb) = jax.lax.cond(b2 % 2 == 0, bwd_branch, fwd_branch,
                                 operand=None)
        bwd_payload = dh

        add = lambda acc, d: jax.tree_util.tree_map(  # noqa: E731
            lambda a, x: a + x, acc, d)
        gacc = add(carry["gacc"], dstage_mb)
        hacc = add(carry["hacc"], dhead_mb)
        dx = jnp.where(
            bwd_on & (rank == 0),
            jax.lax.dynamic_update_slice_in_dim(carry["dx"], dh[None], bi, 0),
            carry["dx"])
        loss = carry["loss"] + loss_mb

        new_carry = {
            "fwd_in": jax.lax.ppermute(fwd_payload, axis_name, fwd_perm),
            "bwd_in": jax.lax.ppermute(bwd_payload, axis_name, bwd_perm),
            "stash": stash, "gacc": gacc, "hacc": hacc, "dx": dx,
            "loss": loss,
        }
        return new_carry, None

    T = 2 * M + 2 * S_int - 2
    final, _ = jax.lax.scan(tick, carry0, jnp.arange(T))

    # GPipe-convention packaging (see docstring): loss + head grads
    # broadcast uniform; stage grads and dx S-inflated, mean over M
    loss = jax.lax.psum(
        jnp.where(rank == S - 1, final["loss"] / M, 0.0), axis_name)
    dstage = jax.tree_util.tree_map(
        lambda a: a * (S / M), final["gacc"])
    dhead = jax.tree_util.tree_map(
        lambda a: jax.lax.psum(a / M, axis_name), final["hacc"])
    # each dx slot is the cotangent of that microbatch's UNdivided loss;
    # the total loss is the mean over M, hence the /M here too
    dx = jnp.where(rank == 0, final["dx"].reshape(x.shape) * (S / M),
                   jnp.zeros(x.shape, final["dx"].dtype))
    return loss, dstage, dhead, dx


import functools as _functools  # noqa: E402


@_functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 6, 7))
def pipeline_loss_1f1b(stage_fn, head_fn, stage_params, head_params, x, y,
                       n_microbatches, axis_name=const.PIPELINE_AXIS):
    """Pipelined loss with the 1F1B schedule (activation residency bounded
    at S microbatches instead of GPipe's M — Narayanan et al. 1806.03377 /
    Megatron-LM 2104.04473).

    ``stage_fn(stage_params, h) -> h`` is this rank's layer chunk;
    ``head_fn(head_params, h, y) -> scalar`` is the per-microbatch loss
    head (runs at the last stage INSIDE the schedule — that is what lets
    backward start while later microbatches are still in forward).

    Differentiable in (stage_params, head_params, x): the forward pass of
    the outer ``jax.grad`` already runs the fused fwd+bwd schedule and
    stashes the grads as residuals, so the outer backward only scales
    them — loss-and-grad costs ONE 1F1B sweep. Gradient scaling matches
    ``pipeline_apply``'s broadcast-loss convention, so the lowering's
    existing psum(complement)/N sync is exact for both schedules.

    Outside shard_map this degenerates to sequential M=1 semantics via the
    plain path (use ``pipeline_apply`` for capture tracing).
    """
    if not axis_bound(axis_name):
        out = stage_fn(stage_params, x)
        return head_fn(head_params, out, y)
    loss, _, _, _ = _run_1f1b(stage_fn, head_fn, stage_params, head_params,
                              x, y, n_microbatches, axis_name)
    return loss


def _zero_cotangent(y):
    from autodist_tpu.kernel.common.variable_utils import zero_cotangent
    return zero_cotangent(y)


def _pl_fwd(stage_fn, head_fn, stage_params, head_params, x, y,
            n_microbatches, axis_name):
    if not axis_bound(axis_name):
        out, loss_vjp = jax.vjp(
            lambda sp, hp, xx: head_fn(hp, stage_fn(sp, xx), y),
            stage_params, head_params, x)
        dsp, dhp, dx = loss_vjp(jnp.ones((), out.dtype))
        return out, (dsp, dhp, dx, y)
    loss, dstage, dhead, dx = _run_1f1b(
        stage_fn, head_fn, stage_params, head_params, x, y,
        n_microbatches, axis_name)
    return loss, (dstage, dhead, dx, y)


def _pl_bwd(stage_fn, head_fn, n_microbatches, axis_name, residuals, g):
    dstage, dhead, dx, y = residuals
    scale = lambda tree: jax.tree_util.tree_map(  # noqa: E731
        lambda a: (a * g).astype(a.dtype), tree)
    return scale(dstage), scale(dhead), (dx * g).astype(dx.dtype), \
        _zero_cotangent(y)


pipeline_loss_1f1b.defvjp(_pl_fwd, _pl_bwd)
