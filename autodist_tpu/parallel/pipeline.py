"""Pipeline parallelism — GPipe-style SPMD microbatch pipelining.

Beyond the reference, which has no pipeline axis ("Currently, AutoDist only
supports data-parallel distribution", reference
``docs/design/architecture.rst:46-48``). On TPU the pipeline is expressed
INSIDE the lowering's shard_map: layer-stacked parameters are sharded over
the ``pipe`` mesh axis (``VarConfig.mp_axes = {0: 'pipe'}``), every pipe
rank runs the same program, and activations flow rank-to-rank with
``lax.ppermute`` over nearest-neighbor ICI links. The schedule is GPipe
(Huang et al., arXiv 1811.06965): M microbatches stream through S stages in
M + S - 1 ticks, implemented as one ``lax.scan`` so XLA compiles a single
fused loop; reverse-mode AD through ppermute/scan yields the exact backward
schedule automatically.

Gradient correctness needs no special-casing: the loss is made uniform
across pipe ranks with a psum broadcast, whose transpose gives every rank
the summed cotangent; the lowering's ``psum(complement)/N`` sync for
pipe-sharded vars and ``psum(all)/N`` for replicated vars are exact against
that convention (same algebra as tensor parallelism — see
``parallel/tensor.py`` and ``kernel/graph_transformer.py``).

Composes with tensor parallelism: stack dim 0 over ``pipe`` and head/hidden
dims over ``model`` in the same ``mp_axes`` spec, and use
``parallel/tensor.py`` ops inside the stage body.
"""
from typing import Callable

import jax
import jax.numpy as jnp

from autodist_tpu import const
from autodist_tpu.parallel.sequence import axis_bound


def num_stages(axis_name: str = const.PIPELINE_AXIS) -> int:
    return jax.lax.psum(1, axis_name) if axis_bound(axis_name) else 1


def pipeline_apply(stage_fn: Callable, stage_params, x,
                   n_microbatches: int,
                   axis_name: str = const.PIPELINE_AXIS):
    """Run ``x`` through the full layer stack, pipelined over ``axis_name``.

    - ``stage_fn(stage_params, h) -> h``: applies this rank's layer chunk;
      ``stage_params`` leaves are stacked [stages_per_device, ...] shards
      (apply them sequentially inside). Activation shape must be uniform
      across stages (the transformer-block invariant).
    - ``x``: local activations [B, ...] (replicated over the pipe axis; B is
      the per-data-shard batch). Split into ``n_microbatches`` along dim 0.
    - Returns the final stage's output for the whole batch, broadcast to all
      pipe ranks (so the loss/head computes identically everywhere).

    Outside shard_map (single device / capture tracing) this degenerates to
    a plain sequential apply — one model definition serves both paths.
    """
    if not axis_bound(axis_name):
        return stage_fn(stage_params, x)

    S = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    M = n_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError("batch %d not divisible by %d microbatches" % (B, M))
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    # stage r receives from r-1; rank 0 reads microbatches, rank S-1's
    # output is collected (no wraparound send)
    perm = [(i, i + 1) for i in range(S - 1)]
    state0 = jnp.zeros_like(x_mb[0])
    outs0 = jnp.zeros_like(x_mb)

    def tick(carry, t):
        state, outs = carry
        inp = jnp.where(rank == 0,
                        jax.lax.dynamic_index_in_dim(
                            x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False),
                        state)
        out = stage_fn(stage_params, inp)
        # the last rank finishes microbatch t-(S-1) at tick t
        idx = t - (S - 1)
        valid = (idx >= 0) & (rank == S - 1)
        written = jax.lax.dynamic_update_slice_in_dim(
            outs, out[None], jnp.clip(idx, 0, M - 1), 0)
        outs = jnp.where(valid, written, outs)
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, outs), None

    (_, outs), _ = jax.lax.scan(tick, (state0, outs0), jnp.arange(M + S - 1))
    # broadcast the last rank's collected outputs to every pipe rank
    outs = jax.lax.psum(
        jnp.where(rank == S - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs.reshape((B,) + x.shape[1:])


def stacked_scan(block_fn: Callable, stacked_params, h):
    """Apply ``block_fn(params_i, h) -> h`` for each leading-dim slice of
    ``stacked_params`` via ``lax.scan`` (compile-time-friendly for deep
    stacks; the standard stage body for ``pipeline_apply``)."""
    def body(carry, p):
        return block_fn(p, carry), None
    out, _ = jax.lax.scan(body, h, stacked_params)
    return out
