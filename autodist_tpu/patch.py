"""Optimizer-capture patches.

Analog of reference ``autodist/patch.py:79-90`` (``PatchTensorFlow``): the
reference wraps every TF optimizer's ``__init__``/``apply_gradients`` so the
``GraphItem`` learns which optimizer the user built and with what arguments.
Here the optimizer is an optax ``GradientTransformation`` — a pytree of pure
functions with no identity of its own — so we wrap the public optax
constructors and keep a side-table from the constructed object's id to
``(name, kwargs)``. ``ModelItem`` consults the table at capture time.

Applied automatically on package import when ``ADT_PATCH_OPTAX`` is set
(mirroring reference ``autodist/__init__.py:50``).
"""
import collections
import functools
import inspect
from typing import Any, Optional, Tuple

# Keyed by id() with a strong reference to the optimizer object itself, so an
# id can never be reused while its entry is live (optax transformations are
# NamedTuples — not weakref-able). Bounded LRU so sweeps don't leak.
_CAPTURED: "collections.OrderedDict[int, Tuple[Any, str, dict]]" = collections.OrderedDict()
_CAPTURED_MAX = 128


def clear_captured() -> None:
    """Drop recorded optimizer constructions (``autodist_tpu.reset()``):
    entries are keyed by object id, and a stale entry can mis-describe a
    NEW optimizer whose id the allocator reused."""
    _CAPTURED.clear()
_PATCHED = False

# The widely-used optax optimizer constructors (the analog of the
# reference's "all OptimizerV1/V2 subclasses" sweep).
_OPTAX_CTORS = [
    "sgd", "adam", "adamw", "adamax", "adamaxw", "adagrad", "adadelta",
    "rmsprop", "lamb", "lars", "lion", "nadam", "nadamw", "novograd",
    "radam", "sm3", "yogi", "fromage", "adafactor", "noisy_sgd", "amsgrad",
]


def _record(name: str, fn, args, kwargs, result):
    try:
        bound = inspect.signature(fn).bind_partial(*args, **kwargs)
        arg_dict = dict(bound.arguments)
    except TypeError:
        arg_dict = {"args": args, "kwargs": kwargs}
    _CAPTURED[id(result)] = (result, name, arg_dict)
    while len(_CAPTURED) > _CAPTURED_MAX:
        _CAPTURED.popitem(last=False)


def _wrap(name: str, fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        result = fn(*args, **kwargs)
        _record(name, fn, args, kwargs, result)
        return result
    wrapper.__adt_patched__ = True
    return wrapper


def patch_optax():
    """Install the constructor wrappers (idempotent)."""
    global _PATCHED
    if _PATCHED:
        return
    try:
        import optax
    except ImportError:
        return
    for name in _OPTAX_CTORS:
        fn = getattr(optax, name, None)
        if fn is None or getattr(fn, "__adt_patched__", False):
            continue
        setattr(optax, name, _wrap(name, fn))
    _PATCHED = True


def unpatch_optax():
    global _PATCHED
    try:
        import optax
    except ImportError:
        return
    for name in _OPTAX_CTORS:
        fn = getattr(optax, name, None)
        if fn is not None and getattr(fn, "__adt_patched__", False):
            setattr(optax, name, fn.__wrapped__)
    _PATCHED = False


def lookup_optimizer(opt) -> Tuple[Optional[str], dict]:
    """Return recorded (name, kwargs) for an optax transformation, if known."""
    entry = _CAPTURED.get(id(opt))
    if entry is None or entry[0] is not opt:
        return None, {}
    return entry[1], entry[2]


def register_optimizer(opt: Any, name: str, args: Optional[dict] = None):
    """Explicit registration for optimizers built outside the patched ctors."""
    _CAPTURED[id(opt)] = (opt, name, dict(args or {}))
    while len(_CAPTURED) > _CAPTURED_MAX:
        _CAPTURED.popitem(last=False)
