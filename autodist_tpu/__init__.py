"""autodist_tpu — a TPU-native distributed training strategy compiler.

A from-scratch JAX/XLA framework with the capabilities of the AutoDist
strategy compiler (reference ``autodist/__init__.py``): single-device user
code + a cluster description in, a compiled serializable per-variable
distribution strategy out, lowered to SPMD programs over a TPU device mesh.

Import-time behavior mirrors the reference (``__init__.py:35-50``): a
backend version gate and optimizer-capture patching.
"""
__version__ = "0.1.0"

import jax as _jax

# version gate (reference enforces TF in [1.15, 2.2], __init__.py:35-43)
_MIN_JAX = (0, 4, 30)
_ver = tuple(int(x) for x in _jax.__version__.split(".")[:3])
if _ver < _MIN_JAX:
    raise RuntimeError("autodist_tpu requires jax >= %s, found %s"
                       % (".".join(map(str, _MIN_JAX)), _jax.__version__))

if not hasattr(_jax, "shard_map"):
    # graceful degradation on older JAX: releases before jax 0.6 ship
    # shard_map under jax.experimental with ``check_vma`` spelled
    # ``check_rep``. Alias the modern spelling so the framework (and user
    # code written against it) runs unchanged instead of dying with
    # AttributeError at the first step compile.
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def _shard_map_compat(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _legacy_shard_map(f, *args, **kwargs)

    _jax.shard_map = _shard_map_compat

from autodist_tpu import const  # noqa: E402
from autodist_tpu import patch as _patch  # noqa: E402

if const.ENV.ADT_PATCH_OPTAX.val:
    _patch.patch_optax()  # reference patches optimizers at import (__init__.py:50)

from autodist_tpu.autodist import AutoDist, get_default_autodist, reset  # noqa: E402
from autodist_tpu.model_item import ModelItem  # noqa: E402
from autodist_tpu.resource_spec import ResourceSpec  # noqa: E402
from autodist_tpu.runtime.sentinel import (SentinelPolicy,  # noqa: E402
                                           TrainingDiverged)
from autodist_tpu.train_state import TrainState  # noqa: E402
from autodist_tpu import strategy  # noqa: E402

ENV = const.ENV

__all__ = ["AutoDist", "ModelItem", "ResourceSpec", "TrainState", "strategy",
           "SentinelPolicy", "TrainingDiverged",
           "ENV", "get_default_autodist", "reset", "__version__"]
