"""Distributed train state.

The runtime analog of the reference's transformed-graph variables: params and
optimizer state live on the mesh in their strategy-assigned storage layout
(replicated, or shard-per-device for partitioned variables), plus
``sync_state`` carrying stateful gradient-compressor residuals (error
feedback, PowerSGD factors — per-device, stored with a leading device axis).
"""
from typing import Any

import flax.struct


@flax.struct.dataclass
class TrainState:
    step: Any
    params: Any
    opt_state: Any
    sync_state: Any
