"""DLRM — deep learning recommendation model (large-embedding flagship).

The BASELINE target config the reference's benchmark suite pointed at
("DLRM/Wide&Deep large-embedding recommender (auto-strategy)"): dense
features through a bottom MLP, many per-feature embedding tables, explicit
pairwise dot-product feature interactions, and a top MLP over
[bottom output, interactions] (arXiv 1906.00091). The tables are the
sparse/PS stress case at its most extreme — total embedding bytes dwarf
the dense parameters by orders of magnitude, which is exactly the regime
``AutoStrategy``'s cost model routes to load-balanced / partitioned PS
with the (ids, values) sparse wire, while the small dense MLPs ride
AllReduce (the Parallax split, chosen automatically).

Every lookup goes through ``SparseEmbed`` so gradients synchronize
batch-sized; interactions are one batched matmul (MXU-friendly), not the
per-pair gathers of the original CUDA implementation.
"""
import dataclasses
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.layers import SparseEmbed


@dataclasses.dataclass
class DLRMConfig:
    # vocab size per sparse feature (ml/criteo-style: wildly uneven)
    table_sizes: Tuple[int, ...] = (1_000_000, 500_000, 100_000, 10_000,
                                    10_000, 1_000, 1_000, 100)
    embed_dim: int = 64
    num_dense: int = 13
    bottom_mlp: Tuple[int, ...] = (512, 256, 64)
    top_mlp: Tuple[int, ...] = (512, 256)
    # Wide&Deep (arXiv 1606.07792): add a linear "wide" term — a 1-dim
    # embedding per sparse feature plus a linear map over the dense
    # features — to the deep tower's logit
    wide: bool = False
    dtype: Any = jnp.float32

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("table_sizes", (64, 48, 32, 16))
        kw.setdefault("embed_dim", 8)
        kw.setdefault("num_dense", 4)
        kw.setdefault("bottom_mlp", (16, 8))
        kw.setdefault("top_mlp", (16,))
        return cls(**kw)

    def __post_init__(self):
        if self.bottom_mlp[-1] != self.embed_dim:
            raise ValueError(
                "bottom_mlp must end at embed_dim (%d != %d): the bottom "
                "output joins the embeddings in the interaction"
                % (self.bottom_mlp[-1], self.embed_dim))


class DLRM(nn.Module):
    config: DLRMConfig

    @nn.compact
    def __call__(self, dense, sparse_ids):
        """dense [B, num_dense] float; sparse_ids [B, num_tables] int."""
        cfg = self.config
        x = dense.astype(cfg.dtype)
        for i, width in enumerate(cfg.bottom_mlp):
            x = nn.relu(nn.Dense(width, dtype=cfg.dtype,
                                 name="bottom_%d" % i)(x))
        embs = [SparseEmbed(size, cfg.embed_dim, dtype=cfg.dtype,
                            name="table_%d" % t)(sparse_ids[:, t])
                for t, size in enumerate(cfg.table_sizes)]
        # explicit pairwise dot interactions: one batched matmul over the
        # stacked feature vectors, lower triangle (excluding self-pairs)
        z = jnp.stack([x] + embs, axis=1)           # [B, F, d]
        inter = jnp.einsum("bfd,bgd->bfg", z, z)    # [B, F, F]
        f = z.shape[1]
        li, lj = jnp.tril_indices(f, k=-1)
        inter = inter[:, li, lj]                    # [B, F*(F-1)/2]
        h = jnp.concatenate([x, inter.astype(cfg.dtype)], axis=-1)
        for i, width in enumerate(cfg.top_mlp):
            h = nn.relu(nn.Dense(width, dtype=cfg.dtype,
                                 name="top_%d" % i)(h))
        logit = nn.Dense(1, dtype=jnp.float32, name="click")(h)[..., 0]
        if cfg.wide:
            # the wide linear term: memorization over raw ids + dense
            for t, size in enumerate(cfg.table_sizes):
                logit = logit + SparseEmbed(
                    size, 1, dtype=jnp.float32,
                    name="wide_table_%d" % t)(sparse_ids[:, t])[..., 0]
            # no bias: the click head's bias already covers the additive
            # scalar degree of freedom
            logit = logit + nn.Dense(
                1, use_bias=False, dtype=jnp.float32, name="wide_dense")(
                dense.astype(jnp.float32))[..., 0]
        return logit


def make_train_setup(config: Optional[DLRMConfig] = None,
                     batch_size: int = 256, seed: int = 0,
                     hot_fraction: float = 0.05):
    """(loss_fn, params, example_batch, apply_fn) — click-through binary
    objective. Synthetic ids are power-law-ish (a ``hot_fraction`` of each
    vocabulary receives most lookups), matching real CTR id skew — the
    distribution PS load balancing and the sparse wire actually face."""
    cfg = config or DLRMConfig()
    model = DLRM(cfg)
    rng = jax.random.PRNGKey(seed)
    d0 = jnp.zeros((1, cfg.num_dense), jnp.float32)
    s0 = jnp.zeros((1, len(cfg.table_sizes)), jnp.int32)
    variables = jax.jit(model.init)(rng, d0, s0)  # one dispatch, not one per initializer

    def loss_fn(params, batch):
        logits = model.apply(params, batch["dense"], batch["sparse"])
        labels = batch["label"].astype(jnp.float32)
        loss = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
            jnp.exp(-jnp.abs(logits)))
        return jnp.mean(loss)

    npr = np.random.RandomState(seed)
    sparse = np.stack(
        [np.where(npr.rand(batch_size) < 0.8,
                  npr.randint(0, max(1, int(size * hot_fraction)),
                              batch_size),
                  npr.randint(0, size, batch_size))
         for size in cfg.table_sizes], axis=1).astype(np.int32)
    example_batch = {
        "dense": npr.randn(batch_size, cfg.num_dense).astype(np.float32),
        "sparse": sparse,
        "label": npr.randint(0, 2, (batch_size,)).astype(np.int32),
    }
    apply_fn = lambda p, d, s: model.apply(p, d, s)  # noqa: E731
    return loss_fn, dict(variables), example_batch, apply_fn
