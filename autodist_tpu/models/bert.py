"""BERT encoder + masked-LM pretraining.

TPU-native counterpart of the reference's BERT benchmark
(``examples/benchmark/bert.py`` + vendored ``utils/bert_*``). From-scratch
flax implementation: word/position/type embeddings, N transformer blocks,
MLM head with tied embeddings. The embedding table is gather-indexed, so
``ModelItem`` marks it sparse and Parallax routes it to load-balanced PS —
the same hybrid the reference benchmarks BERT with.
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.layers import TransformerBlock, SparseEmbed


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dtype: Any = jnp.float32

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def large(cls, **kw):
        return cls(hidden_size=1024, num_layers=24, num_heads=16,
                   mlp_dim=4096, **kw)

    @classmethod
    def tiny(cls, **kw):
        """Test-sized config."""
        return cls(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                   mlp_dim=64, max_position=64, **kw)


class BertEncoder(nn.Module):
    config: BertConfig
    attn_fn: Optional[Any] = None

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None,
                 deterministic=True):
        cfg = self.config
        seq_len = input_ids.shape[-1]
        # SparseEmbed: MLM output is untied, so gradients for these
        # tables can ride the sparse (ids, values) wire; the small
        # position/type tables are auto-kept dense by the cost gate
        x = SparseEmbed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                        name="word_embeddings")(input_ids)
        pos = jnp.arange(seq_len)[None]
        x = x + SparseEmbed(cfg.max_position, cfg.hidden_size,
                            dtype=cfg.dtype,
                            name="position_embeddings")(pos)
        if token_type_ids is not None:
            x = x + SparseEmbed(cfg.type_vocab_size, cfg.hidden_size,
                                dtype=cfg.dtype,
                                name="token_type_embeddings")(token_type_ids)
        x = nn.LayerNorm(dtype=cfg.dtype, name="embeddings_ln")(x)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(jnp.bool_)
        for i in range(cfg.num_layers):
            x = TransformerBlock(cfg.num_heads,
                                 cfg.hidden_size // cfg.num_heads,
                                 cfg.mlp_dim, dtype=cfg.dtype,
                                 attn_fn=self.attn_fn,
                                 name="layer_%d" % i)(x, mask, deterministic)
        return x


class BertForMLM(nn.Module):
    config: BertConfig
    attn_fn: Optional[Any] = None

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None):
        cfg = self.config
        encoder = BertEncoder(cfg, attn_fn=self.attn_fn, name="encoder")
        x = encoder(input_ids, token_type_ids, attention_mask)
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_transform")(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(dtype=cfg.dtype, name="mlm_ln")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                          name="mlm_output")(x)
        return logits


def make_train_setup(config: Optional[BertConfig] = None, seq_len: int = 128,
                     batch_size: int = 32, seed: int = 0,
                     attention: str = "auto"):
    """(loss_fn, params, example_batch, apply_fn) — masked-LM objective.

    ``attention``: "xla" (fused XLA attention), "flash" (the pallas kernel
    with the padding ``attention_mask`` as segment ids,
    ``ops/flash_attention.py``), or "auto" (default): XLA below 8192
    tokens, flash at or above. Measured on the v5e chip (BENCHMARKS.md):
    for masked bidirectional attention XLA is FASTER at every length that
    fits (~1.8x at 512-4096), but it materializes the [S, S] logits and
    fails to compile by seq 8192 at bert-base geometry — the flash
    kernel's O(S) memory is what extends BERT past that wall, so "auto"
    switches exactly where XLA stops being an option.
    """
    cfg = config or BertConfig.base()
    if attention == "auto":
        attention = "flash" if seq_len >= 8192 else "xla"
    attn_fn = None
    if attention == "flash":
        from autodist_tpu.ops.flash_attention import make_flash_attn_fn
        attn_fn = make_flash_attn_fn(causal=False)
    elif attention != "xla":
        raise ValueError("attention must be 'auto', 'flash' or 'xla'")
    model = BertForMLM(cfg, attn_fn=attn_fn)
    rng = jax.random.PRNGKey(seed)
    ids0 = jnp.zeros((1, seq_len), jnp.int32)
    # jitted init: ONE device dispatch for the whole parameter tree
    # (eager flax init issues one RPC per initializer — minutes over a
    # high-latency host<->device link)
    variables = jax.jit(model.init)(rng, ids0, ids0,
                                    jnp.ones((1, seq_len), jnp.int32))

    def loss_fn(params, batch):
        logits = model.apply(params, batch["input_ids"],
                             batch["token_type_ids"], batch["attention_mask"])
        logp = jax.nn.log_softmax(logits)
        # gather, not one_hot: a [tokens, vocab] one-hot would double the
        # biggest tensor in the program for the same math
        per_tok = -jnp.take_along_axis(
            logp, batch["labels"][..., None], axis=-1)[..., 0]
        weights = batch["mlm_weights"].astype(per_tok.dtype)
        return jnp.sum(per_tok * weights) / jnp.maximum(jnp.sum(weights), 1.0)

    npr = np.random.RandomState(seed)
    example_batch = {
        "input_ids": npr.randint(0, cfg.vocab_size, (batch_size, seq_len)).astype(np.int32),
        "token_type_ids": np.zeros((batch_size, seq_len), np.int32),
        "attention_mask": np.ones((batch_size, seq_len), np.int32),
        "labels": npr.randint(0, cfg.vocab_size, (batch_size, seq_len)).astype(np.int32),
        "mlm_weights": (npr.rand(batch_size, seq_len) < 0.15).astype(np.float32),
    }
    apply_fn = lambda p, ids: model.apply(p, ids)  # noqa: E731
    return loss_fn, dict(variables), example_batch, apply_fn
