"""Decoder-only language model (the lm1b benchmark family).

TPU-native counterpart of the reference's 1B-word LM example
(``examples/lm1b/language_model.py`` — an LSTM with sampled softmax, metric
words/sec ``lm1b_train.py:62-75``). Re-designed transformer-first for TPU —
LSTMs serialize on the sequence axis and starve the MXU; a causal
transformer with ``lax``-friendly static shapes is the idiomatic
equivalent at the same objective (next-word prediction on lm1b). The token
embedding and the lm_head are deliberately UNTIED so the big table can
ride the sparse (ids, values) gradient wire (``models/layers.SparseEmbed``
— a tied table would need dense gradients and is auto-kept dense). The big
embedding table is the PartitionedPS stress case, as in the reference
benchmark.
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.layers import TransformerBlock, causal_mask, SparseEmbed


@dataclasses.dataclass
class LMConfig:
    vocab_size: int = 32000
    d_model: int = 512
    num_layers: int = 6
    num_heads: int = 8
    mlp_dim: int = 2048
    max_seq_len: int = 256
    dtype: Any = jnp.float32

    @classmethod
    def lm1b(cls, **kw):
        return cls(vocab_size=793470 // 8, d_model=1024, num_layers=8,
                   num_heads=16, mlp_dim=4096, **kw)

    @classmethod
    def tiny(cls, **kw):
        return cls(vocab_size=128, d_model=32, num_layers=2, num_heads=2,
                   mlp_dim=64, max_seq_len=64, **kw)


class TransformerLM(nn.Module):
    config: LMConfig
    attn_fn: Optional[Any] = None
    seq_parallel: bool = False  # offset positions by the seq-shard index
    decode_attn: str = "reference"  # decode inner loop: "reference"|"flash"

    @nn.compact
    def hidden(self, input_ids):
        """Final-layer-norm hidden states [B, S, d] — the lean-head loss
        applies the lm_head itself through ``ops.xent`` so the [N, vocab]
        logits tensor never materializes."""
        cfg = self.config
        seq_len = input_ids.shape[-1]  # LOCAL length under seq sharding
        # untied lm_head -> the token table can ride the sparse wire
        x = SparseEmbed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                        name="embed")(input_ids)
        x = x * np.sqrt(cfg.d_model)
        positions = jnp.arange(seq_len)
        if self.seq_parallel:
            from autodist_tpu.parallel import sequence
            positions = positions + sequence.position_offset(seq_len)
        pos = SparseEmbed(cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype,
                          name="pos_embed")(positions[None])
        x = x + pos
        # with an injected SP attention the causal structure is handled
        # inside the op; the local mask would be wrong and is skipped
        mask = None if self.attn_fn is not None else causal_mask(seq_len)
        for i in range(cfg.num_layers):
            x = TransformerBlock(cfg.num_heads, cfg.d_model // cfg.num_heads,
                                 cfg.mlp_dim, dtype=cfg.dtype,
                                 attn_fn=self.attn_fn,
                                 name="layer_%d" % i)(x, mask)
        return nn.LayerNorm(dtype=cfg.dtype, name="final_ln")(x)

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        x = self.hidden(input_ids)
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="lm_head")(x)
        return logits

    @nn.compact
    def prefill(self, input_ids, length):
        """Prompt pass seeding a decode KV cache (continuous batching,
        ``serving/decode.py``): ``input_ids`` [B, P] right-padded
        prompts, ``length`` [B] real prompt lengths. Returns the
        last-real-position logits [B, vocab] plus per-layer K/V caches
        [B, layers, max_seq_len, heads, head_dim]. Causality makes the
        padding harmless: position ``length-1`` attends only real
        tokens, and the garbage rows past ``length`` sit above the
        decode cursor, so :func:`ops.attention.cached_attention` never
        reads them before a decode step overwrites them. Submodules are
        created in exactly :meth:`hidden`'s order so the training
        parameters resolve unchanged."""
        cfg = self.config
        seq_len = input_ids.shape[-1]
        x = SparseEmbed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                        name="embed")(input_ids)
        x = x * np.sqrt(cfg.d_model)
        positions = jnp.arange(seq_len)
        pos = SparseEmbed(cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype,
                          name="pos_embed")(positions[None])
        x = x + pos
        mask = None if self.attn_fn is not None else causal_mask(seq_len)
        ks, vs = [], []
        for i in range(cfg.num_layers):
            x, (k, v) = TransformerBlock(
                cfg.num_heads, cfg.d_model // cfg.num_heads, cfg.mlp_dim,
                dtype=cfg.dtype, attn_fn=self.attn_fn,
                decode_attn=self.decode_attn,
                name="layer_%d" % i)(x, mask, return_kv=True)
            pad = [(0, 0), (0, cfg.max_seq_len - seq_len), (0, 0), (0, 0)]
            ks.append(jnp.pad(k, pad))
            vs.append(jnp.pad(v, pad))
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_ln")(x)
        idx = jnp.clip(length - 1, 0, seq_len - 1)
        last = jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                          name="lm_head")(last)
        return logits, jnp.stack(ks, axis=1), jnp.stack(vs, axis=1)

    @nn.compact
    def decode_step(self, token_ids, k_cache, v_cache, cursor, alive=None):
        """One cached decode step: ``token_ids`` [B] current tokens,
        caches [B, layers, max_seq_len, heads, head_dim], ``cursor`` [B]
        the row each token writes (== tokens already cached), ``alive``
        [B] bool gating cache writes for dead slots. Returns next-token
        logits [B, vocab] and the updated caches. Fixed shapes for any
        slot occupancy — the zero-recompile decode contract."""
        cfg = self.config
        x = SparseEmbed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                        name="embed")(token_ids[:, None])
        x = x * np.sqrt(cfg.d_model)
        pos_idx = jnp.clip(cursor, 0, cfg.max_seq_len - 1)
        pos = SparseEmbed(cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype,
                          name="pos_embed")(pos_idx[:, None])
        x = x + pos
        new_ks, new_vs = [], []
        for i in range(cfg.num_layers):
            x, (k, v) = TransformerBlock(
                cfg.num_heads, cfg.d_model // cfg.num_heads, cfg.mlp_dim,
                dtype=cfg.dtype, attn_fn=None,
                decode_attn=self.decode_attn,
                name="layer_%d" % i)(
                x, cache=(k_cache[:, i], v_cache[:, i]),
                cursor=cursor, alive=alive)
            new_ks.append(k)
            new_vs.append(v)
        x = nn.LayerNorm(dtype=cfg.dtype, name="final_ln")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=jnp.float32,
                          name="lm_head")(x[:, 0])
        return (logits, jnp.stack(new_ks, axis=1),
                jnp.stack(new_vs, axis=1))


def make_train_setup(config: Optional[LMConfig] = None, seq_len: int = 128,
                     batch_size: int = 32, seed: int = 0,
                     attention: str = "auto", lean_head="auto"):
    """``attention``: "auto" (XLA softmax attention below seq 8192, the
    pallas flash kernel at/above it on TPU — the measured crossover:
    XLA is ~20% faster at seq 256 but falls over the [S, S] logits HBM
    wall at 8192, where flash is 4.4x and O(seq) memory), "flash"
    (force the kernel; interpreted off-TPU), or "default" (XLA always).

    ``lean_head``: True routes the loss through the chunked cross-entropy
    (``ops.xent.chunked_softmax_xent``) — the [tokens, vocab] fp32 logits
    tensor (3.25 GB for lm1b at batch 32) never materializes, which is
    what lets lm1b train at batch 64 on a 16 GB chip. "auto" (default)
    engages it at vocab >= 32768. Same math to float tolerance."""
    cfg = config or LMConfig()
    if lean_head == "auto":
        lean_head = cfg.vocab_size >= 32768
    elif not isinstance(lean_head, bool):
        raise ValueError("lean_head must be True, False or 'auto', got %r"
                         % (lean_head,))
    if seq_len > cfg.max_seq_len:
        # out-of-range position lookups would silently NaN (jnp.take fills)
        raise ValueError("seq_len %d exceeds config.max_seq_len %d"
                         % (seq_len, cfg.max_seq_len))
    attn_fn = None
    # "auto" matches the measured crossover (BENCHMARKS.md, same policy
    # as models/bert.py): XLA's fused softmax attention is FASTER below
    # seq 8192 (order-alternated on-chip pairs at lm1b seq 256 read
    # ~290 vs ~244 seq/s) and only falls over the [S, S] logits HBM wall
    # at/above it, where the flash kernel's O(S) memory keeps running.
    if attention == "flash" or (attention == "auto"
                                and jax.default_backend() == "tpu"
                                and seq_len >= 8192):
        from autodist_tpu.ops.flash_attention import make_flash_attn_fn
        attn_fn = make_flash_attn_fn(causal=True)
    elif attention not in ("auto", "flash", "default"):
        raise ValueError("attention must be auto|flash|default, got %r"
                         % attention)
    model = TransformerLM(cfg, attn_fn=attn_fn)
    rng = jax.random.PRNGKey(seed)
    variables = jax.jit(model.init)(rng, jnp.zeros((1, seq_len), jnp.int32))

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        targets = tokens[:, 1:]
        if lean_head:
            from autodist_tpu.ops.xent import chunked_softmax_xent
            h = model.apply(params, tokens[:, :-1],
                            method=TransformerLM.hidden)
            head = params["params"]["lm_head"]
            nll = chunked_softmax_xent(
                h.reshape(-1, cfg.d_model),
                head["kernel"].astype(jnp.float32),
                head["bias"].astype(jnp.float32),
                targets.reshape(-1))
            return jnp.mean(nll)
        logits = model.apply(params, tokens[:, :-1])
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    npr = np.random.RandomState(seed)
    example_batch = {"tokens": npr.randint(
        0, cfg.vocab_size, (batch_size, seq_len + 1)).astype(np.int32)}
    apply_fn = lambda p, ids: model.apply(p, ids)  # noqa: E731
    return loss_fn, dict(variables), example_batch, apply_fn


def make_decode_setup(config: Optional[LMConfig] = None,
                      decode_attn: str = "reference",
                      return_logits: bool = False):
    """Continuous-batching decode functions over a trained TransformerLM
    (``serving/decode.py`` DecodeEngine). Returns a
    :class:`~autodist_tpu.serving.decode.DecodeSetup` whose parameters
    resolve against the same variables :func:`make_train_setup` trains.

    ``decode_attn="flash"`` routes the decode inner loop through the
    pallas flash kernel (``ops.attention.flash_cached_attention``);
    greedy argmax sampling runs in-graph so the per-step D2H is one
    int32 per slot. ``return_logits`` adds the full [slots, vocab]
    logits to the step fetches (parity tests; costs a vocab-sized D2H
    per step, keep it off in production)."""
    from autodist_tpu.serving.decode import DecodeSetup

    cfg = config or LMConfig()
    model = TransformerLM(cfg, decode_attn=decode_attn)
    head_dim = cfg.d_model // cfg.num_heads

    def prefill_fn(params, batch):
        logits, k, v = model.apply(params, batch["tokens"], batch["length"],
                                   method=TransformerLM.prefill)
        return {"next_token": jnp.argmax(logits, axis=-1).astype(jnp.int32),
                "k": k, "v": v}

    def decode_fn(params, dstate):
        logits, k, v = model.apply(
            params, dstate["token"], dstate["k"], dstate["v"],
            dstate["cursor"], dstate["alive"],
            method=TransformerLM.decode_step)
        out = {"k": k, "v": v,
               "next_token": jnp.argmax(logits, axis=-1).astype(jnp.int32)}
        if return_logits:
            out["logits"] = logits
        return out

    def init_dstate(slots: int):
        cache_shape = (slots, cfg.num_layers, cfg.max_seq_len,
                       cfg.num_heads, head_dim)
        cache_dtype = np.dtype(jnp.dtype(cfg.dtype).name)
        return {"k": np.zeros(cache_shape, cache_dtype),
                "v": np.zeros(cache_shape, cache_dtype),
                "token": np.zeros((slots,), np.int32),
                "cursor": np.zeros((slots,), np.int32),
                "alive": np.zeros((slots,), np.bool_)}

    return DecodeSetup(prefill_fn=prefill_fn, decode_fn=decode_fn,
                       init_dstate=init_dstate, max_len=cfg.max_seq_len,
                       vocab_size=cfg.vocab_size)


def make_sp_train_setup(config: Optional[LMConfig] = None, seq_len: int = 128,
                        batch_size: int = 32, seed: int = 0,
                        attention: str = "ring"):
    """Sequence-parallel train setup: tokens arrive [B, S] with S sharded
    over the ``seq`` mesh axis; attention runs ring/Ulysses; next-token
    targets cross shard boundaries via ``sequence.shift_left``; the final
    global position is masked out with an SP-exact weighted mean."""
    from autodist_tpu import const
    from autodist_tpu.ops.attention import make_attn_fn
    from autodist_tpu.parallel import sequence

    cfg = config or LMConfig()
    if seq_len > cfg.max_seq_len:
        raise ValueError("seq_len %d exceeds config.max_seq_len %d"
                         % (seq_len, cfg.max_seq_len))
    attn_fn = make_attn_fn(attention, const.SEQUENCE_AXIS, causal=True)
    model = TransformerLM(cfg, attn_fn=None, seq_parallel=True)  # init w/o axis
    rng = jax.random.PRNGKey(seed)
    variables = jax.jit(model.init)(rng, jnp.zeros((1, seq_len), jnp.int32))
    sp_model = TransformerLM(cfg, attn_fn=attn_fn, seq_parallel=True)

    def loss_fn(params, batch):
        tokens = batch["tokens"]          # local chunk [B, C]
        local_len = tokens.shape[1]
        logits = sp_model.apply(params, tokens)
        targets = sequence.shift_left(tokens, const.SEQUENCE_AXIS, axis=1)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        # mask the final GLOBAL position (its target wrapped around)
        pos = jnp.arange(local_len) + sequence.position_offset(
            local_len, const.SEQUENCE_AXIS)
        total_len = local_len * sequence.axis_size(const.SEQUENCE_AXIS)
        weights = (pos < total_len - 1).astype(nll.dtype)[None, :]
        weights = jnp.broadcast_to(weights, nll.shape)
        return sequence.global_weighted_mean(nll, weights, const.SEQUENCE_AXIS)

    npr = np.random.RandomState(seed)
    example_batch = {"tokens": npr.randint(
        0, cfg.vocab_size, (batch_size, seq_len)).astype(np.int32)}
    apply_fn = lambda p, ids: model.apply(p, ids)  # noqa: E731
    return loss_fn, dict(variables), example_batch, apply_fn
