"""Tensor-parallel decoder-only transformer LM (the flagship model).

A functional (pure-pytree) causal transformer whose every op is
shape-polymorphic: the SAME code runs full-size on one device and sharded
inside the lowering's shard_map, consuming whatever parameter shards the
strategy assigned. Model parallelism follows Megatron (arXiv 1909.08053),
built from ``parallel/tensor.py`` primitives:

- attention QKV: column-parallel (heads sharded over ``model``), out-proj
  row-parallel (one psum);
- MLP: up-proj column-parallel, down-proj row-parallel (one psum);
- embedding: vocab-parallel, tied with the output head
  (``vocab_parallel_logits`` + ``vocab_parallel_xent``).

Composes with sequence parallelism: pass ``attention='ring'|'ulysses'`` and
the seq-sharded batch attends globally (``ops/attention.py``) while heads
stay model-sharded — the TP x SP composition the reference never had
(reference is data-parallel only, ``docs/design/architecture.rst:46-48``).

``tp_rules()`` exports the regex -> {dim: mesh-axis} map the
``TensorParallel`` strategy builder uses to shard storage.
"""
import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu import const
from autodist_tpu.parallel import sequence, tensor


@dataclasses.dataclass
class TPLMConfig:
    vocab_size: int = 32000
    d_model: int = 512
    num_layers: int = 6
    num_heads: int = 8
    mlp_dim: int = 2048
    max_seq_len: int = 256
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 64)
        kw.setdefault("d_model", 32)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("mlp_dim", 64)
        kw.setdefault("max_seq_len", 64)
        return cls(**kw)

    @classmethod
    def flagship(cls, **kw):
        """GPT-2-medium-ish: the benchmark configuration."""
        kw.setdefault("vocab_size", 32768)
        kw.setdefault("d_model", 1024)
        kw.setdefault("num_layers", 12)
        kw.setdefault("num_heads", 16)
        kw.setdefault("mlp_dim", 4096)
        kw.setdefault("max_seq_len", 1024)
        kw.setdefault("dtype", jnp.bfloat16)
        return cls(**kw)


def init_params(cfg: TPLMConfig, seed: int = 0) -> Dict:
    """Full (unsharded) parameter pytree; the strategy shards storage."""
    rng = np.random.RandomState(seed)
    d, h, hd, f = cfg.d_model, cfg.num_heads, cfg.head_dim, cfg.mlp_dim

    def normal(*shape, scale):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    params = {
        "embed": normal(cfg.vocab_size, d, scale=0.02),
        "pos_embed": normal(cfg.max_seq_len, d, scale=0.02),
        "final_ln": {"scale": np.ones((d,), np.float32),
                     "bias": np.zeros((d,), np.float32)},
    }
    for i in range(cfg.num_layers):
        params["layer_%d" % i] = {
            "ln1": {"scale": np.ones((d,), np.float32),
                    "bias": np.zeros((d,), np.float32)},
            "attn": {
                "wq": normal(d, h, hd, scale=0.02),
                "wk": normal(d, h, hd, scale=0.02),
                "wv": normal(d, h, hd, scale=0.02),
                "wo": normal(h, hd, d, scale=0.02 / np.sqrt(2 * cfg.num_layers)),
                "bo": np.zeros((d,), np.float32),
            },
            "ln2": {"scale": np.ones((d,), np.float32),
                    "bias": np.zeros((d,), np.float32)},
            "mlp": {
                "w1": normal(d, f, scale=0.02),
                "b1": np.zeros((f,), np.float32),
                "w2": normal(f, d, scale=0.02 / np.sqrt(2 * cfg.num_layers)),
                "b2": np.zeros((d,), np.float32),
            },
        }
    return params


def tp_rules(model_axis: str = const.MODEL_AXIS) -> List[Tuple[str, Dict[int, str]]]:
    """Regex -> {dim: mesh axis} storage-sharding rules for TensorParallel.

    QKV kernels shard dim 1 (heads); the out-projection and MLP down-proj
    shard their input dim (row-parallel); MLP up-proj + bias shard the hidden
    dim (column-parallel); the tied embedding shards the vocab dim.
    LayerNorms / pos_embed / biases-after-reduce stay replicated (no rule).
    """
    return [
        (r".*/attn/w[qkv]$", {1: model_axis}),
        (r".*/attn/wo$", {0: model_axis}),
        (r".*/mlp/w1$", {1: model_axis}),
        (r".*/mlp/b1$", {0: model_axis}),
        (r".*/mlp/w2$", {0: model_axis}),
        (r"^embed$", {0: model_axis}),
    ]


def _layer_norm(x, p, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _causal_attention(q, k, v):
    """Plain causal attention, [B, S, H_local, D] -> [B, S, H_local, D]."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = q.shape[1]
    mask = jnp.tril(jnp.ones((s, s), jnp.bool_))[None, None]
    logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    w = jax.nn.softmax(logits.astype(jnp.float32)).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def forward(params, input_ids, cfg: TPLMConfig,
            attn_fn=None, seq_parallel: bool = False,
            model_axis: str = const.MODEL_AXIS):
    """Logits over the (possibly vocab-sharded) vocabulary.

    ``attn_fn(q, k, v)`` overrides attention (ring/ulysses for SP; pallas
    flash for TPU); default is plain causal. ``input_ids`` is the LOCAL
    sequence chunk under SP.
    """
    dt = cfg.dtype
    seq_len = input_ids.shape[-1]
    x = tensor.vocab_parallel_embed(params["embed"], input_ids, model_axis)
    x = (x * np.sqrt(cfg.d_model)).astype(dt)
    if seq_parallel:
        # each seq shard reads its own row range (offset is axis-dependent,
        # so this is a real gather); the named lookup keeps it on the
        # framework's sparse surface instead of tripping the dense-sync
        # warning — the cost gate then keeps it dense (all rows are read)
        from autodist_tpu.ops.embedding import embedding_lookup
        positions = jnp.arange(seq_len) + sequence.position_offset(seq_len)
        x = x + embedding_lookup(params["pos_embed"], positions,
                                 name="pos_embed").astype(dt)[None]
    else:
        # static slice, not a gather: every position row is used each step
        x = x + params["pos_embed"][:seq_len].astype(dt)[None]
    for i in range(cfg.num_layers):
        lp = params["layer_%d" % i]
        h = _layer_norm(x, lp["ln1"])
        q = tensor.column_parallel_dense(h, lp["attn"]["wq"].astype(dt))
        k = tensor.column_parallel_dense(h, lp["attn"]["wk"].astype(dt))
        v = tensor.column_parallel_dense(h, lp["attn"]["wv"].astype(dt))
        o = attn_fn(q, k, v) if attn_fn is not None else _causal_attention(q, k, v)
        o = tensor.row_parallel_dense(o, lp["attn"]["wo"].astype(dt),
                                      lp["attn"]["bo"].astype(dt),
                                      model_axis, contract_dims=2)
        x = x + o
        h = _layer_norm(x, lp["ln2"])
        h = tensor.column_parallel_dense(h, lp["mlp"]["w1"].astype(dt),
                                         lp["mlp"]["b1"].astype(dt))
        h = jax.nn.gelu(h)
        h = tensor.row_parallel_dense(h, lp["mlp"]["w2"].astype(dt),
                                      lp["mlp"]["b2"].astype(dt), model_axis)
        x = x + h
    x = _layer_norm(x, params["final_ln"])
    return tensor.vocab_parallel_logits(x, params["embed"].astype(dt))


def make_train_setup(cfg: Optional[TPLMConfig] = None, seq_len: int = 128,
                     batch_size: int = 8, seed: int = 0,
                     attention: Optional[str] = None,
                     model_axis: str = const.MODEL_AXIS):
    """(loss_fn, params, example_batch, apply_fn) for the AutoDist stack.

    ``attention``: None (plain causal) or 'ring'/'ulysses' for
    sequence-parallel runs — then tokens arrive seq-sharded, next-token
    targets cross shard boundaries, and the final global position is masked.
    """
    cfg = cfg or TPLMConfig()
    params = init_params(cfg, seed)
    seq_parallel = attention in ("ring", "ulysses")
    attn_fn = None
    if seq_parallel:
        from autodist_tpu.ops.attention import make_attn_fn
        sp_attn = make_attn_fn(attention, const.SEQUENCE_AXIS, causal=True)
        attn_fn = lambda q, k, v: sp_attn(q, k, v, None)  # noqa: E731

    def loss_fn(p, batch):
        tokens = batch["tokens"]
        if seq_parallel:
            logits = forward(p, tokens, cfg, attn_fn=attn_fn,
                             seq_parallel=True, model_axis=model_axis)
            targets = sequence.shift_left(tokens, const.SEQUENCE_AXIS, axis=1)
            nll = tensor.vocab_parallel_xent(logits, targets, model_axis)
            local_len = tokens.shape[1]
            pos = jnp.arange(local_len) + sequence.position_offset(local_len)
            total = local_len * sequence.axis_size(const.SEQUENCE_AXIS)
            w = jnp.broadcast_to(
                (pos < total - 1).astype(nll.dtype)[None, :], nll.shape)
            return sequence.global_weighted_mean(nll, w)
        logits = forward(p, tokens[:, :-1], cfg, model_axis=model_axis)
        nll = tensor.vocab_parallel_xent(logits, tokens[:, 1:], model_axis)
        return jnp.mean(nll)

    npr = np.random.RandomState(seed)
    extra = 0 if seq_parallel else 1
    example_batch = {"tokens": npr.randint(
        0, cfg.vocab_size, (batch_size, seq_len + extra)).astype(np.int32)}
    apply_fn = lambda p, ids: forward(p, ids, cfg, model_axis=model_axis)  # noqa: E731
    return loss_fn, params, example_batch, apply_fn
