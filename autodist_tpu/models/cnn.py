"""VGG16 / InceptionV3 / DenseNet121 — the rest of the reference's
ImageNet benchmark family.

The reference benchmarks four keras CNNs (``examples/benchmark/imagenet.py:
150-182``: resnet101, vgg16, inceptionv3, densenet121); ResNet lives in
``models/resnet.py``, these are the other three. Implemented from scratch
in flax: NHWC layout (TPU conv-native), bfloat16 compute with float32
params/batch-stats, static shapes. Each family ships a Tiny config so the
strategy/transform path is testable on CPU.
"""
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


def _norm(train: bool, name=None):
    return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                        dtype=jnp.float32, name=name)


# ---------------------------------------------------------------------- VGG


class VGG(nn.Module):
    """VGG with batch-norm (the reference's keras VGG16 analog; BN keeps
    bf16 training stable). The default ``flatten`` classifier keeps the
    giant 25088->4096 FC layers — ~102M of VGG16's ~138M params and the
    whole reason vgg16 stresses gradient sync (the reference tunes its
    all-reduce chunk_size down to 25 for it); ``classifier="gap"`` swaps in
    global average pooling for image-size-agnostic uses."""
    stage_sizes: Sequence[int] = (2, 2, 3, 3, 3)
    num_filters: Sequence[int] = (64, 128, 256, 512, 512)
    num_classes: int = 1000
    dense_width: int = 4096
    classifier: str = "flatten"  # "flatten" (reference head) | "gap"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for stage, (n, f) in enumerate(zip(self.stage_sizes, self.num_filters)):
            for _ in range(n):
                x = nn.Conv(f, (3, 3), padding="SAME", use_bias=False,
                            dtype=self.dtype)(x)
                x = _norm(train)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        if self.classifier == "flatten":
            x = x.reshape((x.shape[0], -1))
        else:
            x = jnp.mean(x, axis=(1, 2))
        x = nn.relu(nn.Dense(self.dense_width, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(self.dense_width, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


VGG16 = partial(VGG)
VGGTiny = partial(VGG, stage_sizes=(1, 1), num_filters=(8, 16), dense_width=32)


# ----------------------------------------------------------------- Inception


class ConvBN(nn.Module):
    filters: int
    kernel: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.filters, self.kernel, self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype)(x)
        return nn.relu(_norm(train)(x))


class InceptionBlock(nn.Module):
    """Mixed block: parallel 1x1 / 5x5 / double-3x3 / pool towers
    concatenated on channels (Szegedy et al. 2015, fig. 5-7 shapes)."""
    b1x1: int
    b5x5: Tuple[int, int]
    b3x3dbl: Tuple[int, int]
    pool: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(ConvBN, dtype=self.dtype)
        t1 = conv(self.b1x1, (1, 1))(x, train)
        t2 = conv(self.b5x5[0], (1, 1))(x, train)
        t2 = conv(self.b5x5[1], (5, 5))(t2, train)
        t3 = conv(self.b3x3dbl[0], (1, 1))(x, train)
        t3 = conv(self.b3x3dbl[1], (3, 3))(t3, train)
        t3 = conv(self.b3x3dbl[1], (3, 3))(t3, train)
        t4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        t4 = conv(self.pool, (1, 1))(t4, train)
        return jnp.concatenate([t1, t2, t3, t4], axis=-1)


class InceptionReduction(nn.Module):
    """Grid-size reduction block: strided 3x3 + double-3x3 + max-pool."""
    b3x3: int
    b3x3dbl: Tuple[int, int]
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(ConvBN, dtype=self.dtype)
        t1 = conv(self.b3x3, (3, 3), (2, 2), "VALID")(x, train)
        t2 = conv(self.b3x3dbl[0], (1, 1))(x, train)
        t2 = conv(self.b3x3dbl[1], (3, 3))(t2, train)
        t2 = conv(self.b3x3dbl[1], (3, 3), (2, 2), "VALID")(t2, train)
        t3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([t1, t2, t3], axis=-1)


class Inception(nn.Module):
    """InceptionV3-shaped network (stem + 3 stages of mixed blocks with two
    reductions). Channel counts follow the V3 paper's A/B/C stages; the
    width multiplier scales everything for the Tiny test config."""
    num_classes: int = 1000
    width: float = 1.0
    blocks_per_stage: Sequence[int] = (3, 4, 2)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        w = lambda f: max(8, int(f * self.width))  # noqa: E731
        conv = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem: 299x299 -> 35x35
        x = conv(w(32), (3, 3), (2, 2), "VALID")(x, train)
        x = conv(w(32), (3, 3), padding="VALID")(x, train)
        x = conv(w(64), (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(w(80), (1, 1))(x, train)
        x = conv(w(192), (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        blk = partial(InceptionBlock, dtype=self.dtype)
        for _ in range(self.blocks_per_stage[0]):
            x = blk(w(64), (w(48), w(64)), (w(64), w(96)), w(64))(x, train)
        x = InceptionReduction(w(384), (w(64), w(96)), dtype=self.dtype)(x, train)
        for _ in range(self.blocks_per_stage[1]):
            x = blk(w(192), (w(128), w(192)), (w(128), w(192)), w(192))(x, train)
        x = InceptionReduction(w(320), (w(192), w(192)), dtype=self.dtype)(x, train)
        for _ in range(self.blocks_per_stage[2]):
            x = blk(w(320), (w(384), w(384)), (w(448), w(384)), w(192))(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


InceptionV3 = partial(Inception)
InceptionTiny = partial(Inception, width=0.05, blocks_per_stage=(1, 1, 1))


# ------------------------------------------------------------------ DenseNet


class DenseLayer(nn.Module):
    growth_rate: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.relu(_norm(train)(x))
        y = nn.Conv(4 * self.growth_rate, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(_norm(train)(y))
        y = nn.Conv(self.growth_rate, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        return jnp.concatenate([x, y], axis=-1)


class DenseNet(nn.Module):
    """DenseNet (Huang et al. 2017): dense blocks with channel-concat
    growth, 0.5-compression transitions."""
    stage_sizes: Sequence[int] = (6, 12, 24, 16)  # DenseNet-121
    growth_rate: int = 32
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(2 * self.growth_rate, (7, 7), (2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(_norm(train)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n in enumerate(self.stage_sizes):
            for _ in range(n):
                x = DenseLayer(self.growth_rate, dtype=self.dtype)(x, train)
            if i != len(self.stage_sizes) - 1:
                x = nn.relu(_norm(train)(x))
                x = nn.Conv(x.shape[-1] // 2, (1, 1), use_bias=False,
                            dtype=self.dtype)(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(_norm(train)(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


DenseNet121 = partial(DenseNet)
DenseNetTiny = partial(DenseNet, stage_sizes=(2, 2), growth_rate=8)
