"""Model zoo — the reference's benchmark model families, TPU-first.

Every model exposes ``make_train_setup(...) -> (loss_fn, params,
example_batch, apply_fn)``, plugging directly into
``AutoDist.build(loss_fn, optimizer, params, example_batch)``.
"""
from autodist_tpu.models import bert, cnn, dlrm, lm, ncf, resnet  # noqa: F401

def _bert(cfg_ctor, **kw):
    cfg_kw = {k: kw.pop(k) for k in ("dtype",) if k in kw}
    return bert.make_train_setup(cfg_ctor(**cfg_kw), **kw)


REGISTRY = {
    "resnet18": lambda **kw: resnet.make_train_setup(resnet.ResNet18, **kw),
    "resnet50": lambda **kw: resnet.make_train_setup(resnet.ResNet50, **kw),
    "resnet101": lambda **kw: resnet.make_train_setup(resnet.ResNet101, **kw),
    "vgg16": lambda **kw: resnet.make_train_setup(cnn.VGG16, **kw),
    "inceptionv3": lambda **kw: resnet.make_train_setup(
        cnn.InceptionV3, **{"image_size": 299, **kw}),
    "densenet121": lambda **kw: resnet.make_train_setup(cnn.DenseNet121, **kw),
    "bert_base": lambda **kw: _bert(bert.BertConfig.base, **kw),
    "bert_large": lambda **kw: _bert(bert.BertConfig.large, **kw),
    "lm": lambda **kw: lm.make_train_setup(**kw),
    "ncf": lambda **kw: ncf.make_train_setup(**kw),
    "dlrm": lambda **kw: dlrm.make_train_setup(**kw),
}


def make_train_setup(name: str, **kw):
    if name not in REGISTRY:
        raise ValueError("unknown model %r (have %s)" % (name, sorted(REGISTRY)))
    return REGISTRY[name](**kw)
