"""Neural Collaborative Filtering (NeuMF) recommender.

TPU-native counterpart of the reference's NCF/MovieLens benchmark
(``examples/benchmark/ncf.py`` + ``utils/recommendation/``): GMF + MLP
towers over user/item embeddings with a binary logistic objective. The four
embedding tables are the sparse/PS stress case (Parallax routes them to
load-balanced PS; DLRM-style big-table configs stress PartitionedPS).
"""
import dataclasses
from typing import Any, Optional

import flax.linen as nn

from autodist_tpu.models.layers import SparseEmbed
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class NCFConfig:
    num_users: int = 138_000
    num_items: int = 27_000
    mf_dim: int = 64
    mlp_dims: tuple = (256, 128, 64)
    dtype: Any = jnp.float32

    @classmethod
    def tiny(cls, **kw):
        return cls(num_users=64, num_items=48, mf_dim=8, mlp_dims=(16, 8), **kw)


class NeuMF(nn.Module):
    config: NCFConfig

    @nn.compact
    def __call__(self, user_ids, item_ids):
        cfg = self.config
        # SparseEmbed: gradients for these tables synchronize as
        # (ids, values) pairs — the reference's IndexedSlices wire
        mf_u = SparseEmbed(cfg.num_users, cfg.mf_dim, dtype=cfg.dtype,
                           name="mf_user_embedding")(user_ids)
        mf_i = SparseEmbed(cfg.num_items, cfg.mf_dim, dtype=cfg.dtype,
                           name="mf_item_embedding")(item_ids)
        gmf = mf_u * mf_i
        mlp_dim0 = cfg.mlp_dims[0] // 2
        mlp_u = SparseEmbed(cfg.num_users, mlp_dim0, dtype=cfg.dtype,
                            name="mlp_user_embedding")(user_ids)
        mlp_i = SparseEmbed(cfg.num_items, mlp_dim0, dtype=cfg.dtype,
                            name="mlp_item_embedding")(item_ids)
        h = jnp.concatenate([mlp_u, mlp_i], axis=-1)
        for i, d in enumerate(cfg.mlp_dims[1:]):
            h = nn.relu(nn.Dense(d, dtype=cfg.dtype, name="mlp_%d" % i)(h))
        x = jnp.concatenate([gmf, h], axis=-1)
        return nn.Dense(1, dtype=jnp.float32, name="prediction")(x)[..., 0]


def make_train_setup(config: Optional[NCFConfig] = None, batch_size: int = 256,
                     seed: int = 0):
    cfg = config or NCFConfig()
    model = NeuMF(cfg)
    rng = jax.random.PRNGKey(seed)
    variables = jax.jit(model.init)(rng, jnp.zeros((1,), jnp.int32),
                           jnp.zeros((1,), jnp.int32))

    def loss_fn(params, batch):
        logits = model.apply(params, batch["user"], batch["item"])
        labels = batch["label"].astype(jnp.float32)
        # numerically-stable sigmoid cross-entropy
        loss = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
            jnp.exp(-jnp.abs(logits)))
        return jnp.mean(loss)

    npr = np.random.RandomState(seed)
    example_batch = {
        "user": npr.randint(0, cfg.num_users, (batch_size,)).astype(np.int32),
        "item": npr.randint(0, cfg.num_items, (batch_size,)).astype(np.int32),
        "label": npr.randint(0, 2, (batch_size,)).astype(np.int32),
    }
    apply_fn = lambda p, u, i: model.apply(p, u, i)  # noqa: E731
    return loss_fn, dict(variables), example_batch, apply_fn
