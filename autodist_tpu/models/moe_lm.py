"""Mixture-of-Experts transformer LM (expert-parallel flagship).

Transformer blocks whose feed-forward is a top-1-routed MoE
(``parallel/expert.py``): expert-stacked FFN weights shard over the
``expert`` mesh axis, tokens route with one all_to_all each way, and the
Switch load-balance auxiliary loss keeps routing even. Attention and
everything else stays dense — the standard Switch-Transformer shape
(arXiv 2101.03961). Expert parallelism is an axis the reference's
data-parallel-only strategy space never had
(reference ``docs/design/architecture.rst:46-48``).

The token embedding and the output head are UNTIED (as in ``models/lm.py``)
so the vocab-sized table can ride the sparse (ids, values) gradient wire
(``ops/embedding.embedding_lookup``) — a tied table has a dense gradient
path through the logits matmul and is auto-kept dense. Positions are read
with a static slice (every row is used each step; a gather would only
trip sparse detection for a table that is effectively dense).
"""
import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu import const
from autodist_tpu.models.tp_lm import _layer_norm, _causal_attention
from autodist_tpu.parallel import expert, tensor


@dataclasses.dataclass
class MoEConfig:
    vocab_size: int = 32000
    d_model: int = 512
    num_layers: int = 4
    num_heads: int = 8
    num_experts: int = 8
    expert_dim: int = 1024
    max_seq_len: int = 256
    capacity_factor: float = 2.0
    aux_coef: float = 0.01
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 64)
        kw.setdefault("d_model", 32)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("num_experts", 4)
        kw.setdefault("expert_dim", 64)
        kw.setdefault("max_seq_len", 64)
        return cls(**kw)


def init_params(cfg: MoEConfig, seed: int = 0) -> Dict:
    rng = np.random.RandomState(seed)
    d, h, hd = cfg.d_model, cfg.num_heads, cfg.head_dim
    E, f = cfg.num_experts, cfg.expert_dim

    def normal(*shape, scale):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    out_scale = 0.02 / np.sqrt(2 * cfg.num_layers)
    params = {
        "embed": normal(cfg.vocab_size, d, scale=0.02),
        "pos_embed": normal(cfg.max_seq_len, d, scale=0.02),
        "final_ln": {"scale": np.ones((d,), np.float32),
                     "bias": np.zeros((d,), np.float32)},
        # untied head (see module docstring): the token table stays
        # gather-only so its gradient can sync as (ids, values)
        "lm_head": normal(d, cfg.vocab_size, scale=0.02),
    }
    for i in range(cfg.num_layers):
        params["layer_%d" % i] = {
            "ln1": {"scale": np.ones((d,), np.float32),
                    "bias": np.zeros((d,), np.float32)},
            "attn": {"wq": normal(d, h, hd, scale=0.02),
                     "wk": normal(d, h, hd, scale=0.02),
                     "wv": normal(d, h, hd, scale=0.02),
                     "wo": normal(h, hd, d, scale=out_scale),
                     "bo": np.zeros((d,), np.float32)},
            "ln2": {"scale": np.ones((d,), np.float32),
                    "bias": np.zeros((d,), np.float32)},
            "moe": {"router": normal(d, E, scale=0.02),
                    "w1": normal(E, d, f, scale=0.02),
                    "b1": np.zeros((E, f), np.float32),
                    "w2": normal(E, f, d, scale=out_scale),
                    "b2": np.zeros((E, d), np.float32)},
        }
    return params


def ep_rules(expert_axis: str = const.EXPERT_AXIS) -> List[Tuple[str, Dict[int, str]]]:
    """Expert-stacked FFN weights shard dim 0 over the expert axis; the
    router (and everything else) stays replicated."""
    return [(r".*/moe/[wb][12]$", {0: expert_axis})]


def forward(params, input_ids, cfg: MoEConfig):
    """Logits plus the summed Switch aux loss across layers."""
    from autodist_tpu.ops.embedding import embedding_lookup
    dt = cfg.dtype
    seq_len = input_ids.shape[-1]
    x = embedding_lookup(params["embed"], input_ids, name="embed")
    x = (x * np.sqrt(cfg.d_model)).astype(dt)
    x = x + params["pos_embed"][:seq_len].astype(dt)[None]
    aux_total = jnp.zeros((), jnp.float32)
    for i in range(cfg.num_layers):
        lp = params["layer_%d" % i]
        h = _layer_norm(x, lp["ln1"])
        q = tensor.column_parallel_dense(h, lp["attn"]["wq"].astype(dt))
        k = tensor.column_parallel_dense(h, lp["attn"]["wk"].astype(dt))
        v = tensor.column_parallel_dense(h, lp["attn"]["wv"].astype(dt))
        o = _causal_attention(q, k, v)
        o = tensor.row_parallel_dense(o, lp["attn"]["wo"].astype(dt),
                                      lp["attn"]["bo"].astype(dt),
                                      contract_dims=2)
        x = x + o
        h = _layer_norm(x, lp["ln2"])
        moe_out, aux = expert.moe_ffn(
            h, lp["moe"]["router"], lp["moe"]["w1"], lp["moe"]["b1"],
            lp["moe"]["w2"], lp["moe"]["b2"],
            capacity_factor=cfg.capacity_factor, dtype=dt)
        aux_total = aux_total + aux
        x = x + moe_out
    x = _layer_norm(x, params["final_ln"])
    logits = jnp.tensordot(x, params["lm_head"].astype(dt),
                           axes=((x.ndim - 1,), (0,)))
    return logits, aux_total


def make_train_setup(cfg: Optional[MoEConfig] = None, seq_len: int = 128,
                     batch_size: int = 8, seed: int = 0,
                     aux_coef: Optional[float] = None):
    cfg = cfg or MoEConfig()
    coef = cfg.aux_coef if aux_coef is None else aux_coef
    params = init_params(cfg, seed)

    def loss_fn(p, batch):
        tokens = batch["tokens"]
        logits, aux = forward(p, tokens[:, :-1], cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, tokens[:, 1:][..., None], -1)[..., 0]
        return jnp.mean(nll) + coef * aux

    npr = np.random.RandomState(seed)
    example_batch = {"tokens": npr.randint(
        0, cfg.vocab_size, (batch_size, seq_len + 1)).astype(np.int32)}
    apply_fn = lambda p, ids: forward(p, ids, cfg)[0]  # noqa: E731
    return loss_fn, params, example_batch, apply_fn
