"""Pipeline-parallel (optionally x tensor-parallel) transformer LM.

The stacked-blocks variant of ``models/tp_lm.py``: all transformer blocks'
parameters carry a leading layer dim, sharded over the ``pipe`` mesh axis
(``mp_axes = {0: 'pipe'}``) and streamed with the GPipe schedule of
``parallel/pipeline.py``; head/hidden dims can simultaneously shard over the
``model`` axis with Megatron compute (``parallel/tensor.py``), giving
dp x pp x tp meshes — parallelism axes the reference never had
(reference ``docs/design/architecture.rst:46-48``). Embedding and the tied
output head run replicated on every pipe rank; the pipeline covers the
uniform-shape block stack.
"""
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu import const
from autodist_tpu.models.tp_lm import TPLMConfig, _layer_norm, _causal_attention
from autodist_tpu.parallel import pipeline, tensor


def init_params(cfg: TPLMConfig, seed: int = 0) -> Dict:
    """Full (unsharded) params with layer-stacked blocks."""
    rng = np.random.RandomState(seed)
    d, h, hd, f, L = (cfg.d_model, cfg.num_heads, cfg.head_dim, cfg.mlp_dim,
                      cfg.num_layers)

    def normal(*shape, scale):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    out_scale = 0.02 / np.sqrt(2 * L)
    return {
        "embed": normal(cfg.vocab_size, d, scale=0.02),
        "pos_embed": normal(cfg.max_seq_len, d, scale=0.02),
        "blocks": {
            "ln1": {"scale": np.ones((L, d), np.float32),
                    "bias": np.zeros((L, d), np.float32)},
            "attn": {"wq": normal(L, d, h, hd, scale=0.02),
                     "wk": normal(L, d, h, hd, scale=0.02),
                     "wv": normal(L, d, h, hd, scale=0.02),
                     "wo": normal(L, h, hd, d, scale=out_scale),
                     "bo": np.zeros((L, d), np.float32)},
            "ln2": {"scale": np.ones((L, d), np.float32),
                    "bias": np.zeros((L, d), np.float32)},
            "mlp": {"w1": normal(L, d, f, scale=0.02),
                    "b1": np.zeros((L, f), np.float32),
                    "w2": normal(L, f, d, scale=out_scale),
                    "b2": np.zeros((L, d), np.float32)},
        },
        "final_ln": {"scale": np.ones((d,), np.float32),
                     "bias": np.zeros((d,), np.float32)},
    }


def pp_rules(pipe_axis: str = const.PIPELINE_AXIS,
             model_axis: Optional[str] = None) -> List[Tuple[str, Dict[int, str]]]:
    """mp_axes rules: layer stack over ``pipe``; with ``model_axis`` set,
    heads/hidden additionally shard Megatron-style (dims shifted +1 for the
    stack dim vs. ``tp_lm.tp_rules``)."""
    if model_axis is None:
        return [(r"^blocks/", {0: pipe_axis})]
    return [
        (r"^blocks/attn/w[qkv]$", {0: pipe_axis, 2: model_axis}),
        (r"^blocks/attn/wo$", {0: pipe_axis, 1: model_axis}),
        (r"^blocks/mlp/w1$", {0: pipe_axis, 2: model_axis}),
        (r"^blocks/mlp/b1$", {0: pipe_axis, 1: model_axis}),
        (r"^blocks/mlp/w2$", {0: pipe_axis, 1: model_axis}),
        (r"^blocks/", {0: pipe_axis}),
        (r"^embed$", {0: model_axis}),
    ]


def _block(p, x, dt, model_axis):
    h = _layer_norm(x, p["ln1"])
    q = tensor.column_parallel_dense(h, p["attn"]["wq"].astype(dt))
    k = tensor.column_parallel_dense(h, p["attn"]["wk"].astype(dt))
    v = tensor.column_parallel_dense(h, p["attn"]["wv"].astype(dt))
    o = _causal_attention(q, k, v)
    o = tensor.row_parallel_dense(o, p["attn"]["wo"].astype(dt),
                                  p["attn"]["bo"].astype(dt),
                                  model_axis, contract_dims=2)
    x = x + o
    h = _layer_norm(x, p["ln2"])
    h = tensor.column_parallel_dense(h, p["mlp"]["w1"].astype(dt),
                                     p["mlp"]["b1"].astype(dt))
    h = jax.nn.gelu(h)
    h = tensor.row_parallel_dense(h, p["mlp"]["w2"].astype(dt),
                                  p["mlp"]["b2"].astype(dt), model_axis)
    return x + h


def forward(params, input_ids, cfg: TPLMConfig, n_microbatches: int = 1,
            pipe_axis: str = const.PIPELINE_AXIS,
            model_axis: str = const.MODEL_AXIS,
            virtual_stages: int = 1, pp_shards: int = 0,
            remat_chunks: bool = False):
    dt = cfg.dtype
    seq_len = input_ids.shape[-1]
    x = tensor.vocab_parallel_embed(params["embed"], input_ids, model_axis)
    x = (x * np.sqrt(cfg.d_model)).astype(dt)
    # static slice, not a gather: every position row is used each step, so
    # a sparse wire would be pure overhead and the gather only tripped
    # sparse detection ("sync DENSE" warnings) for a dense-use table
    x = x + params["pos_embed"][:seq_len].astype(dt)[None]

    def stage_fn(blocks_local, h):
        return pipeline.stacked_scan(
            lambda p, hh: _block(p, hh, dt, model_axis), blocks_local, h)

    if virtual_stages > 1:
        x = pipeline.pipeline_apply_interleaved(
            stage_fn, params["blocks"], x, n_microbatches, virtual_stages,
            pipe_axis, pp_shards_hint=pp_shards,
            remat_chunks=remat_chunks)
    else:
        x = pipeline.pipeline_apply(stage_fn, params["blocks"], x,
                                    n_microbatches, pipe_axis)
    x = _layer_norm(x, params["final_ln"])
    return tensor.vocab_parallel_logits(x, params["embed"].astype(dt))


def make_train_setup(cfg: Optional[TPLMConfig] = None, seq_len: int = 128,
                     batch_size: int = 8, seed: int = 0,
                     n_microbatches: int = 1,
                     model_axis: str = const.MODEL_AXIS,
                     schedule: str = "gpipe",
                     virtual_stages: int = 2, pp_shards: int = 0,
                     remat_chunks: bool = False):
    """``schedule="1f1b"`` trains through the fused 1F1B pipeline
    (``parallel/pipeline.pipeline_loss_1f1b``): the loss head moves
    INSIDE the pipelined region so backward microbatches interleave with
    forward ones, bounding activation residency at S microbatches
    instead of GPipe's M. Same math to float tolerance.

    ``schedule="interleaved"`` uses the virtual-stage schedule
    (``pipeline_apply_interleaved``): each rank runs ``virtual_stages``
    layer chunks, cutting the bubble fraction from (S-1)/M to
    (S-1)/(V*M); pass ``pp_shards`` so single-device traces emulate the
    same logical layer order (needed for exact reference comparisons)."""
    cfg = cfg or TPLMConfig()
    params = init_params(cfg, seed)
    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError("schedule must be 'gpipe', '1f1b' or 'interleaved'")
    if remat_chunks and schedule != "interleaved":
        # a silently-dropped memory flag is an OOM the user believes
        # they already fixed; per-chunk remat only exists on the
        # interleaved path (use WithRemat/graph_config.remat for the
        # whole-program trade on the other schedules)
        raise ValueError("remat_chunks=True requires "
                         "schedule='interleaved' (whole-program remat: "
                         "strategy.WithRemat)")
    if schedule == "interleaved" and pp_shards < 2:
        # without the stage count the single-device degenerate trace
        # CANNOT emulate the schedule-defined layer order (physical chunk
        # r*V+c = logical stage c*S+r) and would silently compute a
        # different network than the pipelined program
        raise ValueError("schedule='interleaved' requires pp_shards>=2 "
                         "(the intended pipeline stage count)")
    vstages = virtual_stages if schedule == "interleaved" else 1

    def loss_fn_gpipe(p, batch):
        tokens = batch["tokens"]
        logits = forward(p, tokens[:, :-1], cfg, n_microbatches,
                         model_axis=model_axis, virtual_stages=vstages,
                         pp_shards=pp_shards, remat_chunks=remat_chunks)
        nll = tensor.vocab_parallel_xent(logits, tokens[:, 1:], model_axis)
        return jnp.mean(nll)

    def loss_fn_1f1b(p, batch):
        dt = cfg.dtype
        tokens = batch["tokens"]
        ids = tokens[:, :-1]
        x = tensor.vocab_parallel_embed(p["embed"], ids, model_axis)
        x = (x * np.sqrt(cfg.d_model)).astype(dt)
        x = x + p["pos_embed"][:ids.shape[-1]].astype(dt)[None]

        def stage_fn(blocks_local, h):
            return pipeline.stacked_scan(
                lambda bp, hh: _block(bp, hh, dt, model_axis),
                blocks_local, h)

        def head_fn(hp, h, y):
            h = _layer_norm(h, hp["final_ln"])
            logits = tensor.vocab_parallel_logits(h, hp["embed"].astype(dt))
            return jnp.mean(tensor.vocab_parallel_xent(logits, y, model_axis))

        return pipeline.pipeline_loss_1f1b(
            stage_fn, head_fn, p["blocks"],
            {"final_ln": p["final_ln"], "embed": p["embed"]},
            x, tokens[:, 1:], n_microbatches)

    loss_fn = loss_fn_1f1b if schedule == "1f1b" else loss_fn_gpipe

    npr = np.random.RandomState(seed)
    example_batch = {"tokens": npr.randint(
        0, cfg.vocab_size, (batch_size, seq_len + 1)).astype(np.int32)}
    apply_fn = lambda p, ids: forward(p, ids, cfg, n_microbatches,  # noqa: E731
                                      model_axis=model_axis,
                                      virtual_stages=vstages,
                                      pp_shards=pp_shards,
                                      remat_chunks=remat_chunks)
    return loss_fn, params, example_batch, apply_fn
