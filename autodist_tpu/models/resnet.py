"""ResNet family (ResNet-18/50/101, plus the reference's benchmark CNNs).

TPU-native counterpart of the reference's ImageNet benchmark models
(``examples/benchmark/imagenet.py:150-182`` uses keras ResNet101/VGG16/
InceptionV3/DenseNet121). Implemented from scratch in flax: NHWC layout
(TPU conv-native), bfloat16 compute with float32 params/batch-stats, static
shapes throughout.
"""
from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(self.norm, use_running_average=not train,
                       momentum=0.9, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (3, 3), self.strides, padding="SAME")(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), self.strides,
                            name="conv_proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32
    norm: Callable = nn.BatchNorm

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(self.norm, use_running_average=not train,
                       momentum=0.9, dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), self.strides, padding="SAME")(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1), self.strides,
                            name="conv_proj")(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=jnp.float32, name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, strides,
                                   dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
# a tiny config for tests
ResNetTiny = partial(ResNet, stage_sizes=[1, 1], block_cls=BasicBlock,
                     num_filters=8)


def make_train_setup(model_cls=ResNet50, num_classes: int = 1000,
                     image_size: int = 224, batch_size: int = 64,
                     dtype=jnp.bfloat16, seed: int = 0):
    """(loss_fn, params, example_batch, apply_fn) for the framework's
    loss_fn capture mode. BatchNorm runs in inference mode inside the loss
    (statistics from params) so the captured program is a pure function; the
    training-statistics variant arrives with the mutable-state capture mode."""
    import jax
    import numpy as np
    model = model_cls(num_classes=num_classes, dtype=dtype)
    rng = jax.random.PRNGKey(seed)
    x0 = jnp.ones((1, image_size, image_size, 3), jnp.float32)
    variables = jax.jit(
        lambda r, x: model.init(r, x, train=False))(rng, x0)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["image"], train=False)
        one_hot = jax.nn.one_hot(batch["label"], num_classes)
        loss = -jnp.sum(one_hot * jax.nn.log_softmax(logits), axis=-1)
        return jnp.mean(loss)

    npr = np.random.RandomState(seed)
    example_batch = {
        "image": npr.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "label": npr.randint(0, num_classes, (batch_size,)).astype(np.int32),
    }
    apply_fn = lambda p, x: model.apply(p, x, train=False)  # noqa: E731
    return loss_fn, dict(variables), example_batch, apply_fn
