"""Shared model layers (attention, transformer blocks).

TPU-first building blocks for the model zoo: bfloat16-friendly, static
shapes, MXU-sized matmuls. Attention routes through
``autodist_tpu.ops.attention`` so sequence-parallel (ring) execution can be
swapped in by the strategy layer without touching model code.
"""
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

Dtype = Any


def causal_mask(seq_len: int) -> jnp.ndarray:
    return jnp.tril(jnp.ones((1, 1, seq_len, seq_len), jnp.bool_))


class SparseEmbed(nn.Module):
    """Embedding with the sparse-gradient wire identity.

    Drop-in for ``nn.Embed`` whose lookup routes through
    ``autodist_tpu.ops.embedding.embedding_lookup`` with the table's
    flattened parameter name, so the lowering can synchronize gradients as
    (ids, values) pairs instead of dense vocab-sized arrays (the
    reference's IndexedSlices path). Do NOT use for tied output embeddings
    — a table with other differentiable uses is auto-detected and kept
    dense, making the named lookup pointless there."""
    num_embeddings: int
    features: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, ids):
        from autodist_tpu.ops.embedding import embedding_lookup
        table = self.param(
            "embedding",
            nn.initializers.variance_scaling(1.0, "fan_in", "normal",
                                             out_axis=0),
            (self.num_embeddings, self.features), self.param_dtype)
        name = "/".join(("params",) + tuple(self.path) + ("embedding",))
        return embedding_lookup(table.astype(self.dtype), ids, name=name)


class MultiHeadAttention(nn.Module):
    """Standard MHA with an injectable attention implementation.

    Three modes share one parameter set (submodules are created in the
    same order on every path, so flax resolves identical names):

    - training/eval (default): full-sequence attention, optionally
      through ``attn_fn``;
    - prefill (``return_kv=True``): same, but also returns the projected
      ``(k, v)`` [B, S, H, D] so the caller can seed a decode cache;
    - decode (``cache=(k_cache, v_cache)`` + ``cursor``): x is [B, 1, d],
      the new K/V row is written at ``cursor`` (gated by ``alive`` so
      dead slots never mutate their cache) and attention runs against
      the live cache prefix via ``ops.attention.cached_attention`` (or
      the flash decode inner loop when ``decode_attn="flash"``).
    """
    num_heads: int
    head_dim: int
    dtype: Dtype = jnp.float32
    attn_fn: Optional[Callable] = None  # (q, k, v, mask) -> out
    decode_attn: str = "reference"      # "reference" | "flash"

    @nn.compact
    def __call__(self, x, mask=None, cache=None, cursor=None, alive=None,
                 return_kv=False):
        d_model = x.shape[-1]
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(self.num_heads, self.head_dim), dtype=self.dtype,
            axis=-1, name=name)
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        new_cache = None
        if cache is not None:
            from autodist_tpu.ops.attention import (cached_attention,
                                                    flash_cached_attention)
            if cursor is None:
                raise ValueError("decode mode needs a cursor with the cache")
            k_cache, v_cache = cache
            T = k_cache.shape[1]
            # one-hot write at the cursor row; dead slots write nothing
            write = jnp.arange(T)[None, :] == cursor[:, None]
            if alive is not None:
                write = write & alive[:, None]
            sel = write[..., None, None]
            k_cache = jnp.where(sel, k.astype(k_cache.dtype), k_cache)
            v_cache = jnp.where(sel, v.astype(v_cache.dtype), v_cache)
            attn = (flash_cached_attention if self.decode_attn == "flash"
                    else cached_attention)
            out = attn(q[:, 0], k_cache, v_cache, cursor)[:, None]
            new_cache = (k_cache, v_cache)
        elif self.attn_fn is not None:
            out = self.attn_fn(q, k, v, mask)
        else:
            scale = 1.0 / np.sqrt(self.head_dim)
            logits = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
            if mask is not None:
                logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
            weights = nn.softmax(logits.astype(jnp.float32)).astype(self.dtype)
            out = jnp.einsum("...hqk,...khd->...qhd", weights, v)
        out = nn.DenseGeneral(features=d_model, axis=(-2, -1),
                              dtype=self.dtype, name="out")(out)
        if cache is not None:
            return out, new_cache
        if return_kv:
            return out, (k, v)
        return out


class TransformerBlock(nn.Module):
    num_heads: int
    head_dim: int
    mlp_dim: int
    dtype: Dtype = jnp.float32
    dropout_rate: float = 0.0
    attn_fn: Optional[Callable] = None
    decode_attn: str = "reference"

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True, cache=None,
                 cursor=None, alive=None, return_kv=False):
        kv = None
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = MultiHeadAttention(self.num_heads, self.head_dim, self.dtype,
                               self.attn_fn,
                               decode_attn=self.decode_attn)(
            h, mask, cache=cache, cursor=cursor, alive=alive,
            return_kv=return_kv)
        if cache is not None or return_kv:
            h, kv = h
        if self.dropout_rate:
            h = nn.Dropout(self.dropout_rate)(h, deterministic=deterministic)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype)(h)
        if self.dropout_rate:
            h = nn.Dropout(self.dropout_rate)(h, deterministic=deterministic)
        x = x + h
        if cache is not None or return_kv:
            return x, kv
        return x
