"""InferenceEngine — bucketed forward-only execution of one strategy.

The engine owns the serving-side compiled programs of a built Runner:

- **one forward program per padded batch-bucket size** (e.g. {1, 8, 32,
  128}), derived from the same gather-params + fill-PS-holes path
  ``Runner.evaluate`` runs (``DistributedStep.predict_program``) with the
  batch buffers donated — after :meth:`warmup` every request executes a
  cached XLA executable, ZERO recompiles in steady state (asserted by
  :meth:`recompiles_after_warmup` in tests and the CI smoke leg);
- **a host-PS snapshot** shared across requests: values are pulled once
  and refreshed at most every ``snapshot_max_age_s`` — a high-QPS tier
  must not pay one PCIe pull per request for values that change at
  training cadence;
- **graceful degradation** wired into the PR 1 resilience plane: when
  the snapshot refresh fails (coordination-service blip, circuit breaker
  open, async-PS owner unreachable), the engine keeps serving the LAST
  good snapshot for up to ``degraded_batches`` consecutive batches —
  the same staleness-window contract the training-side degraded pull
  honors — counting each one (``serve.degraded``); past the window it
  raises the typed :class:`ServingUnavailable` so callers shed load in
  bounded time instead of hanging on a dead control plane.

Requests are SINGLE EXAMPLES: pytrees shaped like one row of the
training batch (no leading batch dim), usually without the label leaves.
``stack_batches(..., pad_to=bucket)`` stacks a group into the bucket's
``[bucket, ...]`` feed; rows past the real request count are repeats of
the last example and are masked out of the fetches before fan-out.
"""
import dataclasses
import threading
import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu import const
from autodist_tpu.data.prefetch import stack_batches
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging


class ServingUnavailable(RuntimeError):
    """Typed load-shed: the serving tier cannot answer right now —
    queue overflow, a PS snapshot staler than the strategy's window
    with the control plane still unreachable, or a drain for a planned
    departure. Callers retry/hedge elsewhere; nothing hangs.

    ``retry_after_s`` (when set) is the shed's Retry-After: how long the
    caller should wait — or route elsewhere — before retrying; a
    draining replica sets it from ``ADT_DRAIN_RETRY_AFTER_S`` so load
    balancers back off instead of hammering the leaver."""

    def __init__(self, *args, retry_after_s=None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class ServingConfig:
    """Engine + batcher knobs (docs/serving.md has sizing guidance).

    ``buckets``: padded batch sizes, each a multiple of the mesh's batch
    replica count (None = {1, 8, 32, 128} rounded up to multiples).
    ``max_delay_ms``: the batching deadline — how long the first request
    of a group may wait for company (the latency the batcher TRADES for
    throughput). ``max_queue``: backpressure bound; submits past it shed.
    ``snapshot_max_age_s``: host-PS snapshot refresh period.
    ``degraded_batches``: consecutive batches that may serve the last
    good snapshot while refresh fails (None = max(strategy staleness,
    ``ADT_PS_MAX_LAG``, 1)).

    Brownout (overload-graceful degradation, docs/serving.md): when the
    queue sits above ``brownout_queue_frac * max_queue`` for
    ``brownout_sustain_s``, the batcher widens the group deadline by
    ``brownout_delay_factor`` so dispatches run at full buckets —
    maximum throughput at bounded p99 instead of shedding earlier than
    necessary. ``brownout_delay_factor=1.0`` disables the mode."""

    buckets: Optional[Sequence[int]] = None
    max_delay_ms: float = 2.0
    max_queue: int = 1024
    snapshot_max_age_s: float = 0.1
    degraded_batches: Optional[int] = None
    brownout_queue_frac: float = 0.75
    brownout_sustain_s: float = 1.0
    brownout_delay_factor: float = 4.0

    def __post_init__(self):
        if self.max_delay_ms < 0:
            raise ValueError("max_delay_ms must be >= 0")
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if (self.degraded_batches is not None
                and self.degraded_batches < 0):
            raise ValueError("degraded_batches must be >= 0")
        if not 0.0 < self.brownout_queue_frac <= 1.0:
            raise ValueError("brownout_queue_frac must be in (0, 1]")
        if self.brownout_sustain_s < 0:
            raise ValueError("brownout_sustain_s must be >= 0")
        if self.brownout_delay_factor < 1.0:
            raise ValueError("brownout_delay_factor must be >= 1.0 "
                             "(1.0 disables brownout)")


DEFAULT_BUCKETS = (1, 8, 32, 128)


class InferenceEngine:
    """Bucketed forward-only inference over a built (initialized) Runner.

    ``serve_fn(full_params, batch) -> fetches`` defines the fetch set —
    per-example outputs under the user's own names (e.g. ``{"score":
    apply_fn(p, b["user"], b["item"])}``); the Remapper returns them on
    host in global batch order. ``example_request`` is ONE example
    (leaves without the batch dim) fixing the feed structure — usually
    the training batch minus labels."""

    def __init__(self, runner, serve_fn: Callable, example_request,
                 config: Optional[ServingConfig] = None):
        self._runner = runner
        self._dstep = runner.distributed_step
        self._serve_fn = serve_fn
        self._example_request = example_request
        self.config = config or ServingConfig()
        replicas = runner.remapper.num_replicas
        self.buckets = self._resolve_buckets(self.config.buckets, replicas)
        # ONE jitted program; XLA specializes per bucket shape under it.
        # The example feed passed here fixes the feed STRUCTURE; warmup
        # fixes the shapes. Built at the LARGEST bucket: the lowering
        # classifies output leaves as per-example by their local-batch
        # leading dim, and a big bucket makes that dim distinctive — at
        # the smallest bucket local rows can degenerate to 1 and a
        # replicated (1, ...) output would be mistaken for batch rows.
        self._program = self._dstep.predict_program(
            serve_fn, donate_batch=True,
            example_batch=stack_batches([example_request],
                                        pad_to=self.buckets[-1]))
        # PS snapshot + degradation state (guarded: run_batch may be
        # called from a batcher thread while predict() runs inline)
        self._lock = threading.Lock()
        self._ps_vals = None
        self._snap_t = 0.0
        self._degraded_used = 0
        self.stats = {"batches": 0, "padded_rows": 0, "degraded": 0,
                      "snapshot_refreshes": 0}
        self._warmed = False
        self._cache_size_after_warmup = None

    @staticmethod
    def _resolve_buckets(buckets, replicas: int) -> Tuple[int, ...]:
        if buckets is None:
            # round the defaults up to replica multiples (batch dims must
            # split evenly over the mesh's batch axes) and dedup
            buckets = sorted({max(-(-b // replicas), 1) * replicas
                              for b in DEFAULT_BUCKETS})
        buckets = tuple(sorted(int(b) for b in buckets))
        if not buckets or buckets[0] < 1:
            raise ValueError("buckets must be positive, got %r"
                             % (buckets,))
        if len(set(buckets)) != len(buckets):
            raise ValueError("duplicate buckets: %r" % (buckets,))
        bad = [b for b in buckets if b % replicas]
        if bad:
            raise ValueError(
                "bucket sizes %s are not multiples of the %d batch "
                "replicas — padded bucket batches must split evenly "
                "over the mesh" % (bad, replicas))
        return buckets

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket holding ``n`` requests."""
        if n < 1:
            raise ValueError("empty request group")
        for b in self.buckets:
            if n <= b:
                return b
        raise ServingUnavailable(
            "request group of %d exceeds the largest bucket %d — the "
            "micro-batcher caps groups at max(buckets)" % (n, self.buckets[-1]))

    # ------------------------------------------------------------ snapshot

    @property
    def _degraded_bound(self) -> int:
        if self.config.degraded_batches is not None:
            return self.config.degraded_batches
        store = getattr(self._dstep, "ps_store", None)
        staleness = store.max_staleness() if store is not None else 0
        return max(staleness, const.ENV.ADT_PS_MAX_LAG.val, 1)

    def _snapshot(self):
        """The host-PS values feed of the next dispatch: a placed device
        snapshot, refreshed at most every ``snapshot_max_age_s``. Refresh
        failures degrade to the last good snapshot within the window,
        then shed with :class:`ServingUnavailable` — the engine object
        stays alive and retries the refresh on the next batch."""
        if getattr(self._dstep, "ps_store", None) is None:
            return {}
        now = time.monotonic()
        if (self._ps_vals is not None
                and now - self._snap_t < self.config.snapshot_max_age_s):
            return self._ps_vals
        try:
            vals = self._dstep.pull_ps()
        except (OSError, RuntimeError, TimeoutError) as e:
            # CoordinationUnavailable / CircuitOpenError are OSErrors; the
            # store's exhausted degraded-serve window raises RuntimeError;
            # an owner that never published raises TimeoutError
            if (self._ps_vals is not None
                    and self._degraded_used < self._degraded_bound):
                self._degraded_used += 1
                self.stats["degraded"] += 1
                tel.counter_add("serve.degraded")
                tel.instant("serve.degraded_snapshot", "serve",
                            used=self._degraded_used,
                            bound=self._degraded_bound)
                logging.warning(
                    "serving: PS snapshot refresh failed (%s); serving "
                    "last snapshot (degraded batch %d/%d)", e,
                    self._degraded_used, self._degraded_bound)
                return self._ps_vals
            raise ServingUnavailable(
                "PS snapshot refresh failed and the degraded window "
                "(%d batches) is exhausted: %s"
                % (self._degraded_bound, e)) from e
        self._ps_vals = vals
        self._snap_t = now
        self._degraded_used = 0
        self.stats["snapshot_refreshes"] += 1
        return vals

    # ------------------------------------------------------------- execute

    def warmup(self):
        """Compile every bucket once (one dispatch each, on repeats of
        the example request). After warmup, steady-state serving is
        recompile-free — :meth:`recompiles_after_warmup` proves it."""
        for b in self.buckets:
            with tel.span("serve.warmup", "serve", bucket=b):
                self.run_batch([self._example_request] * b)
        self._warmed = True
        self._cache_size_after_warmup = self._jit_cache_size()
        if self._cache_size_after_warmup is None:
            logging.warning(
                "serving: jit cache size is not introspectable on this jax "
                "version — the zero-recompile contract cannot be verified "
                "(recompiles_after_warmup() will report 0)")
        tel.counter_add("serve.compiles",
                        self._cache_size_after_warmup or len(self.buckets))
        return self

    def _jit_cache_size(self) -> Optional[int]:
        cache_size = getattr(self._program, "_cache_size", None)
        return cache_size() if callable(cache_size) else None

    def recompiles_after_warmup(self) -> int:
        """Compiled-specialization count growth since :meth:`warmup` —
        the zero-recompile serving contract (0 in steady state). Falls
        back to 0 when the jit cache size is not introspectable."""
        if self._cache_size_after_warmup is None:
            return 0
        now = self._jit_cache_size()
        return max(0, (now or 0) - self._cache_size_after_warmup)

    def run_batch(self, requests) -> Tuple[dict, int]:
        """Execute one request group: pad to the nearest bucket, dispatch
        the bucket's compiled program, read fetches back, mask the padded
        rows. Returns ``(host_fetches, n)`` with every leading-dim leaf
        sliced to the ``n`` real requests (global batch order)."""
        n = len(requests)
        bucket = self.bucket_for(n)
        host = stack_batches(list(requests), pad_to=bucket)
        with self._lock:
            # stats read-modify-writes stay under the engine lock: run_batch
            # may race predict() from another thread, and a dropped += would
            # silently underreport batches/padded_rows in stats() and bench
            if bucket > n:
                self.stats["padded_rows"] += bucket - n
                tel.counter_add("serve.padded_rows", bucket - n)
            state = self._runner.state
            if state is None:
                raise RuntimeError("InferenceEngine over an uninitialized "
                                   "Runner — call runner.init() first")
            t0 = time.perf_counter()
            with tel.span("serve.dispatch", "serve", n=n, bucket=bucket):
                ps_vals = self._snapshot()
                placed = self._runner.remapper.remap_feed(host)
                device_out = self._program(state, ps_vals, placed)
            t1 = time.perf_counter()
            with tel.span("serve.readback", "serve", n=n, bucket=bucket):
                fetched = self._runner.remapper.remap_fetch(device_out)
            # per-request goodput buckets: the serving analog of the
            # training decomposition — dispatch (program + snapshot +
            # placement) vs readback (D2H) latency distributions, the
            # third bucket (queue wait) observed by the micro-batcher
            tel.hist_observe("serve.dispatch_ms",
                             (t1 - t0) * 1e3)
            tel.hist_observe("serve.readback_ms",
                             (time.perf_counter() - t1) * 1e3)
            self.stats["batches"] += 1
        tel.counter_add("serve.batches")
        import jax
        # slice by the lowering's own per-leaf classification, not by
        # shape: a replicated fetch whose leading dim equals the bucket
        # size must come back whole
        masked = jax.tree_util.tree_map(
            lambda is_batch, a: (np.asarray(a)[:n] if is_batch else a),
            self._program.batch_mask, fetched)
        return masked, n

    def predict(self, requests) -> list:
        """Convenience: run a request list through one padded batch and
        return one fetch tree PER REQUEST (row i of every batch-dim
        leaf)."""
        fetched, n = self.run_batch(requests)
        return self.fan_out(fetched, n)

    def fan_out(self, fetched, n: int) -> list:
        """Split one masked fetch tree into ``n`` per-request trees (row
        ``i`` of every batch-dim leaf, replicated leaves shared)."""
        import jax
        return [jax.tree_util.tree_map(
            lambda is_batch, a, _i=i: (np.asarray(a)[_i] if is_batch
                                       else a),
            self._program.batch_mask, fetched)
            for i in range(n)]
