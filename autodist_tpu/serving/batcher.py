"""MicroBatcher — the request queue in front of the InferenceEngine.

Concurrent callers :meth:`submit` single-example requests and get
futures; one worker thread accumulates requests into groups — up to the
engine's largest bucket, or until the FIRST request of the group has
waited ``max_delay_ms`` — runs each group as one padded bucketed
dispatch, and fans the fetches back out row-per-request. That deadline
is the serving tier's core latency/throughput trade: a lone request
waits at most ``max_delay_ms`` for company; a burst fills a bucket
immediately and amortizes one program dispatch over the whole group.

Failure behavior is SHED, NEVER HANG: a full queue rejects the submit
with :class:`ServingUnavailable`; an exhausted PS-degradation window
fails the GROUP's futures with the engine's typed error and the worker
keeps serving (the next snapshot refresh may succeed — e.g. after the
circuit breaker's cooldown). Every shed carries a populated
``retry_after_s``: queue-full sheds compute it from the measured drain
rate (an EWMA over recent group service times — the honest answer to
"when will there be room"), drain/close sheds carry the operator knob
``ADT_DRAIN_RETRY_AFTER_S``. Requests may carry a per-request
``deadline_s``: one that would already be expired when its group
dispatches is shed immediately instead of consuming a dispatch slot on
an answer nobody is waiting for. Under SUSTAINED overload (queue near
``max_queue`` for ``brownout_sustain_s``) the batcher enters
**brownout**: the group deadline widens by ``brownout_delay_factor`` so
dispatches run at full buckets — maximum throughput at bounded p99 —
until the backlog recedes. Every request is accounted: ``serve.
requests/batches/shed/deadline_shed/brownouts/degraded/padded_rows``
counters, the ``serve.queue_depth`` gauge, and the ``serve.latency_ms``
histogram (submit -> fan-out) feeding the p50/p99 readout in
:meth:`stats`.
"""
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Optional

from autodist_tpu import const
from autodist_tpu.serving.engine import InferenceEngine, ServingUnavailable
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

_SENTINEL = object()

# every live batcher, so the preemption plane can drain a departing
# process's whole serving tier without threading references through it
_ACTIVE: "weakref.WeakSet" = weakref.WeakSet()


def active_batchers() -> list:
    """The process's live micro-batchers (drained on planned departure
    by ``runtime/preemption.py``)."""
    return list(_ACTIVE)


class _Pending:
    __slots__ = ("example", "future", "t0", "deadline")

    def __init__(self, example, deadline_s: Optional[float] = None):
        self.example = example
        self.future = Future()
        self.t0 = time.perf_counter()
        # absolute expiry on the worker clock (None = no deadline)
        self.deadline = (self.t0 + deadline_s
                         if deadline_s is not None else None)


# clamp on every computed Retry-After: never tell a client to hammer
# back in microseconds, never park it for longer than any drain window
_RETRY_AFTER_MIN_S = 0.05
_RETRY_AFTER_MAX_S = 60.0
# EWMA smoothing for the measured drain rate (requests/s)
_DRAIN_RATE_ALPHA = 0.3


class MicroBatcher:
    """Queue + worker thread over an :class:`InferenceEngine`.

    Context-manager friendly::

        with MicroBatcher(engine) as mb:
            futures = [mb.submit(req) for req in requests]
            results = [f.result() for f in futures]
    """

    def __init__(self, engine: InferenceEngine,
                 max_delay_ms: Optional[float] = None,
                 max_queue: Optional[int] = None):
        self._engine = engine
        cfg = engine.config
        self.max_delay_s = (cfg.max_delay_ms if max_delay_ms is None
                            else max_delay_ms) / 1e3
        self.max_queue = (cfg.max_queue if max_queue is None
                          else int(max_queue))
        self.max_batch = engine.max_batch
        self._queue: "queue.Queue" = queue.Queue()
        self._closed = False
        # serializes submit's closed-check-then-put against close's
        # closed-set-then-drain: without it a submit could enqueue AFTER
        # the drain and its future would hang forever — the one thing
        # this module promises never happens
        self._submit_lock = threading.Lock()
        self.stats_local = {"requests": 0, "batches": 0, "shed": 0,
                            "errors": 0, "fan_out": 0, "drained": 0,
                            "deadline_shed": 0}
        # set while draining/closed: the Retry-After attached to every
        # typed shed past that point
        self._retry_after: Optional[float] = None
        # measured drain rate (requests/s EWMA over group service times);
        # None until the first group completes — the honest source of the
        # queue-full Retry-After
        self._drain_rate: Optional[float] = None
        # brownout: sustained near-full queue widens the group deadline
        # so dispatches run at full buckets (throughput over p50)
        self._brownout = False
        self._brownout_entries = 0
        self._overload_since: Optional[float] = None
        self._effective_delay_s = self.max_delay_s
        self._worker = threading.Thread(target=self._run,
                                        name="adt-serve-batcher",
                                        daemon=True)
        self._worker.start()
        _ACTIVE.add(self)

    # ------------------------------------------------------------- submit

    def submit(self, example, deadline_s: Optional[float] = None) -> Future:
        """Enqueue one single-example request; resolves to its fetch tree
        (row of every batch-dim leaf). Sheds with
        :class:`ServingUnavailable` when the queue is full or the
        batcher is closed — backpressure is synchronous and typed, and
        every shed carries a populated ``retry_after_s`` (measured
        drain-rate estimate on queue-full, the drain knob when
        closed/draining) so an overloaded tier fails fast with an honest
        back-off hint instead of buffering unboundedly. ``deadline_s``
        (optional, seconds from now) arms a per-request deadline: if the
        request would already be expired when its group dispatches, it
        is shed then instead of consuming a dispatch slot."""
        with tel.span("serve.enqueue", "serve"), self._submit_lock:
            if self._closed:
                retry = (const.ENV.ADT_DRAIN_RETRY_AFTER_S.val
                         if self._retry_after is None else self._retry_after)
                raise ServingUnavailable(
                    "micro-batcher is %s (Retry-After %.1fs)"
                    % ("draining" if self._retry_after is not None
                       else "closed", retry),
                    retry_after_s=retry)
            depth = self._queue.qsize()
            if depth >= self.max_queue:
                retry = self._computed_retry_after(depth)
                self.stats_local["shed"] += 1
                tel.counter_add("serve.shed")
                raise ServingUnavailable(
                    "serving queue full (%d pending) — shedding "
                    "(Retry-After %.2fs)" % (self.max_queue, retry),
                    retry_after_s=retry)
            self._maybe_brownout(depth)
            pending = _Pending(example, deadline_s)
            self._queue.put(pending)
            self.stats_local["requests"] += 1
            tel.counter_add("serve.requests")
            tel.gauge_set("serve.queue_depth", self._queue.qsize())
        return pending.future

    def queue_depth(self) -> int:
        """Currently queued (not yet grouped) requests — the live signal
        behind the ``serve.queue_depth`` gauge."""
        return self._queue.qsize()

    def oldest_queue_age_s(self) -> Optional[float]:
        """Age of the OLDEST still-queued request (None when empty) —
        the head-of-line wait a newly shed caller is implicitly being
        quoted on top of the drain-rate backlog estimate."""
        with self._queue.mutex:
            head = next((p for p in self._queue.queue
                         if p is not _SENTINEL), None)
        if head is None:
            return None
        return max(0.0, time.perf_counter() - head.t0)

    def _computed_retry_after(self, depth: int) -> float:
        """Retry-After from the MEASURED drain rate: the current backlog
        over the smoothed requests/s the worker is actually clearing,
        clamped to a sane band. Before any group has completed there is
        no measurement — fall back to the operator knob rather than
        invent a number. The oldest queued request's age FLOORS the
        estimate: a head-of-line request that has already waited T
        seconds proves the tier is clearing slower than the EWMA claims
        (e.g. the worker is parked inside a long dispatch), so the hint
        must not promise anything sooner."""
        rate = self._drain_rate
        if not rate or rate <= 0:
            base = const.ENV.ADT_DRAIN_RETRY_AFTER_S.val
        else:
            base = depth / rate
        oldest = self.oldest_queue_age_s()
        if oldest is not None:
            base = max(base, oldest)
        return min(max(base, _RETRY_AFTER_MIN_S), _RETRY_AFTER_MAX_S)

    def _maybe_brownout(self, depth: int):
        """Brownout state machine, driven from BOTH submit and the
        worker loop (the worker may be parked inside a long dispatch, so
        admission must be able to flip the state without it). Enter when
        the queue has sat above ``brownout_queue_frac * max_queue`` for
        ``brownout_sustain_s``; exit at half the entry threshold —
        hysteresis, so a backlog hovering at the line does not strobe
        the group deadline."""
        cfg = self._engine.config
        factor = getattr(cfg, "brownout_delay_factor", 1.0)
        if factor <= 1.0:
            return
        high = getattr(cfg, "brownout_queue_frac", 0.75) * self.max_queue
        now = time.perf_counter()
        if not self._brownout:
            if depth >= high:
                if self._overload_since is None:
                    self._overload_since = now
                elif (now - self._overload_since
                      >= getattr(cfg, "brownout_sustain_s", 1.0)):
                    self._brownout = True
                    self._brownout_entries += 1
                    self._effective_delay_s = self.max_delay_s * factor
                    tel.counter_add("serve.brownouts")
                    tel.gauge_set("serve.brownout", 1)
                    tel.instant("serve.brownout", "serve", depth=depth,
                                delay_ms=self._effective_delay_s * 1e3)
                    logging.warning(
                        "serving: entering brownout — queue %d/%d "
                        "sustained; widening group deadline to %.1fms "
                        "for full-bucket dispatches", depth,
                        self.max_queue, self._effective_delay_s * 1e3)
            else:
                self._overload_since = None
        elif depth <= high / 2:
            self._brownout = False
            self._overload_since = None
            self._effective_delay_s = self.max_delay_s
            tel.gauge_set("serve.brownout", 0)
            tel.instant("serve.brownout_exit", "serve", depth=depth)
            logging.warning("serving: exiting brownout — queue depth %d "
                            "receded; restoring %.1fms group deadline",
                            depth, self.max_delay_s * 1e3)

    def predict_one(self, example, timeout: Optional[float] = None):
        """Blocking convenience: ``submit(example).result(timeout)``."""
        return self.submit(example).result(timeout=timeout)

    # ------------------------------------------------------------- worker

    def _next_group(self):
        """One request group: the first request opens the group and its
        enqueue time starts the ``max_delay_ms`` deadline; the group
        closes at the deadline, at ``max_batch``, or on shutdown.
        Returns (group, saw_sentinel) — group may be empty."""
        # blocking get: shutdown is signalled in-band (close() posts the
        # sentinel), so an idle worker parks instead of polling
        first = self._queue.get()
        if first is _SENTINEL:
            return [], True
        group = [first]
        # _effective_delay_s, not max_delay_s: under brownout the group
        # deadline is widened so dispatches run at full buckets
        deadline = first.t0 + self._effective_delay_s
        while len(group) < self.max_batch:
            remaining = deadline - time.perf_counter()
            try:
                # past the deadline (e.g. the request queued while the
                # worker served the previous batch), still DRAIN whatever
                # is already waiting — a backlog must coalesce into full
                # buckets, not serialize as size-1 batches
                item = (self._queue.get(timeout=remaining)
                        if remaining > 0 else self._queue.get_nowait())
            except queue.Empty:
                break
            if item is _SENTINEL:
                return group, True
            group.append(item)
        return group, False

    def _run(self):
        while True:
            group, stop = self._next_group()
            if group:
                with tel.span("serve.batch", "serve", n=len(group)):
                    self._serve_group(group)
            # gauge updated UNCONDITIONALLY after every wakeup — a gauge
            # written only on submit reads stale-high forever once
            # traffic stops, and an empty group is exactly the moment
            # the queue went quiet
            depth = self._queue.qsize()
            tel.gauge_set("serve.queue_depth", depth)
            with self._submit_lock:
                self._maybe_brownout(depth)
            if stop:
                break

    def _serve_group(self, group):
        # queue-wait bucket of the per-request goodput decomposition:
        # submit → group start, per request (the other two buckets —
        # dispatch and readback — are observed inside the engine)
        t_start = time.perf_counter()
        # deadline sweep BEFORE the dispatch: a request whose deadline
        # already passed in queue gets an immediate typed shed instead
        # of burning a padded dispatch row on an answer nobody waits for
        expired = [p for p in group
                   if p.deadline is not None and t_start > p.deadline]
        if expired:
            retry = self._computed_retry_after(self._queue.qsize())
            exc = ServingUnavailable(
                "request deadline expired in queue — shedding "
                "(Retry-After %.2fs)" % retry, retry_after_s=retry)
            dead = set(map(id, expired))
            group = [p for p in group if id(p) not in dead]
            self.stats_local["shed"] += len(expired)
            self.stats_local["deadline_shed"] += len(expired)
            tel.counter_add("serve.shed", len(expired))
            tel.counter_add("serve.deadline_shed", len(expired))
            tel.instant("serve.deadline_shed", "serve", n=len(expired))
            for p in expired:
                p.future.set_exception(exc)
            if not group:
                return
        for p in group:
            tel.hist_observe("serve.queue_ms", (t_start - p.t0) * 1e3)
        try:
            fetched, n = self._engine.run_batch(
                [p.example for p in group])
        except ServingUnavailable as e:
            # typed shed: fail THIS group, keep serving — the engine
            # retries its snapshot refresh on the next batch
            self.stats_local["shed"] += len(group)
            tel.counter_add("serve.shed", len(group))
            for p in group:
                p.future.set_exception(e)
            return
        except Exception as e:  # noqa: BLE001 — one bad request (shape
            # mismatch, dtype) must not kill the worker loop for every
            # future caller; the group's futures carry the real error
            self.stats_local["errors"] += len(group)
            logging.warning("serving batch failed: %s", e)
            for p in group:
                p.future.set_exception(e)
            return
        self.stats_local["batches"] += 1
        self.stats_local["fan_out"] += n
        now = time.perf_counter()
        # drain-rate EWMA (requests/s actually cleared): the measured
        # basis for the queue-full Retry-After
        elapsed = now - t_start
        if elapsed > 0:
            rate = len(group) / elapsed
            self._drain_rate = (rate if self._drain_rate is None else
                                _DRAIN_RATE_ALPHA * rate
                                + (1 - _DRAIN_RATE_ALPHA)
                                * self._drain_rate)
        for p, row in zip(group, self._engine.fan_out(fetched, n)):
            tel.hist_observe("serve.latency_ms", (now - p.t0) * 1e3)
            p.future.set_result(row)

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Serving accounting for THIS batcher plus the engine's
        snapshot/padding stats and the process-wide latency percentiles
        (stable keys; percentiles are None before any request)."""
        # engine stats first, then this batcher's — both carry a
        # "batches" key, and the batcher's group count must win (the
        # engine's also counts warmup dispatches and other callers)
        out = dict(self._engine.stats)
        out.update(self.stats_local)
        from autodist_tpu.serving import autoscale as autoscale_lib
        out.update(
            queue_depth=self._queue.qsize(),
            oldest_queue_age_s=self.oldest_queue_age_s(),
            drain_rate_rps=self._drain_rate,
            brownout={"active": self._brownout,
                      "entries": self._brownout_entries},
            # process-wide controller accounting from the pre-registered
            # counters — stable keys even with no autoscaler running
            autoscale=autoscale_lib.stats_snapshot(),
            buckets=list(self._engine.buckets),
            recompiles_after_warmup=self._engine.recompiles_after_warmup(),
            p50_ms=tel.hist_quantile("serve.latency_ms", 0.50),
            p99_ms=tel.hist_quantile("serve.latency_ms", 0.99),
            # per-request goodput buckets: where a request's latency
            # went — queue wait vs program dispatch vs D2H readback
            # (p50s; the full distributions ride the registry
            # histograms / metrics_text)
            goodput={
                "queue_p50_ms": tel.hist_quantile("serve.queue_ms", 0.50),
                "queue_p99_ms": tel.hist_quantile("serve.queue_ms", 0.99),
                "dispatch_p50_ms": tel.hist_quantile("serve.dispatch_ms",
                                                     0.50),
                "readback_p50_ms": tel.hist_quantile("serve.readback_ms",
                                                     0.50),
            },
        )
        return out

    # ------------------------------------------------------------ shutdown

    def drain(self, retry_after_s: Optional[float] = None,
              timeout: float = 30.0) -> int:
        """Planned-departure drain: stop admitting (subsequent submits
        shed with the typed Retry-After), let the IN-FLIGHT group finish
        and resolve its futures, and shed everything still queued —
        typed, with ``retry_after_s`` (default ``ADT_DRAIN_RETRY_AFTER_S``)
        so callers route to another replica instead of hammering the
        leaver. Counts ``serve.drained`` (in-flight requests completed
        during the drain) and ``serve.shed`` (queued requests rejected).
        Returns the shed count. Idempotent; a drained batcher is
        closed."""
        retry = (const.ENV.ADT_DRAIN_RETRY_AFTER_S.val
                 if retry_after_s is None else float(retry_after_s))
        with self._submit_lock:
            if self._closed:
                return 0
            self._closed = True
            self._retry_after = retry
        # shed the QUEUE first (before the sentinel): whatever the worker
        # already took is in-flight and completes; whatever still sits in
        # the queue is work a healthier replica should take
        shed_exc = ServingUnavailable(
            "serving replica draining for departure — retry elsewhere "
            "(Retry-After %.1fs)" % retry, retry_after_s=retry)
        shed = 0
        requeue = []
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is _SENTINEL:
                requeue.append(item)  # a concurrent close posted it
                continue
            if not item.future.done():
                item.future.set_exception(shed_exc)
                shed += 1
        for item in requeue:
            self._queue.put(item)
        tel.gauge_set("serve.queue_depth", self._queue.qsize())
        fan0 = self.stats_local["fan_out"]
        self._queue.put(_SENTINEL)
        self._worker.join(timeout=timeout)
        # a submit that raced the closed-flag flip cannot exist (the flip
        # holds the submit lock), but the worker may have been mid-group:
        # those futures resolved above the fan-out counter
        drained = self.stats_local["fan_out"] - fan0
        self.stats_local["shed"] += shed
        self.stats_local["drained"] += drained
        if shed:
            tel.counter_add("serve.shed", shed)
        tel.counter_add("serve.drained", drained)
        tel.instant("serve.drained", "serve", shed=shed, drained=drained,
                    retry_after_s=retry)
        logging.warning(
            "serving: drained micro-batcher — %d in-flight request(s) "
            "completed, %d queued shed with Retry-After %.1fs",
            drained, shed, retry)
        if self._worker.is_alive():
            self._queue.put(_SENTINEL)  # join timed out mid-group
        return shed

    def close(self, timeout: float = 30.0):
        """Stop accepting, drain the worker, and fail any still-queued
        requests with a typed shed (a silent dropped future would hang
        its caller forever). Idempotent."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        # past this point no submit can enqueue (closed-check holds the
        # same lock), so the drain below cannot race a late put
        self._queue.put(_SENTINEL)
        self._worker.join(timeout=timeout)
        # even a plain close carries a Retry-After: the caller's retry
        # loop should back off the same way it would for a drain, not
        # special-case a None hint
        shed = ServingUnavailable(
            "micro-batcher closed while queued",
            retry_after_s=const.ENV.ADT_DRAIN_RETRY_AFTER_S.val)
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _SENTINEL and not item.future.done():
                self.stats_local["shed"] += 1
                tel.counter_add("serve.shed")
                item.future.set_exception(shed)
        tel.gauge_set("serve.queue_depth", self._queue.qsize())
        if self._worker.is_alive():
            # join timed out mid-group and the drain may have eaten the
            # sentinel — re-post it so the worker exits instead of
            # spinning on an empty queue forever
            self._queue.put(_SENTINEL)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
