"""MicroBatcher — the request queue in front of the InferenceEngine.

Concurrent callers :meth:`submit` single-example requests and get
futures; one worker thread accumulates requests into groups — up to the
engine's largest bucket, or until the FIRST request of the group has
waited ``max_delay_ms`` — runs each group as one padded bucketed
dispatch, and fans the fetches back out row-per-request. That deadline
is the serving tier's core latency/throughput trade: a lone request
waits at most ``max_delay_ms`` for company; a burst fills a bucket
immediately and amortizes one program dispatch over the whole group.

Failure behavior is SHED, NEVER HANG: a full queue rejects the submit
with :class:`ServingUnavailable`; an exhausted PS-degradation window
fails the GROUP's futures with the engine's typed error and the worker
keeps serving (the next snapshot refresh may succeed — e.g. after the
circuit breaker's cooldown). Every request is accounted: ``serve.
requests/batches/shed/degraded/padded_rows`` counters, the
``serve.queue_depth`` gauge, and the ``serve.latency_ms`` histogram
(submit -> fan-out) feeding the p50/p99 readout in :meth:`stats`.
"""
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Optional

from autodist_tpu import const
from autodist_tpu.serving.engine import InferenceEngine, ServingUnavailable
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

_SENTINEL = object()

# every live batcher, so the preemption plane can drain a departing
# process's whole serving tier without threading references through it
_ACTIVE: "weakref.WeakSet" = weakref.WeakSet()


def active_batchers() -> list:
    """The process's live micro-batchers (drained on planned departure
    by ``runtime/preemption.py``)."""
    return list(_ACTIVE)


class _Pending:
    __slots__ = ("example", "future", "t0")

    def __init__(self, example):
        self.example = example
        self.future = Future()
        self.t0 = time.perf_counter()


class MicroBatcher:
    """Queue + worker thread over an :class:`InferenceEngine`.

    Context-manager friendly::

        with MicroBatcher(engine) as mb:
            futures = [mb.submit(req) for req in requests]
            results = [f.result() for f in futures]
    """

    def __init__(self, engine: InferenceEngine,
                 max_delay_ms: Optional[float] = None,
                 max_queue: Optional[int] = None):
        self._engine = engine
        cfg = engine.config
        self.max_delay_s = (cfg.max_delay_ms if max_delay_ms is None
                            else max_delay_ms) / 1e3
        self.max_queue = (cfg.max_queue if max_queue is None
                          else int(max_queue))
        self.max_batch = engine.max_batch
        self._queue: "queue.Queue" = queue.Queue()
        self._closed = False
        # serializes submit's closed-check-then-put against close's
        # closed-set-then-drain: without it a submit could enqueue AFTER
        # the drain and its future would hang forever — the one thing
        # this module promises never happens
        self._submit_lock = threading.Lock()
        self.stats_local = {"requests": 0, "batches": 0, "shed": 0,
                            "errors": 0, "fan_out": 0, "drained": 0}
        # set while draining/closed: the Retry-After attached to every
        # typed shed (None = plain close, no retry hint)
        self._retry_after: Optional[float] = None
        self._worker = threading.Thread(target=self._run,
                                        name="adt-serve-batcher",
                                        daemon=True)
        self._worker.start()
        _ACTIVE.add(self)

    # ------------------------------------------------------------- submit

    def submit(self, example) -> Future:
        """Enqueue one single-example request; resolves to its fetch tree
        (row of every batch-dim leaf). Sheds with
        :class:`ServingUnavailable` when the queue is full or the
        batcher is closed — backpressure is synchronous and typed, so an
        overloaded tier fails fast instead of buffering unboundedly."""
        with tel.span("serve.enqueue", "serve"), self._submit_lock:
            if self._closed:
                raise ServingUnavailable(
                    "micro-batcher is %s" % ("draining"
                                             if self._retry_after is not None
                                             else "closed"),
                    retry_after_s=self._retry_after)
            if self._queue.qsize() >= self.max_queue:
                self.stats_local["shed"] += 1
                tel.counter_add("serve.shed")
                raise ServingUnavailable(
                    "serving queue full (%d pending) — shedding"
                    % self.max_queue)
            pending = _Pending(example)
            self._queue.put(pending)
            self.stats_local["requests"] += 1
            tel.counter_add("serve.requests")
            tel.gauge_set("serve.queue_depth", self._queue.qsize())
        return pending.future

    def predict_one(self, example, timeout: Optional[float] = None):
        """Blocking convenience: ``submit(example).result(timeout)``."""
        return self.submit(example).result(timeout=timeout)

    # ------------------------------------------------------------- worker

    def _next_group(self):
        """One request group: the first request opens the group and its
        enqueue time starts the ``max_delay_ms`` deadline; the group
        closes at the deadline, at ``max_batch``, or on shutdown.
        Returns (group, saw_sentinel) — group may be empty."""
        # blocking get: shutdown is signalled in-band (close() posts the
        # sentinel), so an idle worker parks instead of polling
        first = self._queue.get()
        if first is _SENTINEL:
            return [], True
        group = [first]
        deadline = first.t0 + self.max_delay_s
        while len(group) < self.max_batch:
            remaining = deadline - time.perf_counter()
            try:
                # past the deadline (e.g. the request queued while the
                # worker served the previous batch), still DRAIN whatever
                # is already waiting — a backlog must coalesce into full
                # buckets, not serialize as size-1 batches
                item = (self._queue.get(timeout=remaining)
                        if remaining > 0 else self._queue.get_nowait())
            except queue.Empty:
                break
            if item is _SENTINEL:
                return group, True
            group.append(item)
        return group, False

    def _run(self):
        while True:
            group, stop = self._next_group()
            if group:
                with tel.span("serve.batch", "serve", n=len(group)):
                    self._serve_group(group)
                tel.gauge_set("serve.queue_depth", self._queue.qsize())
            if stop:
                break

    def _serve_group(self, group):
        # queue-wait bucket of the per-request goodput decomposition:
        # submit → group start, per request (the other two buckets —
        # dispatch and readback — are observed inside the engine)
        t_start = time.perf_counter()
        for p in group:
            tel.hist_observe("serve.queue_ms", (t_start - p.t0) * 1e3)
        try:
            fetched, n = self._engine.run_batch(
                [p.example for p in group])
        except ServingUnavailable as e:
            # typed shed: fail THIS group, keep serving — the engine
            # retries its snapshot refresh on the next batch
            self.stats_local["shed"] += len(group)
            tel.counter_add("serve.shed", len(group))
            for p in group:
                p.future.set_exception(e)
            return
        except Exception as e:  # noqa: BLE001 — one bad request (shape
            # mismatch, dtype) must not kill the worker loop for every
            # future caller; the group's futures carry the real error
            self.stats_local["errors"] += len(group)
            logging.warning("serving batch failed: %s", e)
            for p in group:
                p.future.set_exception(e)
            return
        self.stats_local["batches"] += 1
        self.stats_local["fan_out"] += n
        now = time.perf_counter()
        for p, row in zip(group, self._engine.fan_out(fetched, n)):
            tel.hist_observe("serve.latency_ms", (now - p.t0) * 1e3)
            p.future.set_result(row)

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Serving accounting for THIS batcher plus the engine's
        snapshot/padding stats and the process-wide latency percentiles
        (stable keys; percentiles are None before any request)."""
        # engine stats first, then this batcher's — both carry a
        # "batches" key, and the batcher's group count must win (the
        # engine's also counts warmup dispatches and other callers)
        out = dict(self._engine.stats)
        out.update(self.stats_local)
        out.update(
            queue_depth=self._queue.qsize(),
            buckets=list(self._engine.buckets),
            recompiles_after_warmup=self._engine.recompiles_after_warmup(),
            p50_ms=tel.hist_quantile("serve.latency_ms", 0.50),
            p99_ms=tel.hist_quantile("serve.latency_ms", 0.99),
            # per-request goodput buckets: where a request's latency
            # went — queue wait vs program dispatch vs D2H readback
            # (p50s; the full distributions ride the registry
            # histograms / metrics_text)
            goodput={
                "queue_p50_ms": tel.hist_quantile("serve.queue_ms", 0.50),
                "queue_p99_ms": tel.hist_quantile("serve.queue_ms", 0.99),
                "dispatch_p50_ms": tel.hist_quantile("serve.dispatch_ms",
                                                     0.50),
                "readback_p50_ms": tel.hist_quantile("serve.readback_ms",
                                                     0.50),
            },
        )
        return out

    # ------------------------------------------------------------ shutdown

    def drain(self, retry_after_s: Optional[float] = None,
              timeout: float = 30.0) -> int:
        """Planned-departure drain: stop admitting (subsequent submits
        shed with the typed Retry-After), let the IN-FLIGHT group finish
        and resolve its futures, and shed everything still queued —
        typed, with ``retry_after_s`` (default ``ADT_DRAIN_RETRY_AFTER_S``)
        so callers route to another replica instead of hammering the
        leaver. Counts ``serve.drained`` (in-flight requests completed
        during the drain) and ``serve.shed`` (queued requests rejected).
        Returns the shed count. Idempotent; a drained batcher is
        closed."""
        retry = (const.ENV.ADT_DRAIN_RETRY_AFTER_S.val
                 if retry_after_s is None else float(retry_after_s))
        with self._submit_lock:
            if self._closed:
                return 0
            self._closed = True
            self._retry_after = retry
        # shed the QUEUE first (before the sentinel): whatever the worker
        # already took is in-flight and completes; whatever still sits in
        # the queue is work a healthier replica should take
        shed_exc = ServingUnavailable(
            "serving replica draining for departure — retry elsewhere "
            "(Retry-After %.1fs)" % retry, retry_after_s=retry)
        shed = 0
        requeue = []
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is _SENTINEL:
                requeue.append(item)  # a concurrent close posted it
                continue
            if not item.future.done():
                item.future.set_exception(shed_exc)
                shed += 1
        for item in requeue:
            self._queue.put(item)
        fan0 = self.stats_local["fan_out"]
        self._queue.put(_SENTINEL)
        self._worker.join(timeout=timeout)
        # a submit that raced the closed-flag flip cannot exist (the flip
        # holds the submit lock), but the worker may have been mid-group:
        # those futures resolved above the fan-out counter
        drained = self.stats_local["fan_out"] - fan0
        self.stats_local["shed"] += shed
        self.stats_local["drained"] += drained
        if shed:
            tel.counter_add("serve.shed", shed)
        tel.counter_add("serve.drained", drained)
        tel.instant("serve.drained", "serve", shed=shed, drained=drained,
                    retry_after_s=retry)
        logging.warning(
            "serving: drained micro-batcher — %d in-flight request(s) "
            "completed, %d queued shed with Retry-After %.1fs",
            drained, shed, retry)
        if self._worker.is_alive():
            self._queue.put(_SENTINEL)  # join timed out mid-group
        return shed

    def close(self, timeout: float = 30.0):
        """Stop accepting, drain the worker, and fail any still-queued
        requests with a typed shed (a silent dropped future would hang
        its caller forever). Idempotent."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        # past this point no submit can enqueue (closed-check holds the
        # same lock), so the drain below cannot race a late put
        self._queue.put(_SENTINEL)
        self._worker.join(timeout=timeout)
        shed = ServingUnavailable("micro-batcher closed while queued")
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _SENTINEL and not item.future.done():
                self.stats_local["shed"] += 1
                tel.counter_add("serve.shed")
                item.future.set_exception(shed)
        if self._worker.is_alive():
            # join timed out mid-group and the drain may have eaten the
            # sentinel — re-post it so the worker exits instead of
            # spinning on an empty queue forever
            self._queue.put(_SENTINEL)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
