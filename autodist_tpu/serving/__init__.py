"""Serving subsystem: strategy-compiled batched inference.

The training-side machinery — one strategy compiler turning a
single-device program into a distributed one, the Remapper, telemetry,
the resilient control plane — applied to inference traffic
(ROADMAP open item 5; docs/serving.md):

- :class:`~autodist_tpu.serving.engine.InferenceEngine` — forward-only
  donated-buffer programs derived from the evaluate path, one compiled
  specialization per padded batch-bucket size, so steady-state serving
  never recompiles; PS-backed strategies serve from a host-PS snapshot
  with staleness-window degradation when the control plane blips.
- :class:`~autodist_tpu.serving.batcher.MicroBatcher` — a request queue
  in front of the engine: concurrent requests accumulate up to a max
  batch or a deadline (``max_delay_ms``), pad to the nearest bucket, and
  fan results back out per request; queue overflow and exhausted
  degradation windows shed load with a typed
  :class:`ServingUnavailable` instead of hanging.
- per-request observability: ``serve.enqueue/batch/dispatch/readback``
  spans, a ``serve.queue_depth`` gauge, and the ``serve.latency_ms``
  histogram feeding p50/p99 (docs/observability.md).
- drain-aware departure: ``MicroBatcher.drain`` (driven by the
  preemption plane, ``runtime/preemption.py``) stops admitting, lets
  in-flight groups complete, and sheds queued work with a typed
  ``Retry-After`` (``ADT_DRAIN_RETRY_AFTER_S``) so load balancers
  re-route instead of hammering a leaving replica.
- :class:`~autodist_tpu.serving.decode.DecodeEngine` — continuous-
  batching autoregressive decode: ONE donated fixed-shape decode-step
  program over a KV-cache slot pool, a :class:`SlotScheduler` admitting
  queued prefills into freed slots between steps (in-flight batching)
  and evicting finished sequences, zero recompiles at any occupancy
  (docs/serving.md#continuous-batching).
- load-adaptive fleet sizing:
  :class:`~autodist_tpu.serving.autoscale.FleetAutoscaler` +
  :class:`~autodist_tpu.serving.autoscale.AutoscalePolicy` close the
  loop from the serving telemetry (queue depth, p99, batch fill) to the
  elastic actuators — epoch-fenced grow-on-join under sustained
  overload, planned drain-then-shrink under sustained idle — with
  hysteresis bands and per-direction cooldowns so the fleet never
  flaps (docs/serving.md#autoscaling).
"""
from autodist_tpu.serving.engine import (InferenceEngine, ServingConfig,
                                         ServingUnavailable)
from autodist_tpu.serving.batcher import MicroBatcher, active_batchers
from autodist_tpu.serving.decode import (DecodeConfig, DecodeEngine,
                                         DecodeSetup, SlotScheduler,
                                         active_decoders)
from autodist_tpu.serving.autoscale import (AutoscalePolicy, AutoscaleSignals,
                                            FleetAutoscaler)

__all__ = ["InferenceEngine", "MicroBatcher", "ServingConfig",
           "ServingUnavailable", "active_batchers", "AutoscalePolicy",
           "AutoscaleSignals", "FleetAutoscaler", "DecodeConfig",
           "DecodeEngine", "DecodeSetup", "SlotScheduler",
           "active_decoders"]
