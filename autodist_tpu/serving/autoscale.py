"""FleetAutoscaler — the closed loop from serving telemetry to the
elastic actuators.

The robustness arc built every actuator a production serving fleet
needs — grow-on-join admission, planned drain-then-shrink (the
preemption plane's zero-``ckpt.fallback`` departure path) — and the
observability plane measures everything a controller would want: queue
depth, latency percentiles, batch fill, per-worker scrape ages. This
module closes the loop:

- :class:`AutoscalePolicy` — the pure decision function. Hysteresis
  bands (``queue_high``/``queue_low`` — between them NOTHING happens,
  so a signal oscillating across one band edge cannot flap the fleet),
  a sustain window (the signal must sit outside the band for
  ``sustain_s`` before any move), per-direction cooldowns, and hard
  ``min_replicas``/``max_replicas`` clamps. Deterministic and
  clock-injectable, so the unit matrix drives it without threads.
- :class:`FleetAutoscaler` — the actuating controller. Reads the live
  signals (the ``serve.queue_depth`` gauge, ``serve.latency_ms`` p99,
  batch fill from the live micro-batchers, per-worker ``scrape_age_s``
  via ``export.scrape_cluster``), asks the policy, and drives the
  existing actuators: **grow** publishes a grown-roster epoch
  (:func:`~autodist_tpu.runtime.elastic.admit_worker` — the same
  grow-on-join admission a relaunched worker gets), **shrink**
  publishes an advance preemption notice followed by the survivor
  epoch (:func:`~autodist_tpu.runtime.preemption.retire_worker` — the
  planned-departure path, so the leaver drains serving with a typed
  Retry-After and zero checkpoint fallback). Every decision is
  **epoch-fenced**: the actuation re-reads the membership epoch and a
  controller whose decision was computed against a stale epoch gets the
  typed :class:`~autodist_tpu.runtime.elastic.FencedOut` — dropped, so
  two racing controllers can never double-scale. A grow candidate with
  a pending ``preempt/notice`` mark is refused (counted in
  ``autoscale.refusals``): the platform is about to take that host.

Every decision — grow, shrink, hold, refusal, fenced drop — lands in
the pre-registered ``autoscale.*`` counters, an ``autoscale.decision``
span carrying the full signal snapshot as args, and a blackbox
flight-recorder event, so a post-incident dump shows exactly why the
fleet moved (docs/serving.md#autoscaling).
"""
import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from autodist_tpu import const
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging


@dataclasses.dataclass
class AutoscaleSignals:
    """One sampled snapshot of the signals the policy consumes.
    ``queue_depth`` is the ``serve.queue_depth`` gauge; ``p99_ms`` the
    ``serve.latency_ms`` p99 (None before any request); ``batch_fill``
    the realized fan-out per dispatched batch; ``tokens_per_s`` /
    ``slot_occupancy`` the decode tier's smoothed throughput and
    live-slot fraction (``serving/decode.py``; None with no decode
    engine running); ``scrape_ages`` the per-worker telemetry publish
    age (empty when the fleet scrape is not wired)."""

    queue_depth: float = 0.0
    p99_ms: Optional[float] = None
    batch_fill: Optional[float] = None
    tokens_per_s: Optional[float] = None
    slot_occupancy: Optional[float] = None
    scrape_ages: Dict[str, float] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"queue_depth": round(float(self.queue_depth), 2),
                "p99_ms": (round(float(self.p99_ms), 3)
                           if self.p99_ms is not None else None),
                "batch_fill": (round(float(self.batch_fill), 2)
                               if self.batch_fill is not None else None),
                "tokens_per_s": (round(float(self.tokens_per_s), 2)
                                 if self.tokens_per_s is not None else None),
                "slot_occupancy": (round(float(self.slot_occupancy), 3)
                                   if self.slot_occupancy is not None
                                   else None),
                "max_scrape_age_s": (round(max(self.scrape_ages.values()), 2)
                                     if self.scrape_ages else None)}


@dataclasses.dataclass
class Decision:
    """One policy verdict: ``direction`` in {"grow", "shrink", "hold"},
    the replica ``target`` it implies, and the human ``reason`` the
    blackbox/telemetry record."""

    direction: str
    target: int
    reason: str
    signals: Optional[AutoscaleSignals] = None

    def to_dict(self) -> dict:
        out = {"direction": self.direction, "target": int(self.target),
               "reason": self.reason}
        if self.signals is not None:
            out["signals"] = self.signals.to_dict()
        return out


class AutoscalePolicy:
    """Hysteresis-banded, cooldown-guarded scaling policy.

    The band: ``queue_depth > queue_high`` (or ``p99_ms > p99_high_ms``
    when set) is OVERLOAD; ``queue_depth <= queue_low`` (and ``p99``
    below ``p99_high_ms``, and batch fill below ``fill_low`` when set)
    is IDLE; anything between is IN-BAND and resets both sustain
    timers — the gap between ``queue_low`` and ``queue_high`` is what
    keeps a signal oscillating across one edge from flapping the fleet.
    A move additionally requires the condition to have been sustained
    ``sustain_s``, the per-direction cooldown to have lapsed, and the
    replica clamp to allow it. Signals staler than ``stale_signal_s``
    (any worker's ``scrape_age_s``) force a hold — a controller must
    not scale a fleet it cannot currently see.

    ``decide`` never mutates the cooldown stamps itself: the actuator
    confirms a move with :meth:`note_scaled` AFTER it actually landed,
    so a refused or fenced decision does not burn a cooldown."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 8,
                 queue_high: float = 64.0, queue_low: float = 4.0,
                 p99_high_ms: Optional[float] = None,
                 fill_low: Optional[float] = None,
                 sustain_s: float = 5.0,
                 grow_cooldown_s: float = 30.0,
                 shrink_cooldown_s: float = 120.0,
                 stale_signal_s: Optional[float] = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1, got %d"
                             % min_replicas)
        if max_replicas < min_replicas:
            raise ValueError(
                "max_replicas %d < min_replicas %d — the clamp is empty"
                % (max_replicas, min_replicas))
        if queue_low >= queue_high:
            raise ValueError(
                "hysteresis band is empty: queue_low %.1f >= queue_high "
                "%.1f — a signal on the edge would flap grow/shrink"
                % (queue_low, queue_high))
        if sustain_s < 0 or grow_cooldown_s < 0 or shrink_cooldown_s < 0:
            raise ValueError("sustain/cooldown windows must be >= 0")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.p99_high_ms = p99_high_ms
        self.fill_low = fill_low
        self.sustain_s = float(sustain_s)
        self.grow_cooldown_s = float(grow_cooldown_s)
        self.shrink_cooldown_s = float(shrink_cooldown_s)
        self.stale_signal_s = stale_signal_s
        # sustain state: when the signal FIRST left the band in each
        # direction (None = currently in-band in that direction)
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._last_grow = float("-inf")
        self._last_shrink = float("-inf")

    # ------------------------------------------------------------- verdict

    def decide(self, signals: AutoscaleSignals, replicas: int,
               now: Optional[float] = None) -> Decision:
        now = time.monotonic() if now is None else now
        if self.stale_signal_s is not None and signals.scrape_ages:
            worst = max(signals.scrape_ages.values())
            if worst > self.stale_signal_s:
                # blind controller: reset sustain (the window must be
                # measured, not assumed) and refuse to move
                self._above_since = self._below_since = None
                return Decision("hold", replicas,
                                "telemetry stale (%.1fs > %.1fs) — "
                                "refusing to scale blind"
                                % (worst, self.stale_signal_s), signals)
        overloaded = signals.queue_depth > self.queue_high or (
            self.p99_high_ms is not None and signals.p99_ms is not None
            and signals.p99_ms > self.p99_high_ms)
        idle = (not overloaded
                and signals.queue_depth <= self.queue_low
                and (self.fill_low is None or signals.batch_fill is None
                     or signals.batch_fill <= self.fill_low))
        if overloaded:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since < self.sustain_s:
                return Decision("hold", replicas,
                                "overload not yet sustained "
                                "(%.2fs/%.2fs)"
                                % (now - self._above_since,
                                   self.sustain_s), signals)
            if replicas >= self.max_replicas:
                return Decision("hold", replicas,
                                "overloaded but at max_replicas %d"
                                % self.max_replicas, signals)
            if now - self._last_grow < self.grow_cooldown_s:
                return Decision("hold", replicas,
                                "grow cooldown (%.2fs/%.2fs)"
                                % (now - self._last_grow,
                                   self.grow_cooldown_s), signals)
            return Decision("grow", replicas + 1,
                            "queue/p99 above band for >= %.2fs"
                            % self.sustain_s, signals)
        if idle:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since < self.sustain_s:
                return Decision("hold", replicas,
                                "idle not yet sustained (%.2fs/%.2fs)"
                                % (now - self._below_since,
                                   self.sustain_s), signals)
            if replicas <= self.min_replicas:
                return Decision("hold", replicas,
                                "idle but at min_replicas %d"
                                % self.min_replicas, signals)
            if now - self._last_shrink < self.shrink_cooldown_s:
                return Decision("hold", replicas,
                                "shrink cooldown (%.2fs/%.2fs)"
                                % (now - self._last_shrink,
                                   self.shrink_cooldown_s), signals)
            return Decision("shrink", replicas - 1,
                            "idle below band for >= %.2fs"
                            % self.sustain_s, signals)
        # IN-BAND: the hysteresis gap. Reset both sustain timers — a
        # brief excursion must re-earn its full sustain window.
        self._above_since = self._below_since = None
        return Decision("hold", replicas, "in-band", signals)

    def note_scaled(self, direction: str, now: Optional[float] = None):
        """Stamp the cooldown for a move that actually LANDED (called by
        the actuator, never by :meth:`decide`) and reset the sustain
        timers — the post-scale signal must re-earn its window."""
        now = time.monotonic() if now is None else now
        if direction == "grow":
            self._last_grow = now
        elif direction == "shrink":
            self._last_shrink = now
        self._above_since = self._below_since = None


def lint_policy(policy: AutoscalePolicy, strategy=None,
                max_queue: Optional[int] = None, raise_on_error: bool = True):
    """Static soundness check of a policy against the strategy it will
    scale (``analysis/rules.verify_autoscale`` — ADT440/ADT441): a
    ``min_replicas`` below the fail-fast family's floor would drive the
    shrink path into checkpoint-fallback territory the planned-departure
    contract forbids. Returns the diagnostics; raises the first
    error-severity one as :class:`DiagnosticError` by default."""
    from autodist_tpu.analysis import rules
    from autodist_tpu.analysis.diagnostics import (DiagnosticError, Severity,
                                                   has_errors)
    diags = rules.verify_autoscale(policy, strategy=strategy,
                                   max_queue=max_queue)
    if raise_on_error and has_errors(diags):
        raise DiagnosticError(next(d for d in diags
                                   if d.severity >= Severity.ERROR))
    return diags


class FleetAutoscaler:
    """The actuating half: signals -> :class:`AutoscalePolicy` ->
    elastic actuators, epoch-fenced.

    ``client`` is a coordination client on the service holding the
    membership epoch; ``worker`` is this controller's identity (the
    chief — never chosen as a shrink victim); ``pool`` the spare worker
    addresses eligible for grow-on-join. ``scrape_workers`` (optional)
    arms the per-worker ``scrape_age_s`` signal via
    ``export.scrape_cluster``. ``signals_fn`` overrides signal
    collection entirely (tests, remote controllers)."""

    def __init__(self, client, policy: AutoscalePolicy, worker: str,
                 pool: Sequence[str] = (),
                 scrape_workers: Optional[Sequence[str]] = None,
                 signals_fn: Optional[Callable[[], AutoscaleSignals]] = None,
                 notice_deadline_s: Optional[float] = None,
                 strategy=None, max_queue: Optional[int] = None):
        self._client = client
        self.policy = policy
        self.worker = worker
        self.pool = list(pool)
        self._scrape_workers = (list(scrape_workers)
                                if scrape_workers else None)
        self._signals_fn = signals_fn or self._default_signals
        self._notice_deadline_s = notice_deadline_s
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._stats = {"decisions": 0, "grows": 0, "shrinks": 0,
                       "holds": 0, "refusals": 0, "fenced": 0,
                       "epoch": None, "replicas": None, "last": None}
        self._stats_lock = threading.Lock()
        # unsound bounds fail at CONSTRUCTION, not at the 3 a.m. shrink
        lint_policy(policy, strategy=strategy, max_queue=max_queue)

    # ------------------------------------------------------------- signals

    @staticmethod
    def _default_signals() -> AutoscaleSignals:
        from autodist_tpu.serving import batcher as batcher_lib
        depth = 0.0
        fill_n = fill_b = 0
        for mb in batcher_lib.active_batchers():
            local = mb.stats_local
            depth += mb.queue_depth()
            fill_n += local.get("fan_out", 0)
            fill_b += local.get("batches", 0)
        if not batcher_lib.active_batchers():
            depth = tel.gauges().get("serve.queue_depth", 0.0)
        # decode-tier signals (continuous batching, serving/decode.py):
        # queued prompts join the shared backlog; throughput/occupancy
        # aggregate over live engines, falling back to the gauges a
        # remote scrape would have merged
        from autodist_tpu.serving import decode as decode_lib
        decoders = decode_lib.active_decoders()
        tokens_per_s = None
        occupancy = None
        if decoders:
            rates = [d.tokens_per_s() for d in decoders]
            rates = [r for r in rates if r is not None]
            tokens_per_s = sum(rates) if rates else None
            occupancy = (sum(d.scheduler.occupancy() for d in decoders)
                         / len(decoders))
            depth += sum(d.queue_depth() for d in decoders)
        else:
            g = tel.gauges()
            tokens_per_s = g.get("serve.tokens_per_s")
            occupancy = g.get("serve.slot_occupancy")
        return AutoscaleSignals(
            queue_depth=depth,
            p99_ms=tel.hist_quantile("serve.latency_ms", 0.99),
            batch_fill=(fill_n / fill_b) if fill_b else None,
            tokens_per_s=tokens_per_s,
            slot_occupancy=occupancy)

    def signals(self) -> AutoscaleSignals:
        sig = self._signals_fn()
        if self._scrape_workers and not sig.scrape_ages:
            try:
                from autodist_tpu.telemetry import export
                scrape = export.scrape_cluster(self._client,
                                               self._scrape_workers)
                sig.scrape_ages = {
                    w: float(a) for w, a in
                    (scrape.get("scrape_age_s") or {}).items()
                    if a is not None}
            except (OSError, RuntimeError) as e:
                logging.warning("autoscale: fleet scrape failed (%s) — "
                                "deciding on local signals", e)
        return sig

    # -------------------------------------------------------------- loop

    def step(self, now: Optional[float] = None) -> Decision:
        """One control iteration: sample -> decide -> (fenced) actuate.
        A :class:`FencedOut` from the actuation is DROPPED here — the
        epoch moved under the decision, so the decision is void and the
        next iteration re-reads the world; it never half-applies."""
        from autodist_tpu.runtime.elastic import FencedOut, read_epoch
        now = time.monotonic() if now is None else now
        info = read_epoch(self._client)
        if info is None:
            raise RuntimeError(
                "autoscale: no membership epoch published — the fleet "
                "has no roster to scale (publish_epoch first)")
        epoch, roster = info
        sig = self.signals()
        decision = self.policy.decide(sig, replicas=len(roster), now=now)
        with tel.span("autoscale.decision", "autoscale",
                      direction=decision.direction, epoch=epoch,
                      replicas=len(roster), reason=decision.reason,
                      **(sig.to_dict())):
            try:
                decision = self._actuate(decision, epoch, roster, now)
            except FencedOut as e:
                from autodist_tpu.telemetry import blackbox
                tel.instant("autoscale.fenced", "autoscale", op=e.op,
                            mine=e.my_epoch, current=e.current_epoch)
                blackbox.record("autoscale.fenced", op=e.op,
                                mine=e.my_epoch, current=e.current_epoch)
                logging.warning("autoscale: decision dropped — %s", e)
                with self._stats_lock:
                    self._stats["fenced"] += 1
                decision = Decision("hold", len(roster),
                                    "fenced out: %s" % e, sig)
        with self._stats_lock:
            self._stats["decisions"] += 1
            self._stats["epoch"] = epoch
            self._stats["replicas"] = len(roster)
            self._stats["last"] = decision.to_dict()
        return decision

    def _fence(self, op: str, observed_epoch: int, roster: Sequence[str]):
        """The decision was computed against ``observed_epoch``; refuse
        to actuate against any other — a racing controller (or the
        chief's own watchdog) moved the fleet first, and applying a
        stale verdict on top would double-scale. Also honors the
        process-ambient membership fence (a fenced zombie process must
        not scale anything)."""
        from autodist_tpu.runtime import elastic
        elastic.maybe_fence(op)
        current = elastic.read_epoch(self._client)
        if current is not None and current[0] != observed_epoch:
            raise elastic.FencedOut(op, observed_epoch, current[0],
                                    worker=self.worker, roster=roster)

    def _actuate(self, decision: Decision, epoch: int,
                 roster: Sequence[str], now: float) -> Decision:
        from autodist_tpu.telemetry import blackbox
        if decision.direction == "grow":
            candidate = self._grow_candidate(list(roster))
            if candidate is None:
                tel.counter_add("autoscale.holds")
                return Decision("hold", len(roster),
                                "no admissible grow candidate "
                                "(pool exhausted or pending notices)",
                                decision.signals)
            self._fence("autoscale.grow", epoch, roster)
            from autodist_tpu.runtime import elastic
            new_epoch = elastic.admit_worker(self._client, candidate)
            self.policy.note_scaled("grow", now)
            tel.counter_add("autoscale.grows")
            with self._stats_lock:
                self._stats["grows"] += 1
            blackbox.record("autoscale.grow", worker=candidate,
                            epoch=new_epoch, replicas=len(roster) + 1,
                            reason=decision.reason)
            logging.warning("autoscale: grew fleet to %d replicas "
                            "(admitted %s at epoch %d): %s",
                            len(roster) + 1, candidate, new_epoch,
                            decision.reason)
            return decision
        if decision.direction == "shrink":
            leaver = self._shrink_victim(list(roster))
            if leaver is None:
                tel.counter_add("autoscale.holds")
                return Decision("hold", len(roster),
                                "no shrinkable replica (controller is "
                                "the only member)", decision.signals)
            self._fence("autoscale.shrink", epoch, roster)
            from autodist_tpu.runtime import preemption
            preemption.retire_worker(self._client, leaver,
                                     deadline_s=self._notice_deadline_s,
                                     reason="autoscale-idle")
            self.policy.note_scaled("shrink", now)
            tel.counter_add("autoscale.shrinks")
            with self._stats_lock:
                self._stats["shrinks"] += 1
            blackbox.record("autoscale.shrink", worker=leaver,
                            replicas=len(roster) - 1,
                            reason=decision.reason)
            logging.warning("autoscale: shrinking fleet to %d replicas "
                            "(retiring %s via planned departure): %s",
                            len(roster) - 1, leaver, decision.reason)
            return decision
        tel.counter_add("autoscale.holds")
        with self._stats_lock:
            self._stats["holds"] += 1
        return decision

    def _grow_candidate(self, roster: List[str]) -> Optional[str]:
        """First pool worker not already in the roster and NOT under a
        pending preemption notice — growing onto a host the platform is
        about to take would be a scale event that immediately unwinds
        (refusals counted, so the blocked state is visible)."""
        from autodist_tpu.runtime import elastic, preemption
        from autodist_tpu.telemetry import blackbox
        candidates = [w for w in self.pool if w not in roster]
        # a worker that ASKED for admission (announce_join) goes first —
        # it is provisioned and waiting, not a cold spare
        candidates.sort(key=lambda w: not elastic.pending_join(
            self._client, w))
        for cand in candidates:
            if preemption.read_notice(self._client, cand) is not None:
                tel.counter_add("autoscale.refusals")
                tel.instant("autoscale.refusal", "autoscale", worker=cand)
                blackbox.record("autoscale.refusal", worker=cand,
                                why="pending preemption notice")
                with self._stats_lock:
                    self._stats["refusals"] += 1
                logging.warning("autoscale: refusing to grow onto %s — "
                                "pending preemption notice", cand)
                continue
            return cand
        return None

    def _shrink_victim(self, roster: List[str]) -> Optional[str]:
        """Last non-controller roster member — LIFO, so the longest-
        standing members (the launch roster, the chief) outlive the
        surge capacity that joined them."""
        for w in reversed(roster):
            if w != self.worker:
                return w
        return None

    # ------------------------------------------------------------- thread

    def start(self, poll_s: Optional[float] = None) -> "FleetAutoscaler":
        """Run :meth:`step` on a daemon thread every ``poll_s``
        (default ``ADT_AUTOSCALE_POLL_S``). Errors are logged and the
        loop keeps polling — a controller blip must not freeze the
        fleet at its current size forever silently."""
        period = (const.ENV.ADT_AUTOSCALE_POLL_S.val
                  if poll_s is None else float(poll_s))
        self._stop = threading.Event()

        def run():
            while not self._stop.is_set():
                try:
                    self.step()
                except Exception as e:  # noqa: BLE001 — keep polling
                    logging.warning("autoscale: step failed (%s)", e)
                self._stop.wait(period)

        self._thread = threading.Thread(target=run, name="adt-autoscale",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """Stable-key controller accounting (the ``autoscale`` sub-dict
        shape ``MicroBatcher.stats()`` mirrors from the counters)."""
        with self._stats_lock:
            return dict(self._stats)


def stats_snapshot() -> dict:
    """Process-wide autoscale accounting from the pre-registered
    counters — stable keys whether or not a controller runs in this
    process (``MicroBatcher.stats()["autoscale"]``)."""
    c = tel.counters()
    return {"grows": c.get("autoscale.grows", 0.0),
            "shrinks": c.get("autoscale.shrinks", 0.0),
            "holds": c.get("autoscale.holds", 0.0),
            "refusals": c.get("autoscale.refusals", 0.0)}
