"""DecodeEngine — continuous-batching autoregressive decode over a
trained Runner (the serving half ROADMAP item 4 left open: token-by-token
generation, not just fixed-shape forward batches).

The engine compiles ONE donated, fixed-shape decode-step program
(``DistributedStep.decode_program``): params + slot-major KV caches
``[slots, layers, max_len, heads, head_dim]`` + per-slot token/cursor/
alive → next-token per slot + updated caches. Every step runs that same
executable regardless of which sequences occupy which slots — ZERO
recompiles in steady state (asserted by :meth:`recompiles_after_warmup`
and the CI ``--serve-decode`` smoke leg). Slot occupancy is pure host
bookkeeping: a finished sequence flips its ``alive`` bit and the next
admission overwrites its rows; the masked attention in
``ops.attention.cached_attention`` never reads a dead slot's garbage.

**Continuous batching** (the :class:`SlotScheduler`): between steps,
queued prompts are admitted into freed slots — prefill runs through the
existing bucketed forward path (:class:`InferenceEngine`, so it shares
the PS snapshot, degradation ladder and padded-bucket discipline with
plain serving) and the resulting caches are scattered into the live
cache by a third fixed-shape program (insert: ``cache.at[idx].set(rows,
mode="drop")`` with out-of-bounds indices for padding rows, output
sharding pinned to the decode program's so admission steps never
re-specialize it). ``admission="static"`` degrades the scheduler to the
classic static batch — admit only when EVERY slot is free — which is the
head-to-head baseline ``bench.py --serve-decode`` runs.

Shutdown is drain-aware like the micro-batcher: :meth:`drain` stops
admitting, sheds the queue typed with a Retry-After computed from the
measured completion rate, and lets in-flight sequences run to
completion. ``runtime/preemption.drain_serving`` drains live decode
engines alongside batchers.

Telemetry: ``serve.token_ms`` histogram (per-step wall time — the
per-token latency each live slot observed), ``serve.tokens`` /
``serve.prefill_admits`` / ``serve.evictions`` counters, and the
``serve.slot_occupancy`` / ``serve.tokens_per_s`` gauges the autoscaler
reads (``serving/autoscale.py``).
"""
import collections
import dataclasses
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Callable, Optional, Sequence

import numpy as np

from autodist_tpu import const
from autodist_tpu.serving.engine import (InferenceEngine, ServingConfig,
                                         ServingUnavailable)
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

# every live decode engine, so the preemption plane can drain a departing
# process's decode tier without threading references through it
_ACTIVE: "weakref.WeakSet" = weakref.WeakSet()

# Retry-After clamp band, shared with the micro-batcher's
_RETRY_MIN_S = 0.05
_RETRY_MAX_S = 60.0
_RATE_ALPHA = 0.3


def active_decoders() -> list:
    """The process's live decode engines (drained on planned departure
    by ``runtime/preemption.py``)."""
    return list(_ACTIVE)


@dataclasses.dataclass
class DecodeSetup:
    """The model-side decode contract (``models/lm.make_decode_setup``).

    ``prefill_fn(params, {"tokens": [B, P], "length": [B]})`` returns
    ``{"next_token": [B] int32, "k": [B, layers, max_len, heads, dim],
    "v": ...}`` — the first generated token plus the prompt's caches.
    ``decode_fn(params, dstate)`` is the step: dstate carries ``k``/
    ``v`` slot caches plus per-slot ``token``/``cursor``/``alive`` and
    returns updated caches + ``next_token``. ``init_dstate(slots)``
    builds the zeroed host state fixing every shape."""

    prefill_fn: Callable
    decode_fn: Callable
    init_dstate: Callable
    max_len: int
    vocab_size: int


@dataclasses.dataclass
class DecodeConfig:
    """Slot-engine knobs (docs/serving.md "Continuous batching").

    ``slots``: decode batch width — must split evenly over the mesh's
    batch axes. ``max_new_tokens``: per-request generation cap (a submit
    may lower it). ``prefill_len``: the fixed padded prompt length every
    prefill dispatch runs at (prompts longer than this are rejected
    typed). ``prefill_buckets``: padded prefill group sizes (None =
    {1, slots} rounded to replica multiples). ``eos_id``: token ending a
    sequence early (None = length-only stopping). ``admission``:
    "continuous" (admit into any freed slot between steps) or "static"
    (admit only when ALL slots are free — the baseline bench compares
    against). ``max_queue``: backpressure bound on queued prompts.
    ``hbm_budget_bytes``: arms the ADT442 cache-vs-HBM projection lint
    at construction (None skips it)."""

    slots: int = 8
    max_new_tokens: int = 32
    prefill_len: int = 16
    prefill_buckets: Optional[Sequence[int]] = None
    eos_id: Optional[int] = None
    admission: str = "continuous"
    max_queue: int = 1024
    snapshot_max_age_s: float = 0.1
    hbm_budget_bytes: Optional[float] = None

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError("slots must be >= 1")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.prefill_len < 1:
            raise ValueError("prefill_len must be >= 1")
        if self.admission not in ("continuous", "static"):
            raise ValueError("admission must be 'continuous' or 'static', "
                             "got %r" % (self.admission,))
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")


class _Request:
    __slots__ = ("prompt", "max_new", "future", "t0")

    def __init__(self, prompt, max_new: int):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.future = Future()
        self.t0 = time.perf_counter()


class _Slot:
    """One in-flight sequence: its request, the tokens generated so far,
    and how many more it may emit."""
    __slots__ = ("req", "generated", "remaining")

    def __init__(self, req: _Request, first_token: int):
        self.req = req
        self.generated = [int(first_token)]
        self.remaining = req.max_new - 1


class SlotScheduler:
    """Host-side slot bookkeeping + admission policy. Pure state machine
    — no device work — so admission/eviction semantics are unit-testable
    without a compiled engine.

    Lifecycle of a slot: FREE → (admit: prefill seeds cache, cursor =
    prompt_len, first token already generated) → LIVE (each step appends
    one token, cursor advances) → evicted on EOS / per-request token cap
    / cache exhaustion (cursor reaching max_len) → FREE again; the next
    admission overwrites the rows, nothing is ever zeroed."""

    def __init__(self, slots: int, admission: str = "continuous"):
        self.n_slots = int(slots)
        self.admission = admission
        self._slots: list = [None] * self.n_slots

    def free_slots(self) -> list:
        return [i for i, s in enumerate(self._slots) if s is None]

    def live_slots(self) -> list:
        return [i for i, s in enumerate(self._slots) if s is not None]

    def occupancy(self) -> float:
        return (self.n_slots - len(self.free_slots())) / self.n_slots

    def admissible(self, queued: int) -> int:
        """How many queued prompts the policy admits right now.
        Continuous: any freed slot takes work. Static: only a fully
        drained batch re-admits (the classic static-batching idle)."""
        free = len(self.free_slots())
        if self.admission == "static" and free != self.n_slots:
            return 0
        return min(free, queued)

    def occupy(self, idx: int, slot: _Slot):
        assert self._slots[idx] is None
        self._slots[idx] = slot

    def get(self, idx: int) -> Optional[_Slot]:
        return self._slots[idx]

    def evict(self, idx: int) -> _Slot:
        slot = self._slots[idx]
        self._slots[idx] = None
        return slot


class DecodeEngine:
    """Continuous-batching decode over a built (initialized) Runner.

    Composes an :class:`InferenceEngine` for the prefill leg (bucketed,
    snapshot-degradation-aware) and the decode-step / cache-insert
    programs for the token loop. One worker thread owns the loop:
    admit → step → account → evict, forever; callers interact only
    through :meth:`submit` futures."""

    def __init__(self, runner, setup: DecodeSetup,
                 config: Optional[DecodeConfig] = None):
        self._runner = runner
        self._dstep = runner.distributed_step
        self.setup = setup
        self.config = config or DecodeConfig()
        cfg = self.config
        if cfg.prefill_len > setup.max_len:
            raise ValueError(
                "prefill_len %d exceeds the model's max_len %d"
                % (cfg.prefill_len, setup.max_len))
        self.scheduler = SlotScheduler(cfg.slots, cfg.admission)

        # prefill rides the EXISTING bucketed forward path: shared PS
        # snapshot + degradation ladder + padded-bucket discipline
        replicas = runner.remapper.num_replicas
        buckets = cfg.prefill_buckets
        if buckets is None:
            r = max(replicas, 1)
            buckets = sorted({max(-(-b // r), 1) * r
                              for b in (1, cfg.slots)})
        example_req = {"tokens": np.zeros(cfg.prefill_len, np.int32),
                       "length": np.zeros((), np.int32)}
        self._prefill = InferenceEngine(
            runner, setup.prefill_fn, example_req,
            ServingConfig(buckets=buckets,
                          snapshot_max_age_s=cfg.snapshot_max_age_s))

        # the ONE decode-step program (fixed shapes, state donated)
        example_dstate = setup.init_dstate(cfg.slots)
        self._decode_prog = self._dstep.decode_program(
            setup.decode_fn, example_dstate)
        self._cache_dtype = example_dstate["k"].dtype
        self._cache_shape = example_dstate["k"].shape  # [S, L, T, H, D]

        # cache-insert program: scatter freshly prefilled rows into the
        # donated live caches. Output shardings are pinned to the decode
        # program's slot sharding so an admission step feeds the decode
        # jit the exact arrays it expects — no re-specialization
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        shard = NamedSharding(self._dstep.mesh, P(self._dstep.batch_axes))

        def _insert(k, v, idx, pk, pv):
            return (k.at[idx].set(pk, mode="drop"),
                    v.at[idx].set(pv, mode="drop"))

        self._insert_prog = jax.jit(_insert, donate_argnums=(0, 1),
                                    out_shardings=(shard, shard))

        # device-resident cache halves (donated through every step) +
        # host-managed per-slot arrays (fixed shapes, re-placed per
        # dispatch — numpy placement follows the compiled sharding, so
        # this is recompile-free too)
        self._dev_k = example_dstate["k"]
        self._dev_v = example_dstate["v"]
        self._token = np.array(example_dstate["token"])
        self._cursor = np.array(example_dstate["cursor"])
        self._alive = np.array(example_dstate["alive"])

        self._cv = threading.Condition()
        self._pending: "collections.deque" = collections.deque()
        self._closing = False
        self._retry_after: Optional[float] = None
        self._complete_rate: Optional[float] = None  # requests/s EWMA
        self._last_complete_t: Optional[float] = None
        self._token_rate: Optional[float] = None  # tokens/s EWMA
        self._token_ms: list = []
        self.stats_local = {"steps": 0, "tokens": 0, "prefill_admits": 0,
                            "evictions": 0, "completed": 0, "shed": 0,
                            "drained": 0, "errors": 0}
        self._peak_occupancy = 0.0
        self._warmed = False
        self._caches_after_warmup = None
        self._lint_hbm()
        self._worker = threading.Thread(target=self._run,
                                        name="adt-serve-decode",
                                        daemon=True)
        self._worker.start()
        _ACTIVE.add(self)

    # ----------------------------------------------------------- lint

    def _lint_hbm(self):
        """ADT442 at construction: does max_len x slots of KV cache (+
        the gathered full params the decode step holds) project past the
        HBM budget? Warned now, not at the allocation that OOMs."""
        if self.config.hbm_budget_bytes is None:
            return
        from autodist_tpu.analysis import rules
        cache_bytes = 2 * int(np.prod(self._cache_shape)) * \
            np.dtype(self._cache_dtype).itemsize
        param_bytes = float(self._dstep.model_item.total_bytes())
        for d in rules.verify_decode(
                cache_bytes, param_bytes=param_bytes,
                slots=self.config.slots, max_len=self.setup.max_len,
                replicas=self._runner.remapper.num_replicas,
                budget_bytes=self.config.hbm_budget_bytes):
            logging.warning("%s: %s", d.code, d.message)

    # --------------------------------------------------------- warmup

    def warmup(self):
        """Compile every program once: each prefill bucket, the decode
        step (on the empty all-dead state), and the cache insert (on
        all-out-of-bounds indices — a no-op scatter). After this,
        steady-state decode is recompile-free regardless of admissions,
        evictions or occupancy — :meth:`recompiles_after_warmup`."""
        self._prefill.warmup()
        with self._cv:
            with tel.span("serve.decode_warmup", "serve"):
                # step -> insert -> step: the first step compiles the
                # host-fed (uncommitted) cache specialization, the
                # insert compiles on committed device caches, and the
                # SECOND step compiles the committed-cache
                # specialization steady state actually runs — without
                # it the first real step after warmup would count as a
                # recompile
                self._dispatch_step()
                self._dispatch_insert(
                    np.full(self.config.slots, self.config.slots, np.int32),
                    np.zeros(self._cache_shape, self._cache_dtype),
                    np.zeros(self._cache_shape, self._cache_dtype))
                self._dispatch_step()
            # warmup's fake step must not leak into the accounting the
            # bench and smoke legs assert on
            self.stats_local["steps"] = 0
            self.stats_local["tokens"] = 0
            self._token_ms.clear()
            self._warmed = True
            self._caches_after_warmup = self._jit_cache_sizes()
        return self

    def _jit_cache_sizes(self) -> Optional[int]:
        sizes = []
        for prog in (self._decode_prog.fn, self._insert_prog):
            cs = getattr(prog, "_cache_size", None)
            sizes.append(cs() if callable(cs) else None)
        if any(s is None for s in sizes):
            return None
        return sum(sizes)

    def recompiles_after_warmup(self) -> int:
        """Compiled-specialization growth since :meth:`warmup` across
        ALL THREE programs (prefill buckets + decode step + insert) —
        the zero-recompile continuous-batching contract."""
        n = self._prefill.recompiles_after_warmup()
        if self._caches_after_warmup is not None:
            now = self._jit_cache_sizes()
            n += max(0, (now or 0) - self._caches_after_warmup)
        return n

    # --------------------------------------------------------- submit

    def submit(self, prompt, max_new_tokens: Optional[int] = None) -> Future:
        """Enqueue one prompt (1-D int token ids); resolves to
        ``{"tokens": generated ids (int32, EOS included when hit),
        "prompt_len": int, "finished": "eos"|"length"}``. Sheds typed
        with :class:`ServingUnavailable` (Retry-After from the measured
        completion rate) when the queue is full or the engine is
        draining. Prompts longer than ``prefill_len`` are rejected —
        the prefill program's shape is fixed."""
        req = _Request(prompt, max_new_tokens or self.config.max_new_tokens)
        n = req.prompt.shape[0]
        if not 1 <= n <= self.config.prefill_len:
            raise ValueError(
                "prompt length %d outside [1, prefill_len=%d]"
                % (n, self.config.prefill_len))
        if n >= self.setup.max_len:
            raise ValueError(
                "prompt length %d leaves no cache room under max_len %d"
                % (n, self.setup.max_len))
        with self._cv:
            if self._closing:
                retry = (self._retry_after
                         if self._retry_after is not None
                         else const.ENV.ADT_DRAIN_RETRY_AFTER_S.val)
                raise ServingUnavailable(
                    "decode engine is draining (Retry-After %.1fs)" % retry,
                    retry_after_s=retry)
            depth = len(self._pending)
            if depth >= self.config.max_queue:
                retry = self._computed_retry_after(depth)
                self.stats_local["shed"] += 1
                tel.counter_add("serve.shed")
                raise ServingUnavailable(
                    "decode queue full (%d pending) — shedding "
                    "(Retry-After %.2fs)" % (depth, retry),
                    retry_after_s=retry)
            self._pending.append(req)
            tel.counter_add("serve.requests")
            self._cv.notify()
        return req.future

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None) -> dict:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(prompt, max_new_tokens).result(timeout=timeout)

    def _computed_retry_after(self, depth: int) -> float:
        """Retry-After from the measured completion rate (sequences/s
        EWMA): backlog over throughput, clamped to the same sane band
        the micro-batcher uses; the operator drain knob before any
        measurement exists."""
        rate = self._complete_rate
        if not rate or rate <= 0:
            base = const.ENV.ADT_DRAIN_RETRY_AFTER_S.val
        else:
            base = depth / rate
        return min(max(base, _RETRY_MIN_S), _RETRY_MAX_S)

    # ---------------------------------------------------------- worker

    def _run(self):
        while True:
            with self._cv:
                while (not self._pending and not self.scheduler.live_slots()
                       and not self._closing):
                    self._cv.wait(timeout=0.1)
                if (self._closing and not self._pending
                        and not self.scheduler.live_slots()):
                    break
                n_adm = self.scheduler.admissible(len(self._pending))
                n_adm = min(n_adm, self._prefill.max_batch)
                group = [self._pending.popleft() for _ in range(n_adm)]
            try:
                if group:
                    self._admit(group)
                if self.scheduler.live_slots():
                    self._step()
            except ServingUnavailable as e:
                # typed shed (snapshot degradation exhausted): fail the
                # admitted group, keep the loop alive — in-flight slots
                # and later refresh attempts are unaffected
                for r in group:
                    if not r.future.done():
                        r.future.set_exception(e)
                self.stats_local["shed"] += len(group)
                tel.counter_add("serve.shed", len(group))
            except Exception as e:  # noqa: BLE001 — a poisoned dispatch
                # must not silently kill the loop and hang every future
                self.stats_local["errors"] += 1
                logging.warning("decode step failed: %s", e)
                for r in group:
                    if not r.future.done():
                        r.future.set_exception(e)
            occ = self.scheduler.occupancy()
            self._peak_occupancy = max(self._peak_occupancy, occ)
            tel.gauge_set("serve.slot_occupancy", occ)

    # -------------------------------------------------------- admission

    def _admit(self, group):
        """Prefill a request group through the bucketed forward path and
        scatter the caches into freed slots (in-flight batching: live
        slots keep decoding across this boundary untouched)."""
        cfg = self.config
        feeds = []
        for r in group:
            toks = np.zeros(cfg.prefill_len, np.int32)
            toks[:r.prompt.shape[0]] = r.prompt
            feeds.append({"tokens": toks,
                          "length": np.asarray(r.prompt.shape[0], np.int32)})
        with tel.span("serve.prefill", "serve", n=len(group)):
            fetched, n = self._prefill.run_batch(feeds)
        idx = np.full(cfg.slots, cfg.slots, np.int32)  # OOB rows drop
        pk = np.zeros(self._cache_shape, self._cache_dtype)
        pv = np.zeros(self._cache_shape, self._cache_dtype)
        free = self.scheduler.free_slots()
        admitted = 0
        for j, r in enumerate(group):
            first = int(np.asarray(fetched["next_token"])[j])
            plen = r.prompt.shape[0]
            slot = _Slot(r, first)
            # a request satisfied by its prefill alone (cap of 1, or EOS
            # first token) never occupies a slot
            done = self._finished(slot, plen)
            if done:
                self._resolve(slot, plen, done)
            else:
                s = free[admitted]
                idx[admitted] = s
                pk[admitted] = np.asarray(fetched["k"])[j]
                pv[admitted] = np.asarray(fetched["v"])[j]
                self.scheduler.occupy(s, slot)
                self._token[s] = first
                self._cursor[s] = plen
                self._alive[s] = True
                admitted += 1
        if admitted:
            self._dispatch_insert(idx, pk, pv)
        self.stats_local["prefill_admits"] += len(group)
        tel.counter_add("serve.prefill_admits", len(group))
        # every prefill emits each request's first token
        self.stats_local["tokens"] += len(group)
        tel.counter_add("serve.tokens", len(group))

    def _dispatch_insert(self, idx, pk, pv):
        self._dev_k, self._dev_v = self._insert_prog(
            self._dev_k, self._dev_v, idx, pk, pv)

    def _finished(self, slot: _Slot, next_row: int) -> Optional[str]:
        """Eviction verdict AFTER ``slot.generated[-1]`` was produced:
        EOS, the per-request cap, or the cache running out of rows
        (``next_row`` — where another step would write — past the
        cache)."""
        if (self.config.eos_id is not None
                and slot.generated[-1] == self.config.eos_id):
            return "eos"
        if slot.remaining <= 0:
            return "length"
        if next_row >= self.setup.max_len:
            return "length"
        return None

    def _resolve(self, slot: _Slot, prompt_len: int, finished: str):
        slot.req.future.set_result({
            "tokens": np.asarray(slot.generated, np.int32),
            "prompt_len": int(prompt_len),
            "finished": finished})
        self.stats_local["evictions"] += 1
        self.stats_local["completed"] += 1
        tel.counter_add("serve.evictions")
        now = time.perf_counter()
        if self._last_complete_t is not None:
            dt = now - self._last_complete_t
            if dt > 0:
                rate = 1.0 / dt
                self._complete_rate = (
                    rate if self._complete_rate is None else
                    _RATE_ALPHA * rate
                    + (1 - _RATE_ALPHA) * self._complete_rate)
        self._last_complete_t = now

    # ------------------------------------------------------------ step

    def _dispatch_step(self) -> np.ndarray:
        """One decode-step dispatch on the current state; returns the
        [slots] next-token vector (the step's ONLY D2H — one int32 per
        slot)."""
        state = self._runner.state
        if state is None:
            raise RuntimeError("DecodeEngine over an uninitialized Runner "
                               "— call runner.init() first")
        with self._prefill._lock:
            ps_vals = self._prefill._snapshot()
        dstate = {"k": self._dev_k, "v": self._dev_v,
                  "token": self._token.copy(),
                  "cursor": self._cursor.copy(),
                  "alive": self._alive.copy()}
        out = self._decode_prog(state, ps_vals, dstate)
        self._dev_k, self._dev_v = out["k"], out["v"]
        return np.asarray(out["next_token"])

    def _step(self):
        live = self.scheduler.live_slots()
        t0 = time.perf_counter()
        with tel.span("serve.decode_step", "serve", live=len(live)):
            next_tok = self._dispatch_step()
        step_ms = (time.perf_counter() - t0) * 1e3
        # the step's wall time IS each live slot's per-token latency
        tel.hist_observe("serve.token_ms", step_ms)
        self._token_ms.append(step_ms)
        if len(self._token_ms) > 10000:
            del self._token_ms[:5000]
        self.stats_local["steps"] += 1
        self.stats_local["tokens"] += len(live)
        tel.counter_add("serve.tokens", len(live))
        inst = len(live) / max(step_ms / 1e3, 1e-9)
        self._token_rate = (inst if self._token_rate is None else
                            _RATE_ALPHA * inst
                            + (1 - _RATE_ALPHA) * self._token_rate)
        tel.gauge_set("serve.tokens_per_s", self._token_rate)
        for s in live:
            slot = self.scheduler.get(s)
            slot.generated.append(int(next_tok[s]))
            slot.remaining -= 1
            self._token[s] = next_tok[s]
            self._cursor[s] += 1
            done = self._finished(slot, int(self._cursor[s]))
            if done:
                self.scheduler.evict(s)
                self._alive[s] = False
                self._resolve(slot, slot.req.prompt.shape[0], done)

    # ----------------------------------------------------------- stats

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._pending)

    def tokens_per_s(self) -> Optional[float]:
        """Smoothed decode throughput (the ``serve.tokens_per_s`` gauge
        feeding the autoscaler)."""
        return self._token_rate

    def stats(self) -> dict:
        """Decode accounting + the composed prefill engine's, plus
        per-token latency percentiles over recent steps (None before
        any step)."""
        out = {"prefill": dict(self._prefill.stats)}
        out.update(self.stats_local)
        ms = self._token_ms
        out.update(
            slots=self.config.slots,
            admission=self.config.admission,
            queue_depth=self.queue_depth(),
            slot_occupancy=self.scheduler.occupancy(),
            peak_occupancy=self._peak_occupancy,
            tokens_per_s=self._token_rate,
            recompiles_after_warmup=self.recompiles_after_warmup(),
            token_p50_ms=float(np.percentile(ms, 50)) if ms else None,
            token_p99_ms=float(np.percentile(ms, 99)) if ms else None,
        )
        return out

    # -------------------------------------------------------- shutdown

    def drain(self, retry_after_s: Optional[float] = None,
              timeout: float = 30.0) -> int:
        """Planned-departure drain: stop admitting (subsequent submits
        shed typed), shed everything still QUEUED with the Retry-After,
        and let the IN-FLIGHT sequences decode to completion — their
        futures resolve normally. Returns the shed count. Idempotent; a
        drained engine is closed."""
        retry = (const.ENV.ADT_DRAIN_RETRY_AFTER_S.val
                 if retry_after_s is None else float(retry_after_s))
        with self._cv:
            if self._closing:
                return 0
            self._closing = True
            self._retry_after = retry
            shed_exc = ServingUnavailable(
                "decode engine draining for departure — retry elsewhere "
                "(Retry-After %.1fs)" % retry, retry_after_s=retry)
            shed = 0
            while self._pending:
                req = self._pending.popleft()
                if not req.future.done():
                    req.future.set_exception(shed_exc)
                    shed += 1
            in_flight = len(self.scheduler.live_slots())
            self._cv.notify()
        self._worker.join(timeout=timeout)
        self.stats_local["shed"] += shed
        self.stats_local["drained"] += in_flight
        if shed:
            tel.counter_add("serve.shed", shed)
        tel.counter_add("serve.drained", in_flight)
        tel.instant("serve.decode_drained", "serve", shed=shed,
                    drained=in_flight, retry_after_s=retry)
        logging.warning(
            "serving: drained decode engine — %d in-flight sequence(s) "
            "ran to completion, %d queued shed with Retry-After %.1fs",
            in_flight, shed, retry)
        return shed

    def close(self, timeout: float = 30.0):
        """Drain (in-flight sequences complete, queue sheds typed) and
        join the worker. Idempotent."""
        self.drain(timeout=timeout)
        self._worker.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
