"""Closed-loop auto-strategy search — per-variable plan synthesis.

The strategy-compiler half the source paper promised but the reference
never shipped: instead of ranking a fixed zoo of whole-graph templates,
this package *synthesizes* a strategy per variable — PS vs AllReduce
assignment, partition axis + shard count, gradient bucketing, compressor
choice — by searching plan mutations (beam or simulated annealing) scored
through the calibrated analytic :class:`~autodist_tpu.simulator.cost_model.
CostModel` and pruned by ``analysis.verify`` + the ADT501 projected-OOM
gate **before any trace/lower/compile**.

Public surface:

- :func:`run_search` / :class:`SearchConfig` / :class:`SearchResult` —
  the drivers (``drivers.py``);
- :class:`PlanSpace` / :class:`PlanSpec` / :class:`VarChoice` — the typed
  candidate space and mutation operators (``space.py``);
- :class:`PlanScorer` / :class:`ScoreRecord` — verify → estimate →
  memory-gate scoring (``scoring.py``);
- :class:`SearchTrace` — the deterministic, dumpable run record
  (``trace.py``);
- ``python -m autodist_tpu.search`` — the search CLI (``cli.py``).

``AutoStrategy(search=...)`` (``strategy/auto_strategy.py``) wires this in
as the default builder for unseen models: zoo candidates seed the search,
and the searched plan competes in the same ``Simulator.rank`` call, so it
wins exactly when the shared cost model says it is at least as fast.

Exports resolve lazily (PEP 562) to keep ``import autodist_tpu`` light.
"""

__all__ = ["run_search", "SearchConfig", "SearchResult", "PlanSpace",
           "PlanSpec", "VarChoice", "PlanScorer", "ScoreRecord",
           "zoo_best", "SearchTrace"]

_DRIVER_NAMES = {"run_search", "SearchConfig", "SearchResult"}
_SPACE_NAMES = {"PlanSpace", "PlanSpec", "VarChoice"}
_SCORING_NAMES = {"PlanScorer", "ScoreRecord", "zoo_best"}


def __getattr__(name):
    if name in _DRIVER_NAMES:
        from autodist_tpu.search import drivers
        return getattr(drivers, name)
    if name in _SPACE_NAMES:
        from autodist_tpu.search import space
        return getattr(space, name)
    if name in _SCORING_NAMES:
        from autodist_tpu.search import scoring
        return getattr(scoring, name)
    if name == "SearchTrace":
        from autodist_tpu.search.trace import SearchTrace
        return SearchTrace
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
