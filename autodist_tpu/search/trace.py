"""Search trace: a deterministic, dumpable record of a search run.

Every candidate the drivers touch lands here as one entry — seeds,
mutations (with the operator applied and the parent candidate), scores,
prune reasons, duplicates the dedup table absorbed — plus a header
carrying the :class:`~autodist_tpu.search.drivers.SearchConfig` and a
result section naming the chosen plan. The trace contains **no wall-clock
data**: two runs with the same seed/config over the same model produce
byte-identical dumps (the reproducibility contract
``tests/test_search.py`` pins), and re-running from a dumped header must
re-choose the same plan. Wall time lives on
:class:`~autodist_tpu.search.drivers.SearchResult` instead.
"""
import json
import os
from typing import List, Optional


class SearchTrace:
    """Append-only event log of one search run."""

    VERSION = 1

    def __init__(self, header: Optional[dict] = None):
        self.header = dict(header or {})
        self.header.setdefault("version", self.VERSION)
        self.entries: List[dict] = []
        self.result: dict = {}

    def record(self, event: str, **fields) -> dict:
        entry = {"i": len(self.entries), "event": event}
        entry.update({k: v for k, v in fields.items() if v is not None})
        self.entries.append(entry)
        return entry

    def record_score(self, label: str, record, algo: str,
                     op: Optional[str] = None,
                     parent: Optional[str] = None):
        """One scored candidate (or prune) from ``PlanScorer.score``."""
        fields = dict(label=label, algo=algo, op=op, parent=parent)
        if record.pruned is not None:
            fields["pruned"] = record.pruned
            fields["detail"] = record.detail
        else:
            fields["score_ms"] = round(record.score_s * 1e3, 6)
            fields["step_time_ms"] = round(record.step_time_s * 1e3, 6)
        return self.record("score", **fields)

    # ------------------------------------------------------------- summary

    def scored(self) -> List[dict]:
        return [e for e in self.entries if e["event"] == "score"]

    def pruned(self) -> List[dict]:
        return [e for e in self.scored() if "pruned" in e]

    def prune_reasons(self) -> dict:
        out: dict = {}
        for e in self.pruned():
            key = e["pruned"]
            out[key] = out.get(key, 0) + 1
        return out

    # ---------------------------------------------------------------- (de)ser

    def to_dict(self) -> dict:
        return {"header": dict(self.header),
                "entries": list(self.entries),
                "result": dict(self.result)}

    @classmethod
    def from_dict(cls, d: dict) -> "SearchTrace":
        trace = cls(header=d.get("header"))
        trace.entries = list(d.get("entries", []))
        trace.result = dict(d.get("result", {}))
        return trace

    def dump(self, path: str) -> str:
        """Atomic JSON dump (write-then-rename, like Strategy.serialize)."""
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path: str) -> "SearchTrace":
        with open(path) as f:
            return cls.from_dict(json.load(f))
