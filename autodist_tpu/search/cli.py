"""Auto-strategy search CLI: ``python -m autodist_tpu.search <example>``.

Runs the per-variable plan search against one of the bundled examples
(the same registry the plan-linter CLI uses), compares the searched plan
against the zoo ranking under the identical cost model, and prints the
search summary — candidates visited, prune reasons, score trajectory
endpoint, candidates/second. Exit codes: 0 = searched a plan (and it
verifies clean), 1 = the search produced no plan or the chosen plan has
ADT errors, 2 = usage/build failure.

    python -m autodist_tpu.search image_classifier
    python -m autodist_tpu.search lm1b --algo anneal --budget 200 --seed 7
    python -m autodist_tpu.search lm1b --trace-out /tmp/search-trace.json \\
        --dump-plan /tmp/searched-plan.json --format json

``--trace-out`` dumps the deterministic search trace (candidates visited
with mutation operators and parents, prune reasons, scores) — re-running
with the seed/config in its header reproduces the identical run.
``--dump-plan`` serializes the chosen Strategy as JSON, ready for
``python -m autodist_tpu.analysis <example> --strategy-json <file>``.
"""
import argparse
import json
import sys

# the example-model registry and synthetic spec are shared with the
# plan-linter CLI — one place defines what "bundled example" means
from autodist_tpu.analysis.cli import EXAMPLES, default_spec


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m autodist_tpu.search",
        description="Per-variable auto-strategy search over the "
                    "calibrated cost model (no compile). Exit 0 = plan "
                    "found and clean, 1 = no plan / ADT errors, 2 = "
                    "usage failure.")
    p.add_argument("example", nargs="?",
                   help="bundled example: %s" % ", ".join(sorted(EXAMPLES)))
    p.add_argument("--algo", choices=("beam", "anneal", "both"),
                   default="beam", help="search driver (default beam)")
    p.add_argument("--budget", type=int, default=128,
                   help="max scored candidates, seeds included "
                        "(default 128)")
    p.add_argument("--beam-width", type=int, default=4)
    p.add_argument("--branch", type=int, default=6,
                   help="mutations per beam member per round (default 6)")
    p.add_argument("--seed", type=int, default=0,
                   help="rng seed; fixed seed => identical plan and trace")
    p.add_argument("--devices", type=int, default=4,
                   help="device count of the synthetic spec (default 4)")
    p.add_argument("--spec", default=None, metavar="YAML",
                   help="resource spec yaml (default: synthetic "
                        "single-node slice)")
    p.add_argument("--format", choices=("table", "json"), default="table")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="dump the deterministic search trace as JSON")
    p.add_argument("--dump-plan", default=None, metavar="FILE",
                   help="serialize the chosen Strategy as JSON (feed to "
                        "the plan linter's --strategy-json)")
    p.add_argument("--no-zoo", action="store_true",
                   help="skip the zoo comparison (faster; no "
                        "searched-vs-zoo line)")
    p.add_argument("--quiet", action="store_true",
                   help="table mode: print only the chosen-plan line")
    p.add_argument("--list", action="store_true",
                   help="list examples, then exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list:
        print("examples: " + " ".join(sorted(EXAMPLES)))
        return 0
    if not args.example:
        print("error: an example name is required (see --list)",
              file=sys.stderr)
        return 2
    if args.example not in EXAMPLES:
        print("error: unknown example %r (have %s)"
              % (args.example, ", ".join(sorted(EXAMPLES))),
              file=sys.stderr)
        return 2

    from autodist_tpu.analysis.diagnostics import Severity
    from autodist_tpu.model_item import ModelItem
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.search.drivers import SearchConfig, run_search
    from autodist_tpu.simulator.simulator import Simulator

    try:
        loss_fn, params, batch, _mp_rules = EXAMPLES[args.example]()
        item = ModelItem(loss_fn=loss_fn, params=params,
                         example_batch=batch).prepare()
    except Exception as e:  # noqa: BLE001 — build failures are exit 2
        print("error: example %r failed to build: %s: %s"
              % (args.example, type(e).__name__, e), file=sys.stderr)
        return 2

    spec = (ResourceSpec(args.spec) if args.spec
            else default_spec(args.devices))
    try:
        cfg = SearchConfig(algo=args.algo, budget=args.budget,
                           beam_width=args.beam_width, branch=args.branch,
                           seed=args.seed)
    except ValueError as e:
        print("error: %s" % e, file=sys.stderr)
        return 2
    sim = Simulator(item, spec)
    result = run_search(item, spec, config=cfg, simulator=sim,
                        trace_path=args.trace_out)

    doc = {
        "example": args.example,
        "config": cfg.to_dict(),
        "candidates": result.candidates,
        "pruned": result.pruned,
        "prune_reasons": result.trace.prune_reasons(),
        "search_s": round(result.wall_s, 3),
        "candidates_per_s": round(
            result.candidates / max(result.wall_s, 1e-9), 1),
    }
    if not result.ok:
        doc["chosen"] = None
        if args.format == "json":
            print(json.dumps(doc, indent=1, sort_keys=True))
        else:
            print("%s: search pruned every candidate (%s)"
                  % (args.example, doc["prune_reasons"]))
        return 1

    doc["chosen"] = result.trace.result.get("plan")
    doc["est_step_ms"] = round(result.record.step_time_s * 1e3, 6)
    doc["score_ms"] = round(result.record.score_s * 1e3, 6)
    if not args.no_zoo:
        from autodist_tpu.search.scoring import zoo_best
        zoo_label, zoo_score, _zoo = zoo_best(item, spec, sim)
        if zoo_label is not None:
            doc["zoo_best"] = zoo_label
            doc["zoo_score_ms"] = round(zoo_score * 1e3, 6)
            doc["beats_zoo"] = bool(result.record.score_s
                                    <= zoo_score + 1e-12)
    if args.dump_plan:
        result.strategy.serialize(args.dump_plan)
        doc["plan_file"] = args.dump_plan
    if args.trace_out:
        doc["trace_file"] = args.trace_out

    n_errors = sum(1 for d in sim.verify(result.strategy)
                   if d.severity >= Severity.ERROR)
    doc["verify_errors"] = n_errors

    if args.format == "json":
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print("%s: %s  est %.3f ms/step  (%d candidates, %d pruned, "
              "%.2fs, %.0f cand/s, seed %d, %s)"
              % (args.example, doc["chosen"], doc["est_step_ms"],
                 result.candidates, result.pruned, result.wall_s,
                 doc["candidates_per_s"], args.seed, args.algo))
        if not args.quiet:
            if "zoo_best" in doc:
                verdict = ("<= zoo best" if doc["beats_zoo"]
                           else "SLOWER than zoo best")
                print("zoo best: %s  score %.3f ms  -> searched %.3f ms "
                      "(%s)" % (doc["zoo_best"], doc["zoo_score_ms"],
                                doc["score_ms"], verdict))
            for reason, count in sorted(doc["prune_reasons"].items()):
                print("pruned %-16s %d" % (reason, count))
            if args.trace_out:
                print("trace: %s" % args.trace_out)
            if args.dump_plan:
                print("plan:  %s" % args.dump_plan)
    return 1 if n_errors else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
