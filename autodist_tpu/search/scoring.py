"""Candidate scoring for the auto-strategy search.

Every candidate passes a three-stage pipeline — with **no trace, lower or
compile anywhere**:

1. ``analysis.verify`` (via :meth:`Simulator.verify`): error-severity
   diagnostics prune the candidate (pricing an un-compilable plan would
   hand the search a winner that explodes at lowering time);
2. ``CostModel.estimate`` through the shared :class:`Simulator` — so a
   fitted :class:`~autodist_tpu.simulator.calibration.Calibration` and any
   attached :class:`~autodist_tpu.simulator.cost_model.
   StaticCollectiveProfile` (measured wire bytes) price the candidate
   exactly as ``Simulator.rank`` would;
3. the plan-level ADT501 projected-OOM gate (``analysis/memory.py``
   ``budget_diagnostics`` over the estimate's HBM terms): a fast plan
   that OOMs is not a plan.

The returned score is the ranking key ``Simulator.rank`` sorts by —
estimated step seconds times the lossy-compression risk premium — so the
search and the zoo ranking can never disagree about which plan is better.
"""
import dataclasses
from typing import Optional

from autodist_tpu.simulator.simulator import Simulator, _risk_premium
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.telemetry import spans as tel


@dataclasses.dataclass
class ScoreRecord:
    """One scored (or pruned) candidate."""
    label: str
    score_s: float = float("inf")        # ranking key (premium-adjusted)
    step_time_s: float = float("inf")    # physical estimate
    pruned: Optional[str] = None         # "verify:ADT302" | "oom:ADT501"
    detail: str = ""                     # first diagnostic, for the trace
    breakdown: Optional[object] = None   # CostBreakdown when priced

    @property
    def ok(self) -> bool:
        return self.pruned is None


def zoo_best(model_item, resource_spec, sim: Simulator):
    """``(label, premium-adjusted score seconds, SimulationResult)`` of
    the best zoo candidate under ``sim`` — the comparison baseline the
    search CLI, the bench legs, and the tests all quote, in one place so
    the ranking key can never diverge between them. ``(None, None,
    None)`` when no zoo candidate builds or survives the OOM skip."""
    from autodist_tpu.strategy.auto_strategy import default_candidates
    built = []
    for label, builder in default_candidates():
        try:
            built.append((label, builder.build(model_item, resource_spec)))
        except Exception:  # noqa: BLE001 — inapplicable builders drop out
            continue
    ranking = sim.rank(built, skip_projected_oom=True)
    if not ranking:
        return None, None, None
    best = ranking[0]
    return best.label, best.step_time_s * _risk_premium(best.strategy), best


class PlanScorer:
    """Shared scoring state: one :class:`Simulator` (its cost model
    caches the loss trace), plus candidate/prune counters surfaced to
    telemetry and the search trace."""

    def __init__(self, model_item, resource_spec, simulator: Optional[Simulator] = None,
                 **cost_model_kwargs):
        self.sim = simulator or Simulator(model_item, resource_spec,
                                          **cost_model_kwargs)
        self._item = model_item
        self._spec = resource_spec
        self.scored = 0
        self.pruned = 0

    def score(self, label: str, strategy: Strategy) -> ScoreRecord:
        from autodist_tpu.analysis.diagnostics import Severity
        from autodist_tpu.analysis.memory import budget_diagnostics
        with tel.span("search.score", cat="search", label=label):
            self.scored += 1
            tel.counter_add("search.candidates")
            errs = [d for d in self.sim.verify(strategy)
                    if d.severity >= Severity.ERROR]
            if errs:
                self.pruned += 1
                tel.counter_add("search.pruned")
                return ScoreRecord(label=label,
                                   pruned="verify:%s" % errs[0].code,
                                   detail=errs[0].format())
            res = self.sim.simulate(strategy, label)
            oom = [d for d in budget_diagnostics(
                res.breakdown.hbm_bytes, res.breakdown.hbm_capacity,
                source="plan-level") if d.code == "ADT501"]
            if oom:
                self.pruned += 1
                tel.counter_add("search.pruned")
                return ScoreRecord(label=label, pruned="oom:ADT501",
                                   detail=oom[0].format(),
                                   step_time_s=res.step_time_s,
                                   breakdown=res.breakdown)
            return ScoreRecord(
                label=label,
                score_s=res.step_time_s * _risk_premium(strategy),
                step_time_s=res.step_time_s,
                breakdown=res.breakdown)
