"""Entry point for ``python -m autodist_tpu.search``."""
import sys

from autodist_tpu.search.cli import main

sys.exit(main())
