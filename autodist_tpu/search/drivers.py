"""Search drivers: beam search and simulated annealing over plan mutations.

Both drivers walk the :class:`~autodist_tpu.search.space.PlanSpace` under
one caller-seeded ``random.Random`` — fixed seed ⇒ identical visit order,
identical chosen plan, identical dumped trace — and share one candidate
budget measured in **scored candidates** (every score is one verify + one
cost-model estimate; nothing is ever traced, lowered or compiled). Beam
is the default: breadth against the zoo-family seeds, `branch` mutations
per member per round, early stop after `patience` rounds without
improvement. Annealing is the escape hatch for spaces where single
mutations must pass through a worse plan to reach a better one; ``both``
runs beam first and anneals from its winner with the remaining budget.
"""
import dataclasses
import math
import time
from typing import Dict, List, Optional, Sequence, Tuple

from autodist_tpu.search.scoring import PlanScorer, ScoreRecord
from autodist_tpu.search.space import PlanSpace, PlanSpec
from autodist_tpu.search.trace import SearchTrace
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.telemetry import spans as tel
from autodist_tpu.utils import logging

_ALGOS = ("beam", "anneal", "both")


@dataclasses.dataclass
class SearchConfig:
    """Knobs of one search run; serialized into the trace header so a
    dumped trace is sufficient to reproduce the run."""
    algo: str = "beam"
    budget: int = 128        # max scored candidates (seeds included)
    beam_width: int = 4
    branch: int = 6          # mutations per beam member per round
    patience: int = 3        # rounds without improvement before stopping
    seed: int = 0
    init_temp: float = 0.3   # annealing temperature, relative to score
    cooling: float = 0.92

    def __post_init__(self):
        if self.algo not in _ALGOS:
            raise ValueError("algo must be one of %s, got %r"
                             % (_ALGOS, self.algo))
        for knob in ("budget", "beam_width", "branch", "patience"):
            if getattr(self, knob) < 1:
                raise ValueError("%s must be >= 1" % knob)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SearchConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class SearchResult:
    """Outcome of :func:`run_search`. ``plan``/``strategy`` are ``None``
    only when every candidate was pruned (caller falls back to the zoo)."""
    plan: Optional[PlanSpec]
    strategy: Optional[Strategy]
    record: Optional[ScoreRecord]
    trace: SearchTrace
    wall_s: float = 0.0
    candidates: int = 0
    pruned: int = 0

    @property
    def ok(self) -> bool:
        return self.strategy is not None


class _Search:
    """Shared driver state: dedup table, label counter, budget."""

    def __init__(self, space: PlanSpace, scorer: PlanScorer,
                 trace: SearchTrace, rng, budget: int):
        self.space = space
        self.scorer = scorer
        self.trace = trace
        self.rng = rng
        self.budget = budget
        self.evaluated: Dict[PlanSpec, ScoreRecord] = {}

    def budget_left(self) -> int:
        return self.budget - self.scorer.scored

    def evaluate(self, plan: PlanSpec, algo: str, op: Optional[str] = None,
                 parent: Optional[str] = None
                 ) -> Optional[Tuple[ScoreRecord, bool]]:
        """Score one plan: ``(record, was_duplicate)``, or ``None`` when
        the budget is exhausted (the driver's stop signal)."""
        cached = self.evaluated.get(plan)
        if cached is not None:
            self.trace.record("dup", label=cached.label, algo=algo, op=op,
                              parent=parent)
            return cached, True
        if self.budget_left() <= 0:
            return None
        label = "c%03d" % self.scorer.scored
        record = self.scorer.score(label, self.space.build(plan))
        self.evaluated[plan] = record
        self.trace.record_score(label, record, algo=algo, op=op,
                                parent=parent)
        return record, False


def _beam_phase(S: _Search, cfg: SearchConfig,
                seeds: List[Tuple[PlanSpec, ScoreRecord]]
                ) -> Optional[Tuple[PlanSpec, ScoreRecord]]:
    beam = sorted((pr for pr in seeds if pr[1].ok),
                  key=lambda pr: pr[1].score_s)[:cfg.beam_width]
    if not beam:
        return None
    best = beam[0]
    stale = 0
    while S.budget_left() > 0:
        children: List[Tuple[PlanSpec, ScoreRecord]] = []
        for plan, rec in list(beam):
            for _ in range(cfg.branch):
                if S.budget_left() <= 0:
                    break
                mut = S.space.mutate(plan, S.rng)
                if mut is None:
                    continue
                child, op = mut
                out = S.evaluate(child, algo="beam", op=op,
                                 parent=rec.label)
                if out is None:
                    break
                rec2, dup = out
                if not dup and rec2.ok:
                    children.append((child, rec2))
        if not children:
            break  # budget gone, space exhausted, or all pruned
        pool = sorted(beam + children, key=lambda pr: pr[1].score_s)
        seen, beam = set(), []
        for p, r in pool:
            if p in seen:
                continue
            seen.add(p)
            beam.append((p, r))
            if len(beam) >= cfg.beam_width:
                break
        if beam[0][1].score_s < best[1].score_s - 1e-12:
            best = beam[0]
            stale = 0
        else:
            stale += 1
            if stale >= cfg.patience:
                break
    return best


def _anneal_phase(S: _Search, cfg: SearchConfig,
                  start: Tuple[PlanSpec, ScoreRecord]
                  ) -> Tuple[PlanSpec, ScoreRecord]:
    cur = best = start
    temp = cfg.init_temp
    attempts, max_attempts = 0, max(cfg.budget * 4, 64)
    while S.budget_left() > 0 and attempts < max_attempts:
        attempts += 1
        mut = S.space.mutate(cur[0], S.rng)
        if mut is None:
            break
        child, op = mut
        out = S.evaluate(child, algo="anneal", op=op, parent=cur[1].label)
        if out is None:
            break
        rec, _dup = out
        if rec.ok:
            worse_by = rec.score_s - cur[1].score_s
            accept = (worse_by <= 0
                      or S.rng.random() < math.exp(
                          -worse_by / max(cur[1].score_s * temp, 1e-12)))
            if accept:
                cur = (child, rec)
                S.trace.record("accept", label=rec.label, algo="anneal",
                               score_ms=round(rec.score_s * 1e3, 6))
                if rec.score_s < best[1].score_s:
                    best = cur
        temp *= cfg.cooling
    return best


def run_search(model_item, resource_spec,
               config: Optional[SearchConfig] = None,
               simulator=None,
               extra_seeds: Sequence[Tuple[str, Strategy]] = (),
               trace_path: Optional[str] = None,
               **cost_model_kwargs) -> SearchResult:
    """Synthesize a per-variable strategy for ``model_item`` on
    ``resource_spec``.

    ``simulator`` shares a caller's :class:`Simulator` (and therefore its
    calibration, static profiles, and cached loss trace) — this is how
    ``AutoStrategy`` guarantees the search and the zoo ranking price
    candidates identically. ``extra_seeds`` takes built ``(label,
    Strategy)`` pairs (the zoo candidates); those expressible in the
    per-variable space join the seed pool. ``trace_path`` dumps the
    deterministic search trace as JSON.
    """
    cfg = config or SearchConfig()
    import random
    t0 = time.perf_counter()
    space = PlanSpace(model_item, resource_spec)
    scorer = PlanScorer(model_item, resource_spec, simulator=simulator,
                        **cost_model_kwargs)
    trace = SearchTrace(header={
        "config": cfg.to_dict(),
        "vars": len(space.var_names),
        "devices": space.n_replicas,
    })
    rng = random.Random(cfg.seed)
    S = _Search(space, scorer, trace, rng, cfg.budget)

    with tel.span("search.run", cat="search", algo=cfg.algo,
                  budget=cfg.budget):
        seed_pool = list(space.seeds())
        for label, strategy in extra_seeds:
            plan = space.from_strategy(strategy)
            if plan is not None:
                seed_pool.append(("seed:zoo:%s" % label, plan))
        seeds: List[Tuple[PlanSpec, ScoreRecord]] = []
        for slabel, plan in seed_pool:
            out = S.evaluate(plan, algo="seed", op=slabel)
            if out is None:
                break
            rec, dup = out
            if not dup:
                seeds.append((plan, rec))

        best: Optional[Tuple[PlanSpec, ScoreRecord]] = None
        if cfg.algo in ("beam", "both"):
            best = _beam_phase(S, cfg, seeds)
        if cfg.algo in ("anneal", "both"):
            start = best or min((pr for pr in seeds if pr[1].ok),
                                key=lambda pr: pr[1].score_s, default=None)
            if start is not None:
                annealed = _anneal_phase(S, cfg, start)
                if best is None or annealed[1].score_s < best[1].score_s:
                    best = annealed

    wall_s = time.perf_counter() - t0
    tel.gauge_set("search.candidates_per_s",
                  scorer.scored / max(wall_s, 1e-9))
    trace.result = {
        "candidates": scorer.scored,
        "pruned": scorer.pruned,
        "prune_reasons": trace.prune_reasons(),
    }
    if best is None:
        trace.result["chosen"] = None
        logging.warning(
            "auto-search: every one of %d candidate(s) was pruned "
            "(%s); no per-variable plan to offer",
            scorer.scored, trace.result["prune_reasons"] or "none scored")
        return SearchResult(plan=None, strategy=None, record=None,
                            trace=trace, wall_s=wall_s,
                            candidates=scorer.scored, pruned=scorer.pruned)
    plan, record = best
    trace.result.update(
        chosen=record.label, plan=plan.describe(),
        score_ms=round(record.score_s * 1e3, 6),
        step_time_ms=round(record.step_time_s * 1e3, 6))
    if trace_path:
        trace.dump(trace_path)
    logging.info(
        "auto-search(%s): %s -> %s est %.3f ms/step "
        "(%d candidates, %d pruned, %.2fs, %.0f cand/s)",
        cfg.algo, record.label, plan.describe(),
        record.step_time_s * 1e3, scorer.scored, scorer.pruned, wall_s,
        scorer.scored / max(wall_s, 1e-9))
    return SearchResult(plan=plan, strategy=space.build(plan),
                        record=record, trace=trace, wall_s=wall_s,
                        candidates=scorer.scored, pruned=scorer.pruned)
