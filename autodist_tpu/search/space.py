"""Typed per-variable candidate space for the auto-strategy search.

The original AutoDist's AutoSync searched *per-variable* synchronizer
choices (reference ``docs/design/rationale.rst``); our zoo-ranking
``AutoStrategy`` only ever picked a whole-graph template. This module is
the missing dimension: a :class:`PlanSpec` assigns every trainable
variable its own :class:`VarChoice` (PS vs AllReduce, partition axis +
shard count, compressor) plus plan-level knobs (gradient bucketing
granularity, PS staleness window, remat policy), and a :class:`PlanSpace`
that

- enumerates **seed** plans mirroring the zoo families (plus best-effort
  conversions of actual zoo strategies via :meth:`PlanSpace.from_strategy`),
- applies **mutation operators** (deterministic under a caller-owned
  ``random.Random``) that by construction keep plans inside what the
  lowering supports — shard counts are divisors of the split dim, sparse
  variables never take the dense reduce-scatter path (ADT309), compressors
  only ride unpartitioned dense float AllReduce wires (ADT306/308) — so
  ``analysis.verify`` stays a cheap *gate*, not the search's inner loop,
- **materializes** a PlanSpec into a :class:`~autodist_tpu.strategy.base.
  Strategy` using the exact node shapes the zoo builders emit (greedy
  least-loaded PS destination assignment, round-robined shard
  destinations), so a searched plan lowers through the same kernels.

Everything here is pure and trace-free: scoring happens in
``search/scoring.py`` through the calibrated cost model.
"""
import dataclasses
from typing import Dict, List, Optional, Tuple

from autodist_tpu.strategy.base import (AllReduceSynchronizer, GraphConfig,
                                        PSSynchronizer, Strategy,
                                        VarConfig, ZeroShardedSynchronizer)
from autodist_tpu.strategy.partitioned_ps_strategy import (
    make_partition_str, smallest_divisor_shards)
from autodist_tpu.strategy.ps_lb_strategy import byte_size_load_fn, greedy_assign
from autodist_tpu.strategy.ps_strategy import reduction_devices, replica_devices

# gradient-bucketing granularities the search may pick (vars per group,
# AllReduce family; one huge bucket minimizes per-collective launches,
# small buckets overlap earlier — the cost model prices the launch count)
CHUNK_SIZES = (8, 32, 128, 512)
# plan-level staleness windows for host-PS variables (sync training)
STALENESS_CHOICES = (0, 2)
# plan-level remat policies (None = store all activations)
REMAT_CHOICES = (None, "dots")
# compressors the search offers on dense float AllReduce wires; PowerSGD
# additionally requires rank >= 2 (ADT308). The int8 wire rides its own
# ``wire_dtype`` axis below (the blockwise codec is a property of the
# collective, not a gradient compressor), so it composes with PS too.
_DENSE_COMPRESSORS = ("NoneCompressor", "HorovodCompressor")
_MATRIX_COMPRESSORS = _DENSE_COMPRESSORS + ("PowerSGDCompressor:2",)
# wire formats the search offers per variable (dense float, >= one scale
# block — ADT310/311 are excluded BY CONSTRUCTION, never emitted)
WIRE_DTYPES = ("fp32", "int8")
# plan-level compute tiers (GraphConfig.compute_dtype): "bf16" lowers the
# forward/backward in bfloat16 while master params, optimizer state, the
# gradient collectives and the loss stay f32 — the only combination the
# ADT60x numerics rules accept, so the knob is a single safe bit and
# every invalid mixed-precision shape is excluded BY CONSTRUCTION
COMPUTE_DTYPES = ("f32", "bf16")


@dataclasses.dataclass(frozen=True)
class VarChoice:
    """One variable's synchronization decision.

    ``shards``/``axis`` describe partitioned storage (the ``partitioner``
    string of the strategy IR — params sharded, gathered per step);
    ``shards == 1`` means unpartitioned. ``zero`` selects the
    ZeRO-sharded weight update instead (``ZeroShardedSynchronizer``):
    params stay replicated, the gradient reduce-scatters, the optimizer
    applies on the owned 1/P shard (opt state created sharded) and the
    update all-gathers — the memory/speed trade axis for dense variables
    of at least one element per replica (ADT312/313 by construction);
    mutually exclusive with ``shards > 1``, PS, and ``compressor``.
    ``compressor`` only applies to unpartitioned dense AllReduce wires;
    ``ps_proxy`` only to PS. ``wire_dtype`` ("fp32" | "int8") selects
    the blockwise-quantized collective/PS/zero wire — dense float
    variables of at least one scale block, mutually exclusive with
    ``compressor`` (canon resolves conflicts compressor-first).
    ``schedule`` ("auto" | "ring" | "rhd" | "hier") picks the collective
    algorithm for the plain AllReduce wire (strategy/base.py docs):
    "hier" is only in the sub-space when the resource spec declares a
    multi-host topology the replica set spans — on a flat mesh canon
    clamps it back to "auto" (which resolves to the ring), the
    analyzer's refusal semantics."""
    sync: str = "AllReduce"               # "AllReduce" | "PS"
    compressor: str = "NoneCompressor"
    shards: int = 1
    axis: int = 0
    ps_proxy: bool = False
    wire_dtype: str = "fp32"
    zero: bool = False
    schedule: str = "auto"                # auto | ring | rhd | hier


@dataclasses.dataclass(frozen=True)
class PlanSpec:
    """A full per-variable plan: hashable, order-stable, mutation-friendly.

    ``choices`` pairs every trainable variable (in ``ModelItem`` order)
    with its :class:`VarChoice`; the remaining fields are plan-level
    knobs. Frozen so drivers can dedup visited candidates by the spec
    itself."""
    choices: Tuple[Tuple[str, VarChoice], ...]
    chunk_size: int = 128
    staleness: int = 0
    remat: Optional[str] = None
    compute_dtype: str = "f32"
    # lower the gradient sync as a bucketed overlap schedule (reverse
    # layer order, barrier-chained) instead of one epilogue; chunk_size
    # doubles as the bucket-size knob for how many stages it splits into
    overlap: bool = False

    def choice_map(self) -> Dict[str, VarChoice]:
        return dict(self.choices)

    def replace_choice(self, name: str, choice: VarChoice) -> "PlanSpec":
        return dataclasses.replace(self, choices=tuple(
            (n, choice if n == name else c) for n, c in self.choices))

    def describe(self) -> str:
        """Compact human label: sync-family counts + plan knobs."""
        ar = sum(1 for _, c in self.choices if c.sync == "AllReduce")
        ps = len(self.choices) - ar
        comp = sum(1 for _, c in self.choices
                   if c.compressor != "NoneCompressor")
        sharded = sum(1 for _, c in self.choices if c.shards > 1)
        wired = sum(1 for _, c in self.choices if c.wire_dtype == "int8")
        zeroed = sum(1 for _, c in self.choices if c.zero)
        scheds = sorted({c.schedule for _, c in self.choices
                         if c.schedule != "auto"})
        bits = ["ar=%d" % ar, "ps=%d" % ps]
        if comp:
            bits.append("comp=%d" % comp)
        for s in scheds:
            bits.append("sched:%s=%d" % (
                s, sum(1 for _, c in self.choices if c.schedule == s)))
        if wired:
            bits.append("int8w=%d" % wired)
        if sharded:
            bits.append("sharded=%d" % sharded)
        if zeroed:
            bits.append("zero=%d" % zeroed)
        bits.append("chunk=%d" % self.chunk_size)
        if self.staleness:
            bits.append("stale=%d" % self.staleness)
        if self.remat:
            bits.append("remat=%s" % self.remat)
        if self.compute_dtype != "f32":
            bits.append("compute=%s" % self.compute_dtype)
        if self.overlap:
            bits.append("overlap")
        return "plan[%s]" % ",".join(bits)


def _partition_options(shape, cap: int) -> List[Tuple[int, int]]:
    """(axis, shards) pairs that split one axis into an exact divisor
    count — the only partitionings the lowering stores unpadded and the
    linter leaves un-flagged. At most 4 counts per axis (smallest,
    largest, powers of two between) keeps the branching factor bounded."""
    out: List[Tuple[int, int]] = []
    for axis, dim in enumerate(shape or ()):
        divisors = [k for k in range(2, min(int(dim), cap) + 1)
                    if dim % k == 0]
        if not divisors:
            continue
        keep = {divisors[0], divisors[-1]}
        keep.update(k for k in divisors if k & (k - 1) == 0)
        out.extend((axis, k) for k in sorted(keep)[:4])
    return out


class PlanSpace:
    """The candidate space for one (ModelItem, ResourceSpec) pair."""

    def __init__(self, model_item, resource_spec):
        self._item = model_item
        self._spec = resource_spec
        self.var_names: List[str] = list(model_item.trainable_var_names)
        self.infos = {n: model_item.var_infos[n] for n in self.var_names}
        self.destinations = reduction_devices(resource_spec)
        self.replicas = replica_devices(resource_spec)
        self.n_replicas = max(len(self.replicas), 1)
        cap = max(self.n_replicas, len(self.destinations), 2)
        self.partition_options: Dict[str, List[Tuple[int, int]]] = {
            n: _partition_options(self.infos[n].shape, cap)
            for n in self.var_names}
        self.compressor_options: Dict[str, Tuple[str, ...]] = {}
        self.wire_options: Dict[str, Tuple[str, ...]] = {}
        # ZeRO-sharded update eligibility (the builder's gate, shared so
        # ADT312/313 are excluded from the space by construction)
        from autodist_tpu.strategy.zero_sharded_strategy import (
            zero_shardable)
        self.zero_ok: Dict[str, bool] = {
            n: zero_shardable(self.infos[n], self.n_replicas)
            for n in self.var_names}
        from autodist_tpu.parallel.collectives import wire_quantizable
        for n in self.var_names:
            info = self.infos[n]
            dtype = str(getattr(info, "dtype", "float32"))
            if info.sparse or not dtype.startswith(("float", "bfloat")):
                # ADT306: compression is dead weight on sparse or
                # non-float wires — not part of this variable's space
                self.compressor_options[n] = ("NoneCompressor",)
            elif len(info.shape) >= 2:
                self.compressor_options[n] = _MATRIX_COMPRESSORS
            else:
                self.compressor_options[n] = _DENSE_COMPRESSORS
            # int8 wire: dense float, at least one scale block (ADT310 /
            # ADT311 excluded from the space by construction)
            self.wire_options[n] = (
                WIRE_DTYPES if wire_quantizable(info, min_block=True)
                else ("fp32",))
        # collective-schedule axis: "hier" only exists when the spec
        # declares a multi-host topology the replica set actually spans
        # (with >= 2 chips per host there is a payload to shrink) — on a
        # flat mesh the space refuses it by construction, so the searcher
        # can never "pick hierarchical" where the analyzer would lint it
        topo = (resource_spec.topology()
                if hasattr(resource_spec, "topology") else None)
        if (topo is not None and topo.hosts > 1
                and topo.inter_level is not None
                and self.n_replicas > topo.chips_per_host
                and topo.chips_per_host > 1):
            self.schedule_options: Tuple[str, ...] = ("auto", "ring",
                                                      "rhd", "hier")
        else:
            self.schedule_options = ("auto", "rhd")

    # ------------------------------------------------------------- validity

    def canon(self, choice: VarChoice, name: str) -> VarChoice:
        """Clamp a choice to this variable's valid sub-space (the single
        place mutation results are normalized, so operators stay simple)."""
        info = self.infos[name]
        sync = choice.sync if choice.sync in ("PS", "AllReduce") else "AllReduce"
        shards, axis = choice.shards, choice.axis
        if shards > 1 and (axis, shards) not in self.partition_options[name]:
            shards, axis = 1, 0
        if sync == "AllReduce" and info.sparse and shards > 1:
            # ADT309: a partitioned reduce-scatter densifies the
            # row-sparse gradient to the full table every step
            shards, axis = 1, 0
        # ZeRO-sharded update: AllReduce family only, no partitioner on
        # top (ADT312), dense vars of >= one element per replica
        # (ADT313) — the same gate the ZeroSharded builder applies
        zero = (bool(choice.zero) and sync == "AllReduce"
                and shards <= 1 and self.zero_ok[name])
        compressor = choice.compressor
        if (sync != "AllReduce" or shards > 1 or zero
                or compressor not in self.compressor_options[name]):
            # the sharded update owns the payload end to end — a gradient
            # compressor cannot ride it (mirror of the partitioned path)
            compressor = "NoneCompressor"
        proxy = bool(choice.ps_proxy) if sync == "PS" else False
        # wire codec: dense float >= one block only (ADT310/311), never on
        # the AR reduce-scatter path (shards > 1), never on a proxied PS
        # var (no host wire), and compressor-first on conflicts; the
        # ZeroSharded rs/ag wire quantizes like the PS wire
        wire = choice.wire_dtype if choice.wire_dtype in WIRE_DTYPES else "fp32"
        if wire == "int8":
            if ("int8" not in self.wire_options[name]
                    or compressor != "NoneCompressor"
                    or (sync == "AllReduce" and shards > 1)
                    or (sync == "PS" and proxy)):
                wire = "fp32"
        # collective schedule: plain AllReduce wire only (the ZeRO and
        # partitioned paths already ARE scatter/gather compositions), and
        # only algorithms this spec's topology can realize
        sched = (choice.schedule or "auto").lower()
        if (sync != "AllReduce" or zero or shards > 1
                or sched not in self.schedule_options):
            sched = "auto"
        if wire == "int8" and zero:
            # the zero kernel rounds each shard to whole scale blocks:
            # below P x block elements the padded int8 wire is WORSE
            # than fp32 (and the cost model prices the padded truth)
            from autodist_tpu.strategy.zero_sharded_strategy import (
                zero_wire_quantizable)
            if not zero_wire_quantizable(info, self.n_replicas):
                wire = "fp32"
        return VarChoice(sync=sync, compressor=compressor, shards=shards,
                         axis=axis, ps_proxy=proxy, wire_dtype=wire,
                         zero=zero, schedule=sched)

    def make_plan(self, choices: Dict[str, VarChoice], chunk_size: int = 128,
                  staleness: int = 0, remat: Optional[str] = None,
                  compute_dtype: str = "f32",
                  overlap: bool = False) -> PlanSpec:
        canon = tuple((n, self.canon(choices.get(n, VarChoice()), n))
                      for n in self.var_names)
        if any(c.zero for _, c in canon):
            # ADT312 by construction: the ZeRO rs+ag pair is lockstep
            # every step, so a staleness window cannot coexist — drop it
            # in the SPEC (not just at materialization) so describe(),
            # dedup, and the built strategy all agree
            staleness = 0
        if compute_dtype not in COMPUTE_DTYPES:
            # ADT602 by construction: an unknown compute tier has no
            # f32-master guarantee — clamp rather than emit an invalid
            # plan (only the managed tiers exist in this space)
            compute_dtype = "f32"
        # overlap by construction: the schedule sequences SYNC gradient
        # collectives behind the backward pass — a staleness window (the
        # lowering would disarm it with a warning) or fewer than two
        # AllReduce-family sync units (nothing to overlap: one stage is
        # the epilogue) drop the bit in the SPEC so describe()/dedup and
        # the built strategy agree
        ar_units = sum(1 for _, c in canon if c.sync == "AllReduce")
        overlap = bool(overlap) and staleness == 0 and ar_units >= 2
        return PlanSpec(choices=canon, chunk_size=chunk_size,
                        staleness=staleness, remat=remat,
                        compute_dtype=compute_dtype, overlap=overlap)

    # ---------------------------------------------------------------- seeds

    def seeds(self) -> List[Tuple[str, PlanSpec]]:
        """Per-variable re-expressions of the zoo families — the search
        starts where the hand-written builders already are and only moves
        when the cost model says a deviation pays."""
        def compressed(comp, base=None):
            """All-AllReduce (or ``base``) with ``comp`` on every variable
            whose sub-space allows it (canon strips the rest) — the
            analog of the zoo's whole-graph compressor variants."""
            base = base or {}
            return {n: base.get(n) or VarChoice(compressor=comp)
                    for n in self.var_names}

        ar = {n: VarChoice() for n in self.var_names}
        host_ps = {n: VarChoice(sync="PS") for n in self.var_names}
        proxy_ps = {n: VarChoice(sync="PS", ps_proxy=True)
                    for n in self.var_names}
        sparse_ps = {n: VarChoice(sync="PS") for n in self.var_names
                     if self.infos[n].sparse}
        parallax = {n: sparse_ps.get(n) or VarChoice()
                    for n in self.var_names}
        cap = max(len(self.destinations), 2)
        part_ps = {}
        for n in self.var_names:
            dim0 = self.infos[n].shape[0] if self.infos[n].shape else 0
            k = smallest_divisor_shards(dim0, cap) if dim0 > 1 else 1
            part_ps[n] = (VarChoice(sync="PS", shards=k, axis=0)
                          if k > 1 else VarChoice(sync="PS"))
        part_ar = {}
        for n in self.var_names:
            dim0 = self.infos[n].shape[0] if self.infos[n].shape else 0
            k = (smallest_divisor_shards(dim0, self.n_replicas)
                 if dim0 > 1 and not self.infos[n].sparse else 1)
            part_ar[n] = (VarChoice(shards=k, axis=0) if k > 1
                          else VarChoice())
        # the ZeRO-sharded update families: canon strips ineligible vars
        # (sparse, sub-replica-sized) back to plain AllReduce
        zero = {n: VarChoice(zero=True) for n in self.var_names}
        zero_int8 = {n: VarChoice(zero=True, wire_dtype="int8")
                     for n in self.var_names}
        def wired(base=None, sync="AllReduce"):
            """``base`` (or all-``sync``) with the int8 wire on every
            variable whose sub-space allows it (canon strips the rest) —
            the quantized-wire analog of the compressor seed families."""
            base = base or {}
            return {n: base.get(n) or VarChoice(sync=sync,
                                                wire_dtype="int8")
                    for n in self.var_names}

        out = [
            ("seed:ar", self.make_plan(ar)),
            ("seed:ar512", self.make_plan(ar, chunk_size=512)),
            ("seed:ar-bf16", self.make_plan(
                compressed("HorovodCompressor"))),
            ("seed:ar-int8w", self.make_plan(wired())),
            ("seed:ar-psgd2", self.make_plan(
                compressed("PowerSGDCompressor:2"))),
            ("seed:host-ps", self.make_plan(host_ps)),
            ("seed:ps-int8w", self.make_plan(wired(sync="PS"))),
            ("seed:ps-stale2", self.make_plan(host_ps, staleness=2)),
            ("seed:proxy-ps", self.make_plan(proxy_ps)),
            ("seed:parallax", self.make_plan(parallax)),
            ("seed:parallax-bf16", self.make_plan(
                compressed("HorovodCompressor", base=sparse_ps))),
            ("seed:parallax-int8w", self.make_plan(wired(base=sparse_ps))),
            ("seed:part-ps", self.make_plan(part_ps)),
            ("seed:part-ar", self.make_plan(part_ar)),
            ("seed:zero", self.make_plan(zero)),
            ("seed:zero-int8w", self.make_plan(zero_int8)),
            ("seed:ar-remat", self.make_plan(ar, chunk_size=512,
                                             remat="dots")),
            # the managed bf16 compute tier (f32 master — ADT60x-clean by
            # construction), alone and beside the ZeRO f32-sharded update
            ("seed:ar-bf16c", self.make_plan(ar, compute_dtype="bf16")),
            ("seed:zero-bf16c", self.make_plan(zero,
                                               compute_dtype="bf16")),
            # the overlapped bucketed schedule: small chunks split the
            # backward into more stages (earlier launches, more hiding);
            # make_plan drops the bit on single-sync-unit models
            ("seed:ar-overlap", self.make_plan(ar, chunk_size=8,
                                               overlap=True)),
            ("seed:zero-overlap", self.make_plan(zero, chunk_size=8,
                                                 overlap=True)),
        ]
        if "hier" in self.schedule_options:
            # the two-level schedule exists in this space (multi-host
            # topology spanned): start one family there so the searcher
            # does not have to discover it by mutation alone
            hier = {n: VarChoice(schedule="hier") for n in self.var_names}
            out.append(("seed:ar-hier", self.make_plan(hier)))
        return out

    def from_strategy(self, strategy: Strategy) -> Optional[PlanSpec]:
        """Best-effort conversion of a built (zoo) strategy into a
        PlanSpec seed; ``None`` when the plan uses dimensions outside
        this space (model-parallel ``mp_axes``, uneven ``shard_sizes``,
        async PS, unknown variables)."""
        gc = strategy.graph_config
        if gc.mesh_shape or gc.seq_axis or gc.pp_schedule:
            return None
        choices: Dict[str, VarChoice] = {}
        staleness = 0
        for name in self.var_names:
            node = strategy.find(name)
            if node is None or node.mp_axes or node.shard_sizes is not None:
                return None
            syncs = ([node.synchronizer] if node.synchronizer else
                     [p.synchronizer for p in node.part_configs])
            syncs = [s for s in syncs if s is not None]
            if not syncs:
                return None
            first = syncs[0]
            shards = node.num_shards if node.partitioner else 1
            axis = (node.partition_axis or 0) if node.partitioner else 0
            if isinstance(first, ZeroShardedSynchronizer):
                if node.partitioner:
                    return None  # ADT312 combination: outside the space
                choice = VarChoice(zero=True,
                                   wire_dtype=first.wire_dtype or "fp32")
                canon = self.canon(choice, name)
                if not canon.zero:
                    return None  # ineligible var: not expressible here
                choices[name] = canon
                continue
            if isinstance(first, AllReduceSynchronizer):
                comp = first.compressor or "NoneCompressor"
                wire = first.wire_dtype or "fp32"
                if comp.split(":")[0] in ("Int8Compressor",
                                          "Int8CompressorEF"):
                    # the compressor axis no longer carries int8 (the
                    # wire axis owns it, and the kernels are identical):
                    # convert instead of silently stripping the ~4x
                    # compression the zoo strategy configured
                    comp, wire = "NoneCompressor", "int8"
                choice = VarChoice(compressor=comp, shards=shards,
                                   axis=axis, wire_dtype=wire,
                                   schedule=(getattr(first, "schedule",
                                                     "auto") or "auto"))
            elif isinstance(first, PSSynchronizer):
                if not first.sync:
                    return None  # async PS is outside the search space
                staleness = max(staleness, int(first.staleness or 0))
                choice = VarChoice(sync="PS", shards=shards, axis=axis,
                                   ps_proxy=bool(first.local_replication),
                                   wire_dtype=first.wire_dtype or "fp32")
            else:
                return None
            canon = self.canon(choice, name)
            if canon.shards != choice.shards:
                return None  # partitioning this space cannot express
            choices[name] = canon
        cd = getattr(gc, "compute_dtype", "f32") or "f32"
        if cd not in COMPUTE_DTYPES:
            return None  # an unmanaged compute tier: outside the space
        return self.make_plan(choices, staleness=staleness, remat=gc.remat,
                              compute_dtype=cd,
                              overlap=bool(getattr(gc, "overlap", False)))

    # ------------------------------------------------------------ mutations

    def mutate(self, plan: PlanSpec, rng) -> Optional[Tuple[PlanSpec, str]]:
        """One random plan mutation: ``(new_plan, op_description)`` or
        ``None`` when no operator applies. Deterministic given ``rng``
        state; the result is canonicalized, so it always materializes to
        a strategy the verifier accepts."""
        ops = []
        names = self.var_names
        cm = plan.choice_map()

        def pick_var():
            return names[rng.randrange(len(names))]

        def flip_sync():
            n = pick_var()
            c = cm[n]
            target = "PS" if c.sync == "AllReduce" else "AllReduce"
            new = self.canon(dataclasses.replace(c, sync=target), n)
            return plan.replace_choice(n, new), "sync[%s]=%s" % (n, target)

        ops.append(flip_sync)

        comp_vars = [n for n in names
                     if cm[n].sync == "AllReduce" and cm[n].shards == 1
                     and len(self.compressor_options[n]) > 1]
        if comp_vars:
            def set_compressor():
                n = comp_vars[rng.randrange(len(comp_vars))]
                opts = [o for o in self.compressor_options[n]
                        if o != cm[n].compressor]
                comp = opts[rng.randrange(len(opts))]
                new = self.canon(
                    dataclasses.replace(cm[n], compressor=comp), n)
                return (plan.replace_choice(n, new),
                        "compressor[%s]=%s" % (n, comp))
            ops.append(set_compressor)

        wire_vars = [n for n in names
                     if len(self.wire_options[n]) > 1
                     and not (cm[n].sync == "AllReduce"
                              and cm[n].shards > 1)
                     and not (cm[n].sync == "PS" and cm[n].ps_proxy)]
        if wire_vars:
            def set_wire_dtype():
                n = wire_vars[rng.randrange(len(wire_vars))]
                target = "int8" if cm[n].wire_dtype == "fp32" else "fp32"
                # setting the wire codec clears any compressor (they are
                # mutually exclusive — ADT310; canon resolves
                # compressor-first, so the operator states its intent)
                new = self.canon(dataclasses.replace(
                    cm[n], wire_dtype=target,
                    compressor=("NoneCompressor" if target == "int8"
                                else cm[n].compressor)), n)
                return (plan.replace_choice(n, new),
                        "wire[%s]=%s" % (n, target))
            ops.append(set_wire_dtype)

        zero_vars = [n for n in names if self.zero_ok[n]]
        if zero_vars:
            def set_zero():
                n = zero_vars[rng.randrange(len(zero_vars))]
                target = not cm[n].zero
                # arming the sharded update clears partitioning, the
                # compressor, AND any plan-level staleness window
                # (ADT312; canon would strip zero otherwise — the
                # operator states its intent, mirroring set_wire)
                new = self.canon(dataclasses.replace(
                    cm[n], zero=target,
                    sync="AllReduce" if target else cm[n].sync,
                    shards=1 if target else cm[n].shards,
                    axis=0 if target else cm[n].axis,
                    compressor=("NoneCompressor" if target
                                else cm[n].compressor)), n)
                out = plan.replace_choice(n, new)
                if new.zero and out.staleness:
                    out = dataclasses.replace(out, staleness=0)
                return out, "zero[%s]=%s" % (n, target)
            ops.append(set_zero)

        ps_vars = [n for n in names if cm[n].sync == "PS"]
        if ps_vars:
            def toggle_proxy():
                n = ps_vars[rng.randrange(len(ps_vars))]
                target = not cm[n].ps_proxy
                new = self.canon(
                    dataclasses.replace(cm[n], ps_proxy=target), n)
                return (plan.replace_choice(n, new),
                        "proxy[%s]=%s" % (n, target))
            ops.append(toggle_proxy)

        sched_vars = [n for n in names
                      if cm[n].sync == "AllReduce" and cm[n].shards == 1
                      and not cm[n].zero]
        if sched_vars and len(self.schedule_options) > 1:
            def set_schedule():
                n = sched_vars[rng.randrange(len(sched_vars))]
                opts = [s for s in self.schedule_options
                        if s != cm[n].schedule]
                s = opts[rng.randrange(len(opts))]
                new = self.canon(
                    dataclasses.replace(cm[n], schedule=s), n)
                return (plan.replace_choice(n, new),
                        "schedule[%s]=%s" % (n, s))
            ops.append(set_schedule)

        part_vars = [n for n in names if self.partition_options[n]
                     and not (self.infos[n].sparse
                              and cm[n].sync == "AllReduce")]
        if part_vars:
            def set_shards():
                n = part_vars[rng.randrange(len(part_vars))]
                opts = [(0, 1)] + self.partition_options[n]
                opts = [o for o in opts if o != (cm[n].axis, cm[n].shards)]
                axis, k = opts[rng.randrange(len(opts))]
                new = self.canon(
                    dataclasses.replace(cm[n], shards=k, axis=axis), n)
                return (plan.replace_choice(n, new),
                        "shards[%s]=%dx@%d" % (n, k, axis))
            ops.append(set_shards)

        def set_chunk():
            opts = [c for c in CHUNK_SIZES if c != plan.chunk_size]
            c = opts[rng.randrange(len(opts))]
            return dataclasses.replace(plan, chunk_size=c), "chunk=%d" % c

        ops.append(set_chunk)

        host_ps = [n for n in names
                   if cm[n].sync == "PS" and not cm[n].ps_proxy]
        # the staleness window is a lockstep conflict with the ZeRO
        # rs+ag pair (ADT312): not offered while any zero var is armed
        if host_ps and not any(cm[n].zero for n in names):
            def set_staleness():
                opts = [s for s in STALENESS_CHOICES if s != plan.staleness]
                s = opts[rng.randrange(len(opts))]
                # arming a staleness window disarms the overlap schedule
                # (the lowering would only warn and fall back — the spec
                # states the truth so dedup/describe agree)
                return (dataclasses.replace(
                    plan, staleness=s,
                    overlap=plan.overlap and s == 0), "stale=%d" % s)
            ops.append(set_staleness)

        # the overlap schedule needs >= 2 AllReduce-family sync units
        # (else one stage IS the epilogue) and no staleness window
        ar_units = sum(1 for n in names if cm[n].sync == "AllReduce")
        if ar_units >= 2 and (plan.overlap or plan.staleness == 0):
            def toggle_overlap():
                target = not plan.overlap
                return (dataclasses.replace(plan, overlap=target),
                        "overlap=%s" % target)
            ops.append(toggle_overlap)

        def set_remat():
            opts = [r for r in REMAT_CHOICES if r != plan.remat]
            r = opts[rng.randrange(len(opts))]
            return dataclasses.replace(plan, remat=r), "remat=%s" % r

        ops.append(set_remat)

        def set_compute_dtype():
            opts = [d for d in COMPUTE_DTYPES if d != plan.compute_dtype]
            d = opts[rng.randrange(len(opts))]
            return (dataclasses.replace(plan, compute_dtype=d),
                    "compute=%s" % d)

        ops.append(set_compute_dtype)

        if not ops:
            return None
        op = ops[rng.randrange(len(ops))]
        new_plan, desc = op()
        if new_plan.overlap:
            # a var-level mutation (flip_sync) may have dropped the plan
            # below two AllReduce-family units — re-apply the plan-level
            # canon so overlap never survives on a spec make_plan would
            # refuse to mint
            new_ar = sum(1 for _, c in new_plan.choices
                         if c.sync == "AllReduce")
            if new_ar < 2 or new_plan.staleness:
                new_plan = dataclasses.replace(new_plan, overlap=False)
        if new_plan == plan:
            return None
        return new_plan, desc

    # -------------------------------------------------------- materialize

    def build(self, plan: PlanSpec) -> Strategy:
        """Materialize a PlanSpec into the strategy IR, emitting the same
        node shapes the zoo builders do so the searched plan lowers
        through the exact same kernels."""
        cm = plan.choice_map()
        n_ps = len(self.destinations)
        # greedy least-loaded destination for single-dest host/proxy PS
        # vars (PSLoadBalancing's assignment, deterministic)
        ps_infos = [self.infos[n] for n in self.var_names
                    if cm[n].sync == "PS" and cm[n].shards <= 1]
        assignment = greedy_assign(ps_infos, self.destinations,
                                   byte_size_load_fn)
        # validity by construction (ADT312): the ZeRO-sharded rs+ag pair
        # is lockstep every step, so a plan mixing zero vars with a
        # staleness window materializes with the window dropped — the
        # per-var choices stay free to mutate independently of the
        # plan-level knob
        plan_staleness = (0 if any(c.zero for c in cm.values())
                          else plan.staleness)
        nodes: List[VarConfig] = []
        ar_index = 0   # bucket index over AllReduce-synced vars
        rr = 0         # round-robin pointer for partitioned-PS shards
        for name in self.var_names:
            c = cm[name]
            info = self.infos[name]
            rank = len(info.shape)
            if c.zero:
                nodes.append(VarConfig(
                    var_name=name,
                    synchronizer=ZeroShardedSynchronizer(
                        wire_dtype=c.wire_dtype)))
                continue
            if c.sync == "AllReduce":
                group = ar_index // max(plan.chunk_size, 1)
                ar_index += 1
                if c.shards > 1:
                    parts = [VarConfig(
                        var_name="%s/part_%d" % (name, i),
                        synchronizer=AllReduceSynchronizer(group=group))
                        for i in range(c.shards)]
                    nodes.append(VarConfig(
                        var_name=name,
                        partitioner=make_partition_str(rank, c.axis,
                                                       c.shards),
                        part_configs=parts))
                else:
                    nodes.append(VarConfig(
                        var_name=name,
                        synchronizer=AllReduceSynchronizer(
                            compressor=c.compressor, group=group,
                            wire_dtype=c.wire_dtype,
                            schedule=c.schedule)))
                continue
            staleness = 0 if c.ps_proxy else plan_staleness
            if c.shards > 1:
                parts = []
                for i in range(c.shards):
                    parts.append(VarConfig(
                        var_name="%s/part_%d" % (name, i),
                        synchronizer=PSSynchronizer(
                            reduction_destination=self.destinations[
                                rr % n_ps],
                            local_replication=c.ps_proxy,
                            sync=True, staleness=staleness,
                            wire_dtype=c.wire_dtype)))
                    rr += 1
                nodes.append(VarConfig(
                    var_name=name,
                    partitioner=make_partition_str(rank, c.axis, c.shards),
                    part_configs=parts))
            else:
                nodes.append(VarConfig(
                    var_name=name,
                    synchronizer=PSSynchronizer(
                        reduction_destination=assignment[name],
                        local_replication=c.ps_proxy,
                        sync=True, staleness=staleness,
                        wire_dtype=c.wire_dtype)))
        return Strategy(node_config=nodes,
                        graph_config=GraphConfig(
                            replicas=list(self.replicas), remat=plan.remat,
                            compute_dtype=plan.compute_dtype,
                            overlap=plan.overlap))
