"""Constants and environment-variable configuration.

TPU-native analog of the reference's ``autodist/const.py`` (see
reference ``autodist/const.py:32-89``): working directories, default port
range for the coordination service, replica naming prefixes, group-leader
identity, and a typed ``ENV`` enum of environment variables.
"""
import os
from enum import Enum

DEFAULT_WORKING_DIR = os.environ.get("ADT_WORKING_DIR", "/tmp/autodist_tpu")
DEFAULT_SERIALIZATION_DIR = os.path.join(DEFAULT_WORKING_DIR, "strategies")
DEFAULT_LOG_DIR = os.path.join(DEFAULT_WORKING_DIR, "logs")
DEFAULT_TRACE_DIR = os.path.join(DEFAULT_WORKING_DIR, "traces")
DEFAULT_SNAPSHOT_DIR = os.path.join(DEFAULT_WORKING_DIR, "snapshots")
DEFAULT_CHECKPOINT_DIR = os.path.join(DEFAULT_WORKING_DIR, "checkpoints")
DEFAULT_BLACKBOX_DIR = os.path.join(DEFAULT_WORKING_DIR, "blackbox")

# Port range for the coordination service (analog of the reference's TF
# server ports 15000-16000, reference autodist/const.py:36-38).
DEFAULT_PORT_RANGE = iter(range(15000, 16000))
DEFAULT_COORDINATOR_PORT = 15999   # jax.distributed coordination
DEFAULT_COORDSVC_PORT = 15998      # native coordination service (barriers/staleness)

# Naming prefixes (analog of replica name-scope prefixes,
# reference autodist/const.py:40-44).
REPLICA_PREFIX = "adt-replica-{}"
SHARD_SUFFIX = "/part_{}"
GROUP_LEADER = "/job:worker/replica:0/task:0"

# Mesh axis names used throughout the framework.
DATA_AXIS = "data"           # data-parallel axis (replicas)
MODEL_AXIS = "model"         # tensor/model-parallel axis
PIPELINE_AXIS = "pipe"       # pipeline-parallel axis
SEQUENCE_AXIS = "seq"        # sequence/context-parallel axis
EXPERT_AXIS = "expert"       # expert-parallel axis

MAX_INT32 = 2 ** 31 - 1
MAX_INT64 = 2 ** 63 - 1


class ENV(Enum):
    """Typed environment variables (analog of reference autodist/const.py:55-89).

    Each member's value is a lambda producing the parsed value; access via
    ``ENV.NAME.val``.
    """

    ADT_WORKER = ("ADT_WORKER", str, "")                  # non-empty => this process is a worker, value = its address
    ADT_STRATEGY_ID = ("ADT_STRATEGY_ID", str, "")        # strategy id assigned by chief
    ADT_MIN_LOG_LEVEL = ("ADT_MIN_LOG_LEVEL", str, "INFO")
    ADT_IS_TESTING = ("ADT_IS_TESTING", bool, False)      # enables extra invariant checks
    ADT_DEBUG_REMOTE = ("ADT_DEBUG_REMOTE", bool, False)  # suppress real SSH exec (dry-run)
    ADT_PATCH_OPTAX = ("ADT_PATCH_OPTAX", bool, True)     # record optimizer construction info
    ADT_INTERNAL_BACKEND = ("ADT_INTERNAL_BACKEND", str, "")
    SYS_DATA_PATH = ("SYS_DATA_PATH", str, "")
    SYS_RESOURCE_PATH = ("SYS_RESOURCE_PATH", str, "")
    ADT_COORDINATOR_ADDR = ("ADT_COORDINATOR_ADDR", str, "")  # host:port of chief coordination service
    ADT_NUM_PROCESSES = ("ADT_NUM_PROCESSES", int, 1)
    ADT_PROCESS_ID = ("ADT_PROCESS_ID", int, 0)
    # set (on every process) by external launchers (GKE/mpirun style) that
    # start all processes simultaneously; switches the strategy handoff from
    # chief-writes-file-then-launches-workers to a collective broadcast
    ADT_EXTERNAL_LAUNCH = ("ADT_EXTERNAL_LAUNCH", bool, False)
    # coordination-service port override (tests / colocated jobs); read at
    # access time like every other ADT_* var, not frozen at import
    ADT_COORDSVC_PORT = ("ADT_COORDSVC_PORT", int, DEFAULT_COORDSVC_PORT)
    # async-PS backpressure: max gradient blobs in flight per owner queue
    # before push blocks; 0 disables
    # the client-side pacing, but the coordination service still enforces
    # a hard 4096-entry queue cap (qpush raises past it) so a dead owner
    # can never eat the host's memory
    ADT_PS_MAX_LAG = ("ADT_PS_MAX_LAG", int, 2)
    # every N steps, sync multi-process PS compares a digest of the host
    # mirrors across processes via the coordination service (0 = off);
    # catches silent mirror divergence from heterogeneous host codegen
    ADT_PS_MIRROR_CHECK_EVERY = ("ADT_PS_MIRROR_CHECK_EVERY", int, 0)
    # comma-separated mesh axis names to treat as DCN (cross-host) for the
    # spec=DCN hierarchical reduce; default: detected from process layout
    ADT_DCN_AXES = ("ADT_DCN_AXES", str, "")
    # elastic async-PS jobs: max RESTARTS per worker before the chief
    # fail-fasts (0 = reference fail-fast semantics). Elastic jobs skip the
    # jax.distributed join entirely — async PS couples processes only
    # through the parameter service, which is what makes a worker
    # restartable at all; sync strategies are collective-lockstep and stay
    # fail-fast (resume them from a checkpoint instead).
    ADT_ELASTIC = ("ADT_ELASTIC", int, 0)
    # liveness window (seconds): workers heartbeat every quarter of it;
    # the chief's watchdog treats silence longer than it as death/deadlock
    ADT_HEARTBEAT_TIMEOUT_S = ("ADT_HEARTBEAT_TIMEOUT_S", float, 60.0)
    # sync-elastic bring-up: with ADT_ELASTIC, declares the job's strategy
    # SYNCHRONOUS so processes still join jax.distributed (lockstep
    # collectives need the global mesh; recovery is whole-job re-exec with
    # a fresh process set, not per-worker rejoin)
    ADT_ELASTIC_SYNC = ("ADT_ELASTIC_SYNC", bool, False)
    # in-run elastic reconfiguration (runtime/elastic.py): with
    # ADT_ELASTIC_SYNC, a confirmed sync-worker death shrinks the job to
    # the survivors IN-RUN (epoch-fenced membership, jax.distributed
    # rejoin, in-memory re-shard) instead of the whole-job re-exec; a
    # relaunched worker grows it back. Validated loudly at bring-up
    # (elastic.validate_elastic_knobs).
    ADT_ELASTIC_INRUN = ("ADT_ELASTIC_INRUN", bool, False)
    # chief-side escalation: how long to wait for every survivor's
    # elastic/ack/<epoch> after publishing a shrink before falling back
    # to the whole-job checkpoint-restore restart (a survivor wedged in a
    # collective the dead worker will never re-enter cannot reach its
    # reconfiguration boundary)
    ADT_ELASTIC_ACK_TIMEOUT_S = ("ADT_ELASTIC_ACK_TIMEOUT_S", float, 120.0)
    # how often the Runner polls the membership epoch at readback
    # boundaries (seconds; bounds reconfiguration downtime from above)
    ADT_ELASTIC_POLL_S = ("ADT_ELASTIC_POLL_S", float, 0.5)
    # sync-elastic recovery (runtime/coordinator.py _restart_whole_job):
    # set on the re-exec'd job so Runner.init restores the latest
    # checkpoint from ADT_CKPT_DIR instead of starting fresh. Users can
    # also set it for at-most-once resume semantics on any job.
    ADT_AUTO_RESUME = ("ADT_AUTO_RESUME", bool, False)
    # checkpoint directory the auto-resume (and its periodic saves) use
    ADT_CKPT_DIR = ("ADT_CKPT_DIR", str, DEFAULT_CHECKPOINT_DIR)
    # sync-elastic reduced-world restart: comma-separated worker addresses
    # treated as PERMANENTLY lost — AutoDist drops them from the resource
    # spec at construction, so the restarted job runs at reduced world
    # size (the cross-topology sharded restore reassembles state). Set by
    # the coordinator when a worker's death triggers two consecutive
    # whole-job restarts; can also be set by hand to decommission a host.
    ADT_ELASTIC_EXCLUDE = ("ADT_ELASTIC_EXCLUDE", str, "")
    # ---- preemption plane (runtime/preemption.py): advance-notice
    # graceful departure. Default grace window a SIGTERM notice budgets
    # when the sender attached no explicit deadline (seconds — TPU
    # maintenance gives minutes, spot VMs ~30s); the rescue checkpoint is
    # skipped when the remaining budget is below the measured save p99.
    # Validated loudly (preemption.validate_preempt_knobs).
    ADT_PREEMPT_DEADLINE_S = ("ADT_PREEMPT_DEADLINE_S", float, 30.0)
    # how often Runners poll the preempt/<worker> notice marks at
    # readback boundaries (piggybacked on the elastic epoch poll;
    # 0 disables the KV poll — local SIGTERM notices still work)
    ADT_PREEMPT_POLL_S = ("ADT_PREEMPT_POLL_S", float, 1.0)
    # Retry-After (seconds) a draining serving tier attaches to its typed
    # sheds, so load balancers re-route instead of hammering the leaver
    ADT_DRAIN_RETRY_AFTER_S = ("ADT_DRAIN_RETRY_AFTER_S", float, 5.0)
    # FleetAutoscaler.start() control-loop period (seconds): how often the
    # serving autoscaler samples queue depth/p99 and re-decides; the
    # policy's sustain window and cooldowns gate actual scale events, so
    # a fast poll sharpens reaction time without causing flap
    ADT_AUTOSCALE_POLL_S = ("ADT_AUTOSCALE_POLL_S", float, 2.0)
    # cloud maintenance-event poll hook: a path whose EXISTENCE signals a
    # pending maintenance eviction for this host (its JSON body may carry
    # {"deadline_s": ..., "reason": ...}). Cloud integrations materialize
    # the metadata-server event into this file; tests touch it directly.
    ADT_MAINTENANCE_FILE = ("ADT_MAINTENANCE_FILE", str, "")
    # ---- control-plane resilience knobs (runtime/resilience.py, the
    # failure model in docs/failure_model.md documents how they compose)
    # TCP connect timeout for every CoordinationClient (seconds)
    ADT_CONNECT_TIMEOUT_S = ("ADT_CONNECT_TIMEOUT_S", float, 5.0)
    # how long CoordinationServer.start() waits for the service to come up
    ADT_COORDSVC_START_TIMEOUT_S = ("ADT_COORDSVC_START_TIMEOUT_S", float, 5.0)
    # per-RPC deadline for the resilient client (seconds; 0 = no deadline).
    # Blocking RPCs (BARRIER / WAITMIN) are exempt — they park server-side
    # by design and retry across drops on their idempotency token instead.
    ADT_RPC_TIMEOUT_S = ("ADT_RPC_TIMEOUT_S", float, 30.0)
    # retry budget: max automatic retries per RPC after a transport error
    ADT_RPC_RETRIES = ("ADT_RPC_RETRIES", int, 5)
    # circuit breaker: consecutive transport failures that open the
    # circuit, and how long it stays open before a half-open probe
    ADT_BREAKER_FAILURES = ("ADT_BREAKER_FAILURES", int, 8)
    ADT_BREAKER_COOLDOWN_S = ("ADT_BREAKER_COOLDOWN_S", float, 5.0)
    # async-PS owner apply loop: how long it keeps trying to reconnect
    # through a service blip before declaring itself unhealthy (Runner
    # then fails the job loudly instead of stalling)
    ADT_PS_OWNER_RETRY_S = ("ADT_PS_OWNER_RETRY_S", float, 60.0)
    # declarative fault plan for the FaultyProxy harness
    # (runtime/faultinject.py): JSON, or @/path/to/plan.json
    ADT_FAULT_PLAN = ("ADT_FAULT_PLAN", str, "")
    # declarative checkpoint-lifecycle fault plan (kill-at-phase SIGKILLs,
    # post-commit file damage) executed by the savers' fault hooks
    # (runtime/faultinject.py CheckpointFaultPlan): JSON, or @/path/plan.json
    ADT_CKPT_FAULT_PLAN = ("ADT_CKPT_FAULT_PLAN", str, "")
    # declarative gradient fault plan (runtime/faultinject.py
    # GradFaultPlan): deterministic step-keyed NaN/Inf/bit-flip/scale
    # injection into a named variable's gradient, COMPILED into the
    # lowering at transform time. JSON, or @/path/plan.json
    ADT_GRAD_FAULT_PLAN = ("ADT_GRAD_FAULT_PLAN", str, "")
    # training health sentinel (runtime/sentinel.py): "" / "0" off,
    # "1" default policy, or a JSON dict of SentinelPolicy knobs —
    # compiles in-graph anomaly guards and arms skip/rollback/quarantine
    ADT_SENTINEL = ("ADT_SENTINEL", str, "")
    # watchdog grace for a worker that marked itself "compiling": a first
    # dispatch's XLA compile can legitimately exceed the heartbeat window
    ADT_COMPILE_GRACE_S = ("ADT_COMPILE_GRACE_S", float, 600.0)
    # host-PS transfer/compute overlap (parallel/ps.py PSPipeline): 1 =
    # background push + prefetched pull (bit-exact for sync PS; with
    # staleness>=1 or async serving the prefetch overlaps compute fully);
    # 0 = the serial pull->step->push baseline
    ADT_PS_OVERLAP = ("ADT_PS_OVERLAP", int, 1)
    # host-PS apply parallelism: shard updates are independent by
    # construction, so they run on a thread pool of this many workers
    # (0 = auto: min(4, cpu_count); 1 = the single-dispatch baseline).
    # Bit-exact either way — grouping never changes per-shard math.
    ADT_PS_APPLY_THREADS = ("ADT_PS_APPLY_THREADS", int, 0)
    # quantized-wire scale-block size (parallel/collectives.py): elements
    # per absmax-scale block for the int8 wire codec (wire_dtype="int8" /
    # Int8 compressors). Smaller blocks = tighter scales but a bigger f32
    # sidecar: payload bytes per element = 1 + 4/block. 256 keeps the
    # sidecar under 2% while bounding each block's quantization range.
    ADT_WIRE_BLOCK = ("ADT_WIRE_BLOCK", int, 256)
    # ---- runtime telemetry (telemetry/spans.py; docs/observability.md)
    # span tracing mode: "0" off (counters still collected), "1" record
    # every span, "sampled" record 1/ADT_TRACE_SAMPLE spans
    ADT_TRACE = ("ADT_TRACE", str, "0")
    # ring-buffer capacity (completed spans kept; oldest dropped first)
    ADT_TRACE_BUFFER = ("ADT_TRACE_BUFFER", int, 65536)
    # sampled-mode stride: record one span out of every N
    ADT_TRACE_SAMPLE = ("ADT_TRACE_SAMPLE", int, 16)
    # where bench/CLI write exported traces by default
    ADT_TRACE_FILE = ("ADT_TRACE_FILE", str, "")
    # log line format: "text" (default) or "json" (structured lines
    # carrying span ids so logs correlate with traces)
    ADT_LOG_FORMAT = ("ADT_LOG_FORMAT", str, "text")
    # ---- cluster observability plane (telemetry/cluster.py, goodput.py,
    #      blackbox.py; docs/observability.md)
    # clock-offset handshake rounds against the chief's ClockSyncResponder
    # (the min-RTT round wins; more rounds ride out jitter)
    ADT_CLOCKSYNC_ROUNDS = ("ADT_CLOCKSYNC_ROUNDS", int, 8)
    # straggler flagging: EWMA z-score threshold and consecutive-dispatch
    # patience before this worker marks itself slow-but-alive
    ADT_STRAGGLER_Z = ("ADT_STRAGGLER_Z", float, 4.0)
    ADT_STRAGGLER_PATIENCE = ("ADT_STRAGGLER_PATIENCE", int, 3)
    # serviceless fleet profiling: "N:M" captures a jax.profiler trace
    # for steps N..M (inclusive) on THIS process
    ADT_PROFILE_STEPS = ("ADT_PROFILE_STEPS", str, "")
    # how often the Runner polls the coordination service's fleet
    # profiling flag (seconds; 0 disables the poll)
    ADT_PROFILE_POLL_S = ("ADT_PROFILE_POLL_S", float, 2.0)
    # flight recorder: "1" (default) arms dumps + the SIGTERM hook; "0"
    # keeps recording in memory but never writes a file
    ADT_BLACKBOX = ("ADT_BLACKBOX", bool, True)
    ADT_BLACKBOX_DIR = ("ADT_BLACKBOX_DIR", str, DEFAULT_BLACKBOX_DIR)
    # dump at normal process exit too (postmortems for runs that end
    # "cleanly" but wrong)
    ADT_BLACKBOX_DUMP = ("ADT_BLACKBOX_DUMP", bool, False)
    # bounded retention: events kept in memory, dump files kept on disk
    ADT_BLACKBOX_EVENTS = ("ADT_BLACKBOX_EVENTS", int, 256)
    ADT_BLACKBOX_KEEP = ("ADT_BLACKBOX_KEEP", int, 8)

    @property
    def val(self):
        name, typ, default = self.value
        raw = os.environ.get(name)
        if raw is None:
            return default
        if typ is bool:
            return raw not in ("", "0", "False", "false")
        return typ(raw)

    @property
    def name_str(self):
        return self.value[0]


def is_worker() -> bool:
    """True when this process was launched by the coordinator as a worker."""
    return bool(ENV.ADT_WORKER.val)


def is_chief() -> bool:
    return not is_worker()


def makedirs():
    for d in (DEFAULT_WORKING_DIR, DEFAULT_SERIALIZATION_DIR, DEFAULT_LOG_DIR,
              DEFAULT_TRACE_DIR, DEFAULT_SNAPSHOT_DIR, DEFAULT_CHECKPOINT_DIR):
        os.makedirs(d, exist_ok=True)
