"""Cost-model calibration from measured step times.

The reference's AutoSync materials (``autodist/simulator/dataset/README``)
describe LEARNED <resource_spec, strategy> -> runtime models trained on
measured runs; the shipped simulator is an empty stub. Here the analytic
cost model (``cost_model.py``) gets the measured-runs treatment without a
learned black box: each cost TERM (compute, collective, host-PS link,
launch latency) carries a multiplicative scale factor, and ``fit`` finds
the scales that best explain a handful of measured (strategy, seconds)
pairs. The analytic structure stays — calibration corrects the constants
(achieved MXU efficiency, effective link bandwidths, real launch
overheads) that no closed form gets right on every chip/tunnel/host.

Scales persist as JSON so one measured session calibrates future
``AutoStrategy`` decisions on the same hardware
(``AutoStrategy(calibration=...)``).
"""
import dataclasses
import json
import math
from typing import Sequence

from autodist_tpu.utils import logging


@dataclasses.dataclass
class Calibration:
    """Multiplicative scales for the cost model's terms. 1.0 = the
    uncalibrated analytic value."""
    compute_scale: float = 1.0   # achieved vs assumed MXU efficiency
    ar_scale: float = 1.0        # collective (ICI/DCN ring) time
    ps_scale: float = 1.0        # host link (PCIe pull/push + NIC serving)
    latency_scale: float = 1.0   # per-collective launch overhead

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Calibration":
        return cls(**{f.name: float(d.get(f.name, 1.0))
                      for f in dataclasses.fields(cls)})

    def save(self, path: str) -> str:
        import os
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)  # a long measurement session
        # must not die on a missing directory at the very last step
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        return path

    @classmethod
    def load(cls, path: str) -> "Calibration":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def _predict(breakdown, scales: Sequence[float]) -> float:
    """Step time under scaled terms — delegates to
    ``CostBreakdown.step_time_s`` on a scaled copy so the fit objective
    can never diverge from the formula simulate()/rank() use (the serial
    epilogue sum, or the exposed-tail form when the plan lowers as an
    overlapped schedule)."""
    c, a, p, l = scales
    return dataclasses.replace(
        breakdown, compute_s=breakdown.compute_s * c,
        allreduce_s=breakdown.allreduce_s * a,
        ps_s=breakdown.ps_s * p,
        mp_s=breakdown.mp_s * a,  # rides the same wire as gradient AR
        # the exposed overlap tail is wire time too — same link, same
        # bandwidth error, so the same scale corrects it
        overlap_exposed_s=breakdown.overlap_exposed_s * a,
        latency_s=breakdown.latency_s * l).step_time_s


_REGULARIZER = 1e-3


def _loss(breakdowns, measured, scales) -> float:
    # relative squared error: a 10ms model and a 200ms model weigh equally.
    # The log-space ridge term keeps UNIDENTIFIABLE scales at 1.0: a term
    # that is negligible in every measurement (e.g. launch latency under
    # millisecond steps, or an overlap tail that hides almost all wire)
    # gets no signal from the data, and without the penalty the line
    # search would walk it to an arbitrary bound.
    data = sum(((_predict(b, scales) - t) / t) ** 2
               for b, t in zip(breakdowns, measured))
    reg = _REGULARIZER * sum(math.log(s) ** 2 for s in scales)
    return data + reg


def fit(breakdowns: Sequence, measured_s: Sequence[float],
        span: float = 30.0, rounds: int = 12) -> Calibration:
    """Fit term scales by coordinate descent with golden-section line
    search in log-space (deterministic, numpy-free, a few hundred model
    evaluations). ``span`` bounds each scale to [1/span, span] — a
    measured time explained only by a 100x bandwidth error is noise, not
    signal. A term that no measurement exercises (e.g. ps_s == 0
    everywhere) keeps scale 1.0."""
    if len(breakdowns) != len(measured_s) or not breakdowns:
        raise ValueError("need equal, nonzero numbers of breakdowns and "
                         "measured times")
    if not all(t > 0 and math.isfinite(t) for t in measured_s):
        # NaN passes a `t <= 0` check and would silently corrupt every
        # golden-section comparison downstream
        raise ValueError("measured times must be positive finite seconds")
    scales = [1.0, 1.0, 1.0, 1.0]
    # ar_scale covers everything on the collective wire (allreduce_s,
    # mp_s AND the overlapped schedule's exposed tail — _predict applies
    # it to all three), so an mp-only or overlap-only measurement set
    # still exercises it
    terms = [lambda b: b.compute_s, lambda b: b.allreduce_s + b.mp_s,
             lambda b: b.ps_s, lambda b: b.latency_s]
    gr = (math.sqrt(5.0) - 1.0) / 2.0

    def golden(idx: int) -> float:
        lo, hi = -math.log(span), math.log(span)

        def f(x):
            trial = list(scales)
            trial[idx] = math.exp(x)
            return _loss(breakdowns, measured_s, trial)
        x1 = hi - gr * (hi - lo)
        x2 = lo + gr * (hi - lo)
        f1, f2 = f(x1), f(x2)
        for _ in range(40):
            if f1 < f2:
                hi, x2, f2 = x2, x1, f1
                x1 = hi - gr * (hi - lo)
                f1 = f(x1)
            else:
                lo, x1, f1 = x1, x2, f2
                x2 = lo + gr * (hi - lo)
                f2 = f(x2)
        return math.exp((lo + hi) / 2.0)

    for _ in range(rounds):
        for idx in range(4):
            if all(terms[idx](b) == 0.0 for b in breakdowns):
                continue  # unexercised term: leave at 1.0
            scales[idx] = golden(idx)
    cal = Calibration(*scales)
    logging.info("calibration fit over %d measurements: %s (residual "
                 "rel-rmse %.3f)", len(measured_s), cal.to_dict(),
                 rel_rmse(breakdowns, measured_s, cal))
    return cal


def rel_rmse(breakdowns, measured_s, cal: Calibration) -> float:
    """Root-mean-square RELATIVE prediction error of a calibration over
    measurements (0.1 = predictions within ~10%)."""
    scales = (cal.compute_scale, cal.ar_scale, cal.ps_scale,
              cal.latency_scale)
    return math.sqrt(sum(((_predict(b, scales) - t) / t) ** 2
                         for b, t in zip(breakdowns, measured_s))
                     / len(measured_s))


def fit_auto_span(breakdowns, measured_s,
                  spans=(30.0, 1e3, 1e5)) -> Calibration:
    """fit() with automatic span expansion: the tight default span keeps
    noise from masquerading as a 100x constant error, but on hardware
    whose step times are STRUCTURALLY far from the analytic terms (e.g. a
    host-dispatch-dominated CPU mesh, where per-step overhead is 1000x
    the modeled wire time) every scale saturates at the bound and the fit
    explains nothing. When the residual stays above 50% the span expands
    — with a warning, because needing it means the analytic model's
    structure, not just its constants, is off for this hardware."""
    cal = None
    for span in spans:
        cal = fit(breakdowns, measured_s, span=span)
        if rel_rmse(breakdowns, measured_s, cal) <= 0.5:
            if span != spans[0]:
                logging.warning(
                    "calibration needed scale span %g — measured times are "
                    "structurally far from the analytic terms on this "
                    "hardware; treat ranking as measurement-driven, not "
                    "model-driven", span)
            return cal
    logging.warning("calibration residual stays >50%% even at span %g; "
                    "the fitted model explains these measurements poorly",
                    spans[-1])
    return cal
