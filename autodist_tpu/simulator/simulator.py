"""Strategy simulator — ranks candidate strategies by estimated step time.

The realized version of the reference's absent AutoSync simulator
(``autodist/simulator/`` stub; its dataset README describes learned
<resource_spec, strategy> -> runtime models). Interface mirrors what the
AutoSync paper's pipeline needs: ``simulate`` one strategy, ``rank`` many.
"""
import dataclasses
from typing import List, Optional, Sequence, Tuple

from autodist_tpu.simulator.cost_model import CostBreakdown, CostModel
from autodist_tpu.strategy.base import Strategy
from autodist_tpu.utils import logging


@dataclasses.dataclass
class SimulationResult:
    strategy: Strategy
    breakdown: CostBreakdown
    label: str = ""

    @property
    def step_time_s(self) -> float:
        return self.breakdown.step_time_s


# Accuracy-risk premium for LOSSY gradient compression, applied to the
# RANKING key only (step-time estimates stay physical). Quality is not on
# the cost model's seconds scale, but a selector that defaults to rank-2
# PowerSGD because it wins microseconds on an unconstrained network is
# making an accuracy decision the user never asked for — lossless-first
# unless the wire saving is decisive (bf16 rounding is near-lossless;
# int8+EF costs measurable accuracy; low-rank PowerSGD the most).
_LOSSY_PREMIUM = {
    "HorovodCompressor": 1.02, "BF16Compressor": 1.02,
    "HorovodCompressorEF": 1.02, "BF16CompressorEF": 1.02,
    "Int8Compressor": 1.15, "Int8CompressorEF": 1.15,
    "PowerSGDCompressor": 1.35,
}


def _risk_premium(strategy: Strategy) -> float:
    """Max lossy-compression premium across the strategy's synchronizers.
    The ``wire_dtype="int8"`` quantized wire carries the same premium as
    the Int8 compressors (blockwise int8 + error feedback): it wins only
    when the wire saving is decisive — i.e. when bandwidth-bound."""
    worst = 1.0
    for node in strategy.node_config:
        syncs = ([node.synchronizer] if node.synchronizer else
                 [p.synchronizer for p in node.part_configs])
        for sync in syncs:
            name = getattr(sync, "compressor", "") or ""
            name = name.split(":")[0]
            worst = max(worst, _LOSSY_PREMIUM.get(name, 1.0))
            if (getattr(sync, "wire_dtype", "fp32") or "fp32") == "int8":
                worst = max(worst, _LOSSY_PREMIUM["Int8CompressorEF"])
    return worst


class Simulator:
    def __init__(self, model_item, resource_spec, **cost_model_kwargs):
        self._cost_model = CostModel(model_item, resource_spec,
                                     **cost_model_kwargs)

    def simulate(self, strategy: Strategy, label: str = "") -> SimulationResult:
        return SimulationResult(strategy, self._cost_model.estimate(strategy),
                                label)

    def verify(self, strategy: Strategy):
        """Static diagnostics for one candidate (``analysis/rules.py``) —
        the same gate :meth:`rank` applies, exposed for the auto-strategy
        search's per-candidate pruning."""
        return self._cost_model.verify(strategy)

    def attach_static_profile(self, profile, strategy: Strategy = None):
        """Attach measured collective costs from a lowered program (see
        ``CostModel.attach_static_profile``); subsequent simulate/rank
        calls price that strategy from measurements, logging drift."""
        self._cost_model.attach_static_profile(profile, strategy)

    def calibrate(self, measured: Sequence[Tuple[Strategy, float]],
                  save_path: Optional[str] = None):
        """Fit the cost model's term scales to measured step times
        (AutoSync's measured-runs idea over the analytic model — see
        ``calibration.py``). ``measured`` pairs each strategy with its
        observed seconds/step on THIS model and hardware. The fitted
        ``Calibration`` is applied to this simulator (subsequent
        ``simulate``/``rank`` calls use it), optionally saved to
        ``save_path`` for reuse via
        ``AutoStrategy(calibration=...)``."""
        from autodist_tpu.simulator import calibration as cal_lib
        prev = self._cost_model.calibration
        self._cost_model.calibration = None  # fit against RAW terms
        try:
            breakdowns = [self._cost_model.estimate(s) for s, _ in measured]
        finally:
            self._cost_model.calibration = prev
        cal = cal_lib.fit_auto_span(breakdowns, [t for _, t in measured])
        self._cost_model.calibration = cal
        if save_path:
            cal.save(save_path)
        return cal

    def rank(self, candidates: Sequence[Tuple[str, Strategy]],
             skip_projected_oom: bool = False) -> List[SimulationResult]:
        """Feasible (fits-in-HBM) candidates rank ahead of infeasible
        ones regardless of estimated speed — a fast strategy that OOMs is
        not a strategy; within each group, cheapest step time wins. If
        nothing fits, the ranking still returns (cheapest first) with a
        warning rather than failing the build. Lossy-compression
        candidates carry an accuracy-risk premium in the sort key (see
        ``_risk_premium``) so they win only when the wire saving is
        decisive, not on microsecond ties.

        Before any pricing, each candidate runs the static verifier
        (``CostModel.verify`` -> ``analysis/rules.py``); candidates with
        error-severity diagnostics are skipped with a logged reason —
        there is no point ranking a plan that cannot compile. If EVERY
        candidate fails verification the unverified ranking is returned
        (with a warning) so a caller always gets an ordering.

        ``skip_projected_oom=True`` additionally DROPS candidates whose
        memory estimate raises ``ADT501`` (projected per-device OOM
        against the chip's HBM budget), mirroring the verify() skip path
        — each skip is logged with the diagnostic, and if every candidate
        would OOM the unskipped ranking is returned with a warning. The
        default keeps the softer rank-infeasible-last behavior."""
        from autodist_tpu.analysis.diagnostics import Severity
        kept = []
        for label, s in candidates:
            errs = [d for d in self._cost_model.verify(s)
                    if d.severity >= Severity.ERROR]
            if errs:
                logging.info(
                    "simulator: skipping un-compilable candidate %s: %s",
                    label or s.id,
                    "; ".join(d.format() for d in errs[:3])
                    + ("; +%d more" % (len(errs) - 3) if len(errs) > 3
                       else ""))
                continue
            kept.append((label, s))
        if candidates and not kept:
            logging.warning(
                "simulator: every candidate failed static verification; "
                "ranking them unverified — expect the build to fail with "
                "the same diagnostics")
            kept = list(candidates)
        results = [self.simulate(s, label) for label, s in kept]
        if skip_projected_oom:
            from autodist_tpu.analysis.memory import budget_diagnostics
            fitting = []
            for r in results:
                oom = [d for d in budget_diagnostics(
                    r.breakdown.hbm_bytes, r.breakdown.hbm_capacity,
                    source="plan-level") if d.code == "ADT501"]
                if oom:
                    logging.info(
                        "simulator: skipping projected-OOM candidate %s: "
                        "%s", r.label or r.strategy.id, oom[0].format())
                    continue
                fitting.append(r)
            if results and not fitting:
                logging.warning(
                    "simulator: every candidate is projected to OOM "
                    "(ADT501); ranking them anyway — expect allocation "
                    "failures at the first step")
            else:
                results = fitting
        results.sort(key=lambda r: (not r.breakdown.feasible,
                                    r.step_time_s * _risk_premium(r.strategy)))
        if results and not results[0].breakdown.feasible:
            logging.warning(
                "no candidate strategy fits the HBM estimate (best %s needs "
                "%.1f GB of %.1f GB); ranking by speed anyway",
                results[0].label, results[0].breakdown.hbm_bytes / 1e9,
                results[0].breakdown.hbm_capacity / 1e9)
        for r in results:
            logging.debug("simulated %-28s step=%.3fms (compute=%.3f ar=%.3f "
                          "ps=%.3f hbm=%.2fGB%s)", r.label,
                          r.step_time_s * 1e3,
                          r.breakdown.compute_s * 1e3,
                          r.breakdown.allreduce_s * 1e3,
                          r.breakdown.ps_s * 1e3,
                          r.breakdown.hbm_bytes / 1e9,
                          "" if r.breakdown.feasible else " INFEASIBLE")
        return results
