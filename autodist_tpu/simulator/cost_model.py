"""Analytic strategy cost model.

The reference ships an EMPTY simulator (``autodist/simulator/__init__.py``
is 0 lines — only the AutoSync dataset README survives; SURVEY §L8), while
its docs describe automatic strategy optimization. Here the cost model is
real: an analytic roofline for one training step under a given Strategy on
a given TPU topology, in the spirit of the scaling-book communication
recipes — compute from jaxpr FLOPs on the MXU, collective costs from
ICI/DCN link bandwidths, PS costs from per-server byte loads.

Deliberately simple (closed-form, no learned component): its job is to
*rank* candidate strategies for ``AutoStrategy``, not to predict wall time
exactly.
"""
import dataclasses
import math
from typing import Dict, Optional

from autodist_tpu.strategy.base import (AllReduceSynchronizer, PSSynchronizer,
                                        Strategy, ZeroShardedSynchronizer)
from autodist_tpu.utils import logging

# Peak dense bf16 FLOP/s per chip by generation (public figures).
CHIP_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "cpu": 5e10,
}
# HBM per chip now lives in resource_spec.py (the ResourceSpec owns the
# cluster's memory budget; re-exported here for back-compat)
from autodist_tpu.resource_spec import CHIP_HBM_BYTES  # noqa: E402,F401
# extra compute for gradient rematerialization: "full" re-runs the whole
# forward in the backward (fwd+bwd ~3x fwd -> ~4x), "dots" recomputes
# only the cheap non-contraction work (~3.5x)
REMAT_COMPUTE_FACTOR = {None: 1.0, "full": 4.0 / 3.0, "dots": 3.5 / 3.0}
# step-time gain of the managed bf16 compute tier
# (graph_config.compute_dtype="bf16") over the f32 baseline the model is
# calibrated against: the MXU runs bf16 matmuls at ~2x the f32 rate and
# halves the activation traffic, but the f32 master update, the casts and
# the f32 gradient collectives claw some back — ~1.8x is the typical
# measured envelope, conservative enough that the searcher only picks
# bf16 when the plan is genuinely compute-bound
BF16_COMPUTE_SPEEDUP = 1.8
# Price of the fused 1F1B implementation (parallel/pipeline._run_1f1b):
# 2(M+S-1) ticks whose lax.cond body executes ONE of {stage forward,
# recompute+backward vjp} per tick (parity is uniform over model/data
# axes, so in-branch collectives stay matched) — ~4(M+S-1) fwd-units vs
# GPipe's ~3(M+S-1): the 4/3 is the per-microbatch recompute.
F1B_RECOMPUTE_FACTOR = 4.0 / 3.0
DEFAULT_MXU_EFFICIENCY = 0.4      # achieved/peak for typical training steps
WIRE_DTYPE_BYTES = 4              # gradients travel fp32 unless compressed
# host<->device link for the host-offloaded PS path (no-proxy PS keeps
# values+opt state in host RAM; every step pulls/pushes over PCIe)
PCIE_BANDWIDTH_BYTES_S = 32e9
COMPRESSED_BYTES = {"HorovodCompressor": 2, "HorovodCompressorEF": 2,
                    "BF16Compressor": 2, "BF16CompressorEF": 2,
                    "Int8Compressor": 1, "Int8CompressorEF": 1}
PER_COLLECTIVE_LATENCY_S = 5e-6   # launch overhead per collective/bucket
PER_HOP_LATENCY_S = 1e-6          # per ring/tree hop under topology pricing

# forward wire factors per cost class at axis size k: bytes crossing each
# link of a ring, relative to the TRACED payload (gather traces one shard,
# scatter/permute/alltoall trace the full input, reduce traces the psum
# operand — see _COLLECTIVE_KINDS in kernel/common/utils.py)
_FWD_WIRE_FACTOR = {
    "reduce": lambda k: 2.0 * (k - 1) / k,   # ring all-reduce
    "gather": lambda k: float(k - 1),        # all_gather of one shard
    "scatter": lambda k: (k - 1) / k,        # reduce_scatter of the input
    "permute": lambda k: (k - 1) / k,        # ring hop amortized
    "alltoall": lambda k: (k - 1) / k,
}

# the transpose of each collective is its DUAL class
_DUAL_CLASS = {"gather": "scatter", "scatter": "gather",
               "reduce": "reduce", "permute": "permute",
               "alltoall": "alltoall"}


def collective_wire_bytes(kind: str, traced_bytes: float, k: int,
                          direction: str = "fwd") -> float:
    """Ring wire bytes for one collective of ``kind`` with
    ``traced_bytes`` payload at axis size ``k``.

    ``direction="bwd"`` prices the TRANSPOSE as its dual class with the
    dual's payload:

    - gather (traced B = one shard) transposes to a reduce_scatter of the
      FULL cotangent k*B: wire (k-1)/k * kB = (k-1)B — equal to fwd.
    - scatter (traced B = full input) transposes to an all_gather of k
      shards of B/k: wire (k-1) * B/k — equal to fwd's (k-1)/k * B.
    - reduce's transpose is free, but every Megatron-style layer pairs a
      fwd psum with its dual layer's bwd psum (row- vs column-parallel),
      so the program-level backward moves the same reduce bytes.
    - permute/alltoall are self-dual (inverted permutation / shuffle).
    """
    if direction == "bwd":
        dual = _DUAL_CLASS[kind]
        if kind == "gather":
            return collective_wire_bytes(dual, traced_bytes * k, k, "fwd")
        if kind == "scatter":
            return collective_wire_bytes(dual, traced_bytes / k, k, "fwd")
        return collective_wire_bytes(dual, traced_bytes, k, "fwd")
    return _FWD_WIRE_FACTOR[kind](k) * traced_bytes


@dataclasses.dataclass
class StaticCollectiveProfile:
    """Measured per-step collective costs of a LOWERED program — the
    replacement for the jaxpr-level heuristics when a lowering exists.

    Built from a :class:`~autodist_tpu.analysis.hlo.CollectiveSchedule`
    (duck-typed: anything iterable of objects with ``kind``,
    ``payload_bytes`` and ``group_size``). Payloads are the per-device
    operand bytes the program actually moves (forward AND backward ops
    are both present in the text, so no dual-class doubling applies);
    wire bytes are ring-priced per op at its OWN replica-group size —
    more precise than pricing by a single mesh-axis extent.
    """

    class_payload_bytes: Dict[str, float]
    class_wire_bytes: Dict[str, float]
    num_collectives: int = 0
    # per-link-level wire bytes (level name -> bytes/step), populated
    # when the profile is built against a multi-level topology: every
    # replica group's ring edges are attributed to the physical level
    # they cross (analysis/topology.py). Empty on flat specs.
    level_wire_bytes: Dict[str, float] = dataclasses.field(
        default_factory=dict)

    @classmethod
    def from_schedule(cls, schedule, default_group_size: int = 1,
                      topology=None) -> "StaticCollectiveProfile":
        per_step = (schedule.per_step() if hasattr(schedule, "per_step")
                    else schedule)
        levels: Dict[str, float] = {}
        if topology is not None:
            from autodist_tpu.analysis.topology import schedule_level_bytes
            levels = schedule_level_bytes(
                per_step, topology, default_group_size=default_group_size)
        payload: Dict[str, float] = {}
        wire: Dict[str, float] = {}
        n = 0
        for c in per_step:
            k = c.group_size if c.group_size > 1 else default_group_size
            if k <= 1:
                continue  # single-device group: no wire crossed
            payload[c.kind] = payload.get(c.kind, 0.0) + c.payload_bytes
            wire[c.kind] = (wire.get(c.kind, 0.0)
                            + collective_wire_bytes(c.kind,
                                                    c.payload_bytes, k))
            n += 1
        return cls(payload, wire, n, level_wire_bytes=levels)

    @property
    def total_wire_bytes(self) -> float:
        return sum(self.class_wire_bytes.values())


@dataclasses.dataclass
class CostBreakdown:
    compute_s: float
    allreduce_s: float
    ps_s: float
    latency_s: float
    # model-parallel collective time (Megatron psums, ring-attention
    # ppermutes, MoE all_to_alls): these live INSIDE the forward/backward
    # on the compute critical path, so unlike the gradient collectives
    # they do not overlap with compute
    mp_s: float = 0.0
    # per-device HBM estimate (params + optimizer + gradient buffer +
    # activations) and whether it fits the chip — strategies change all
    # four terms: host-PS offloads params/opt, ZeRO partitions them,
    # remat shrinks activations
    hbm_bytes: float = 0.0
    hbm_capacity: float = float("inf")
    # overlapped gradient-sync schedule (graph_config.overlap): did the
    # plan lower sync as a barrier-chained per-bucket schedule, how many
    # stages, and how much collective time stays EXPOSED past the end of
    # the backward (the per-bucket max(compute_tail, wire) queueing
    # recurrence in ``estimate()``, calibrated against the goodput
    # report's measured collective_wait by the drift row "overlap")
    overlap: bool = False
    overlap_stages: int = 0
    overlap_exposed_s: float = 0.0

    @property
    def feasible(self) -> bool:
        return self.hbm_bytes <= self.hbm_capacity

    @property
    def step_time_s(self) -> float:
        # The epilogue lowering computes the FULL gradient, then runs the
        # collectives, then applies: compute and gradient wire add (they
        # only overlap when the program is lowered as an overlap schedule
        # — the old unconditional max(compute, wire) here silently
        # credited every plan with an overlap the lowering never did).
        # Under an overlapped schedule only the EXPOSED tail of the wire
        # (what the backward could not hide — never less than the last
        # bucket's reduce) is paid on top of compute. PS wire,
        # model-parallel collectives and launch latency are serial in
        # both lowerings, so they cancel in overlap-vs-epilogue
        # comparisons.
        wire = self.overlap_exposed_s if self.overlap else self.allreduce_s
        return (self.compute_s + wire + self.ps_s
                + self.mp_s + self.latency_s)


class CostModel:
    def __init__(self, model_item, resource_spec,
                 chip_kind: Optional[str] = None,
                 mxu_efficiency: float = DEFAULT_MXU_EFFICIENCY,
                 flops_per_step: Optional[float] = None,
                 hbm_capacity_bytes: Optional[float] = None,
                 calibration=None, while_trip_count: int = 1,
                 static_profile: Optional[StaticCollectiveProfile] = None):
        self._item = model_item
        self._spec = resource_spec
        # measured collective costs from lowered programs: one profile per
        # strategy id, plus an optional default applied to every strategy
        # (the `static_profile` kwarg). When a strategy has a profile, its
        # collective seconds are priced from MEASURED wire bytes and the
        # heuristic-vs-measured drift is logged per collective class.
        self._static_profiles: Dict[Optional[str], StaticCollectiveProfile] = {}
        if static_profile is not None:
            self._static_profiles[None] = static_profile
        self._chip = chip_kind or self._guess_chip()
        self._eff = mxu_efficiency
        self._flops = flops_per_step
        if hbm_capacity_bytes is not None:
            self._hbm_capacity = hbm_capacity_bytes
        elif chip_kind is not None:
            # an explicit chip override prices that generation's memory
            # even when the spec describes another
            self._hbm_capacity = CHIP_HBM_BYTES[chip_kind]
        else:
            self._hbm_capacity = resource_spec.chip_hbm_bytes()
        self._act_cache = None
        # assumed iterations for while_loop bodies when profiling the
        # loss's collectives (statically unknowable; see
        # kernel/common/utils.py collective_comm_profile)
        self._while_trip_count = int(while_trip_count)
        # measured-run correction of the analytic constants: a Calibration,
        # a path to a saved one, or None (uncalibrated)
        if isinstance(calibration, str):
            from autodist_tpu.simulator.calibration import Calibration
            calibration = Calibration.load(calibration)
        self.calibration = calibration

    def attach_static_profile(self, profile: StaticCollectiveProfile,
                              strategy: Optional[Strategy] = None):
        """Attach MEASURED collective costs (extracted from a lowered
        program via ``analysis.hlo.collective_schedule`` /
        ``Runner.static_profile``) for ``strategy`` — or, with no
        strategy, as the default for every estimate. Subsequent
        :meth:`estimate` calls price that strategy's collectives from the
        measured wire bytes instead of the jaxpr heuristics and log the
        per-class drift."""
        key = getattr(strategy, "id", None) if strategy is not None else None
        self._static_profiles[key] = profile

    def _static_profile_for(self, strategy: Strategy
                            ) -> Optional[StaticCollectiveProfile]:
        by_id = self._static_profiles.get(getattr(strategy, "id", None))
        return by_id if by_id is not None else self._static_profiles.get(None)

    def _heuristic_wire_by_class(self, strategy: Strategy, n: int,
                                 ar_bytes: float) -> Dict[str, float]:
        """The jaxpr-heuristic wire bytes per collective class — the
        numbers a static profile replaces, kept for drift logging."""
        out: Dict[str, float] = {}
        if n > 1 and ar_bytes > 0:
            out["reduce"] = 2.0 * (n - 1) / n * ar_bytes
        mesh_shape = strategy.graph_config.mesh_shape or {}
        for axis, by_kind in self._collective_profile().items():
            k = int(mesh_shape.get(axis, 1))
            if k <= 1:
                continue
            for kind, traced in by_kind.items():
                out[kind] = out.get(kind, 0.0) + (
                    collective_wire_bytes(kind, traced, k, "fwd")
                    + collective_wire_bytes(kind, traced, k, "bwd"))
        return out

    def _log_static_drift(self, strategy: Strategy,
                          profile: StaticCollectiveProfile, n: int,
                          ar_bytes: float):
        heur = self._heuristic_wire_by_class(strategy, n, ar_bytes)
        for kind in sorted(set(heur) | set(profile.class_wire_bytes)):
            h = heur.get(kind, 0.0)
            m = profile.class_wire_bytes.get(kind, 0.0)
            ratio = (m / h) if h > 0 else float("inf") if m > 0 else 1.0
            logging.info(
                "static profile drift [%s/%s]: heuristic=%.0fB "
                "measured=%.0fB ratio=%.2f", strategy.id, kind, h, m, ratio)

    def verify(self, strategy: Strategy):
        """Static diagnostics for a candidate (``analysis/rules.py``):
        the cheap validity gate the simulator applies BEFORE estimating —
        pricing an un-compilable plan would just hand the auto-strategy
        search a winner that explodes at lowering time."""
        from autodist_tpu.analysis import verify as _verify
        return _verify(strategy, self._item, self._spec)

    def _guess_chip(self) -> str:
        kind = self._spec.chip_kind()
        return kind if kind in CHIP_PEAK_FLOPS else "v4"

    # ---------------------------------------------------------------- pieces

    def _example_batch_size(self) -> int:
        """Leading dim of the example batch (the real batch size), falling
        back to 32 only when no batch is attached."""
        try:
            import jax
            leaves = jax.tree_util.tree_leaves(self._item.example_batch)
            for leaf in leaves:
                shape = getattr(leaf, "shape", ())
                if len(shape) >= 1 and shape[0] > 0:
                    return int(shape[0])
        except Exception:  # noqa: BLE001
            pass
        return 32

    def _loss_jaxpr(self):
        """ONE cached trace of the loss (under a bound axis env so
        collective-using losses trace too) shared by the FLOPs and
        activation estimates — two traces could silently diverge when one
        falls back and the other succeeds."""
        if not hasattr(self, "_jaxpr_cache"):
            try:
                import jax
                from autodist_tpu.utils.axis_env import bound_axes
                with bound_axes():
                    self._jaxpr_cache = jax.make_jaxpr(self._item.loss_fn)(
                        self._item.params, self._item.example_batch)
            except Exception:  # noqa: BLE001 — callers fall back
                self._jaxpr_cache = None
        return self._jaxpr_cache

    def flops_per_step(self) -> float:
        if self._flops is not None:
            return self._flops
        closed = self._loss_jaxpr()
        if closed is not None:
            from autodist_tpu.kernel.common.utils import count_flops_estimate
            fwd = count_flops_estimate(closed.jaxpr)
        else:
            # dense fwd ~ 2 * params * batch (the REAL batch size, not a
            # guess — a hardcoded 32 misranks compute- vs comm-bound
            # candidates for large-batch CNNs)
            fwd = 2.0 * (self._item.total_bytes() / 4) * self._example_batch_size()
        self._flops = 3.0 * fwd  # fwd + ~2x bwd
        return self._flops

    def compute_time(self, num_devices: int) -> float:
        peak = CHIP_PEAK_FLOPS[self._chip] * self._eff
        return self.flops_per_step() / max(num_devices, 1) / peak

    # shape-only ops fuse away in XLA and hold no residual of their own
    _FUSED_OPS = frozenset({
        "broadcast_in_dim", "reshape", "transpose", "convert_element_type",
        "squeeze", "expand_dims", "slice", "rev", "copy", "stop_gradient",
        "reduce_precision"})

    def _activation_profile(self):
        """(saved-residual bytes, dot/conv output bytes, batch input
        bytes) from the loss jaxpr — the activation-memory inputs for the
        three remat modes. The walk counts LEAF eqn outputs only (a call
        primitive's outputs are its body's outputs — counting both would
        double), multiplies scan bodies by their trip count (a scanned
        48-layer stack saves 48 layers of residuals, not one), and skips
        shape-only ops XLA fuses away. Still a heuristic — no liveness
        analysis — but for TRAINING the sum of non-trivial forward
        outputs approximates the residual set autodiff actually keeps,
        which is exactly the memory remat trades away."""
        if self._act_cache is not None:
            return self._act_cache
        import numpy as np

        def aval_bytes(v):
            aval = getattr(v, "aval", None)
            if aval is None or not hasattr(aval, "shape"):
                return 0
            return int(np.prod(aval.shape or (1,))) * np.dtype(
                aval.dtype).itemsize

        total, dots = 0.0, 0.0

        def sub_jaxprs(eqn):
            subs = []
            for val in eqn.params.values():
                for item in (val if isinstance(val, (list, tuple))
                             else (val,)):
                    if hasattr(item, "jaxpr"):
                        subs.append(item.jaxpr)
                    elif hasattr(item, "eqns") and hasattr(item, "invars"):
                        subs.append(item)
            return subs

        def walk(jaxpr, mult):
            nonlocal total, dots
            for eqn in jaxpr.eqns:
                name = eqn.primitive.name
                subs = sub_jaxprs(eqn)
                if name == "scan":
                    inner_mult = mult * int(eqn.params.get("length", 1) or 1)
                    for sub in subs:
                        walk(sub, inner_mult)
                elif subs:  # pjit/checkpoint/custom_vjp/while/cond bodies
                    for sub in subs:
                        walk(sub, mult)
                elif name in self._FUSED_OPS:
                    continue
                else:
                    b = mult * sum(aval_bytes(ov) for ov in eqn.outvars)
                    total += b
                    if name in ("dot_general", "conv_general_dilated"):
                        dots += b

        closed = self._loss_jaxpr()
        if closed is not None:
            import jax
            walk(closed.jaxpr, 1)
            batch_in = float(sum(
                int(np.prod(np.shape(l) or (1,))) * np.dtype(
                    np.asarray(l).dtype).itemsize
                for l in jax.tree_util.tree_leaves(self._item.example_batch)))
        else:  # params-based bound
            total = 2.0 * self._item.total_bytes()
            dots = total / 2
            batch_in = total / 8
        self._act_cache = (float(total), float(dots), float(batch_in))
        return self._act_cache

    def _collective_profile(self):
        """{axis: fwd payload bytes} of the loss's own collectives, from
        ONE cached trace (the same jaxpr the FLOPs/activation estimates
        use). Empty when the loss has no model-parallel collectives or
        the trace failed."""
        if not hasattr(self, "_coll_cache"):
            closed = self._loss_jaxpr()
            if closed is None:
                self._coll_cache = {}
            else:
                from autodist_tpu.kernel.common.utils import (
                    collective_comm_profile)
                self._coll_cache = collective_comm_profile(
                    closed.jaxpr,
                    while_trip_count=self._while_trip_count)
        return self._coll_cache

    def mp_comm_time(self, strategy: Strategy, ici_bw: float) -> float:
        """Serial model-parallel collective seconds per step, by cost
        class (see ``_COLLECTIVE_KINDS`` in kernel/common/utils.py for
        how each class's traced bytes relate to real wire at axis size
        k). The backward is priced as each collective's DUAL CLASS via
        :func:`collective_wire_bytes` — a gather's transpose is a
        reduce_scatter of the full cotangent, a scatter's is an
        all_gather of the shards, reduce pairs with its dual layer's
        psum (row- vs column-parallel), permute/alltoall invert
        themselves. Per class the dual's wire equals the forward's (see
        the algebra in ``collective_wire_bytes``), so the total comes
        out fwd+bwd = 2x — now computed, not asserted
        (tests/test_simulator.py::test_dual_class_backward_pricing)."""
        mesh_shape = strategy.graph_config.mesh_shape or {}
        total = 0.0
        for axis, by_kind in self._collective_profile().items():
            k = int(mesh_shape.get(axis, 1))
            if k <= 1:
                continue  # axis not materialized: collective is a no-op
            wire = sum(
                collective_wire_bytes(kind, traced, k, "fwd")
                + collective_wire_bytes(kind, traced, k, "bwd")
                for kind, traced in by_kind.items())
            total += wire / ici_bw
        return total

    def opt_state_bytes(self) -> float:
        """Total optimizer-state bytes (full tree, undistributed); 0.0
        when no optimizer is attached. Shared by :meth:`hbm_bytes` and
        the plan-level memory analyzer (``analysis/memory.py``)."""
        try:
            import jax
            import numpy as np
            spec = self._item.opt_state_spec
            return float(sum(
                int(np.prod(l.shape or (1,))) * np.dtype(l.dtype).itemsize
                for l in jax.tree_util.tree_leaves(spec)))
        except Exception:  # noqa: BLE001 — no optimizer attached
            return 0.0

    def hbm_bytes(self, strategy: Strategy) -> float:
        """Per-device HBM estimate under a strategy: device-resident
        params + optimizer state + one gradient buffer + activations.
        Host-PS (no proxy) offloads optimizer state (values are still
        pulled to device each step); partitioned storage divides by the
        replica count (ZeRO-3-style); ZeroSharded sync keeps params full
        but divides the optimizer-state share by the replica count (the
        ~(P-1)/P drop the ADT501 gate must project, or sharded plans
        would be refused the memory they just freed);
        ``graph_config.remat`` shrinks the activation term ("dots":
        contraction outputs only; "full": batch residuals plus the peak
        recompute window)."""
        infos = self._item.var_infos
        n = max(len(strategy.graph_config.replicas), 1)
        opt_total = self.opt_state_bytes()
        params_total = float(self._item.total_bytes())

        mesh_shape = strategy.graph_config.mesh_shape or {}
        device_params = 0.0
        device_param_fraction_num = 0.0
        for node in strategy.node_config:
            info = infos.get(node.var_name)
            if info is None:
                continue
            syncs = ([node.synchronizer] if node.synchronizer else
                     [p.synchronizer for p in node.part_configs])
            host_ps = any(isinstance(s, PSSynchronizer)
                          and not s.local_replication for s in syncs)
            zero = any(isinstance(s, ZeroShardedSynchronizer)
                       for s in syncs)
            share = (1.0 / n) if node.partitioner and not host_ps else 1.0
            if node.mp_axes:
                # model-parallel storage: each device holds 1/extent of
                # every sharded dim (tensor/pipeline/expert axes)
                for _dim, axis in dict(node.mp_axes).items():
                    share /= max(int(mesh_shape.get(axis, 1)), 1)
            if host_ps:
                # pulled copy lives on device during the step, but the
                # optimizer state does not
                device_params += info.byte_size
            elif zero:
                # ZeRO-sharded update: params (and the gradient buffer)
                # stay full, but optimizer state is created sharded —
                # each chip holds 1/P of this variable's opt-state share
                device_params += info.byte_size
                device_param_fraction_num += info.byte_size / n
            else:
                device_params += info.byte_size * share
                device_param_fraction_num += info.byte_size * share
        opt_bytes = (opt_total * device_param_fraction_num / params_total
                     if params_total else 0.0)
        grad_bytes = device_params  # one gradient buffer alongside params

        total_act, dot_act, batch_in = self._activation_profile()
        remat = strategy.graph_config.remat
        if remat == "full":
            act = batch_in + (total_act - dot_act) * 0.1  # peak recompute
        elif remat == "dots":
            act = dot_act + batch_in
        else:
            act = total_act + batch_in
        act /= n  # activations scale with the per-device batch shard
        if getattr(strategy.graph_config, "compute_dtype", "f32") == "bf16":
            # the managed bf16 tier stores residuals at half width (params,
            # opt state, and the gradient buffer stay f32 — the master)
            act *= 0.5
        # 1F1B pipeline schedule: at most S microbatches in flight per
        # rank vs GPipe's all-M residency (Narayanan et al. 1806.03377)
        from autodist_tpu import const as _const
        mesh = strategy.graph_config.mesh_shape or {}
        pp = int(mesh.get(_const.PIPELINE_AXIS, 1))
        m = int(strategy.graph_config.pp_microbatches or 1)
        if pp > 1 and strategy.graph_config.pp_schedule == "1f1b" and m > pp:
            act *= pp / m
        return device_params + opt_bytes + grad_bytes + act

    @staticmethod
    def _int8_payload(num_elements: int) -> float:
        """Quantized wire payload at its TRUE byte width: int8 body padded
        to scale blocks PLUS the f32 scale sidecar — the same formula the
        lowering's telemetry counters use
        (``collectives.int8_wire_payload_bytes``), so predicted and
        measured bytes can only drift by padding, never by formula."""
        from autodist_tpu.parallel.collectives import int8_wire_payload_bytes
        q, _ = int8_wire_payload_bytes(num_elements, WIRE_DTYPE_BYTES)
        return float(q)

    def _wire_bytes(self, info, sync, compressed: bool = True,
                    wire_ok: bool = True) -> float:
        from autodist_tpu.kernel.synchronization import compressor as compressor_lib
        from autodist_tpu.parallel.collectives import wire_quantizable
        if getattr(info, "sparse", False):
            # sparse (gather-indexed) gradients ship as (ids, values)
            # pairs and the lowering IGNORES compressors on them (the
            # linter's ADT306) — pricing them compressed let whole-graph
            # compressor candidates win on bytes they never save
            compressed = False
        if (getattr(sync, "wire_dtype", "fp32") or "fp32") == "int8" \
                and wire_ok and wire_quantizable(info):
            # wire_dtype=int8: blockwise int8 + scale sidecar. On the PS
            # path the host wire quantizes regardless of partitioning
            # (shards split host-side after dequant); on AllReduce only
            # the unpartitioned collective honors it (the reduce-scatter
            # path ignores wire codecs — ADT310 warns). The ZeroSharded
            # rs/ag pair is priced separately in :meth:`estimate`
            # through the kernel's padded formula. Callers pass
            # ``wire_ok=False`` on paths the runtime never quantizes
            # (proxied PS, model-parallel complement reductions) so a
            # mispinned plan is not priced 4x cheaper than it runs.
            if getattr(sync, "kind", "") == "PS" or compressed:
                comp = getattr(sync, "compressor", "") or "NoneCompressor"
                if getattr(sync, "kind", "") == "PS" \
                        or comp == "NoneCompressor":
                    return self._int8_payload(info.num_elements)
        if not compressed:
            # partitioned/reduce-scatter syncs ignore compressors entirely
            return info.num_elements * WIRE_DTYPE_BYTES
        try:
            name, rank = compressor_lib.parse_name(getattr(sync, "compressor", ""))
        except ValueError:
            name, rank = getattr(sync, "compressor", ""), None
        if name == "PowerSGDCompressor":
            if len(info.shape) >= 2:
                # PowerSGD flattens trailing dims to an n x m matrix and
                # ships P (n x r) + Q (m x r), so wire bytes scale with rank
                n = info.shape[0]
                m = info.num_elements // max(n, 1)
                return float(rank or 1) * (n + m) * WIRE_DTYPE_BYTES
            # rank-0/1 tensors pass through PowerSGD uncompressed
            return info.num_elements * WIRE_DTYPE_BYTES
        if name in ("Int8Compressor", "Int8CompressorEF"):
            # int8 compressors ride the same blockwise wire codec: the
            # scale sidecar is part of the payload, not free (the byte
            # accounting the drift tests assert on)
            return self._int8_payload(info.num_elements)
        factor = COMPRESSED_BYTES.get(name, None)
        if factor is None:
            factor = WIRE_DTYPE_BYTES
        return info.num_elements * factor

    def _topology_ar_time(self, sched: str, payload: float, topo,
                          n: int) -> float:
        """Price one resolved gradient-sync algorithm per link level.

        ring/rhd move the full 2(n-1)/n*P over the bottleneck level (the
        inter-host link once the group spans hosts) and differ only in
        hop count — 2(n-1) vs 2*ceil(log2 n) latency hops; hier pays
        2(c-1)/c*P at intra speed plus 2(H-1)/H*(P/c) at inter speed
        with 2(c-1)+2(H-1) hops (arXiv 2110.10548's two-level
        reduction). Hops are charged at PER_HOP_LATENCY_S each, which is
        what lets recursive halving/doubling win small payloads and the
        hierarchical schedule win slow inter-host links."""
        if n <= 1 or payload <= 0:
            return 0.0
        intra_bw = topo.intra_level.bandwidth_bytes_s
        inter = topo.inter_level
        inter_bw = inter.bandwidth_bytes_s if inter is not None else intra_bw
        cph = max(topo.chips_per_host, 1)
        hosts = min(max(1, -(-n // cph)), max(topo.hosts, 1))
        c = min(n, cph)
        if sched == "hier" and hosts > 1 and c > 1:
            t = (2.0 * (c - 1) / c * payload / intra_bw
                 + 2.0 * (hosts - 1) / hosts * (payload / c) / inter_bw)
            hops = 2 * (c - 1) + 2 * (hosts - 1)
        else:
            bw = inter_bw if hosts > 1 else intra_bw
            t = 2.0 * (n - 1) / n * payload / bw
            hops = (2 * int(math.ceil(math.log2(n))) if sched == "rhd"
                    else 2 * (n - 1))
        return t + hops * PER_HOP_LATENCY_S

    # ------------------------------------------------------------------ main

    def estimate(self, strategy: Strategy,
                 use_static_profile: bool = True) -> CostBreakdown:
        """Price one candidate. ``use_static_profile=False`` forces the
        pure jaxpr-heuristic pricing even when a measured profile is
        attached — the baseline the drift reports compare against
        (``telemetry/drift.py``) without touching shared state."""
        n = max(len(strategy.graph_config.replicas), 1)
        # int8 rings run per-axis on multi-axis meshes (sequential rings),
        # so compression no longer degrades off single-axis meshes
        infos = self._item.var_infos
        ici_bw = self._spec.ici_bandwidth_gbps() * 1e9 / 8  # bytes/s
        # cross-host PS traffic rides the node NICs
        dcn_bw = min((self._spec.network_bandwidth_gbps(a)
                      for a in self._spec.node_addresses)) * 1e9 / 8

        ar_bytes = 0.0
        # gradient-sync payload bytes by RESOLVED collective algorithm
        # (analysis/topology.py resolve_schedule): only plain AllReduce
        # syncs carry the schedule knob; ZeRO/proxied-PS contributions
        # stay on the ring formula. Irrelevant (all "ring") without a
        # topology on the spec.
        ar_sched_bytes: Dict[str, float] = {}
        topo = self._spec.topology()
        ps_load: Dict[str, float] = {}
        groups = set()
        num_ps_transfers = 0
        num_zero_colls = 0
        # overlapped-schedule stage accounting: one stage per concat
        # bucket (group x compressor), per individually-synced AR var,
        # and per ZeRO reduce-scatter — mirrors the lowering's
        # build_grad_sync_schedule unit construction
        from autodist_tpu.parallel.collectives import (_CONCATABLE,
                                                       wire_quantizable)
        overlap_groups = set()
        overlap_pervar = 0
        num_zero_vars = 0
        ps_stale = False
        mesh_cfg = strategy.graph_config.mesh_shape or {}
        for node in strategy.node_config:
            info = infos.get(node.var_name)
            if info is None:
                continue
            syncs = ([node.synchronizer] if node.synchronizer else
                     [p.synchronizer for p in node.part_configs])
            partitioned = bool(node.partitioner)
            # model-parallel vars sync their LOCAL shard over the
            # complement axes only: the payload is 1/extent of the var
            # per sharded mesh axis, and with a trivial complement
            # (dp == 1) there is no gradient collective at all — pricing
            # the full dense bytes here is what made EP/TP/PP candidates
            # look as wire-heavy as plain AllReduce
            mp_share, mp_extent = 1.0, 1
            for _dim, ax in dict(node.mp_axes or {}).items():
                e = max(int(mesh_cfg.get(ax, 1)), 1)
                mp_share /= e
                mp_extent *= e
            complement = max(n // mp_extent, 1)
            for sync in syncs:
                if isinstance(sync, ZeroShardedSynchronizer):
                    # rs + ag move the same ring bytes as one all-reduce
                    # (2(n-1)/n of the payload per link — the factor
                    # applied to ar_bytes below), at lower HBM: the
                    # memory side is priced in hbm_bytes. Two extra
                    # collective launches per variable (no bucketing).
                    # Payload priced through the kernel's own padded
                    # formula (per-shard block rounding on the int8
                    # wire) so predicted and telemetry bytes agree.
                    from autodist_tpu.kernel.synchronization.\
                        zero_synchronizer import zero_wire_payload_bytes
                    wd = (sync.wire_dtype or "fp32"
                          if wire_quantizable(info) else "fp32")
                    ar_bytes += zero_wire_payload_bytes(
                        info.num_elements, n, wd) / max(len(syncs), 1)
                    num_zero_colls += 2
                    num_zero_vars += 1
                elif isinstance(sync, AllReduceSynchronizer):
                    if node.mp_axes and complement == 1:
                        continue  # whole mesh is model axes: no grad sync
                    contrib = mp_share * self._wire_bytes(
                        info, sync, compressed=not partitioned,
                        wire_ok=not node.mp_axes) / max(len(syncs), 1)
                    ar_bytes += contrib
                    if topo is not None:
                        from autodist_tpu.analysis.topology import \
                            resolve_schedule
                        resolved = resolve_schedule(
                            getattr(sync, "schedule", "auto"), topo, n)
                        ar_sched_bytes[resolved] = (
                            ar_sched_bytes.get(resolved, 0.0) + contrib)
                    groups.add(sync.group)
                    if not node.mp_axes:
                        # schedule-unit classification, mirroring the
                        # lowering: compressed concatable vars share a
                        # bucket stage per (group, compressor); a
                        # NoneCompressor var on the int8 wire gets
                        # Int8CompressorEF substituted and buckets too;
                        # everything else syncs as its own stage
                        comp = (getattr(sync, "compressor", None)
                                or "NoneCompressor")
                        wd = getattr(sync, "wire_dtype", "fp32") or "fp32"
                        if (comp == "NoneCompressor" and wd == "int8"
                                and wire_quantizable(info)):
                            comp = "Int8CompressorEF"
                        if (not partitioned and comp != "NoneCompressor"
                                and comp in _CONCATABLE):
                            overlap_groups.add((sync.group, comp))
                        else:
                            overlap_pervar += 1
                elif isinstance(sync, PSSynchronizer):
                    if ((getattr(sync, "staleness", 0) or 0) > 0
                            or not getattr(sync, "sync_mode", True)):
                        ps_stale = True  # overlap disarms (lowering parity)
                    if sync.local_replication:
                        # proxied PS is device-resident: its sync is an
                        # on-device psum — ICI traffic, no PCIe (and no
                        # host wire for wire_dtype to quantize)
                        ar_bytes += (self._wire_bytes(
                            info, sync, compressed=False, wire_ok=False)
                            / max(len(syncs), 1))
                        num_ps_transfers += 1
                        continue
                    dest = sync.reduction_destination.split(":")[0] or "ps"
                    ps_load[dest] = ps_load.get(dest, 0.0) + (
                        self._wire_bytes(info, sync,
                                         compressed=not partitioned)
                        / max(len(syncs), 1))
                    num_ps_transfers += 1

        # ring all-reduce: 2*(N-1)/N of the payload crosses each link;
        # with a multi-level topology on the spec each resolved schedule
        # is priced per level at that level's link speed instead
        if topo is not None and n > 1 and ar_bytes > 0:
            other = ar_bytes - sum(ar_sched_bytes.values())
            if other > 0:
                ar_sched_bytes["ring"] = (ar_sched_bytes.get("ring", 0.0)
                                          + other)
            allreduce_s = sum(
                self._topology_ar_time(sched, payload, topo, n)
                for sched, payload in ar_sched_bytes.items())
        else:
            allreduce_s = ((2.0 * (n - 1) / n) * ar_bytes / ici_bw
                           if n > 1 else 0.0)
        mp_s = self.mp_comm_time(strategy, ici_bw)
        profile = (self._static_profile_for(strategy)
                   if use_static_profile else None)
        if profile is not None:
            # a lowering exists: price collectives from the MEASURED wire
            # bytes (fwd+bwd ops are both in the program text, each ring-
            # priced at its own replica-group size) and log the drift the
            # heuristics would have had. Reduce-class stays on the
            # overlappable gradient path; everything else (gathers,
            # permutes, all-to-alls) is in-loss model-parallel traffic on
            # the compute critical path, like the heuristic mp_s.
            self._log_static_drift(strategy, profile, n, ar_bytes)
            allreduce_s = profile.class_wire_bytes.get("reduce", 0.0) / ici_bw
            mp_s = sum(w for kind, w in profile.class_wire_bytes.items()
                       if kind != "reduce") / ici_bw
        # PS (host-offloaded, no proxy): every step pulls values host->device
        # and pushes grads device->host over PCIe on each node, plus
        # cross-node serving over the busiest server's NIC
        single = self._spec.is_single_node()
        ps_bytes = max(ps_load.values(), default=0.0)
        pcie_s = (2.0 * sum(ps_load.values()) / PCIE_BANDWIDTH_BYTES_S
                  if ps_load else 0.0)
        ps_s = pcie_s + (ps_bytes * 2.0 * (n - 1) / n / dcn_bw
                         if (n > 1 and not single) else 0.0)
        latency_s = PER_COLLECTIVE_LATENCY_S * (len(groups) + num_ps_transfers
                                                + num_zero_colls)
        remat_factor = REMAT_COMPUTE_FACTOR.get(
            strategy.graph_config.remat, 1.0)
        compute_s = self.compute_time(n) * remat_factor
        if getattr(strategy.graph_config, "compute_dtype",
                   "f32") == "bf16":
            # managed bf16 tier: forward/backward at the bf16 MXU rate;
            # master params, opt state and gradient collectives stay f32,
            # so only the compute term moves (wire terms are unchanged)
            compute_s /= BF16_COMPUTE_SPEEDUP
        # GPipe bubble: S stages over M microbatches keep each device
        # busy M/(S-1+M) of the schedule (Huang et al. 1811.06965)
        from autodist_tpu import const as _const
        mesh_shape_cfg = strategy.graph_config.mesh_shape or {}
        pp = int(mesh_shape_cfg.get(_const.PIPELINE_AXIS, 1))
        if pp > 1:
            m = int(strategy.graph_config.pp_microbatches or 1)
            if strategy.graph_config.pp_schedule == "interleaved":
                # virtual stages cut the fill/drain bubble by V: per-rank
                # work slots go M -> M*V while the bubble stays S-1 slots
                # (Narayanan et al. 2104.04473)
                v = max(int(strategy.graph_config.pp_virtual or 2), 1)
                compute_s *= ((pp - 1) / v + m) / m
            else:
                compute_s *= (pp - 1 + m) / m
            if strategy.graph_config.pp_schedule == "1f1b":
                # the fused schedule recomputes each stage forward from
                # the stashed input in its backward tick (per-microbatch
                # remat): ~one extra forward on top of fwd+bwd
                compute_s *= F1B_RECOMPUTE_FACTOR
        cal = self.calibration
        if cal is not None:
            compute_s *= cal.compute_scale
            allreduce_s *= cal.ar_scale
            ps_s *= cal.ps_scale
            latency_s *= cal.latency_scale
            mp_s *= cal.ar_scale  # same wire as the gradient collectives
        # overlapped schedule (graph_config.overlap): per-bucket
        # launch-as-ready recurrence over the CALIBRATED compute/wire
        # terms. Buckets become launchable as the backward sweep reaches
        # them (uniform spacing over the backward ~2/3 of compute); each
        # reduce occupies the wire for ar/k, so
        #   wire_free_i = max(ready_i, wire_free_{i-1}) + ar/k
        # and the EXPOSED wait is what spills past the end of compute —
        # never less than the tail bucket's ar/k (its gradients only
        # exist once the backward finishes). The un-merged launch chain
        # additionally pays one collective latency per stage, which is
        # what makes a compute-bound spec (tiny ar, many stages) refuse
        # overlap while a bandwidth-bound one hides ~ar*(k-1)/k.
        overlap = (bool(getattr(strategy.graph_config, "overlap", False))
                   and n > 1 and not ps_stale)
        overlap_stages = 0
        overlap_exposed_s = 0.0
        if overlap:
            k = max(len(overlap_groups) + overlap_pervar + num_zero_vars, 1)
            overlap_stages = k
            fwd = compute_s / 3.0
            bwd = compute_s - fwd
            w = allreduce_s / k
            wire_free = 0.0
            for i in range(1, k + 1):
                ready = fwd + bwd * (i / k)
                wire_free = max(ready, wire_free) + w
            overlap_exposed_s = max(wire_free - compute_s, 0.0)
            latency_s += (PER_COLLECTIVE_LATENCY_S
                          * (cal.latency_scale if cal is not None else 1.0)
                          * k)
        return CostBreakdown(compute_s=compute_s,
                             allreduce_s=allreduce_s, ps_s=ps_s,
                             latency_s=latency_s, mp_s=mp_s,
                             hbm_bytes=self.hbm_bytes(strategy),
                             hbm_capacity=self._hbm_capacity,
                             overlap=overlap,
                             overlap_stages=overlap_stages,
                             overlap_exposed_s=overlap_exposed_s)
