"""Analytic strategy cost model.

The reference ships an EMPTY simulator (``autodist/simulator/__init__.py``
is 0 lines — only the AutoSync dataset README survives; SURVEY §L8), while
its docs describe automatic strategy optimization. Here the cost model is
real: an analytic roofline for one training step under a given Strategy on
a given TPU topology, in the spirit of the scaling-book communication
recipes — compute from jaxpr FLOPs on the MXU, collective costs from
ICI/DCN link bandwidths, PS costs from per-server byte loads.

Deliberately simple (closed-form, no learned component): its job is to
*rank* candidate strategies for ``AutoStrategy``, not to predict wall time
exactly.
"""
import dataclasses
from typing import Dict, Optional

from autodist_tpu.strategy.base import (AllReduceSynchronizer, PSSynchronizer,
                                        Strategy)

# Peak dense bf16 FLOP/s per chip by generation (public figures).
CHIP_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "cpu": 5e10,
}
DEFAULT_MXU_EFFICIENCY = 0.4      # achieved/peak for typical training steps
WIRE_DTYPE_BYTES = 4              # gradients travel fp32 unless compressed
# host<->device link for the host-offloaded PS path (no-proxy PS keeps
# values+opt state in host RAM; every step pulls/pushes over PCIe)
PCIE_BANDWIDTH_BYTES_S = 32e9
COMPRESSED_BYTES = {"HorovodCompressor": 2, "HorovodCompressorEF": 2,
                    "BF16Compressor": 2, "BF16CompressorEF": 2,
                    "Int8Compressor": 1, "Int8CompressorEF": 1}
PER_COLLECTIVE_LATENCY_S = 5e-6   # launch overhead per collective/bucket


@dataclasses.dataclass
class CostBreakdown:
    compute_s: float
    allreduce_s: float
    ps_s: float
    latency_s: float

    @property
    def step_time_s(self) -> float:
        # collectives overlap partially with compute on TPU; assume the
        # slower of the two dominates, plus fixed launch latency
        return max(self.compute_s, self.allreduce_s + self.ps_s) + self.latency_s


class CostModel:
    def __init__(self, model_item, resource_spec,
                 chip_kind: Optional[str] = None,
                 mxu_efficiency: float = DEFAULT_MXU_EFFICIENCY,
                 flops_per_step: Optional[float] = None):
        self._item = model_item
        self._spec = resource_spec
        self._chip = chip_kind or self._guess_chip()
        self._eff = mxu_efficiency
        self._flops = flops_per_step

    def _guess_chip(self) -> str:
        kind = str(self._spec.slice_info.get("type", "")).lower()
        for k in ("v5p", "v5e", "v4"):
            if k in kind:
                return k
        return "v4" if self._spec.num_tpus else "cpu"

    # ---------------------------------------------------------------- pieces

    def _example_batch_size(self) -> int:
        """Leading dim of the example batch (the real batch size), falling
        back to 32 only when no batch is attached."""
        try:
            import jax
            leaves = jax.tree_util.tree_leaves(self._item.example_batch)
            for leaf in leaves:
                shape = getattr(leaf, "shape", ())
                if len(shape) >= 1 and shape[0] > 0:
                    return int(shape[0])
        except Exception:  # noqa: BLE001
            pass
        return 32

    def flops_per_step(self) -> float:
        if self._flops is not None:
            return self._flops
        try:
            import jax
            from autodist_tpu.kernel.common.utils import count_flops_estimate
            closed = jax.make_jaxpr(self._item.loss_fn)(
                self._item.params, self._item.example_batch)
            fwd = count_flops_estimate(closed.jaxpr)
        except Exception:  # noqa: BLE001 — fall back to a params-based bound
            # dense fwd ~ 2 * params * batch (the REAL batch size, not a
            # guess — a hardcoded 32 misranks compute- vs comm-bound
            # candidates for large-batch CNNs)
            fwd = 2.0 * (self._item.total_bytes() / 4) * self._example_batch_size()
        self._flops = 3.0 * fwd  # fwd + ~2x bwd
        return self._flops

    def compute_time(self, num_devices: int) -> float:
        peak = CHIP_PEAK_FLOPS[self._chip] * self._eff
        return self.flops_per_step() / max(num_devices, 1) / peak

    def _wire_bytes(self, info, sync, compressed: bool = True) -> float:
        from autodist_tpu.kernel.synchronization import compressor as compressor_lib
        if not compressed:
            # partitioned/reduce-scatter syncs ignore compressors entirely
            return info.num_elements * WIRE_DTYPE_BYTES
        try:
            name, rank = compressor_lib.parse_name(getattr(sync, "compressor", ""))
        except ValueError:
            name, rank = getattr(sync, "compressor", ""), None
        if name == "PowerSGDCompressor":
            if len(info.shape) >= 2:
                # PowerSGD flattens trailing dims to an n x m matrix and
                # ships P (n x r) + Q (m x r), so wire bytes scale with rank
                n = info.shape[0]
                m = info.num_elements // max(n, 1)
                return float(rank or 1) * (n + m) * WIRE_DTYPE_BYTES
            # rank-0/1 tensors pass through PowerSGD uncompressed
            return info.num_elements * WIRE_DTYPE_BYTES
        factor = COMPRESSED_BYTES.get(name, None)
        if factor is None:
            factor = WIRE_DTYPE_BYTES
        return info.num_elements * factor

    # ------------------------------------------------------------------ main

    def estimate(self, strategy: Strategy) -> CostBreakdown:
        n = max(len(strategy.graph_config.replicas), 1)
        # int8 rings run per-axis on multi-axis meshes (sequential rings),
        # so compression no longer degrades off single-axis meshes
        infos = self._item.var_infos
        ici_bw = self._spec.ici_bandwidth_gbps() * 1e9 / 8  # bytes/s
        # cross-host PS traffic rides the node NICs
        dcn_bw = min((self._spec.network_bandwidth_gbps(a)
                      for a in self._spec.node_addresses)) * 1e9 / 8

        ar_bytes = 0.0
        ps_load: Dict[str, float] = {}
        groups = set()
        num_ps_transfers = 0
        for node in strategy.node_config:
            info = infos.get(node.var_name)
            if info is None:
                continue
            syncs = ([node.synchronizer] if node.synchronizer else
                     [p.synchronizer for p in node.part_configs])
            partitioned = bool(node.partitioner)
            for sync in syncs:
                if isinstance(sync, AllReduceSynchronizer):
                    ar_bytes += self._wire_bytes(
                        info, sync,
                        compressed=not partitioned) / max(len(syncs), 1)
                    groups.add(sync.group)
                elif isinstance(sync, PSSynchronizer):
                    if sync.local_replication:
                        # proxied PS is device-resident: its sync is an
                        # on-device psum — ICI traffic, no PCIe
                        ar_bytes += (self._wire_bytes(
                            info, sync, compressed=False)
                            / max(len(syncs), 1))
                        num_ps_transfers += 1
                        continue
                    dest = sync.reduction_destination.split(":")[0] or "ps"
                    ps_load[dest] = ps_load.get(dest, 0.0) + (
                        self._wire_bytes(info, sync,
                                         compressed=not partitioned)
                        / max(len(syncs), 1))
                    num_ps_transfers += 1

        # ring all-reduce: 2*(N-1)/N of the payload crosses each link
        allreduce_s = (2.0 * (n - 1) / n) * ar_bytes / ici_bw if n > 1 else 0.0
        # PS (host-offloaded, no proxy): every step pulls values host->device
        # and pushes grads device->host over PCIe on each node, plus
        # cross-node serving over the busiest server's NIC
        single = self._spec.is_single_node()
        ps_bytes = max(ps_load.values(), default=0.0)
        pcie_s = (2.0 * sum(ps_load.values()) / PCIE_BANDWIDTH_BYTES_S
                  if ps_load else 0.0)
        ps_s = pcie_s + (ps_bytes * 2.0 * (n - 1) / n / dcn_bw
                         if (n > 1 and not single) else 0.0)
        latency_s = PER_COLLECTIVE_LATENCY_S * (len(groups) + num_ps_transfers)
        return CostBreakdown(compute_s=self.compute_time(n),
                             allreduce_s=allreduce_s, ps_s=ps_s,
                             latency_s=latency_s)
