"""Runtime telemetry: span tracing, metrics registry, drift tracking.

The runtime observability layer (docs/observability.md):

- :mod:`~autodist_tpu.telemetry.spans` — the thread-safe ring-buffer
  :class:`TraceRecorder` and the ``span()``/``counter_add()`` helpers the
  framework's hot paths are instrumented with (near-zero cost when
  ``ADT_TRACE=0``);
- :mod:`~autodist_tpu.telemetry.export` — Chrome-trace/Perfetto JSON,
  Prometheus ``metrics_text()``, and cross-process publish/scrape over
  the coordination service;
- :mod:`~autodist_tpu.telemetry.drift` — measured-vs-predicted drift
  reports feeding ``simulator/calibration.py``;
- :mod:`~autodist_tpu.telemetry.cluster` — NTP-style clock-offset
  handshake over the coordination service (step-aligned merged
  timelines) + the fleet-coordinated profiling flag;
- :mod:`~autodist_tpu.telemetry.goodput` — attributed wall-time
  decomposition (compute / collective-wait / PS-wire / host-input /
  checkpoint / rollback-replay), cross-worker skew, straggler flagging;
- :mod:`~autodist_tpu.telemetry.blackbox` — the always-on bounded
  flight recorder, dumped atomically on divergence/rollback/breaker-open
  and fatal signals;
- ``python -m autodist_tpu.telemetry`` — inspect/merge/diff/validate
  trace files, print drift/goodput tables, read blackbox dumps, post
  fleet profiling windows.
"""
from autodist_tpu.telemetry.spans import (  # noqa: F401
    TraceRecorder, configure, counter_add, counters, current_span_id,
    gauge_set, get_recorder, instant, reset, span, tracing_enabled)
from autodist_tpu.telemetry.export import (  # noqa: F401
    chrome_trace, merge_traces, metrics_text, publish_telemetry,
    scrape_cluster, validate_chrome_trace, write_trace)
from autodist_tpu.telemetry.drift import (  # noqa: F401
    DriftReport, build_report, fit_calibration, report_for_runner)
from autodist_tpu.telemetry.cluster import (  # noqa: F401
    ClockOffset, ClockSyncResponder, estimate_clock_offset,
    request_profile, step_alignment, sync_recorder_clock)
from autodist_tpu.telemetry.goodput import (  # noqa: F401
    GoodputReport, StragglerEwma, cluster_goodput)
from autodist_tpu.telemetry.goodput import (  # noqa: F401
    build_report as build_goodput_report)
from autodist_tpu.telemetry.blackbox import (  # noqa: F401
    FlightRecorder, get_flight_recorder)

__all__ = [
    "TraceRecorder", "configure", "counter_add", "counters",
    "current_span_id", "gauge_set", "get_recorder", "instant", "reset",
    "span", "tracing_enabled",
    "chrome_trace", "merge_traces", "metrics_text", "publish_telemetry",
    "scrape_cluster", "validate_chrome_trace", "write_trace",
    "DriftReport", "build_report", "fit_calibration", "report_for_runner",
    "ClockOffset", "ClockSyncResponder", "estimate_clock_offset",
    "request_profile", "step_alignment", "sync_recorder_clock",
    "GoodputReport", "StragglerEwma", "cluster_goodput",
    "build_goodput_report",
    "FlightRecorder", "get_flight_recorder",
]
