"""Cluster-correlated tracing: clock-offset handshake + fleet profiling.

Per-process traces re-base onto each host's wall clock at export
(``spans.TraceRecorder.epoch_offset_ns``), but wall clocks on different
hosts disagree — typically by milliseconds under NTP, by *seconds* on a
mis-configured fleet — which is the same order as a training step, so a
merged timeline without correction shows step N on worker A overlapping
step N+3 on worker B. This module closes that gap with the plumbing the
cluster already has (the coordination service; no new server):

- **Clock-offset handshake** (:func:`estimate_clock_offset`): an
  NTP-style multi-round exchange against a reference process (the chief,
  running a :class:`ClockSyncResponder`). Each round the worker enqueues
  a request stamped with its local send time, the responder answers with
  its own wall time, and the worker computes ``offset = t_ref - (t0 +
  t1)/2`` with error bound ``rtt/2``. The **minimum-RTT round wins** —
  queueing jitter and control-plane blips (exactly what the fault proxy
  injects in tests) inflate RTT, and the min-RTT filter discards them.
  The result is stored on the recorder (``clock_offset_ns`` /
  ``clock_error_ns``); ``export.chrome_trace`` adds the offset so every
  published trace is already in reference-clock time and
  ``merge_traces`` produces ONE step-aligned timeline.

- **Fleet-coordinated profiling** (:func:`request_profile` /
  :func:`read_profile_window`): a coordination-service KV flag
  ("profile steps N..M") every Runner polls (``ADT_PROFILE_POLL_S``).
  When a window lands, every worker captures a ``jax.profiler`` trace
  for the SAME step interval (the generalization of the ad-hoc
  first-step hook in ``runtime/runner.py``), written under the trace
  dir next to the merged telemetry trace. ``ADT_PROFILE_STEPS=N:M``
  arms the same machinery locally without a service.

- **Step alignment** (:func:`step_alignment`): reads a merged trace's
  per-step ``runner.dispatch`` spans (the ``step`` arg every dispatch
  and barrier span now carries) and reports the cross-worker start-time
  spread per step — the skew figure the CI driver asserts on.
"""
import dataclasses
import time
import uuid
from typing import Dict, Optional

from autodist_tpu import const
from autodist_tpu.telemetry import spans as spans_lib
from autodist_tpu.utils import logging

CLOCKSYNC_QUEUE = "clocksync"
CLOCKSYNC_RESP = "clocksync-resp/%s"
PROFILE_KEY = "profile/window"


# ----------------------------------------------------------- clock offset


@dataclasses.dataclass
class ClockOffset:
    """One worker's estimated wall-clock offset against the reference.
    ``offset_ns`` ADDS to local wall time to yield reference time;
    ``error_ns`` is the ± bound (half the winning round's RTT)."""

    offset_ns: int
    error_ns: int
    rtt_ns: int
    rounds: int

    def to_dict(self) -> dict:
        return {"offset_ns": int(self.offset_ns),
                "error_ns": int(self.error_ns),
                "rtt_ns": int(self.rtt_ns), "rounds": int(self.rounds)}

    @classmethod
    def from_dict(cls, d: dict) -> "ClockOffset":
        return cls(offset_ns=int(d.get("offset_ns", 0)),
                   error_ns=int(d.get("error_ns", 0)),
                   rtt_ns=int(d.get("rtt_ns", 0)),
                   rounds=int(d.get("rounds", 0)))


class ClockSyncResponder:
    """Reference-side half of the handshake (run on the chief): drains
    the ``clocksync`` request queue and answers each request with the
    reference wall clock. One responder serves every worker — requests
    carry the worker name, so the responder needs no roster.

    Runs on a daemon thread; ``stop()`` is idempotent. ``clock`` is
    injectable for tests (simulated reference skew)."""

    def __init__(self, client, poll_s: float = 0.002, clock=time.time_ns):
        self._client = client
        self._poll_s = poll_s
        self._clock = clock
        self._stop = None
        self._thread = None
        self.answered = 0

    def start(self) -> "ClockSyncResponder":
        import threading
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="adt-clocksync", daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            if not self.answer_once():
                self._stop.wait(self._poll_s)

    def answer_once(self) -> bool:
        """Drain and answer one queued request (returns False when the
        queue was empty) — the loop body, callable directly from tests
        and single-threaded drivers."""
        try:
            blob = self._client.qpop(CLOCKSYNC_QUEUE)
        except OSError:
            return False  # service blip: the estimator's round times out
        if blob is None:
            return False
        try:
            worker, nonce, _t_send = blob.decode().split(" ", 2)
        except ValueError:
            return False  # malformed request: drop it
        try:
            self._client.put(CLOCKSYNC_RESP % worker,
                             "%s %d" % (nonce, self._clock()))
        except OSError:
            return False
        self.answered += 1
        return True

    def stop(self):
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def estimate_clock_offset(client, worker: str,
                          rounds: Optional[int] = None,
                          round_timeout_s: float = 2.0,
                          clock=time.time_ns) -> ClockOffset:
    """Worker-side handshake: ``rounds`` request/response exchanges
    against the chief's :class:`ClockSyncResponder`; the minimum-RTT
    round's offset wins (error bound = its RTT/2). Rounds that time out
    (fault-injected delays, a wedged responder) are simply skipped —
    at least one round must complete or ``TimeoutError`` raises.

    ``clock`` is this worker's wall-clock source (``time.time_ns``);
    injectable so tests can simulate host skew without touching the
    system clock."""
    n = rounds if rounds is not None else max(
        int(const.ENV.ADT_CLOCKSYNC_ROUNDS.val), 1)
    token = uuid.uuid4().hex[:8]
    samples = []
    for i in range(n):
        nonce = "%s-%d" % (token, i)
        t0 = clock()
        try:
            client.qpush(CLOCKSYNC_QUEUE,
                         ("%s %s %d" % (worker, nonce, t0)).encode())
        except OSError:
            continue  # transport blip: this round is lost, not the sync
        deadline = time.monotonic() + round_timeout_s
        while time.monotonic() < deadline:
            try:
                val = client.get(CLOCKSYNC_RESP % worker)
            except OSError:
                break
            if val:
                got_nonce, _, ref_raw = val.partition(" ")
                if got_nonce == nonce:
                    t1 = clock()
                    rtt = max(int(t1 - t0), 1)
                    offset = int(ref_raw) - (t0 + t1) // 2
                    samples.append((rtt, offset))
                    break
            time.sleep(0.0005)
    if not samples:
        raise TimeoutError(
            "clock-offset handshake: no round completed in %d attempts — "
            "is a ClockSyncResponder running on the chief?" % n)
    rtt, offset = min(samples)
    est = ClockOffset(offset_ns=offset, error_ns=rtt // 2 + 1,
                      rtt_ns=rtt, rounds=len(samples))
    logging.info("clock sync [%s]: offset %+.3f ms ± %.3f ms over %d/%d "
                 "rounds (min rtt %.3f ms)", worker, est.offset_ns / 1e6,
                 est.error_ns / 1e6, est.rounds, n, est.rtt_ns / 1e6)
    return est


def sync_recorder_clock(client, worker: str,
                        recorder: Optional[spans_lib.TraceRecorder] = None,
                        **kwargs) -> ClockOffset:
    """Run the handshake and store the estimate on the recorder, so
    every subsequent export/publish is reference-clock corrected."""
    rec = recorder if recorder is not None else spans_lib.get_recorder()
    est = estimate_clock_offset(client, worker, **kwargs)
    rec.clock_offset_ns = est.offset_ns
    rec.clock_error_ns = est.error_ns
    return est


# -------------------------------------------------------- fleet profiling


def request_profile(client, first_step: int, last_step: int) -> int:
    """Post the fleet profiling flag: every polling Runner captures a
    ``jax.profiler`` trace for steps ``first_step..last_step``
    (inclusive). Returns the window sequence number (monotonic — a new
    request supersedes an old one even for workers that already served
    it)."""
    if last_step < first_step or first_step < 0:
        raise ValueError("profile window %d..%d is empty/negative"
                         % (first_step, last_step))
    seq = client.incr("profile/seq")
    client.put(PROFILE_KEY, "%d %d %d" % (seq, first_step, last_step))
    return seq


def clear_profile(client) -> None:
    """Withdraw the profiling flag (workers that already started a
    window finish it; nobody new arms)."""
    client.put(PROFILE_KEY, "0 -1 -1")


def read_profile_window(client) -> Optional[tuple]:
    """The posted ``(seq, first_step, last_step)``, or None."""
    try:
        val = client.get(PROFILE_KEY)
    except OSError:
        return None
    if not val:
        return None
    try:
        seq, first, last = (int(x) for x in val.split())
    except ValueError:
        return None
    if first < 0 or last < first:
        return None  # cleared ("0 -1 -1") or malformed
    return seq, first, last


def parse_profile_env(raw: str) -> Optional[tuple]:
    """``ADT_PROFILE_STEPS="N:M"`` → ``(first, last)`` or None — the
    serviceless local arm of the same window machinery."""
    raw = (raw or "").strip()
    if not raw:
        return None
    try:
        first, _, last = raw.partition(":")
        window = int(first), int(last or first)
    except ValueError:
        logging.warning("ADT_PROFILE_STEPS=%r is not N:M — ignored", raw)
        return None
    if window[1] < window[0]:
        return None
    return window


# ---------------------------------------------------------- step alignment


def step_alignment(trace: dict, span: str = "runner.dispatch") -> dict:
    """Cross-worker step skew from a MERGED trace: for every global
    ``step`` arg on ``span`` events, the per-pid start timestamps and
    their spread. Returns ``{"steps": {step: {"spread_us": float,
    "starts_us": {pid: ts}}}, "max_spread_us": float, "aligned_steps":
    int}`` — the number the CI driver asserts against the clock
    estimator's reported error."""
    per_step: Dict[int, Dict[int, float]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("name") != span:
            continue
        step = (e.get("args") or {}).get("step")
        if step is None:
            continue
        starts = per_step.setdefault(int(step), {})
        pid = e.get("pid", 0)
        # a worker can dispatch the same step twice after a rollback
        # replay; keep the FIRST occurrence (the aligned one)
        starts.setdefault(pid, float(e["ts"]))
    steps = {}
    max_spread = 0.0
    for step, starts in sorted(per_step.items()):
        spread = (max(starts.values()) - min(starts.values())
                  if len(starts) > 1 else 0.0)
        max_spread = max(max_spread, spread)
        steps[step] = {"spread_us": round(spread, 3), "starts_us": starts}
    return {"steps": steps, "max_spread_us": round(max_spread, 3),
            "aligned_steps": sum(1 for s in steps.values()
                                 if len(s["starts_us"]) > 1)}
