"""Trace-file tooling: ``python -m autodist_tpu.telemetry <cmd>``.

Subcommands:

- ``inspect FILE``            per-span-name summary + final counters
- ``merge OUT FILE...``       merge per-process traces into one timeline
- ``diff A B``                per-span-name total-duration deltas
- ``validate FILE``           schema check (exit 1 on violations)
- ``drift REPORT.json``       pretty-print a saved DriftReport table
- ``goodput FILE``            attributed wall-time buckets per process
  (+ cross-worker skew/stragglers on a merged trace); FILE may also be
  a saved GoodputReport json
- ``blackbox DUMP.json``      pretty-print a flight-recorder dump
- ``profile FIRST LAST``      post the fleet profiling flag on the
  coordination service (``--clear`` withdraws it)
"""
import argparse
import json
import sys
from typing import Dict

from autodist_tpu.telemetry import export


def _span_totals(trace: dict) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        row = out.setdefault(e["name"], {"cat": e.get("cat", ""),
                                         "count": 0, "total_us": 0.0,
                                         "max_us": 0.0})
        row["count"] += 1
        row["total_us"] += float(e.get("dur", 0.0))
        row["max_us"] = max(row["max_us"], float(e.get("dur", 0.0)))
    return out


def _counters(trace: dict) -> Dict[str, float]:
    """Final counter values — SUMMED across processes on a merged trace
    (each process reports its own monotonic totals; the cluster total is
    their sum), never double-counted against the duplicate ph="C"
    samples (those are the fallback for traces lacking otherData)."""
    other = trace.get("otherData", {})
    procs = ([other] if "counters" in other
             else list(other.get("processes", {}).values()))
    out: Dict[str, float] = {}
    if procs:
        for proc in procs:
            for name, val in proc.get("counters", {}).items():
                out[name] = out.get(name, 0.0) + val
        return out
    last: Dict[tuple, float] = {}  # last C sample per (pid, name)
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "C":
            last[(e.get("pid"), e["name"])] = (
                e.get("args", {}).get("value", 0.0))
    for (_pid, name), val in last.items():
        out[name] = out.get(name, 0.0) + val
    return out


def cmd_inspect(args) -> int:
    trace = export.load_trace(args.file)
    totals = _span_totals(trace)
    print("%-28s %-10s %8s %12s %12s %12s"
          % ("span", "cat", "count", "total_ms", "mean_ms", "max_ms"))
    for name in sorted(totals, key=lambda n: -totals[n]["total_us"]):
        r = totals[name]
        print("%-28s %-10s %8d %12.3f %12.3f %12.3f"
              % (name, r["cat"], r["count"], r["total_us"] / 1e3,
                 r["total_us"] / 1e3 / max(r["count"], 1),
                 r["max_us"] / 1e3))
    counters = _counters(trace)
    if counters:
        print("\ncounters:")
        for name in sorted(counters):
            print("  %-40s %g" % (name, counters[name]))
    return 0


def cmd_merge(args) -> int:
    traces = [export.load_trace(p) for p in args.inputs]
    merged = export.merge_traces(traces)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    print("merged %d traces (%d events) -> %s"
          % (len(traces), len(merged["traceEvents"]), args.out))
    return 0


def cmd_diff(args) -> int:
    a = _span_totals(export.load_trace(args.a))
    b = _span_totals(export.load_trace(args.b))
    print("%-28s %12s %12s %10s" % ("span", "a_total_ms", "b_total_ms",
                                    "b/a"))
    for name in sorted(set(a) | set(b)):
        ta = a.get(name, {}).get("total_us", 0.0) / 1e3
        tb = b.get(name, {}).get("total_us", 0.0) / 1e3
        ratio = ("%10.3f" % (tb / ta)) if ta > 0 else "       new"
        print("%-28s %12.3f %12.3f %s" % (name, ta, tb, ratio))
    return 0


def cmd_validate(args) -> int:
    errors = export.validate_chrome_trace(export.load_trace(args.file))
    if errors:
        for e in errors:
            print("INVALID: %s" % e, file=sys.stderr)
        return 1
    print("%s: valid chrome trace" % args.file)
    return 0


def cmd_drift(args) -> int:
    from autodist_tpu.telemetry import drift as drift_lib
    report = drift_lib.DriftReport.from_dict(
        drift_lib.load_report(args.file))
    print(report.format_table())
    return 0


def cmd_goodput(args) -> int:
    from autodist_tpu.telemetry import goodput as goodput_lib
    with open(args.file) as f:
        doc = json.load(f)
    if "buckets" in doc and "traceEvents" not in doc:
        # a saved GoodputReport json, not a trace
        print(goodput_lib.GoodputReport.from_dict(doc).format_table())
        return 0
    cluster = goodput_lib.cluster_goodput(doc)
    for pid, row in sorted(cluster["workers"].items()):
        print("process %s (%s):" % (pid, row["label"]))
        print(goodput_lib.GoodputReport.from_dict(row).format_table())
    if len(cluster["workers"]) > 1:
        print("cluster: skew_ratio=%s stragglers=%s"
              % (cluster["skew_ratio"],
                 [s["label"] for s in cluster["stragglers"]] or "none"))
    return 0


def cmd_blackbox(args) -> int:
    from autodist_tpu.telemetry import blackbox as blackbox_lib
    print(blackbox_lib.format_dump(blackbox_lib.load_dump(args.file)))
    return 0


def cmd_profile(args) -> int:
    from autodist_tpu import const
    from autodist_tpu.runtime.coordination import CoordinationClient
    from autodist_tpu.telemetry import cluster as cluster_lib
    client = CoordinationClient(args.host,
                                args.port or const.ENV.ADT_COORDSVC_PORT.val)
    try:
        if args.clear:
            cluster_lib.clear_profile(client)
            print("fleet profiling flag cleared")
            return 0
        seq = cluster_lib.request_profile(client, args.first, args.last)
        print("fleet profiling window #%d posted: steps %d..%d "
              "(every polling worker captures a jax.profiler trace)"
              % (seq, args.first, args.last))
        return 0
    finally:
        client.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m autodist_tpu.telemetry",
        description="Inspect, merge, diff and validate telemetry traces.")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("inspect", help="per-span summary of a trace file")
    p.add_argument("file")
    p.set_defaults(fn=cmd_inspect)
    p = sub.add_parser("merge", help="merge per-process traces")
    p.add_argument("out")
    p.add_argument("inputs", nargs="+")
    p.set_defaults(fn=cmd_merge)
    p = sub.add_parser("diff", help="compare two traces per span name")
    p.add_argument("a")
    p.add_argument("b")
    p.set_defaults(fn=cmd_diff)
    p = sub.add_parser("validate", help="schema-check a trace file")
    p.add_argument("file")
    p.set_defaults(fn=cmd_validate)
    p = sub.add_parser("drift", help="print a saved drift-report table")
    p.add_argument("file")
    p.set_defaults(fn=cmd_drift)
    p = sub.add_parser("goodput",
                       help="attributed wall-time buckets of a trace "
                            "(per process + cluster skew) or a saved "
                            "goodput report")
    p.add_argument("file")
    p.set_defaults(fn=cmd_goodput)
    p = sub.add_parser("blackbox",
                       help="pretty-print a flight-recorder dump")
    p.add_argument("file")
    p.set_defaults(fn=cmd_blackbox)
    p = sub.add_parser("profile",
                       help="post the fleet profiling flag "
                            "(steps FIRST..LAST) on the coordination "
                            "service")
    p.add_argument("first", type=int, nargs="?", default=0)
    p.add_argument("last", type=int, nargs="?", default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--clear", action="store_true",
                   help="withdraw the flag instead")
    p.set_defaults(fn=cmd_profile)
    args = parser.parse_args(argv)
    return args.fn(args)
