"""Trace/metric export: Chrome-trace (Perfetto) JSON + Prometheus text.

Two consumers, two formats:

- **Traces** export as Chrome Trace Event JSON (``traceEvents`` with
  ``ph="X"`` complete events) — the format https://ui.perfetto.dev loads
  directly. Each process is one trace ``pid`` track labeled
  ``host:pid``; threads are named sub-tracks; final counter values ride
  as ``ph="C"`` counter samples so they graph alongside the timeline.
- **Metrics** export as a Prometheus-style text exposition
  (:func:`metrics_text`): every registry counter as
  ``adt_<name>_total`` and every gauge as ``adt_<name>``, names
  sanitized to the metric charset.

Cross-process plumbing rides the EXISTING coordination service (the
async-PS wire — no new server): each worker :func:`publish_telemetry`\\ s
a versioned blob (``BPUT telemetry/<worker>``), the coordinator
:func:`scrape_cluster`\\ s every worker (``BGET``) and merges the
per-process timelines into one trace — pid/host become the track
identity, exactly what the Perfetto UI groups by.
"""
import json
import re
import time
from typing import Dict, Iterable, List, Optional

from autodist_tpu.telemetry import spans as spans_lib

TELEMETRY_KEY = "telemetry/%s"


# ------------------------------------------------------------ chrome trace


def chrome_trace(recorder: Optional[spans_lib.TraceRecorder] = None,
                 label: Optional[str] = None) -> dict:
    """Chrome Trace Event JSON dict for one recorder's events + final
    counter values. ``label`` overrides the process track name."""
    rec = recorder if recorder is not None else spans_lib.get_recorder()
    pid = rec.pid
    proc_name = label or ("%s:%d" % (rec.host, pid))
    events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": proc_name}},
    ]
    for tid, tname in sorted(rec.thread_names().items()):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
    # re-base the monotonic span clocks onto the wall clock — PLUS the
    # cluster clock-offset correction (telemetry/cluster.py handshake)
    # when one was estimated — so traces published by different
    # processes/hosts merge onto ONE step-aligned timeline
    # (perf_counter_ns origins are arbitrary per process; wall clocks
    # disagree across hosts)
    epoch = (getattr(rec, "epoch_offset_ns", 0)
             + getattr(rec, "clock_offset_ns", 0))
    # counters-only export (tracing disabled — the always-on registry
    # mode): the C samples must still land at wall-clock NOW, not 1970,
    # or a merged scrape mixes timebases 56 years apart
    last_ts = (epoch + time.perf_counter_ns()) / 1e3 if epoch else 0.0
    for e in rec.events():
        ts = (e.ts_ns + epoch) / 1e3  # chrome-trace ts are microseconds
        last_ts = max(last_ts, ts + e.dur_ns / 1e3)
        ev = {"ph": "X", "name": e.name, "cat": e.cat, "ts": ts,
              "dur": e.dur_ns / 1e3, "pid": pid, "tid": e.tid,
              "args": dict(e.args or {}, span_id=e.span_id,
                           parent_id=e.parent_id)}
        events.append(ev)
    # final counter/gauge values as one counter sample at the trace end
    for name, val in sorted(rec.counters().items()):
        events.append({"ph": "C", "name": name, "pid": pid, "tid": 0,
                       "ts": last_ts, "args": {"value": val}})
    for name, val in sorted(rec.gauges().items()):
        events.append({"ph": "C", "name": name, "pid": pid, "tid": 0,
                       "ts": last_ts, "args": {"value": val}})
    # histogram summaries as counter samples: Chrome-trace has no native
    # histogram phase, so the p50/p99 readouts graph as counter tracks —
    # the SLO numbers land on the same timeline as the spans they time
    for name, h in sorted(rec.histograms().items()):
        if not h["count"]:
            continue
        for q_label in ("p50", "p99"):
            events.append({"ph": "C", "name": "%s.%s" % (name, q_label),
                           "pid": pid, "tid": 0, "ts": last_ts,
                           "args": {"value": h[q_label]}})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "host": rec.host, "pid": pid,
            "dropped_events": rec.dropped_events,
            "clock_offset_ns": getattr(rec, "clock_offset_ns", 0),
            "clock_error_ns": getattr(rec, "clock_error_ns", None),
            "counters": rec.counters(),
            "gauges": rec.gauges(),
        },
    }


def write_trace(path: str,
                recorder: Optional[spans_lib.TraceRecorder] = None,
                label: Optional[str] = None) -> str:
    """Serialize :func:`chrome_trace` to ``path`` (Perfetto-loadable)."""
    import os
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(chrome_trace(recorder, label=label), f)
    return path


def load_trace(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def merge_traces(traces: Iterable[dict]) -> dict:
    """Merge per-process trace dicts into one timeline. Colliding pids
    (two single-process hosts both pid 1234) are remapped so every
    process keeps its own track; ``otherData`` aggregates per-process."""
    merged: List[dict] = []
    per_proc: Dict[str, dict] = {}
    seen_pids: Dict[int, str] = {}
    next_free = 1 << 20  # remap target far above real pids
    for i, t in enumerate(traces):
        other = t.get("otherData", {})
        # traces lacking otherData (external producers) each get a UNIQUE
        # fallback key — sharing one would defeat the collision remap and
        # interleave two processes' events on one track
        if "host" in other or "pid" in other:
            key = "%s:%s" % (other.get("host", "?"), other.get("pid", "?"))
        else:
            key = "trace-%d" % i
        events = t.get("traceEvents", [])
        pids = {e.get("pid") for e in events if "pid" in e}
        remap = {}
        for pid in pids:
            owner = seen_pids.get(pid)
            if owner is not None and owner != key:
                remap[pid] = next_free
                seen_pids[next_free] = key
                next_free += 1
            else:
                seen_pids[pid] = key
        for e in events:
            if remap and e.get("pid") in remap:
                e = dict(e, pid=remap[e["pid"]])
            merged.append(e)
        per_proc[key] = other
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "otherData": {"processes": per_proc}}


# the minimal contract a Perfetto-loadable export satisfies — the CI
# smoke leg validates the bench trace against this before uploading it
def validate_chrome_trace(trace: dict) -> List[str]:
    """Schema check; returns a list of violations (empty = valid)."""
    errors: List[str] = []
    if not isinstance(trace, dict):
        return ["trace is not a JSON object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["missing traceEvents list"]
    if not events:
        errors.append("traceEvents is empty")
    for i, e in enumerate(events):
        if len(errors) > 20:  # checked FIRST: every error branch below
            errors.append("... (truncated)")  # continues, so a fully
            break                             # malformed file must not
        if not isinstance(e, dict) or "ph" not in e:  # build one error
            errors.append("event %d: missing ph" % i)  # per event
            continue
        ph = e["ph"]
        if ph not in ("X", "M", "C", "i", "I", "B", "E"):
            errors.append("event %d: unknown phase %r" % (i, ph))
            continue
        if "name" not in e or "pid" not in e:
            errors.append("event %d (%s): missing name/pid" % (i, ph))
        if ph == "X":
            for field in ("ts", "dur", "tid"):
                if not isinstance(e.get(field), (int, float)):
                    errors.append("event %d (X %r): non-numeric %s"
                                  % (i, e.get("name"), field))
    # a span-less export is still valid when it carries counter samples —
    # the documented ADT_TRACE=0 counters-only mode produces exactly that
    if not any(isinstance(e, dict) and e.get("ph") in ("X", "C")
               for e in events):
        errors.append("no span (ph=X) or counter (ph=C) events")
    return errors


# ---------------------------------------------------------------- metrics

_METRIC_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(name: str) -> str:
    return "adt_" + _METRIC_RE.sub("_", name)


def _escape_label_value(value: str) -> str:
    """Prometheus exposition-format label escaping: backslash, double
    quote and newline must be escaped or a strict scraper rejects the
    whole exposition (worker names and host labels are caller data)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _help_text(name: str, kind: str) -> str:
    """One-line HELP for a registry entry. Metric names are the
    ``<subsystem>.<operation>`` taxonomy (docs/observability.md), so the
    help derives from the name — a curated per-metric string registry
    would drift the moment a counter is added anywhere else."""
    sub, _, op = name.partition(".")
    return ("autodist_tpu %s %r of subsystem %r (registry key %r)"
            % (kind, op or sub, sub, name))


def metrics_text(recorder: Optional[spans_lib.TraceRecorder] = None,
                 labels: Optional[Dict[str, str]] = None) -> str:
    """Prometheus text exposition of the registry: counters as
    ``adt_<name>_total``, gauges as ``adt_<name>``, each with ``# HELP``
    + ``# TYPE`` headers; ``labels`` (e.g. ``{"worker": "w0"}``) attach
    to every sample — the scrape merge uses them to keep per-worker
    series distinct. Label values are escaped per the exposition format
    (backslash/quote/newline), so arbitrary worker/host names survive a
    strict scraper."""
    rec = recorder if recorder is not None else spans_lib.get_recorder()
    lbl = ""
    if labels:
        lbl = "{%s}" % ",".join(
            '%s="%s"' % (k, _escape_label_value(v))
            for k, v in sorted(labels.items()))
    lines: List[str] = []
    for name, val in sorted(rec.counters().items()):
        mname = _metric_name(name) + "_total"
        lines.append("# HELP %s %s" % (mname, _help_text(name, "counter")))
        lines.append("# TYPE %s counter" % mname)
        lines.append("%s%s %s" % (mname, lbl, _fmt_value(val)))
    for name, val in sorted(rec.gauges().items()):
        mname = _metric_name(name)
        lines.append("# HELP %s %s" % (mname, _help_text(name, "gauge")))
        lines.append("# TYPE %s gauge" % mname)
        lines.append("%s%s %s" % (mname, lbl, _fmt_value(val)))
    for name, h in sorted(rec.histograms().items()):
        mname = _metric_name(name)
        lines.append("# HELP %s %s" % (mname,
                                       _help_text(name, "histogram")))
        lines.append("# TYPE %s histogram" % mname)
        # Prometheus histogram exposition: cumulative bucket counts with
        # an ``le`` label (the extra label merges with the caller's), a
        # +Inf bucket, and _sum/_count
        cumulative = 0
        for bound, c in zip(list(h["bounds"]) + [float("inf")],
                            h["counts"]):
            cumulative += c
            le = "+Inf" if bound == float("inf") else _fmt_value(bound)
            blbl = ('{%s,le="%s"}' % (lbl[1:-1], le)) if lbl \
                else '{le="%s"}' % le
            lines.append("%s_bucket%s %d" % (mname, blbl, cumulative))
        lines.append("%s_sum%s %s" % (mname, lbl, _fmt_value(h["sum"])))
        lines.append("%s_count%s %d" % (mname, lbl, h["count"]))
    return "\n".join(lines) + "\n"


def _fmt_value(val: float) -> str:
    return ("%d" % val) if float(val).is_integer() else repr(float(val))


# ------------------------------------------- cross-process publish/scrape


def publish_telemetry(client, worker: str,
                      recorder: Optional[spans_lib.TraceRecorder] = None,
                      version: Optional[int] = None) -> int:
    """Publish this process's telemetry (trace + registry) as a versioned
    blob on the coordination service (``BPUT telemetry/<worker>``) —
    same wire the async-PS values ride, so any deployed job already has
    the plumbing. Returns the published version."""
    rec = recorder if recorder is not None else spans_lib.get_recorder()
    if version is None:
        # a per-publish sequence, NOT the span tally: counters-only mode
        # (tracing disabled) records no spans, and the version must still
        # advance every publish or consumers read live workers as stale
        version = next(rec._publish_seq)
    payload = {
        "worker": worker, "host": rec.host, "pid": rec.pid,
        # reference-corrected publish stamp: the scraper derives per-
        # worker scrape AGE from it, so the clock offset must already be
        # applied or a skewed host reads permanently stale (or from the
        # future)
        "published_at": (time.time()
                         + getattr(rec, "clock_offset_ns", 0) / 1e9),
        "clock": {"offset_ns": getattr(rec, "clock_offset_ns", 0),
                  "error_ns": getattr(rec, "clock_error_ns", None)},
        "trace": chrome_trace(rec, label="%s (%s:%d)"
                              % (worker, rec.host, rec.pid)),
        "metrics": rec.counters(),
        "gauges": rec.gauges(),
        "histograms": rec.histograms(),
    }
    client.bput(TELEMETRY_KEY % worker, version,
                json.dumps(payload).encode())
    return version


def fetch_telemetry(client, worker: str) -> Optional[dict]:
    """The latest telemetry blob a worker published, or None."""
    res = client.bget(TELEMETRY_KEY % worker)
    if res is None:
        return None
    _version, blob = res
    return json.loads(blob.decode())


def scrape_cluster(client, workers: Iterable[str]) -> dict:
    """Coordinator-side scrape: fetch every worker's published blob,
    merge the traces into one multi-track timeline and the registries
    into one labeled exposition. Workers that have not published are
    listed in ``missing`` — and counted in the ``cluster.workers_missing``
    gauge (set on the local registry AND emitted in the returned
    exposition) so a dashboard can alert on silent workers instead of
    diffing lists. ``scrape_age_s`` carries each worker's publish age
    (reference-clock corrected), the freshness signal per worker; a
    scrape never blocks on a dead worker."""
    blobs, missing = {}, []
    for w in workers:
        payload = fetch_telemetry(client, w)
        if payload is None:
            missing.append(w)
        else:
            blobs[w] = payload
    trace = merge_traces([p["trace"] for p in blobs.values()])
    now = time.time()
    ages = {w: (round(max(now - p["published_at"], 0.0), 3)
                if p.get("published_at") else None)
            for w, p in blobs.items()}
    clocks = {w: p.get("clock", {}) for w, p in blobs.items()}
    texts = []
    for w, p in sorted(blobs.items()):
        shadow = spans_lib.TraceRecorder(capacity=1, pid=p["pid"],
                                         host=p["host"])
        shadow._counters = dict(p.get("metrics", {}))
        shadow._gauges = dict(p.get("gauges", {}))
        shadow._histograms = {
            n: spans_lib.Histogram.from_dict(d)
            for n, d in p.get("histograms", {}).items()}
        texts.append(metrics_text(shadow, labels={"worker": w}))
    # coordinator-side cluster gauges: appended to the exposition (a
    # scraper sees them next to the per-worker series) AND set on the
    # local registry (step_stats/bench readers see them without parsing
    # text)
    spans_lib.gauge_set("cluster.workers_missing", float(len(missing)))
    spans_lib.counter_add("cluster.scrapes")
    cluster_lines = [
        "# HELP adt_cluster_workers_missing workers that never published "
        "a telemetry blob this scrape",
        "# TYPE adt_cluster_workers_missing gauge",
        "adt_cluster_workers_missing %d" % len(missing)]
    age_samples = [
        'adt_cluster_scrape_age_seconds{worker="%s"} %s'
        % (_escape_label_value(w), _fmt_value(ages[w]))
        for w in sorted(ages) if ages[w] is not None]
    if age_samples:
        cluster_lines.append(
            "# HELP adt_cluster_scrape_age_seconds age of each "
            "worker's latest published blob (reference clock)")
        cluster_lines.append(
            "# TYPE adt_cluster_scrape_age_seconds gauge")
        cluster_lines.extend(age_samples)
    texts.append("\n".join(cluster_lines) + "\n")
    return {"trace": trace, "metrics_text": "".join(texts),
            "workers": sorted(blobs), "missing": missing,
            "scrape_age_s": ages, "clocks": clocks}
