"""Cost-model drift: measured runtime vs analytic prediction.

The simulator predicts (``CostModel.estimate``), the static analyzers
measure what the LOWERING emits (``StaticCollectiveProfile``, PR 4), and
the telemetry recorder measures what the RUNTIME does (span durations,
wire-byte counters). This module joins the three into a
:class:`DriftReport`:

- **per-collective rows**: heuristic wire bytes (the jaxpr pricing the
  cost model falls back to) vs the lowering's measured per-class wire
  bytes — the drift `attach_static_profile` corrects;
- **per-term rows**: predicted seconds per step (compute / collective /
  host-PS / launch) vs measured seconds from the recorder's spans
  (dispatch wall time, PS pull/push time) and the PS store's byte
  counters;
- **a calibration feed**: :func:`fit_calibration` hands the
  (breakdown, measured step seconds) pairs to
  ``simulator/calibration.fit`` so ``Simulator.rank`` re-ranks with
  measured coefficients — the measure→calibrate loop closed.

Reports serialize to JSON (``save``/``load``) and pretty-print as a
table (``format_table``; also ``python -m autodist_tpu.telemetry drift
report.json``).
"""
import dataclasses
import json
import statistics
from typing import Dict, List, Optional

from autodist_tpu.telemetry import spans as spans_lib
from autodist_tpu.utils import logging

# the span whose duration is "one dispatch" — Runner.run / run_superstep
DISPATCH_SPAN = "runner.dispatch"
PS_SPANS = ("ps.pull", "ps.push")


@dataclasses.dataclass
class CollectiveDrift:
    """One collective class: heuristic (predicted) vs lowering-measured
    wire bytes per step."""
    kind: str
    predicted_wire_bytes: float
    measured_wire_bytes: float

    @property
    def ratio(self) -> float:
        if self.predicted_wire_bytes > 0:
            return self.measured_wire_bytes / self.predicted_wire_bytes
        return float("inf") if self.measured_wire_bytes > 0 else 1.0

    def to_dict(self) -> dict:
        return dict(kind=self.kind,
                    predicted_wire_bytes=round(self.predicted_wire_bytes),
                    measured_wire_bytes=round(self.measured_wire_bytes),
                    ratio=(round(self.ratio, 4)
                           if self.ratio != float("inf") else None))


@dataclasses.dataclass
class TermDrift:
    """One cost-model term: predicted vs runtime-measured seconds per
    step (``measured_s`` None when the recorder saw no samples)."""
    term: str
    predicted_s: float
    measured_s: Optional[float]

    @property
    def ratio(self) -> Optional[float]:
        if self.measured_s is None:
            return None
        if self.predicted_s > 0:
            return self.measured_s / self.predicted_s
        return float("inf") if self.measured_s > 0 else 1.0

    def to_dict(self) -> dict:
        r = self.ratio
        return dict(term=self.term, predicted_s=round(self.predicted_s, 9),
                    measured_s=(round(self.measured_s, 9)
                                if self.measured_s is not None else None),
                    ratio=(round(r, 4)
                           if r not in (None, float("inf")) else None))


@dataclasses.dataclass
class DriftReport:
    strategy_id: str
    num_steps: int
    predicted_step_s: float
    measured_step_s: Optional[float]
    terms: List[TermDrift]
    collectives: List[CollectiveDrift]
    breakdown: dict                      # CostBreakdown fields, serialized
    counters: Dict[str, float]
    # attributed wall-time buckets (telemetry/goodput.py) the term rows
    # were joined against — None when the recorder had no decomposable
    # spans (tracing off / sampled)
    goodput: Optional[dict] = None
    # quantized-wire accounting (wire.* counters): quantized payload
    # bytes, bytes saved vs full width, the resulting reduction factor,
    # and the per-step quantized payload — None when no quantized wire
    # crossed during the window
    wire: Optional[dict] = None
    # per-link-level bytes (topology-aware): plan-level predicted bytes
    # per level (analysis/topology.plan_level_bytes) joined against the
    # static profile's measured per-level rows — None when the spec
    # declares no multi-level topology. Each row: {level, predicted_bytes,
    # measured_bytes, ratio}
    levels: Optional[List[dict]] = None

    @property
    def step_ratio(self) -> Optional[float]:
        if self.measured_step_s is None or self.predicted_step_s <= 0:
            return None
        return self.measured_step_s / self.predicted_step_s

    def to_dict(self) -> dict:
        return {
            "strategy_id": self.strategy_id,
            "num_steps": self.num_steps,
            "predicted_step_s": round(self.predicted_step_s, 9),
            "measured_step_s": (round(self.measured_step_s, 9)
                                if self.measured_step_s is not None
                                else None),
            "step_ratio": (round(self.step_ratio, 4)
                           if self.step_ratio is not None else None),
            "terms": [t.to_dict() for t in self.terms],
            "collectives": [c.to_dict() for c in self.collectives],
            "breakdown": self.breakdown,
            "counters": self.counters,
            "goodput": self.goodput,
            "wire": self.wire,
            "levels": self.levels,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DriftReport":
        """Inverse of :meth:`to_dict` — the ONE deserialization point
        (the CLI's ``drift`` subcommand loads through this, so a schema
        change lives here, next to the serializer)."""
        return cls(
            strategy_id=d.get("strategy_id", "?"),
            num_steps=d.get("num_steps", 0),
            predicted_step_s=d.get("predicted_step_s", 0.0),
            measured_step_s=d.get("measured_step_s"),
            terms=[TermDrift(t["term"], t["predicted_s"], t["measured_s"])
                   for t in d.get("terms", [])],
            collectives=[CollectiveDrift(c["kind"],
                                         c["predicted_wire_bytes"],
                                         c["measured_wire_bytes"])
                         for c in d.get("collectives", [])],
            breakdown=d.get("breakdown", {}),
            counters=d.get("counters", {}),
            goodput=d.get("goodput"),
            wire=d.get("wire"),
            levels=d.get("levels"))

    def save(self, path: str) -> str:
        import os
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        return path

    def format_table(self) -> str:
        d = self.to_dict()
        lines = ["drift report: strategy=%s steps=%d"
                 % (self.strategy_id, self.num_steps),
                 "  step time: predicted=%.6gs measured=%s ratio=%s"
                 % (self.predicted_step_s,
                    "%.6gs" % self.measured_step_s
                    if self.measured_step_s is not None else "-",
                    d["step_ratio"] if d["step_ratio"] is not None else "-"),
                 "  %-12s %14s %14s %8s" % ("term", "predicted_s",
                                            "measured_s", "ratio")]
        for t in d["terms"]:
            lines.append("  %-12s %14.6g %14s %8s"
                         % (t["term"], t["predicted_s"],
                            "%.6g" % t["measured_s"]
                            if t["measured_s"] is not None else "-",
                            t["ratio"] if t["ratio"] is not None else "-"))
        lines.append("  %-12s %14s %14s %8s"
                     % ("collective", "heuristic_B", "measured_B", "ratio"))
        for c in d["collectives"]:
            lines.append("  %-12s %14d %14d %8s"
                         % (c["kind"], c["predicted_wire_bytes"],
                            c["measured_wire_bytes"],
                            c["ratio"] if c["ratio"] is not None else "inf"))
        if self.wire:
            lines.append(
                "  quantized wire: %d B on the wire, %d B saved "
                "(%.2fx reduction, %.0f B/step)"
                % (self.wire.get("bytes_quantized", 0),
                   self.wire.get("bytes_saved", 0),
                   self.wire.get("reduction_x") or 1.0,
                   self.wire.get("per_step_quantized") or 0.0))
        if self.levels:
            lines.append("  %-12s %14s %14s %8s"
                         % ("level", "predicted_B", "measured_B", "ratio"))
            for row in self.levels:
                lines.append("  %-12s %14d %14s %8s"
                             % (row["level"], row["predicted_bytes"],
                                "%d" % row["measured_bytes"]
                                if row.get("measured_bytes") is not None
                                else "-",
                                row["ratio"] if row.get("ratio") is not None
                                else "-"))
        return "\n".join(lines)


def load_report(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


# ------------------------------------------------------------------ build


def _median(vals: List[float]) -> Optional[float]:
    return statistics.median(vals) if vals else None


def build_report(cost_model, strategy,
                 recorder: Optional[spans_lib.TraceRecorder] = None,
                 static_profile=None) -> DriftReport:
    """Join one strategy's cost-model prediction against what the
    recorder measured. ``static_profile`` (``Runner.static_profile`` /
    ``StaticCollectiveProfile``) supplies the measured per-collective
    wire bytes; without one the report still carries the timing terms."""
    rec = recorder if recorder is not None else spans_lib.get_recorder()
    breakdown = cost_model.estimate(strategy)
    counters = rec.counters()

    dispatch = rec.durations_s(DISPATCH_SPAN)
    num_steps = len(dispatch)
    measured_step = _median(dispatch)

    # host-PS seconds per step: total pull+push span time over dispatches
    ps_total = sum(sum(rec.durations_s(n)) for n in PS_SPANS)
    measured_ps = (ps_total / num_steps) if num_steps and ps_total else None

    # ATTRIBUTED time (telemetry/goodput.py): the self-time decomposition
    # splits each dispatch into compute vs nested wait/wire buckets, so
    # calibration consumes per-term measurements instead of fitting every
    # coefficient against one total — the compute term gets the dispatch
    # self time, the collective term the barrier/backoff wait
    from autodist_tpu.telemetry import goodput as goodput_lib
    gp = goodput_lib.build_report(rec) if num_steps else None
    if gp is not None and (gp.wall_s <= 0 or gp.approximate):
        gp = None  # sampled/empty traces cannot be decomposed honestly
    measured_compute = (gp.buckets["compute"] / num_steps
                        if gp is not None else None)
    measured_wait = (gp.buckets["collective_wait"] / num_steps
                     if gp is not None and gp.buckets["collective_wait"] > 0
                     else None)

    terms = [
        TermDrift("step", breakdown.step_time_s, measured_step),
        TermDrift("compute", breakdown.compute_s, measured_compute),
        TermDrift("allreduce", breakdown.allreduce_s, measured_wait),
        TermDrift("ps", breakdown.ps_s, measured_ps),
        TermDrift("mp", breakdown.mp_s, None),
        TermDrift("latency", breakdown.latency_s, None),
    ]
    if breakdown.overlap:
        # under the overlap schedule the residual barrier wait IS the
        # exposed (un-hidden) collective tail — the predicted exposure
        # joins the same measurement the allreduce row consumes, so the
        # two rows together show how much wire the schedule actually hid
        terms.append(TermDrift("overlap", breakdown.overlap_exposed_s,
                               measured_wait))

    collectives: List[CollectiveDrift] = []
    if static_profile is not None:
        # reuse the cost model's own heuristic-by-class pricing so the
        # drift rows can never disagree with what estimate() replaced
        n = max(len(strategy.graph_config.replicas), 1)
        heur = _heuristic_wire(cost_model, strategy, n)
        measured = dict(static_profile.class_wire_bytes)
        for kind in sorted(set(heur) | set(measured)):
            collectives.append(CollectiveDrift(
                kind, heur.get(kind, 0.0), measured.get(kind, 0.0)))

    # quantized-wire rows (wire.* counters are credited by the lowering's
    # per-dispatch static accounting AND the PS store's boundary codec,
    # both via collectives.int8_wire_payload_bytes — the same formula the
    # cost model prices, so these rows expose measured-vs-priced drift)
    wq = counters.get("wire.bytes_quantized", 0.0)
    ws = counters.get("wire.bytes_saved", 0.0)
    wire = None
    if wq > 0:
        wire = {"bytes_quantized": round(wq),
                "bytes_saved": round(ws),
                "reduction_x": round((wq + ws) / wq, 4),
                "per_step_quantized": (round(wq / num_steps, 1)
                                       if num_steps else None)}

    # per-link-level rows (topology-aware): the plan-level prediction
    # (analysis/topology.plan_level_bytes, the same formulas the cost
    # model prices with) joined against the static profile's measured
    # per-level attribution — the drift row that shows whether the
    # hierarchical schedule actually moved its bytes off the slow level
    levels = None
    topo = (cost_model._spec.topology()
            if hasattr(cost_model._spec, "topology") else None)
    if topo is not None:
        from autodist_tpu.analysis.topology import plan_level_bytes
        predicted = plan_level_bytes(strategy, cost_model._item, topo)
        measured_levels = (dict(getattr(static_profile, "level_wire_bytes",
                                        None) or {})
                           if static_profile is not None else {})
        levels = []
        for lv in topo.levels:
            p = predicted.get(lv.name, 0.0)
            m = measured_levels.get(lv.name)
            ratio = (round(m / p, 4) if m is not None and p > 0 else None)
            levels.append({"level": lv.name,
                           "predicted_bytes": round(p),
                           "measured_bytes": (round(m) if m is not None
                                              else None),
                           "ratio": ratio})

    report = DriftReport(
        strategy_id=getattr(strategy, "id", "?"),
        num_steps=num_steps,
        predicted_step_s=breakdown.step_time_s,
        measured_step_s=measured_step,
        terms=terms,
        collectives=collectives,
        breakdown={f.name: getattr(breakdown, f.name)
                   for f in dataclasses.fields(breakdown)},
        counters=counters,
        goodput=gp.to_dict() if gp is not None else None,
        wire=wire,
        levels=levels)
    logging.info("drift report [%s]: predicted=%.6gs measured=%s over %d "
                 "dispatches", report.strategy_id, report.predicted_step_s,
                 "%.6gs" % measured_step if measured_step is not None
                 else "n/a", num_steps)
    return report


def _heuristic_wire(cost_model, strategy, n) -> Dict[str, float]:
    """The cost model's per-class heuristic wire bytes (what a static
    profile replaces). The gradient all-reduce payload is re-derived by
    pricing the strategy with ``use_static_profile=False`` — the public
    heuristic-only estimate — then inverting the ring formula; the
    model-parallel classes come from the model's own jaxpr profile."""
    # ar_bytes from the heuristic reduce seconds: the heuristic prices
    # reduce as 2(n-1)/n * ar_bytes / ici_bw
    bd = cost_model.estimate(strategy, use_static_profile=False)
    ici_bw = cost_model._spec.ici_bandwidth_gbps() * 1e9 / 8
    ar_bytes = (bd.allreduce_s * ici_bw / (2.0 * (n - 1) / n)
                if n > 1 and bd.allreduce_s > 0 else 0.0)
    return cost_model._heuristic_wire_by_class(strategy, n, ar_bytes)


def report_for_runner(runner, resource_spec=None, batch=None,
                      recorder: Optional[spans_lib.TraceRecorder] = None
                      ) -> DriftReport:
    """Convenience join for a live Runner: builds the CostModel from its
    model item + ``resource_spec`` (default: the local machine), takes
    the static profile from the runner's own lowering when ``batch`` is
    given, and reads the global recorder."""
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.simulator.cost_model import CostModel
    spec = resource_spec or ResourceSpec.from_local()
    dstep = runner.distributed_step
    cm = CostModel(dstep.model_item, spec)
    topo = spec.topology() if hasattr(spec, "topology") else None
    profile = (runner.static_profile(batch, topology=topo)
               if batch is not None else None)
    return build_report(cm, dstep.strategy, recorder=recorder,
                        static_profile=profile)


# ------------------------------------------------------------ calibration


def fit_calibration(reports: List[DriftReport]):
    """Feed measured step times into ``simulator/calibration.fit``: one
    (CostBreakdown, measured seconds) pair per report that has a
    measurement. Returns the fitted ``Calibration`` — attach it via
    ``CostModel(calibration=...)`` / ``Simulator.calibrate`` so ranking
    runs on measured coefficients."""
    from autodist_tpu.simulator import calibration as cal_lib
    from autodist_tpu.simulator.cost_model import CostBreakdown
    breakdowns, measured = [], []
    for r in reports:
        if r.measured_step_s is None:
            continue
        breakdowns.append(CostBreakdown(**{
            k: v for k, v in r.breakdown.items()
            if k in {f.name for f in dataclasses.fields(CostBreakdown)}}))
        measured.append(r.measured_step_s)
    if not breakdowns:
        raise ValueError("no report carries a measured step time — run "
                         "steps with telemetry enabled first")
    return cal_lib.fit_auto_span(breakdowns, measured)
