"""Low-overhead runtime span tracing + metrics registry.

The runtime half of the observability story (the static half is PR 2/4's
analyzers): a thread-safe ring-buffer :class:`TraceRecorder` that the
steady-state paths — ``Runner.run``/``run_superstep``, ``DistributedStep``
dispatch and PS pull/push, the resilient control plane, the prefetcher,
sharded checkpoints — instrument with nested **spans** (wall-clock
intervals on a per-thread track) and **counters** (monotonic totals:
dispatches, wire bytes, retries, dropped batches).

Cost model, enforced by tests (``tests/test_telemetry.py``):

- **disabled** (``ADT_TRACE=0``, the default): ``span()`` returns a
  shared no-op context manager after one module-attribute check —
  sub-microsecond enter/exit, no allocation, no lock. Counters are still
  collected (a dict add under a lock, ~100ns — the registry is the
  always-on metrics surface `metrics_text()` exposes).
- **enabled** (``ADT_TRACE=1``): completed spans append to a bounded
  ``deque`` (oldest dropped first, drop count kept); timestamps are
  ``time.perf_counter_ns()`` (monotonic).
- **sampled** (``ADT_TRACE=sampled``): record one span out of every
  ``ADT_TRACE_SAMPLE`` — the always-on production setting.

Span ids are per-recorder monotonic ints carried on a thread-local stack,
so logs can correlate with traces (``utils/logging.py`` JSON mode embeds
``current_span_id()``) and children record their parent. Export formats
live in :mod:`autodist_tpu.telemetry.export`.
"""
import collections
import itertools
import os
import threading
import time
from typing import Dict, List, Optional

from autodist_tpu import const

# ------------------------------------------------------------- span records


class SpanEvent:
    """One completed span. ``ts_ns``/``dur_ns`` are perf_counter_ns
    wall-clock; ``tid`` is a small per-recorder thread index (thread
    names ride in the recorder's thread table)."""

    __slots__ = ("name", "cat", "ts_ns", "dur_ns", "tid", "span_id",
                 "parent_id", "args")

    def __init__(self, name, cat, ts_ns, dur_ns, tid, span_id, parent_id,
                 args):
        self.name = name
        self.cat = cat
        self.ts_ns = ts_ns
        self.dur_ns = dur_ns
        self.tid = tid
        self.span_id = span_id
        self.parent_id = parent_id
        self.args = args

    def __repr__(self):
        return ("SpanEvent(%s/%s id=%d dur=%.3fms)"
                % (self.cat, self.name, self.span_id, self.dur_ns / 1e6))


class _Span:
    """Live (entered) span — the enabled-path context manager."""

    __slots__ = ("_rec", "name", "cat", "args", "_t0", "id", "_parent")

    def __init__(self, rec, name, cat, args):
        self._rec = rec
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        rec = self._rec
        self.id = next(rec._ids)
        stack = rec._span_stack()
        self._parent = stack[-1] if stack else 0
        stack.append(self.id)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        rec = self._rec
        stack = rec._span_stack()
        if stack and stack[-1] == self.id:
            stack.pop()
        rec._append(SpanEvent(self.name, self.cat, self._t0, t1 - self._t0,
                              rec._tid(), self.id, self._parent, self.args))
        return False


class _NoopSpan:
    """Disabled-path context manager: one shared instance, trivial
    enter/exit — the <1µs overhead guarantee."""

    __slots__ = ()
    id = 0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


# ---------------------------------------------------------------- recorder


# counters pre-registered at zero so `metrics_text()` exposes the full
# registry surface even before the corresponding path first runs —
# scrapers see a stable key set, not one that grows as code paths fire
DEFAULT_COUNTERS = (
    "runner.steps", "runner.supersteps", "runner.d2h_bytes",
    "runner.readbacks",
    "dstep.dispatches", "dstep.ps_pulls", "dstep.ps_flushes",
    "ps.pulls", "ps.pushes", "ps.applies",
    "ps.bytes_pulled", "ps.bytes_pushed", "ps.degraded_pulls",
    "ps.dropped_pushes", "ps_service.applied", "ps_service.published",
    "wire.bytes_quantized", "wire.bytes_saved",
    "zero.rs_bytes", "zero.ag_bytes",
    "overlap.buckets", "overlap.exposed_wait_ms",
    "coord.retries", "coord.reconnects", "coord.breaker_opens",
    "coord.backoff_s",
    "prefetch.batches", "prefetch.dropped_batches",
    "prefetch.dropped_examples",
    "ckpt.saves", "ckpt.barrier_s", "ckpt.gc_removed",
    "ckpt.restores", "ckpt.fallback", "ckpt.corrupt_shards",
    "ckpt.gc_orphans", "ckpt.unhealthy_skipped",
    "sentinel.skips", "sentinel.rollbacks", "sentinel.nan_steps",
    "sentinel.save_vetoes", "sentinel.ps_suppressed",
    "sentinel.lr_halvings",
    "search.candidates", "search.pruned",
    "serve.requests", "serve.batches", "serve.compiles",
    "serve.padded_rows", "serve.degraded", "serve.shed", "serve.drained",
    "serve.deadline_shed", "serve.brownouts",
    "serve.tokens", "serve.prefill_admits", "serve.evictions",
    "autoscale.grows", "autoscale.shrinks", "autoscale.holds",
    "autoscale.refusals",
    "preempt.notices", "preempt.rescue_saves", "preempt.rescue_skips",
    "preempt.handoffs", "preempt.planned_shrinks",
    "telemetry.straggler_flags", "blackbox.dumps", "profiler.windows",
    "cluster.scrapes",
)


class Histogram:
    """Fixed-bucket histogram (log-spaced bounds by default) — the
    latency-distribution metric type counters cannot express: p50/p99
    need the shape of the distribution, not its sum.

    Buckets are CUMULATIVE-exportable (Prometheus ``le`` semantics come
    from a running sum at export time); observation is one bisect + two
    adds under the registry lock — cheap enough for a per-request serving
    hot path. Quantile readout interpolates linearly inside the winning
    bucket, clamped to the observed min/max so tiny samples do not report
    a quantile outside the data."""

    __slots__ = ("bounds", "counts", "count", "sum", "min", "max")

    # log-spaced defaults sized for millisecond-unit observations:
    # 0.05 ms .. ~105 s, x2 per bucket (22 finite bounds + overflow)
    DEFAULT_BOUNDS = tuple(0.05 * 2 ** i for i in range(22))

    def __init__(self, bounds=None):
        self.bounds = tuple(float(b) for b in (
            self.DEFAULT_BOUNDS if bounds is None else bounds))
        if list(self.bounds) != sorted(self.bounds) or not self.bounds:
            raise ValueError("histogram bounds must be sorted and "
                             "non-empty, got %r" % (self.bounds,))
        self.counts = [0] * (len(self.bounds) + 1)  # +1 = overflow bucket
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, value: float):
        import bisect
        v = float(value)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile (0 <= q <= 1) from the bucket counts;
        None when empty."""
        if self.count == 0:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile q must be in [0, 1], got %r" % q)
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (self.bounds[i] if i < len(self.bounds)
                      else (self.max if self.max is not None else lo))
                frac = (rank - seen) / c
                est = lo + (hi - lo) * max(0.0, min(1.0, frac))
                return min(max(est, self.min), self.max)
            seen += c
        return self.max

    def to_dict(self) -> dict:
        return {"bounds": list(self.bounds), "counts": list(self.counts),
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "p50": self.quantile(0.5), "p99": self.quantile(0.99)}

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        """Rebuild from :meth:`to_dict` output (the cross-process scrape
        wire format)."""
        h = cls(bounds=d["bounds"])
        h.counts = list(d["counts"])
        h.count = int(d["count"])
        h.sum = float(d["sum"])
        h.min, h.max = d.get("min"), d.get("max")
        return h


class TraceRecorder:
    """Thread-safe span ring buffer + counter/gauge registry.

    One process-global instance (``get_recorder()``) backs the module-
    level ``span()``/``counter_add()`` helpers the framework instruments
    with; independent instances are constructible for tests and for
    merging other processes' scraped traces."""

    def __init__(self, capacity: Optional[int] = None,
                 sample: Optional[int] = None,
                 pid: Optional[int] = None, host: Optional[str] = None):
        if capacity is None:
            capacity = max(int(const.ENV.ADT_TRACE_BUFFER.val), 1)
        self.capacity = capacity
        self.sample = max(int(sample if sample is not None
                              else const.ENV.ADT_TRACE_SAMPLE.val), 1)
        self.pid = os.getpid() if pid is None else int(pid)
        import socket
        self.host = host if host is not None else socket.gethostname()
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        # wall-clock anchor for the monotonic span timestamps:
        # perf_counter_ns has an ARBITRARY per-process origin, so traces
        # from different hosts/processes can only merge onto one timeline
        # after re-basing onto the wall clock (export adds this offset)
        self.epoch_offset_ns = time.time_ns() - time.perf_counter_ns()
        # cross-host correction on TOP of the wall clock: hosts disagree
        # by ms (NTP) to seconds (unsynced fleets), the same order as a
        # training step. telemetry/cluster.py's NTP-style handshake fills
        # these in (offset ADDS local→reference; error is the ± bound the
        # estimator reports), and export applies them so a merged scrape
        # is step-aligned across workers.
        self.clock_offset_ns = 0
        self.clock_error_ns: Optional[int] = None
        self._counters: Dict[str, float] = dict.fromkeys(DEFAULT_COUNTERS,
                                                         0.0)
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._ids = itertools.count(1)
        self._sample_tick = itertools.count()
        self._publish_seq = itertools.count(1)  # telemetry blob versions
        self._appended = 0
        self._tls = threading.local()
        # small-int thread ids with names, for readable trace tracks
        self._threads: Dict[int, int] = {}
        self._thread_names: Dict[int, str] = {}

    # ------------------------------------------------------------- plumbing

    def _span_stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._threads.get(ident)
        if tid is None:
            with self._lock:
                tid = self._threads.setdefault(ident, len(self._threads))
                self._thread_names[tid] = threading.current_thread().name
        return tid

    def _append(self, event: SpanEvent):
        # deque.append with maxlen is atomic (GIL) — no lock for the ring
        # itself; the appended tally is a read-modify-write shared with
        # background threads (async checkpoint writer, PS apply loop), so
        # it takes the registry lock (span exits are µs-scale relative to
        # the work they time — contention is noise)
        self._events.append(event)
        with self._lock:
            self._appended += 1

    # ------------------------------------------------------------ span API

    def span(self, name: str, cat: str = "app", **args):
        """Context manager timing a nested span. Honors the recorder's
        sampling stride; returns a shared no-op when sampled out."""
        if self.sample > 1 and next(self._sample_tick) % self.sample:
            return _NOOP
        return _Span(self, name, cat, args or None)

    def instant(self, name: str, cat: str = "app", **args):
        """Zero-duration marker event (state flips, drops, retries).
        NEVER sampled out: instants mark rare diagnostic events (breaker
        opens, degraded pulls, dropped tails) — exactly what a sampled
        production trace must not lose; only hot-path spans pay the
        stride."""
        self._append(SpanEvent(name, cat, time.perf_counter_ns(), 0,
                               self._tid(), next(self._ids),
                               (self._span_stack() or [0])[-1],
                               args or None))

    def current_span_id(self) -> int:
        """Innermost live span id on this thread (0 = none)."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else 0

    # ---------------------------------------------------------- registries

    def counter_add(self, name: str, value: float = 1.0):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def gauge_set(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = float(value)

    def hist_observe(self, name: str, value: float, bounds=None):
        """Record one observation into the named histogram (created with
        log-spaced default bounds — or ``bounds`` — on first use)."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(bounds)
            h.observe(value)

    def hist_quantile(self, name: str, q: float) -> Optional[float]:
        """Approximate q-quantile of a histogram (None when absent or
        empty) — the p50/p99 readout serving SLOs watch."""
        with self._lock:
            h = self._histograms.get(name)
            return h.quantile(q) if h is not None else None

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def histograms(self) -> Dict[str, dict]:
        """Snapshot of every histogram as a plain dict (bounds, counts,
        count, sum, min/max, p50/p99)."""
        with self._lock:
            return {n: h.to_dict() for n, h in self._histograms.items()}

    # ------------------------------------------------------------ snapshots

    def events(self) -> List[SpanEvent]:
        return list(self._events)

    @property
    def dropped_events(self) -> int:
        """Spans lost to ring-buffer wraparound."""
        return max(0, self._appended - len(self._events))

    def thread_names(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._thread_names)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name aggregate: count, total/mean/max seconds."""
        out: Dict[str, Dict[str, float]] = {}
        for e in self.events():
            row = out.setdefault(e.name, {"cat": e.cat, "count": 0,
                                          "total_s": 0.0, "max_s": 0.0})
            row["count"] += 1
            row["total_s"] += e.dur_ns / 1e9
            row["max_s"] = max(row["max_s"], e.dur_ns / 1e9)
        for row in out.values():
            row["mean_s"] = row["total_s"] / max(row["count"], 1)
        return out

    def durations_s(self, name: str) -> List[float]:
        """All recorded durations (seconds) of spans named ``name`` —
        the drift report's measured-time input."""
        return [e.dur_ns / 1e9 for e in self.events() if e.name == name]

    def clear(self):
        with self._lock:
            self._events.clear()
            self._appended = 0
            self._counters = dict.fromkeys(DEFAULT_COUNTERS, 0.0)
            self._gauges.clear()
            self._histograms.clear()


# ------------------------------------------------------- module-level state
#
# The module-level helpers are what the framework calls on hot paths, so
# the enabled/disabled decision must be ONE attribute check. `_TRACING`
# caches the parsed ADT_TRACE mode; configure() overrides it at runtime
# (tests, bench) and refresh_from_env() re-reads the environment.

_recorder: Optional[TraceRecorder] = None
_recorder_lock = threading.Lock()
_TRACING = False          # spans recorded at all
_SAMPLED = False          # spans recorded 1/N
# explicit configure() choice: (mode, sample) — survives reset(), wins
# over the env. None = env-driven. Without this, every helper that calls
# autodist_tpu.reset() (test fixtures, sequential programmatic builds)
# would silently revert a configure("1") to the env default and the
# traced run would come back empty.
_OVERRIDE: Optional[tuple] = None


def _parse_mode(raw: str):
    mode = (raw or "0").strip().lower()
    if mode in ("0", "", "off", "false"):
        return False, False
    if mode in ("sampled", "sample"):
        return True, True
    return True, False  # "1"/"on"/anything truthy: record every span


def _sync_mode():
    """Re-derive mode + the live recorder's sampling stride from ONE
    source (the configure() override when set, else the env) — a stale
    stride after a mode change silently drops (or over-records) spans
    while ``tracing_enabled()`` claims otherwise."""
    global _TRACING, _SAMPLED
    mode, sample = (_OVERRIDE if _OVERRIDE is not None
                    else (const.ENV.ADT_TRACE.val, None))
    _TRACING, _SAMPLED = _parse_mode(mode)
    rec = _recorder
    if rec is not None:
        if not _SAMPLED:
            rec.sample = 1
        else:
            rec.sample = max(int(sample if sample is not None
                                 else const.ENV.ADT_TRACE_SAMPLE.val), 1)


def refresh_from_env():
    """Re-derive the tracing mode (tests set env vars mid-process); an
    explicit :func:`configure` override keeps winning until
    ``configure(None)`` clears it."""
    _sync_mode()


refresh_from_env()


def configure(mode: Optional[str], capacity: Optional[int] = None,
              sample: Optional[int] = None) -> TraceRecorder:
    """Set the tracing mode programmatically ("0" | "1" | "sampled") and
    (optionally) rebuild the global recorder with a new capacity/stride.
    The choice is STICKY: it survives ``reset()`` /
    ``autodist_tpu.reset()`` (which otherwise re-reads ``ADT_TRACE``);
    ``configure(None)`` returns control to the env. Returns the active
    recorder."""
    global _OVERRIDE, _recorder
    _OVERRIDE = None if mode is None else (mode, sample)
    with _recorder_lock:
        if capacity is not None or sample is not None or _recorder is None:
            _recorder = TraceRecorder(capacity=capacity, sample=sample)
    _sync_mode()
    return _recorder


def get_recorder() -> TraceRecorder:
    """The process-global recorder (created on first use)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = TraceRecorder()
        _sync_mode()  # stride follows the active mode, not the env default
    return _recorder


def tracing_enabled() -> bool:
    return _TRACING


def span(name: str, cat: str = "app", **args):
    """Module-level span helper — THE instrumented-code entry point.
    Disabled mode returns a shared no-op after one flag check."""
    if not _TRACING:
        return _NOOP
    return get_recorder().span(name, cat, **args)


def instant(name: str, cat: str = "app", **args):
    if not _TRACING:
        return
    get_recorder().instant(name, cat, **args)


def counter_add(name: str, value: float = 1.0):
    """Always-on registry increment (works with tracing disabled)."""
    get_recorder().counter_add(name, value)


def gauge_set(name: str, value: float):
    get_recorder().gauge_set(name, value)


def hist_observe(name: str, value: float, bounds=None):
    """Always-on histogram observation (works with tracing disabled) —
    the latency-distribution companion to :func:`counter_add`."""
    get_recorder().hist_observe(name, value, bounds=bounds)


def hist_quantile(name: str, q: float) -> Optional[float]:
    return get_recorder().hist_quantile(name, q)


def histograms() -> Dict[str, dict]:
    return get_recorder().histograms()


def counters() -> Dict[str, float]:
    return get_recorder().counters()


def gauges() -> Dict[str, float]:
    return get_recorder().gauges()


def current_span_id() -> int:
    rec = _recorder
    return rec.current_span_id() if rec is not None else 0


def reset():
    """Drop all recorded state (test isolation — wired into
    ``autodist_tpu.reset()``). The MODE is re-derived, not dropped: an
    explicit ``configure()`` override survives (so a traced programmatic
    session keeps tracing across builds); env-driven mode re-reads
    ``ADT_TRACE``."""
    rec = _recorder
    if rec is not None:
        rec.clear()
    _sync_mode()
